"""Detection losses — masked, fixed-shape, batch-global semantics.

Capability parity with reference `train.py:29-57` (``_fast_rcnn_loc_loss``)
and the CE calls at `train.py:83,121`:

  * smooth-L1 with sigma: 0.5*s^2*d^2 below 1/s^2, |d| - 0.5/s^2 above
    (`train.py:43-52`), summed over positives and normalized by the
    batch-global positive count, floored at 1 (`train.py:55-57`).
  * softmax cross-entropy with ignore_index=-1 semantics: mean over
    non-ignored entries across the whole batch (`train.py:83,121`).

Under `jax.jit` auto-partitioning these global reductions become XLA
cross-replica collectives on a sharded batch, so data-parallel training is
bit-for-bit the same objective as single-device — the psum'd allreduce of
the BASELINE north star falls out of the sharding, not hand-written comms.

Under the explicit `shard_map` backend (`parallel/spmd.py`) each shard sees
only its local batch slice, so the batch-global normalizers must be summed
across shards by hand: pass ``axis_name`` and the positive/valid counts are
`lax.psum`'d over that mesh axis before dividing, keeping the objective
identical to the auto-partitioned path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

Array = jnp.ndarray


def _global_sum(x: Array, axis_name: Optional[str]) -> Array:
    return jax.lax.psum(x, axis_name) if axis_name else x


def smooth_l1(pred: Array, target: Array, sigma: float = 1.0) -> Array:
    """Elementwise smooth-L1 (Huber with the sigma^2 knee of `train.py:43-52`)."""
    s2 = sigma * sigma
    diff = jnp.abs(pred - target)
    return jnp.where(diff < 1.0 / s2, 0.5 * s2 * diff * diff, diff - 0.5 / s2)


def loc_loss(
    pred: Array,
    target: Array,
    labels: Array,
    sigma: float = 1.0,
    axis_name: Optional[str] = None,
) -> Array:
    """Localization loss on positive samples only (labels > 0), summed and
    normalized by max(#pos, 1) over the whole batch (`train.py:40-57`).

    pred/target: [..., 4]; labels: [...] with >0 = positive. With
    ``axis_name``, #pos is the global count across that mesh axis (the
    local sum/global count quotient psums to the global quotient).
    """
    pos = (labels > 0).astype(pred.dtype)
    per = smooth_l1(pred, target, sigma).sum(-1)  # [...]
    n_pos = jnp.maximum(_global_sum(pos.sum(), axis_name), 1.0)
    return (per * pos).sum() / n_pos


def ignore_cross_entropy(
    logits: Array, labels: Array, axis_name: Optional[str] = None
) -> Array:
    """Softmax CE averaged over entries with label >= 0 (torch
    ``ignore_index=-1`` semantics, `train.py:83,121`).

    logits: [..., C]; labels: [...] int with -1 = ignore. With
    ``axis_name``, the mean is over the global valid count.
    """
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0).astype(jnp.int32)
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, safe)
    n = jnp.maximum(_global_sum(valid.sum(), axis_name), 1)
    return jnp.where(valid, ce, 0.0).sum() / n
