"""Fixed-shape greedy NMS — the TPU-native replacement for
``torchvision.ops.nms`` (reference `nets/rpn.py:75`; SURVEY.md §2.3).

The reference's NMS returns a data-dependent number of boxes, which cannot
live inside a jit-compiled graph. Here NMS is a `lax.fori_loop` with exactly
``max_out`` iterations: each iteration selects the highest-scoring surviving
candidate and suppresses everything with IoU above the threshold against it.
The result is the same set, in the same score order, as sort-then-greedy NMS,
but as padded ``[max_out]`` indices plus a validity mask — a fixed shape XLA
can compile once and the batch dimension can vmap over.

Cost: ``max_out`` sequential steps of O(N) vector work. At the reference's
budgets (600 selections over <=12k candidates) this is latency- not
FLOP-bound; a Pallas kernel is the optimization path if profiling shows it
dominating (it does not — the conv stacks do).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.ops import boxes as box_ops

Array = jnp.ndarray

_NEG = -jnp.inf


@partial(jax.jit, static_argnames=("max_out",))
def nms_fixed(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
) -> tuple[Array, Array]:
    """Greedy NMS with a fixed output size.

    Args:
      boxes: [N, 4] candidate boxes ([r1, c1, r2, c2]).
      scores: [N] scores; higher is better.
      iou_thresh: suppress candidates with IoU strictly greater than this
        against a kept box (torchvision semantics).
      max_out: number of output slots (e.g. post_nms budget).
      mask: optional [N] bool; False entries are never selected.

    Returns:
      (idx, valid): [max_out] int32 indices into ``boxes`` in descending
      score order, and a [max_out] bool mask of which slots hold real
      selections. Invalid slots point at index 0.
    """
    n = boxes.shape[0]
    live_scores = scores.astype(jnp.float32)
    # Non-finite scores (NaN from a diverging score head) must never win
    # argmax — a NaN selection would mark the slot invalid without
    # suppressing anything, stalling every remaining iteration.
    live_scores = jnp.where(jnp.isfinite(live_scores), live_scores, _NEG)
    if mask is not None:
        live_scores = jnp.where(mask, live_scores, _NEG)

    def body(i, state):
        live, idx, valid = state
        best = jnp.argmax(live)
        best_score = live[best]
        is_valid = best_score > _NEG
        idx = idx.at[i].set(jnp.where(is_valid, best, 0).astype(jnp.int32))
        valid = valid.at[i].set(is_valid)
        ious = box_ops.iou(boxes[best][None, :], boxes)[0]  # [N]
        # The selected box suppresses itself (IoU 1) and all overlaps.
        suppress = (ious > iou_thresh) | (jnp.arange(n) == best)
        live = jnp.where(is_valid & suppress, _NEG, live)
        return live, idx, valid

    idx0 = jnp.zeros((max_out,), jnp.int32)
    valid0 = jnp.zeros((max_out,), bool)
    _, idx, valid = jax.lax.fori_loop(0, max_out, body, (live_scores, idx0, valid0))
    return idx, valid


def batched_nms_fixed(
    boxes: Array,
    scores: Array,
    class_ids: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
) -> tuple[Array, Array]:
    """Per-class NMS in one pass (for inference postprocessing).

    Boxes of different classes never suppress each other: each class's boxes
    are shifted into a disjoint coordinate region (the standard trick), then
    a single fixed-shape NMS runs over all of them (backend chosen by
    `nms_pallas.nms_fixed_auto` — same dispatch as the proposal path).
    """
    from replication_faster_rcnn_tpu.ops.nms_pallas import nms_fixed_auto

    extent = jnp.max(boxes) + 1.0
    offsets = class_ids.astype(boxes.dtype)[:, None] * extent
    shifted = boxes + offsets
    return nms_fixed_auto(shifted, scores, iou_thresh, max_out, mask=mask)
