"""Box geometry primitives — pure jnp, fixed-shape, vmap/jit-ready.

Convention (identical to the reference's, SURVEY.md preamble): boxes are
``[r1, c1, r2, c2]`` with ``r`` along image rows (height), ``c`` along
columns (width); deltas are ``[dr, dc, dh, dw]`` where ``h`` is the row
extent and ``w`` the column extent. The reference calls rows "x"
(`nets/faster_rcnn.py:10`); we use row/col naming to avoid that ambiguity.

Semantics match reference `utils/utils.py`:
  * :func:`decode`  == ``reg2bbox``  (`utils/utils.py:47-73`)
  * :func:`encode`  == ``bbox2reg``  (`utils/utils.py:75-100`)
  * :func:`iou`     == ``bbox_iou``  (`utils/utils.py:102-119`)
with two deliberate deviations: all functions are defined for batched/
broadcast shapes, and :func:`iou` divides safely (0 where the union is
empty) instead of emitting NaN for degenerate boxes.
"""

from __future__ import annotations

import jax.numpy as jnp

Array = jnp.ndarray

# Clamp for log-space size deltas before exp(): exp(12) ~ 1.6e5 px, far beyond
# any valid box, but finite — keeps decode/gradients NaN-free early in training
# when the regression head emits garbage.
_MAX_DLOG = 12.0


def centers_sizes(b: Array) -> tuple[Array, Array, Array, Array]:
    """Return (center_r, center_c, h, w) for boxes [..., 4]."""
    h = b[..., 2] - b[..., 0]
    w = b[..., 3] - b[..., 1]
    cr = (b[..., 0] + b[..., 2]) * 0.5
    cc = (b[..., 1] + b[..., 3]) * 0.5
    return cr, cc, h, w


def decode(anchors: Array, deltas: Array) -> Array:
    """Deltas -> boxes (reference ``reg2bbox``, `utils/utils.py:47-73`).

    anchors: [..., 4] boxes; deltas: [..., 4] ``[dr, dc, dh, dw]``.
    ``r = dr * h_a + cr_a``; ``h = exp(dh) * h_a`` (likewise for c/w).
    """
    cr, cc, h, w = centers_sizes(anchors)
    r = deltas[..., 0] * h + cr
    c = deltas[..., 1] * w + cc
    nh = jnp.exp(jnp.clip(deltas[..., 2], max=_MAX_DLOG)) * h
    nw = jnp.exp(jnp.clip(deltas[..., 3], max=_MAX_DLOG)) * w
    return jnp.stack(
        [r - nh * 0.5, c - nw * 0.5, r + nh * 0.5, c + nw * 0.5], axis=-1
    )


def encode(anchors: Array, boxes: Array, eps: float = 1e-8) -> Array:
    """Boxes -> deltas (reference ``bbox2reg``, `utils/utils.py:75-100`).

    ``dr = (cr_b - cr_a) / h_a``; ``dh = log(h_b / h_a)``. The reference's
    numpy version emits -inf/NaN for degenerate boxes; we clamp sizes to
    ``eps`` so padded (invalid) entries stay finite — callers mask them.
    """
    acr, acc, ah, aw = centers_sizes(anchors)
    bcr, bcc, bh, bw = centers_sizes(boxes)
    ah = jnp.maximum(ah, eps)
    aw = jnp.maximum(aw, eps)
    return jnp.stack(
        [
            (bcr - acr) / ah,
            (bcc - acc) / aw,
            jnp.log(jnp.maximum(bh, eps) / ah),
            jnp.log(jnp.maximum(bw, eps) / aw),
        ],
        axis=-1,
    )


def area(b: Array) -> Array:
    """Signed area product, as the reference computes it (`utils/utils.py:117-118`)."""
    return (b[..., 2] - b[..., 0]) * (b[..., 3] - b[..., 1])


def iou(a: Array, b: Array) -> Array:
    """Pairwise IoU: a [..., Na, 4], b [..., Nb, 4] -> [..., Na, Nb].

    Matches reference ``bbox_iou`` (`utils/utils.py:102-119`): intersection
    counts only when top-left < bottom-right on both axes. Division is safe
    (0 where the union is <= 0) rather than NaN.
    """
    tl = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    br = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = br - tl
    valid = jnp.all(wh > 0, axis=-1)
    inter = jnp.where(valid, wh[..., 0] * wh[..., 1], 0.0)
    union = area(a)[..., :, None] + area(b)[..., None, :] - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def clip(b: Array, img_h: float, img_w: float) -> Array:
    """Clamp boxes to the image (reference `nets/rpn.py:62-63`)."""
    r = jnp.clip(b[..., 0::2], 0.0, img_h)
    c = jnp.clip(b[..., 1::2], 0.0, img_w)
    return jnp.stack([r[..., 0], c[..., 0], r[..., 1], c[..., 1]], axis=-1)
