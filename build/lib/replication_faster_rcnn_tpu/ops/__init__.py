from replication_faster_rcnn_tpu.ops import (  # noqa: F401
    anchors,
    boxes,
    nms,
    nms_tiled,
    roi_ops,
)
