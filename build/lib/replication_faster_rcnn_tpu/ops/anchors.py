"""Anchor generation — static-shape jnp, computed once per (image_size, cfg).

Reference: `utils/anchors.py:5-61`. Base anchors are K = len(ratios) *
len(scales) boxes centered at the origin with ``h = base * scale * sqrt(r)``,
``w = base * scale / sqrt(r)``; the grid places them at every feat_stride
step over the feature map, flattened position-major with the K base anchors
contiguous per cell (matching how the RPN heads reshape their conv output,
reference `nets/rpn.py:118-124`).

Deliberate fix vs the reference: `utils/anchors.py:46-52` pairs conv cell
(row, col) with an anchor centered at the *transposed* image location
(its meshgrid "x" runs along columns but lands in the row coordinate of the
row-major box). That only appears to work because images are square. Here
cell (r, c) is centered at image (r * stride, c * stride).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

from replication_faster_rcnn_tpu.config import AnchorConfig


def anchor_base(
    base_size: int = 16,
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
    scales: Sequence[float] = (8.0, 16.0, 32.0),
) -> np.ndarray:
    """[K, 4] origin-centered base anchors, ratio-major (reference
    `utils/anchors.py:17-31` ordering: index = r_ind * len(scales) + s_ind)."""
    ratios = np.asarray(ratios, np.float32)
    scales = np.asarray(scales, np.float32)
    h = base_size * scales[None, :] * np.sqrt(ratios)[:, None]  # [R, S]
    w = base_size * scales[None, :] * np.sqrt(1.0 / ratios)[:, None]
    h = h.reshape(-1)
    w = w.reshape(-1)
    return np.stack([-h / 2, -w / 2, h / 2, w / 2], axis=1).astype(np.float32)


def grid_anchors(
    base: np.ndarray, feat_stride: int, feat_h: int, feat_w: int
) -> np.ndarray:
    """[feat_h * feat_w * K, 4] anchors over the feature grid.

    Flat index = (r * feat_w + c) * K + k, so it aligns with an RPN head
    output reshaped from [H, W, K*d] to [H*W*K, d].
    """
    rr = np.arange(feat_h, dtype=np.float32) * feat_stride
    cc = np.arange(feat_w, dtype=np.float32) * feat_stride
    shift_r, shift_c = np.meshgrid(rr, cc, indexing="ij")
    shifts = np.stack(
        [shift_r.ravel(), shift_c.ravel(), shift_r.ravel(), shift_c.ravel()], axis=1
    )  # [HW, 4]
    all_anchors = shifts[:, None, :] + base[None, :, :]  # [HW, K, 4]
    return all_anchors.reshape(-1, 4).astype(np.float32)


def make_anchors(cfg: AnchorConfig, feat_size: Tuple[int, int]) -> np.ndarray:
    """All anchors for a feature map of size ``feat_size`` under ``cfg``."""
    base = anchor_base(cfg.base_size, cfg.ratios, cfg.scales)
    return grid_anchors(base, cfg.feat_stride, feat_size[0], feat_size[1])
