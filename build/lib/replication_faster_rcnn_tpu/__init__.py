"""TPU-native Faster R-CNN framework.

A brand-new JAX/XLA implementation with the capabilities of the PyTorch
reference `juniorliu95/replication_faster_rcnn` (see SURVEY.md): VOC data
pipeline, ResNet backbones with the conv1..layer3 / layer4 split, 9-anchor
RPN, fixed-shape device-side proposal NMS, ROIPool/ROIAlign heads,
device-side anchor/proposal target assignment, one jit-compiled train step,
data-parallel over a TPU mesh via psum gradient allreduce.

Design principle (SURVEY.md §7): every stage that is dynamic-shape and
host-side in the reference (proposal NMS, target assignment) is fixed-shape,
masked, vmapped and device-side here, so the whole train step is one XLA
program.
"""

from replication_faster_rcnn_tpu.config import (
    AnchorConfig,
    DataConfig,
    EvalConfig,
    FasterRCNNConfig,
    MeshConfig,
    ModelConfig,
    ProposalConfig,
    ROITargetConfig,
    RPNTargetConfig,
    TrainConfig,
    get_config,
)

__version__ = "0.2.0"

__all__ = [
    "AnchorConfig",
    "DataConfig",
    "EvalConfig",
    "FasterRCNNConfig",
    "MeshConfig",
    "ModelConfig",
    "ProposalConfig",
    "ROITargetConfig",
    "RPNTargetConfig",
    "TrainConfig",
    "get_config",
]
