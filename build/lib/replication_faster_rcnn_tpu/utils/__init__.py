from replication_faster_rcnn_tpu.utils import debug, profiling  # noqa: F401
from replication_faster_rcnn_tpu.utils.logging import MetricLogger  # noqa: F401
