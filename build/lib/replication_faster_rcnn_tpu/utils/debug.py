"""Numeric-health guards — SURVEY.md §5 "race detection / sanitizers" (the
reference's only debug relics are a commented detect_anomaly and a stray
pdb.set_trace, `nets/resnet.py:190,283`).

* :func:`enable_nan_checks` — turn on jax's global NaN debugging (every jit
  output checked; errors pinpoint the emitting op).
* :func:`assert_tree_finite` — explicit pytree check for use at loss/grad
  boundaries when the global mode's recompilation cost is unwanted.
* :func:`finite_or_raise` — trainer hook: validate a metrics dict once per
  log interval and fail fast with context instead of training on NaNs.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax
import numpy as np


def enable_nan_checks(enable: bool = True) -> None:
    jax.config.update("jax_debug_nans", enable)


def assert_tree_finite(tree: Any, name: str = "tree") -> None:
    flat, _ = jax.tree_util.tree_flatten(tree)
    for i, leaf in enumerate(flat):
        arr = np.asarray(jax.device_get(leaf))
        if not np.all(np.isfinite(arr)):
            bad = int(np.sum(~np.isfinite(arr)))
            raise FloatingPointError(
                f"{name}: leaf {i} has {bad} non-finite values "
                f"(shape {arr.shape}, dtype {arr.dtype})"
            )


def finite_or_raise(metrics: Mapping[str, Any], step: int) -> Dict[str, float]:
    vals = {k: float(v) for k, v in metrics.items()}
    bad = [k for k, v in vals.items() if not np.isfinite(v)]
    if bad:
        raise FloatingPointError(
            f"non-finite metrics at step {step}: {bad} (all: {vals})"
        )
    return vals
