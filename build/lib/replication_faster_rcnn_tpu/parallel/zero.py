"""Cross-replica weight-update (optimizer-state) sharding — ZeRO-1 on XLA.

The technique of "Automatic Cross-Replica Sharding of Weight Update in
Data-Parallel Training" (Xu et al., arXiv:2004.13336, developed for TPUs and
cited in PAPERS.md): in data-parallel training every replica holds a full
copy of the Adam moments and performs the identical weight update. Sharding
the optimizer state over the ``data`` axis removes that redundancy — each
chip stores and updates only its 1/N slice of mu/nu and of the updated
parameters, and GSPMD turns the gradient allreduce into
reduce-scatter + all-gather around the update (same bytes on the wire as a
plain allreduce, 1/N of the update FLOPs and moment memory per chip).

Here this is expressed purely through sharding annotations (the GSPMD
recipe, no manual collectives): optimizer-state leaves get a
``NamedSharding`` that splits their largest evenly-divisible dimension over
the data axis; parameters stay replicated in the step's out_shardings, so
the forward pass is unchanged. ``jax.jit`` then places the
reduce-scatter/all-gather automatically.

Enabled by ``train.shard_opt_state`` / CLI ``--shard-opt`` (jit
auto-partitioning backend only — the explicit shard_map backend replicates
state by construction).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from replication_faster_rcnn_tpu.config import MeshConfig


def _leaf_sharding(leaf: Any, mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    """Shard the largest dim divisible by the data-axis size; scalars and
    indivisible shapes stay replicated."""
    n = mesh.shape[cfg.data_axis]
    shape = np.shape(leaf)
    if n <= 1 or not shape:
        return NamedSharding(mesh, P())
    divisible = [d for d, s in enumerate(shape) if s % n == 0 and s >= n]
    if not divisible:
        return NamedSharding(mesh, P())
    best = max(divisible, key=lambda d: shape[d])
    spec = [None] * len(shape)
    spec[best] = cfg.data_axis
    return NamedSharding(mesh, P(*spec))


def opt_state_shardings(opt_state: Any, mesh: Mesh, cfg: MeshConfig) -> Any:
    """Pytree of shardings for the optimizer state (leafwise rule above)."""
    return jax.tree_util.tree_map(
        lambda leaf: _leaf_sharding(leaf, mesh, cfg), opt_state
    )


def train_state_shardings(
    state: Any, mesh: Mesh, cfg: MeshConfig, shard_opt: bool
) -> Any:
    """Shardings for a full TrainState: params/BN stats/step/rng replicated,
    optimizer state leafwise-sharded when ``shard_opt``. Usable as both the
    jit in_shardings (via device_put) and out_shardings — the state layout
    is then stable across steps under donation."""
    replicated = NamedSharding(mesh, P())
    full = jax.tree_util.tree_map(lambda _: replicated, state)
    if not shard_opt:
        return full
    return full.replace(opt_state=opt_state_shardings(state.opt_state, mesh, cfg))


def place_train_state(state: Any, shardings: Any) -> Any:
    """Place the whole state pytree onto its target shardings (one batched
    device_put, as in `mesh.replicate_tree`)."""
    return jax.device_put(state, shardings)
