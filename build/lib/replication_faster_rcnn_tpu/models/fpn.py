"""Feature Pyramid Network — BASELINE.json config #3 ("FPN neck over
ResNet50 + multi-scale anchors").

No reference implementation exists (the reference is single-scale C4;
its `utils/anchors.py` multi-scale anchors are scale-multiples at one
stride). This follows the FPN paper (Lin et al., arXiv:1612.03144) with the
standard Faster-R-CNN-FPN wiring, built fixed-shape for XLA:

  * backbone exposes C2..C5 (strides 4/8/16/32);
  * 1x1 lateral convs + nearest top-down upsample + 3x3 smoothing -> P2..P5,
    plus P6 = stride-2 subsample of P5 (RPN-only level);
  * the RPN head is ONE set of convs shared across levels;
  * anchors use one scale per level (AnchorConfig.scales=(8,)) over
    per-level strides (4, 8, 16, 32, 64);
  * ROIs are assigned to levels by the paper's k = k0 + log2(sqrt(area)/224)
    rule. On TPU the per-level gather is computed for ALL rois on every
    level and blended by a one-hot level mask — 4x the (cheap) ROIAlign
    gathers in exchange for fully static shapes, no sorting/regrouping.

All spatial tensors are NHWC; levels are a list ordered fine -> coarse.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.models.resnet import _WIDTHS, _conv, _norm, _spec, _stage
from replication_faster_rcnn_tpu.ops import roi_ops

Array = jnp.ndarray

FPN_STRIDES: Tuple[int, ...] = (4, 8, 16, 32, 64)  # P2..P6


class ResNetFeatures(nn.Module):
    """ResNet trunk exposing every stage: [C2, C3, C4, C5]
    (strides 4/8/16/32; channels x1 for BasicBlock, x4 for Bottleneck).

    Same parameter naming/layout as ResNetTrunk+ResNetTail so pretrained
    torch checkpoints convert identically (layer4 lives here, not in the
    head, when FPN is on)."""

    arch: str = "resnet50"
    dtype: Any = jnp.bfloat16
    bn_axis: Any = None
    remat: bool = False  # jax.checkpoint each residual block

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> List[Array]:
        depths = _spec(self.arch)[1]
        ax, rm = self.bn_axis, self.remat
        x = x.astype(self.dtype)
        x = _conv(64, 7, 2, 3, self.dtype, "conv1")(x)
        x = _norm(self.dtype, train, "bn1", ax)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        c2 = _stage(self.arch, x, _WIDTHS[0], depths[0], 1, self.dtype, train, "layer1", ax, rm)
        c3 = _stage(self.arch, c2, _WIDTHS[1], depths[1], 2, self.dtype, train, "layer2", ax, rm)
        c4 = _stage(self.arch, c3, _WIDTHS[2], depths[2], 2, self.dtype, train, "layer3", ax, rm)
        c5 = _stage(self.arch, c4, _WIDTHS[3], depths[3], 2, self.dtype, train, "layer4", ax, rm)
        return [c2, c3, c4, c5]


def _upsample_nearest(x: Array, target_hw: Tuple[int, int]) -> Array:
    """2x nearest upsample cropped to the (possibly odd) finer shape."""
    n, h, w, c = x.shape
    y = jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)
    return y[:, : target_hw[0], : target_hw[1], :]


class FPNNeck(nn.Module):
    """[C2..C5] -> [P2..P6], all ``channels`` wide."""

    channels: int = 256
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, feats: Sequence[Array]) -> List[Array]:
        c2, c3, c4, c5 = feats
        laterals = [
            _conv(self.channels, 1, 1, 0, self.dtype, f"lateral{i}")(c)
            for i, c in enumerate((c2, c3, c4, c5))
        ]
        # top-down pathway
        tds = [laterals[3]]
        for i in (2, 1, 0):
            finer = laterals[i]
            tds.insert(
                0, finer + _upsample_nearest(tds[0], finer.shape[1:3])
            )
        outs = [
            _conv(self.channels, 3, 1, 1, self.dtype, f"smooth{i}")(t)
            for i, t in enumerate(tds)
        ]
        # P6: stride-2 subsample of P5 (maxpool k=1 s=2, Detectron convention)
        p6 = outs[3][:, ::2, ::2, :]
        return outs + [p6]


def roi_levels(rois: Array, k0: int = 4, canonical: float = 224.0) -> Array:
    """FPN paper level assignment: [..., 4] rois -> int level index 0..3
    (P2..P5; P6 is RPN-only). k = k0 + log2(sqrt(area)/canonical)."""
    h = jnp.maximum(rois[..., 2] - rois[..., 0], 1e-6)
    w = jnp.maximum(rois[..., 3] - rois[..., 1], 1e-6)
    k = jnp.floor(k0 + jnp.log2(jnp.sqrt(h * w) / canonical))
    return jnp.clip(k, 2, 5).astype(jnp.int32) - 2


def multilevel_roi_align(
    feats: Sequence[Array],
    rois: Array,
    img_h: float,
    img_w: float,
    out_size: int = 7,
    sampling_ratio: int = 2,
) -> Array:
    """ROIAlign across P2..P5 with level assignment, fixed-shape.

    feats: 4 arrays [N, Hl, Wl, C]; rois: [N, R, 4] image coords.
    Returns [N, R, out, out, C]. Every roi is aligned on every level and the
    results blended with a one-hot mask — static shapes, no partitioning.

    Uses the gather roi_align method: the einsum (MXU) formulation's dense
    [R, P, H] weight matmul is a win on the stride-16 single-scale map but
    scales with H*W, which at P2 (stride 4, e.g. 150x150 for 600 input)
    costs ~10x the whole backbone — random gathers are the right tool on
    the fine levels.
    """
    levels = roi_levels(rois)  # [N, R]
    out = None
    for li, feat in enumerate(feats[:4]):
        scale_r = feat.shape[1] / img_h
        scale_c = feat.shape[2] / img_w
        scale = jnp.asarray([scale_r, scale_c, scale_r, scale_c], rois.dtype)

        def align_one(f: Array, rb: Array) -> Array:
            return roi_ops.roi_align(
                f,
                rb * scale,
                out_size=out_size,
                sampling_ratio=sampling_ratio,
                method="gather",
            )

        crops = jax.vmap(align_one)(feat, rois)  # [N, R, s, s, C]
        mask = (levels == li).astype(crops.dtype)[..., None, None, None]
        out = crops * mask if out is None else out + crops * mask
    return out
