"""VGG16 backbone — the original py-faster-rcnn architecture that the
reference documents via its checked-in Caffe prototxt
(`reference/train_frcnn.prototxt:1-641`: conv1_1..conv5_3 shared features,
RoIPool 7x7 at spatial_scale 1/16, fc6/fc7 4096 head; SURVEY.md §2.1 #16).
The reference never executes it — the prototxt is documentation — so this
is built from the published architecture, TPU-first (NHWC, bfloat16
compute, float32 params).

Split mirrors the framework's trunk/tail convention:
  * ``VGG16Trunk``: conv1_1..conv5_3 with 2x2/s2 max pools after blocks
    1-4 only (pool5 is dropped, as in py-faster-rcnn) -> stride-16,
    512-channel feature map. Pools use ceil semantics (Caffe's default
    rounding, and what keeps 600 -> 38 matching the ResNet trunks and
    ``FasterRCNNConfig.feature_size``).
  * ``VGG16Tail``: flatten the pooled 7x7x512 ROI crop -> fc6 -> relu ->
    dropout -> fc7 -> relu -> dropout -> 4096-d embedding (the prototxt's
    classifier head; dropout p=0.5 active in train mode).

Parameter names (conv1_1, ..., fc7) map 1:1 onto torchvision's vgg16
state_dict via the index table in `models/convert.py::convert_vgg16`.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

Array = jnp.ndarray

# (block, convs-in-block, channels) — VGG configuration "D" (16 layers)
VGG16_BLOCKS = ((1, 2, 64), (2, 2, 128), (3, 3, 256), (4, 3, 512), (5, 3, 512))

VGG16_TRUNK_CHANNELS = 512
VGG16_TAIL_CHANNELS = 4096


def _ceil_max_pool(x: Array) -> Array:
    """2x2/s2 max pool with Caffe's ceil rounding: odd extents are padded
    (with -inf, via flax's reduce_window init) so 75 -> 38, matching the
    ResNet trunks' ceil-halving and ``FasterRCNNConfig.feature_size``."""
    ph, pw = x.shape[1] % 2, x.shape[2] % 2
    return nn.max_pool(x, (2, 2), strides=(2, 2), padding=((0, ph), (0, pw)))


class VGG16Trunk(nn.Module):
    """conv1_1..conv5_3 -> [N, ceil(H/16), ceil(W/16), 512].

    ``remat`` applies jax.checkpoint per conv block (conv{b}_1..conv{b}_n):
    backward recomputes the block's activations instead of keeping them in
    HBM. Wrapping the bound method keeps the parameter names (conv1_1, ...)
    at trunk scope, so checkpoints/conversion are unaffected.
    """

    dtype: Any = jnp.bfloat16
    remat: bool = False

    def _block(self, x: Array, block: int, n_convs: int, ch: int) -> Array:
        for i in range(1, n_convs + 1):
            x = nn.Conv(
                ch,
                (3, 3),
                padding=((1, 1), (1, 1)),
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name=f"conv{block}_{i}",
            )(x)
            x = nn.relu(x)
        return x

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        run = (
            nn.remat(VGG16Trunk._block, static_argnums=(2, 3, 4))
            if self.remat
            else VGG16Trunk._block
        )
        x = x.astype(self.dtype)
        for block, n_convs, ch in VGG16_BLOCKS:
            if block > 1:
                x = _ceil_max_pool(x)
            x = run(self, x, block, n_convs, ch)
        return x


class VGG16Tail(nn.Module):
    """Pooled ROI crop [R, s, s, 512] -> fc6/fc7 -> [R, 4096] embedding.

    The two 25088x4096 / 4096x4096 matmuls run in compute dtype on the MXU
    (param_dtype f32). Dropout (p=0.5, prototxt `train_frcnn.prototxt`
    drop6/drop7) is active only in train mode and needs a 'dropout' rng.
    """

    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        x = x.reshape(x.shape[0], -1).astype(self.dtype)
        for name in ("fc6", "fc7"):
            x = nn.Dense(
                VGG16_TAIL_CHANNELS, dtype=self.dtype, param_dtype=jnp.float32, name=name
            )(x)
            x = nn.relu(x)
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x.astype(jnp.float32)
