from replication_faster_rcnn_tpu.models import convert, faster_rcnn, head, resnet, rpn  # noqa: F401
