"""Jit-able masked random subsampling.

The reference subsamples with ``np.random.choice(index, size, replace=False)``
on host (`utils/utils.py:192-202,248-258`) — dynamic-size, host-side, and
unjittable. The XLA-native equivalent: draw a uniform priority per element,
and keep an element iff it is a member AND its priority ranks inside the
budget. The budget may be a traced scalar (e.g. "n_sample minus however many
positives were kept"), which a fixed-size sort handles where ``top_k`` with a
dynamic k could not.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def random_subset_mask(rng: Array, member: Array, k: Array) -> Array:
    """Uniformly choose min(k, member.sum()) elements of a masked set.

    Args:
      rng: PRNG key.
      member: [N] bool — the candidate set.
      k: scalar int (python or traced) — max elements to keep.

    Returns: [N] bool mask, a uniform random subset of ``member`` with
    ``min(k, member.sum())`` True entries.
    """
    r = jax.random.uniform(rng, member.shape)
    score = jnp.where(member, r, -jnp.inf)
    order = jnp.sort(score)[::-1]  # descending
    n_member = jnp.sum(member)
    kk = jnp.minimum(jnp.asarray(k, jnp.int32), n_member.astype(jnp.int32))
    # kk-th largest score is the cut; kk == 0 keeps nothing.
    cut = order[jnp.maximum(kk - 1, 0)]
    return member & (score >= cut) & (kk > 0)


def pack_by_priority(rng: Array, priority: Array, n_out: int) -> Array:
    """Order indices by (priority, random tiebreak) and take the first n_out.

    priority: [N] small non-negative ints; lower packs first. Returns
    [n_out] int32 indices. Used to lay out "positives first, then negatives,
    then filler" into a fixed-size sample block.
    """
    r = jax.random.uniform(rng, priority.shape)
    key = priority.astype(jnp.float32) + r  # r < 1 preserves class ordering
    order = jnp.argsort(key)
    return order[:n_out].astype(jnp.int32)
