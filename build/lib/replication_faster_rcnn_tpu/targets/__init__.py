from replication_faster_rcnn_tpu.targets.anchor_targets import (  # noqa: F401
    anchor_targets,
    batched_anchor_targets,
)
from replication_faster_rcnn_tpu.targets.proposal_targets import (  # noqa: F401
    batched_proposal_targets,
    proposal_targets,
)
from replication_faster_rcnn_tpu.targets.sampling import (  # noqa: F401
    pack_by_priority,
    random_subset_mask,
)
