"""Inference decode — proposals + head outputs -> final detections.

The reference never wrote this path (`test_eval.py` is empty; the combined
forward is broken — SURVEY.md §3.2), so the decode is designed from the
Faster R-CNN paper + the reference's training-time conventions:

  * head reg outputs were trained against targets normalized by
    ``roi_targets.reg_std`` (reference `utils/utils.py:216,271-272`), so
    predictions are de-normalized with the same std/mean before decoding.
  * class-specific boxes: class c uses deltas [4c:4c+4] (the gather
    semantics of reference `train.py:112-117`).
  * scores are softmax over 21 classes; background (class 0) is dropped.
  * score threshold, per-class NMS (class-offset trick), top
    ``max_detections`` kept — all fixed-shape with validity masks.

Everything is jit/vmap-safe; the batch decode is one XLA program.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import EvalConfig, ROITargetConfig
from replication_faster_rcnn_tpu.ops import boxes as box_ops
from replication_faster_rcnn_tpu.ops import nms as nms_ops

Array = jnp.ndarray


def decode_detections(
    rois: Array,
    roi_valid: Array,
    cls_logits: Array,
    reg_out: Array,
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """Per-image decode.

    Args:
      rois: [R, 4]; roi_valid: [R]; cls_logits: [R, C]; reg_out: [R, C*4].

    Returns dict with 'boxes' [D, 4], 'scores' [D], 'classes' [D] int32,
    'valid' [D] bool, D = eval_cfg.max_detections.
    """
    r = rois.shape[0]
    c = cls_logits.shape[-1]
    probs = jax.nn.softmax(cls_logits, axis=-1)  # [R, C]

    # de-normalize all class deltas and decode each class's box
    mean = jnp.asarray(roi_cfg.reg_mean, jnp.float32)
    std = jnp.asarray(roi_cfg.reg_std, jnp.float32)
    deltas = reg_out.reshape(r, c, 4) * std + mean  # [R, C, 4]
    boxes = box_ops.decode(rois[:, None, :], deltas)  # [R, C, 4]
    boxes = box_ops.clip(boxes, img_h, img_w)

    # flatten (roi, class>0) pairs; background column dropped by masking
    flat_boxes = boxes.reshape(r * c, 4)
    flat_scores = probs.reshape(r * c)
    class_ids = jnp.tile(jnp.arange(c), (r,))
    fg = (class_ids > 0) & jnp.repeat(roi_valid, c)
    fg &= flat_scores >= eval_cfg.score_thresh

    idx, valid = nms_ops.batched_nms_fixed(
        flat_boxes,
        flat_scores,
        class_ids,
        eval_cfg.nms_thresh,
        eval_cfg.max_detections,
        mask=fg,
    )
    return {
        "boxes": flat_boxes[idx] * valid[:, None],
        "scores": jnp.where(valid, flat_scores[idx], 0.0),
        "classes": jnp.where(valid, class_ids[idx], 0).astype(jnp.int32),
        "valid": valid,
    }


def batched_decode(
    rois: Array,
    roi_valid: Array,
    cls_logits: Array,
    reg_out: Array,
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """vmap over the batch: rois [N, R, 4] -> dict of [N, D, ...]."""
    return jax.vmap(
        lambda r, v, cl, rg: decode_detections(
            r, v, cl, rg, img_h, img_w, eval_cfg, roi_cfg
        )
    )(rois, roi_valid, cls_logits, reg_out)
