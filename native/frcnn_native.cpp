// Native host-side kernels for the data pipeline and CPU post-processing.
//
// The reference delegates its host-side heavy lifting to compiled kernels it
// doesn't ship (skimage's C resize at utils/data_loader.py:72, torchvision's
// C++ NMS at nets/rpn.py:75 — see SURVEY.md §2.3). This library is the
// framework's own native layer for the host side of the pipeline: the TPU
// compute path is XLA, but image preprocessing happens on CPU per sample and
// in Python it costs more than the device step at high chip counts.
//
// Exposed via a C ABI, loaded with ctypes (no pybind11 in this image).
// Build: make -C native  (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>

extern "C" {

// Bilinear resize (align_corners=False sampling: src = (dst + .5) * scale
// - .5) of an HWC uint8 RGB image, fused with /255 + mean/std normalization
// into float32 output. Matches data/native_ops.py:_resize_normalize_numpy
// exactly; parity-tested in tests/test_native.py.
void resize_bilinear_normalize(const uint8_t* src, int sh, int sw,
                               float* dst, int dh, int dw,
                               const float* mean, const float* stddev) {
  const float rscale = static_cast<float>(sh) / dh;
  const float cscale = static_cast<float>(sw) / dw;
  const float inv_std[3] = {1.0f / stddev[0], 1.0f / stddev[1], 1.0f / stddev[2]};
  for (int r = 0; r < dh; ++r) {
    float sr = (r + 0.5f) * rscale - 0.5f;
    sr = std::min(std::max(sr, 0.0f), static_cast<float>(sh - 1));
    const int r0 = static_cast<int>(sr);
    const int r1 = std::min(r0 + 1, sh - 1);
    const float fr = sr - r0;
    for (int c = 0; c < dw; ++c) {
      float sc = (c + 0.5f) * cscale - 0.5f;
      sc = std::min(std::max(sc, 0.0f), static_cast<float>(sw - 1));
      const int c0 = static_cast<int>(sc);
      const int c1 = std::min(c0 + 1, sw - 1);
      const float fc = sc - c0;
      const float w00 = (1 - fr) * (1 - fc), w01 = (1 - fr) * fc;
      const float w10 = fr * (1 - fc), w11 = fr * fc;
      const uint8_t* p00 = src + (static_cast<int64_t>(r0) * sw + c0) * 3;
      const uint8_t* p01 = src + (static_cast<int64_t>(r0) * sw + c1) * 3;
      const uint8_t* p10 = src + (static_cast<int64_t>(r1) * sw + c0) * 3;
      const uint8_t* p11 = src + (static_cast<int64_t>(r1) * sw + c1) * 3;
      float* out = dst + (static_cast<int64_t>(r) * dw + c) * 3;
      for (int ch = 0; ch < 3; ++ch) {
        const float v =
            p00[ch] * w00 + p01[ch] * w01 + p10[ch] * w10 + p11[ch] * w11;
        out[ch] = (v * (1.0f / 255.0f) - mean[ch]) * inv_std[ch];
      }
    }
  }
}

// Greedy score-sorted NMS (torchvision semantics: suppress IoU strictly
// greater than thresh). boxes are [n, 4] row-major [r1, c1, r2, c2].
// Writes up to max_keep kept indices; returns how many were written.
int nms_greedy(const float* boxes, const float* scores, int n, float thresh,
               int* keep, int max_keep) {
  if (n <= 0 || max_keep <= 0) return 0;
  // argsort by descending score (stable for deterministic ties)
  int* order = new int[n];
  for (int i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order, order + n,
                   [&](int a, int b) { return scores[a] > scores[b]; });
  float* areas = new float[n];
  for (int i = 0; i < n; ++i) {
    const float* b = boxes + static_cast<int64_t>(i) * 4;
    areas[i] = (b[2] - b[0]) * (b[3] - b[1]);
  }
  bool* dead = new bool[n]();
  int n_keep = 0;
  for (int oi = 0; oi < n && n_keep < max_keep; ++oi) {
    const int i = order[oi];
    if (dead[i]) continue;
    keep[n_keep++] = i;
    const float* bi = boxes + static_cast<int64_t>(i) * 4;
    for (int oj = oi + 1; oj < n; ++oj) {
      const int j = order[oj];
      if (dead[j]) continue;
      const float* bj = boxes + static_cast<int64_t>(j) * 4;
      const float tr = std::max(bi[0], bj[0]);
      const float tc = std::max(bi[1], bj[1]);
      const float br = std::min(bi[2], bj[2]);
      const float bc = std::min(bi[3], bj[3]);
      const float ih = br - tr, iw = bc - tc;
      if (ih <= 0 || iw <= 0) continue;
      const float inter = ih * iw;
      const float uni = areas[i] + areas[j] - inter;
      if (uni > 0 && inter / uni > thresh) dead[j] = true;
    }
  }
  delete[] order;
  delete[] areas;
  delete[] dead;
  return n_keep;
}

// Scale + round padded [m, 4] boxes from original to resized image coords,
// preserving -1 padding (reference utils/data_loader.py:66-69,115).
// nearbyint (FE_TONEAREST = half-to-even) matches numpy's np.round — the
// Python fallback is the behavioral spec, so ties must round identically.
void scale_boxes(float* boxes, const int32_t* labels, int m, float row_scale,
                 float col_scale) {
  for (int i = 0; i < m; ++i) {
    if (labels[i] < 0) continue;
    float* b = boxes + static_cast<int64_t>(i) * 4;
    b[0] = std::nearbyint(b[0] * row_scale);
    b[1] = std::nearbyint(b[1] * col_scale);
    b[2] = std::nearbyint(b[2] * row_scale);
    b[3] = std::nearbyint(b[3] * col_scale);
  }
}

}  // extern "C"
