// Native host-side kernels for the data pipeline and CPU post-processing.
//
// The reference delegates its host-side heavy lifting to compiled kernels it
// doesn't ship (skimage's C resize at utils/data_loader.py:72, torchvision's
// C++ NMS at nets/rpn.py:75 — see SURVEY.md §2.3). This library is the
// framework's own native layer for the host side of the pipeline: the TPU
// compute path is XLA, but image preprocessing happens on CPU per sample and
// in Python it costs more than the device step at high chip counts.
//
// Exposed via a C ABI, loaded with ctypes (no pybind11 in this image).
// Build: make -C native  (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <vector>

#ifndef FRCNN_NO_JPEG
#include <csetjmp>

#include <jpeglib.h>
#endif

extern "C" {

// Bilinear resize (align_corners=False sampling: src = (dst + .5) * scale
// - .5) of an HWC uint8 RGB image, fused with /255 + mean/std normalization
// into float32 output. Matches data/native_ops.py:_resize_normalize_numpy
// exactly; parity-tested in tests/test_native.py.
void resize_bilinear_normalize(const uint8_t* src, int sh, int sw,
                               float* dst, int dh, int dw,
                               const float* mean, const float* stddev) {
  const float rscale = static_cast<float>(sh) / dh;
  const float cscale = static_cast<float>(sw) / dw;
  // fold /255 into the per-channel affine so the inner loop is one fma
  float scale[3], shift[3];
  for (int ch = 0; ch < 3; ++ch) {
    scale[ch] = 1.0f / (255.0f * stddev[ch]);
    shift[ch] = -mean[ch] / stddev[ch];
  }
  // column sample positions don't depend on the row: precompute byte
  // offsets and blend weights once instead of per output pixel
  std::vector<int32_t> off0(dw), off1(dw);
  std::vector<float> fcs(dw);
  for (int c = 0; c < dw; ++c) {
    float sc = (c + 0.5f) * cscale - 0.5f;
    sc = std::min(std::max(sc, 0.0f), static_cast<float>(sw - 1));
    const int c0 = static_cast<int>(sc);
    const int c1 = std::min(c0 + 1, sw - 1);
    off0[c] = c0 * 3;
    off1[c] = c1 * 3;
    fcs[c] = sc - c0;
  }
  for (int r = 0; r < dh; ++r) {
    float sr = (r + 0.5f) * rscale - 0.5f;
    sr = std::min(std::max(sr, 0.0f), static_cast<float>(sh - 1));
    const int r0 = static_cast<int>(sr);
    const int r1 = std::min(r0 + 1, sh - 1);
    const float fr = sr - r0;
    const uint8_t* row0 = src + static_cast<int64_t>(r0) * sw * 3;
    const uint8_t* row1 = src + static_cast<int64_t>(r1) * sw * 3;
    float* out = dst + static_cast<int64_t>(r) * dw * 3;
    for (int c = 0; c < dw; ++c) {
      const float fc = fcs[c];
      const float w00 = (1 - fr) * (1 - fc), w01 = (1 - fr) * fc;
      const float w10 = fr * (1 - fc), w11 = fr * fc;
      const uint8_t* p00 = row0 + off0[c];
      const uint8_t* p01 = row0 + off1[c];
      const uint8_t* p10 = row1 + off0[c];
      const uint8_t* p11 = row1 + off1[c];
      for (int ch = 0; ch < 3; ++ch) {
        const float v =
            p00[ch] * w00 + p01[ch] * w01 + p10[ch] * w10 + p11[ch] * w11;
        out[ch] = v * scale[ch] + shift[ch];
      }
      out += 3;
    }
  }
}

}  // extern "C"

#ifndef FRCNN_NO_JPEG

namespace {

// libjpeg's default error handler exit()s the process; a longjmp handler
// turns decode failures into an error return so Python can fall back to PIL.
struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

void jpeg_err_silent(j_common_ptr, int) {}
void jpeg_err_nomsg(j_common_ptr) {}

}  // namespace

extern "C" {

// Decode a JPEG from memory straight into the fused resize+normalize
// kernel above: one native call replaces PIL.open + np.asarray + resize +
// normalize in the loader hot loop, and reports the pre-resize source
// dimensions (*orig_h, *orig_w — the loader scales gt boxes by them).
// Grayscale/CMYK sources are converted to RGB by libjpeg. With
// fast_scale != 0, the decoder's DCT-domain scaling (1/2, 1/4, 1/8) is
// used to decode at the smallest intermediate size that still covers
// (dh, dw), cutting IDCT + bilinear cost for downscales; the quality
// difference vs full-size decode is below the bilinear kernel's own
// resampling error for the >= 2x reductions it triggers on. Returns 0 on
// success, -1 on any decode error.
int decode_jpeg_resize_normalize(const uint8_t* data, int64_t len,
                                 float* dst, int dh, int dw,
                                 const float* mean, const float* stddev,
                                 int fast_scale, int32_t* orig_h,
                                 int32_t* orig_w) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  jerr.pub.emit_message = jpeg_err_silent;
  jerr.pub.output_message = jpeg_err_nomsg;
  std::vector<uint8_t> pixels;  // declared before setjmp: longjmp-safe
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, data, static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *orig_h = static_cast<int32_t>(cinfo.image_height);
  *orig_w = static_cast<int32_t>(cinfo.image_width);
  cinfo.out_color_space = JCS_RGB;
  if (fast_scale && dh > 0 && dw > 0) {
    // largest denominator whose scaled size still covers the target
    for (int denom = 8; denom >= 2; denom /= 2) {
      if (static_cast<int>(cinfo.image_height) >= dh * denom &&
          static_cast<int>(cinfo.image_width) >= dw * denom) {
        cinfo.scale_num = 1;
        cinfo.scale_denom = denom;
        break;
      }
    }
  }
  jpeg_start_decompress(&cinfo);
  const int sh = static_cast<int>(cinfo.output_height);
  const int sw = static_cast<int>(cinfo.output_width);
  if (cinfo.output_components != 3 || sh <= 0 || sw <= 0) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  pixels.resize(static_cast<size_t>(sh) * sw * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row =
        pixels.data() + static_cast<size_t>(cinfo.output_scanline) * sw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  resize_bilinear_normalize(pixels.data(), sh, sw, dst, dh, dw, mean, stddev);
  return 0;
}

}  // extern "C"

#endif  // FRCNN_NO_JPEG

extern "C" {

// Greedy score-sorted NMS (torchvision semantics: suppress IoU strictly
// greater than thresh). boxes are [n, 4] row-major [r1, c1, r2, c2].
// Writes up to max_keep kept indices; returns how many were written.
int nms_greedy(const float* boxes, const float* scores, int n, float thresh,
               int* keep, int max_keep) {
  if (n <= 0 || max_keep <= 0) return 0;
  // argsort by descending score (stable for deterministic ties)
  int* order = new int[n];
  for (int i = 0; i < n; ++i) order[i] = i;
  std::stable_sort(order, order + n,
                   [&](int a, int b) { return scores[a] > scores[b]; });
  float* areas = new float[n];
  for (int i = 0; i < n; ++i) {
    const float* b = boxes + static_cast<int64_t>(i) * 4;
    areas[i] = (b[2] - b[0]) * (b[3] - b[1]);
  }
  bool* dead = new bool[n]();
  int n_keep = 0;
  for (int oi = 0; oi < n && n_keep < max_keep; ++oi) {
    const int i = order[oi];
    if (dead[i]) continue;
    keep[n_keep++] = i;
    const float* bi = boxes + static_cast<int64_t>(i) * 4;
    for (int oj = oi + 1; oj < n; ++oj) {
      const int j = order[oj];
      if (dead[j]) continue;
      const float* bj = boxes + static_cast<int64_t>(j) * 4;
      const float tr = std::max(bi[0], bj[0]);
      const float tc = std::max(bi[1], bj[1]);
      const float br = std::min(bi[2], bj[2]);
      const float bc = std::min(bi[3], bj[3]);
      const float ih = br - tr, iw = bc - tc;
      if (ih <= 0 || iw <= 0) continue;
      const float inter = ih * iw;
      const float uni = areas[i] + areas[j] - inter;
      if (uni > 0 && inter / uni > thresh) dead[j] = true;
    }
  }
  delete[] order;
  delete[] areas;
  delete[] dead;
  return n_keep;
}

// Scale + round padded [m, 4] boxes from original to resized image coords,
// preserving -1 padding (reference utils/data_loader.py:66-69,115).
// nearbyint (FE_TONEAREST = half-to-even) matches numpy's np.round — the
// Python fallback is the behavioral spec, so ties must round identically.
void scale_boxes(float* boxes, const int32_t* labels, int m, float row_scale,
                 float col_scale) {
  for (int i = 0; i < m; ++i) {
    if (labels[i] < 0) continue;
    float* b = boxes + static_cast<int64_t>(i) * 4;
    b[0] = std::nearbyint(b[0] * row_scale);
    b[1] = std::nearbyint(b[1] * col_scale);
    b[2] = std::nearbyint(b[2] * row_scale);
    b[3] = std::nearbyint(b[3] * col_scale);
  }
}

}  // extern "C"
