"""Fast-tier wall-time budget accounting.

The tier-1 verify command runs ``pytest -m 'not slow'`` under a hard
``timeout 870`` (ROADMAP.md). Every PR that adds fast-tier tests eats
into that headroom, and the failure mode is brutal: the suite times out
as a unit and the WHOLE tier reads as broken. This module makes the
budget a number the suite itself enforces (see
``tests/test_tier_budget.py``) instead of a constant nobody re-checks:

1. **Bank** a measured run:  ``pytest -m 'not slow' --durations=0 -vv``
   prints per-phase (setup/call/teardown) durations; pipe the log here
   to write ``benchmarks/records/tier_durations.json``::

       python -m pytest tests/ -q -m 'not slow' --durations=0 \\
           --durations-min=0 | tee /tmp/t1.log
       python benchmarks/tier_budget_audit.py bank /tmp/t1.log

2. **Audit** a collection against the bank: project wall time as the sum
   of banked durations for every collected fast-tier test, charging
   ``DEFAULT_UNKNOWN_S`` for tests with no banked number (new tests are
   assumed cheap until measured — the point is catching the pattern of
   many new compiles, not hiding them)::

       python benchmarks/tier_budget_audit.py audit   # exit 1 over budget

The parsing/projection functions are pure (stdlib only, no pytest, no
jax) so the fast tier can unit-test them and run the projection in-
process against its own collected items at zero subprocess cost.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RECORD_PATH = os.path.join(_REPO, "benchmarks", "records", "tier_durations.json")
SCHEMA = "tier_durations/v1"

# The tier-1 timeout (ROADMAP.md verify command). Projection must land
# UNDER this with margin: the banked numbers come from one host state and
# CI hosts jitter, so the audit fails at the budget, and the margin field
# in reports tells you how close you are.
BUDGET_S = 870.0

# Charged for a collected test with no banked duration. Most unit tests
# cost milliseconds; anything that compiles a train step costs minutes
# and MUST be measured into the bank (or marked slow) — 2 s splits the
# difference loudly enough that ~30 new unbanked tests ring the alarm.
DEFAULT_UNKNOWN_S = 2.0

# `--durations` line:  "  12.34s call     tests/test_x.py::test_y"
_DURATION_RE = re.compile(
    r"^\s*(?P<sec>\d+(?:\.\d+)?)s\s+(?P<phase>call|setup|teardown)\s+"
    r"(?P<id>\S+)\s*$"
)


def parse_durations(text: str):
    """{test_id: total_seconds} summed over setup+call+teardown from a
    pytest ``--durations=0`` log. Lines that are not duration rows are
    ignored, so the whole run log can be piped in unfiltered."""
    out = {}
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if not m:
            continue
        out[m.group("id")] = out.get(m.group("id"), 0.0) + float(m.group("sec"))
    return out


def project_wall(collected_ids, banked_durations, default_s: float = DEFAULT_UNKNOWN_S):
    """Projected wall seconds for ``collected_ids`` plus accounting detail.

    Returns a dict: projected_s, banked_s (portion with measurements),
    n_known, n_unknown, unknown_ids (capped at 20 for readability)."""
    banked_s = 0.0
    unknown = []
    for tid in collected_ids:
        sec = banked_durations.get(tid)
        if sec is None:
            unknown.append(tid)
        else:
            banked_s += sec
    projected = banked_s + default_s * len(unknown)
    return {
        "projected_s": round(projected, 1),
        "banked_s": round(banked_s, 1),
        "n_known": len(collected_ids) - len(unknown),
        "n_unknown": len(unknown),
        "unknown_ids": unknown[:20],
    }


def audit_report(collected_ids, banked_record, budget_s: float = BUDGET_S,
                 default_s: float = DEFAULT_UNKNOWN_S):
    """Projection + verdict against the budget. ``banked_record`` is the
    loaded tier_durations.json dict."""
    report = project_wall(
        collected_ids, banked_record.get("durations", {}), default_s
    )
    report["budget_s"] = budget_s
    report["margin_s"] = round(budget_s - report["projected_s"], 1)
    report["over_budget"] = report["projected_s"] > budget_s
    report["banked_at"] = banked_record.get("measured")
    return report


def load_bank(path: str = RECORD_PATH):
    with open(path) as f:
        return json.load(f)


def bank(log_path: str, record_path: str = RECORD_PATH) -> dict:
    """Parse a durations log and write the bank record."""
    with open(log_path) as f:
        durations = parse_durations(f.read())
    if not durations:
        raise SystemExit(
            f"tier_budget_audit: no duration rows found in {log_path} — "
            "run pytest with --durations=0 (and --durations-min=0 on "
            "pytest>=6.2 so sub-5ms rows are kept)"
        )
    record = {
        "schema": SCHEMA,
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "n_tests": len(durations),
        "total_s": round(sum(durations.values()), 1),
        "durations": {k: round(v, 3) for k, v in sorted(durations.items())},
    }
    os.makedirs(os.path.dirname(record_path), exist_ok=True)
    tmp = f"{record_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    os.replace(tmp, record_path)
    return record


def _collect_fast_tier_ids():
    """Collected fast-tier test ids via a pytest --collect-only subprocess
    (CLI audit path; the in-suite test uses its own live collection)."""
    import subprocess

    r = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            "tests/",
            "-q",
            "-m",
            "not slow",
            "--collect-only",
            "-p",
            "no:cacheprovider",
        ],
        capture_output=True,
        text=True,
        cwd=_REPO,
    )
    ids = [
        line.strip()
        for line in r.stdout.splitlines()
        if "::" in line and not line.startswith(("=", "<"))
    ]
    if not ids:
        raise SystemExit(
            "tier_budget_audit: collection produced no test ids "
            f"(rc={r.returncode}):\n{r.stdout[-2000:]}\n{r.stderr[-2000:]}"
        )
    return ids


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] not in ("bank", "audit"):
        print(__doc__, file=sys.stderr)
        return 2
    if argv[0] == "bank":
        if len(argv) < 2:
            print("usage: tier_budget_audit.py bank <pytest-log>", file=sys.stderr)
            return 2
        record = bank(argv[1])
        print(
            f"banked {record['n_tests']} tests, {record['total_s']}s total "
            f"-> {RECORD_PATH}"
        )
        return 0
    # audit
    report = audit_report(_collect_fast_tier_ids(), load_bank())
    print(json.dumps(report, indent=1))
    if report["over_budget"]:
        print(
            f"tier_budget_audit: FAIL projected {report['projected_s']}s > "
            f"budget {report['budget_s']}s — mark tests slow or shrink "
            "configs, then re-bank",
            file=sys.stderr,
        )
        return 1
    print(
        f"tier_budget_audit: OK {report['projected_s']}s projected, "
        f"{report['margin_s']}s margin",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
