"""On-chip MFU experiment matrix (VERDICT r2 item 1 + queued measurements).

Runs a prioritized sequence of single-chip bench configurations, each in a
DETACHED process (the relay discipline in verify SKILL.md: never wrap a TPU
compile in `timeout`, never SIGKILL mid-RPC, treat every new-shape compile
as potentially the session's last). Results are appended to
``benchmarks/mfu_experiments.json`` IMMEDIATELY after each measurement; on
the first experiment that exceeds its deadline the runner records the stall
and STOPS — an abandoned compile may be wedging the service, and pushing
more work at it is how previous sessions lost the tunnel.

Experiment order (value-first, so an early death still pays):
  1. flagship voc_resnet18 b16 — re-record with the static-bound top_k
     subsample cut (queued item a; committed 210.4 predates it)
  2. voc_resnet50_fpn b8 — restore the UNVERIFIED 84.7 evidence chain
     (provenance finding; ~6min init compile expected)
  3. NMS tile sweep at b16: FRCNN_NMS_TILE in {256, 1024} (vs 512 in #1)
  4. adam mu bfloat16 at b16 (halves first-moment update traffic)
  5. voc_resnet50_fpn b16 (queued item b)
  6. eval-mode re-record (queued item c)
  7. profiler trace of the b16 loop (op-level attribution, VERDICT r3 #2)
  8. loader-fed Trainer throughput at 600x600 (VERDICT r3 #4)

Run (relay must be alive — the script refuses otherwise):
  python benchmarks/mfu_experiments.py [--only N,M] [--deadline 1800]

Round-4 note: experiment 0 (flagship b16) recorded 197.3 img/s, then
experiment 1 (fpn_b8_reverify) died UNAVAILABLE during its long init
compile and wedged the tunnel. The safe RESUME order defers the two
FPN configs (compile-heavy, observed wedge trigger) to just before the
Pallas tail risk:
  python benchmarks/mfu_experiments.py --only 2,3,4,6,7,8,9,10,11,1,5,12
(safe configs first; FPN pair — the observed wedge trigger — next; the
Pallas in-step validation, the other known wedge risk, dead last.)

Round-4 resume (fresh relay post-restart, 08:30Z): experiments 2,3,4,6
all measured (tile256 214.6 / tile1024 212.8 / bf16-mu 216.3 /
eval 358.8). Experiment 7 (profile_trace_b16, `--profile`) then blocked
from its FIRST RPC (2 s of CPU after 25 min — before any profiling
started) and the service wedged for all new clients; the bench process
exited on its own after the runner abandoned it. Treat `--profile`
through this tunnel as a wedge risk alongside FPN init and Pallas.
Remaining resume order (profile leg dropped): the service wedged for
new clients after the --profile block and the relay process itself died
~09:45Z. When a fresh relay appears, run — cheap settled questions
first, wedge risks last:
  python benchmarks/mfu_experiments.py --only 13,15,16,8,9,10,11,14,1,5,12
(13 = clean default-config flagship point; 15 = frozen-BN A/B against
it; 8,9 = fed-trainer legs; 10,11 = align/coco first records;
14 = grad_breakdown attribution; then the FPN pair and Pallas dead
last.)

Round-5 plan (tunnel dead at round start AGAIN — watcher at
/tmp/tpu_watch.sh polls every 150 s). The moment it reports ALIVE:
  1. python bench.py                  # bench of record FIRST (r4 VERDICT #2);
                                      # breakdown now emits dispatch_floor_ms +
                                      # opt_update_direct_adj_ms (VERDICT #1:
                                      # is the 15-22 ms direct row just the
                                      # tunnel's per-program RPC floor?)
  2. python benchmarks/mfu_experiments.py --only 13,8,9,14,1,15,16,17,10,11
     (13 flagship re-record; 8,9 fed-trainer legs = VERDICT #5; 14 grad
     attribution = VERDICT #7; then 1 = the FPN b8 re-verify, VERDICT #4 —
     a known wedge class, placed after the four most-wanted numbers but
     before the lever A/Bs; stop-on-failure halts everything behind a
     wedge. 17 = the new GroupNorm point on the BN-density axis.)
  3. python bench.py                  # bench-late (VERDICT #8): a later wedge
                                      # must not erase the round's live number
  4. python benchmarks/mfu_experiments.py --only 5,7
     (FPN b16 -> profile: remaining wedge classes after everything else
     is banked. The Pallas tail slot is a tombstone now — backend
     deleted mid-round per VERDICT #6.)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "mfu_experiments.json")

EXPERIMENTS = [
    {
        "name": "flagship_b16_topk",
        "env": {"BENCH_BATCH": "16"},
        "args": [],
        "why": "re-record the flagship with the top_k subsample cut (4a78230)",
    },
    {
        "name": "fpn_b8_reverify",
        # the bench's internal watchdog defaults to 1500s and would
        # wedge-exit before the outer deadline; FPN needs ~6min of init
        # compile first, so raise both
        "env": {"BENCH_WATCHDOG_S": "2300"},
        "args": ["--config", "voc_resnet50_fpn", "--batch-size", "8"],
        "why": "restore the unverified 84.7 FPN record on hardware",
        "deadline": 2400,
    },
    {
        "name": "b16_tile256",
        "env": {"BENCH_BATCH": "16", "FRCNN_NMS_TILE": "256"},
        "args": [],
        "why": "NMS tile sweep: 9.0ms proposal NMS at b16 under tile 512",
    },
    {
        "name": "b16_tile1024",
        "env": {"BENCH_BATCH": "16", "FRCNN_NMS_TILE": "1024"},
        "args": [],
        "why": "NMS tile sweep (large tile, fewer sequential steps)",
    },
    {
        "name": "b16_mu_bf16",
        # --mu-dtype makes the CLI build an explicit config, and an
        # explicit config's train.batch_size wins over BENCH_BATCH — so
        # the batch must be an explicit flag here
        "env": {},
        "args": ["--mu-dtype", "bfloat16", "--batch-size", "16"],
        "why": "Adam mu in bf16: backward+update is 40.7ms of the 76.1ms step",
    },
    {
        "name": "fpn_b16",
        "env": {"BENCH_WATCHDOG_S": "2300"},
        "args": ["--config", "voc_resnet50_fpn", "--batch-size", "16"],
        "why": "queued item b: b16 was the better operating point elsewhere",
        "deadline": 2400,
    },
    {
        "name": "eval_b8_topk",
        # the eval measurement reads BENCH_EVAL_BATCH (not BENCH_BATCH)
        "env": {"BENCH_MODE": "eval", "BENCH_EVAL_BATCH": "8"},
        "args": [],
        "why": "queued item c: re-record eval throughput post-top_k (was 328.1)",
    },
    {
        # same compiled program as experiment 1 (cache-warm) + a profiler
        # trace of the timed loop for op-level attribution of the
        # backward/update split the breakdown reports (VERDICT r3 #2)
        "name": "profile_trace_b16",
        "env": {"BENCH_BATCH": "16"},
        "args": ["--profile", "/tmp/trace_b16"],
        "why": "op-level trace behind the backward_ms/opt_update_ms split",
        # on success the runner summarizes the trace into
        # benchmarks/profile_trace_b16_ops.json (cli trace-summary —
        # pure host-side parsing, no jax import, safe post-measurement)
        "post_trace": "/tmp/trace_b16",
    },
    {
        # VERDICT r3 #4: the real loader-fed Trainer throughput at
        # 600x600 — the end-to-end counterpart of the synthetic-tensor
        # 210 img/s record. The script self-probes the backend and its
        # trainer leg runs full-shape only on TPU.
        "name": "loader_trainer_600",
        "env": {},
        "cmd": [sys.executable, "benchmarks/loader_throughput.py"],
        "success_key": "trainer_loop",
        # loader_throughput self-probes and falls back to a 128px CPU
        # trainer leg; for THIS queue that fallback means the relay died
        # mid-suite and must stop the runner, not be recorded as success
        "require_backend": "tpu",
        "why": "loader-fed trainer img/s at 600x600 vs the 210 synthetic",
        "deadline": 2400,
    },
    {
        # the same fed loop on the uint8/device-normalize path: quarter
        # the per-step host->device bytes. The delta vs loader_trainer_600
        # measures how transfer-bound the fed loop actually is.
        "name": "loader_trainer_600_u8",
        "env": {"LOADER_BENCH_U8": "1"},
        "cmd": [sys.executable, "benchmarks/loader_throughput.py"],
        "success_key": "trainer_loop",
        "require_backend": "tpu",
        "why": "u8 fed trainer at 600x600 vs the f32 fed row",
        "deadline": 2400,
    },
    {
        # BASELINE config #4 (ROIAlign head) at flagship scale — no
        # on-chip row exists; also isolates the align-vs-pool head cost
        # against the flagship's ROIPool number
        "name": "voc12_align_b16",
        "env": {},
        "args": ["--config", "voc12_resnet18_align", "--batch-size", "16"],
        "why": "first on-chip record for the align-head BASELINE config",
    },
    {
        # BASELINE config #5 at b8 (its preset batch 32 is FORBIDDEN:
        # b32 600x600 wedged the tunnel in round 1 — verify SKILL.md)
        "name": "coco_resnet50_b8",
        "env": {},
        "args": ["--config", "coco_resnet50", "--batch-size", "8"],
        "why": "first on-chip record for the coco_resnet50 BASELINE config",
    },
    {
        # index 12 — TOMBSTONE (keeps later indices stable). The Pallas
        # NMS backend was deleted in round 5 (VERDICT r4 #6: three rounds
        # as "pending validation" with no live chip slot; see git history
        # for ops/nms_pallas.py) and REBUILT under ISSUE 13 as
        # ops/pallas/ behind ops.backend (FRCNN_NMS=pallas resolves to it
        # again; interpret-mode parity gates it in tier 1, compiles go
        # through the warmup registry only). This slot keeps recording
        # the round-5 removal — on-chip measurement of the rebuilt
        # backend belongs to a fresh experiment index, not a rewrite of
        # this one's history.
        "name": "pallas_nms_instep_removed",
        "env": {},
        "cmd": ["/bin/sh", "-c",
                "echo '{\"metric\": \"note\", \"value\": "
                "\"pallas backend deleted round 5; rebuilt as "
                "ops/pallas behind ops.backend in ISSUE 13\"}'"],
        "success_key": "metric",
        "why": "tombstone: backend deleted round 5 per VERDICT #6",
    },
    {
        # index 13 — the post-restart sessions measured every b16 VARIANT
        # at 212.8-216.3 while the pre-wedge default pair sat at 196-197;
        # this clean default-config point settles whether the gap was
        # service state (expected) or the variants themselves
        "name": "flagship_b16_default_rerecord",
        "env": {"BENCH_BATCH": "16"},
        "args": [],
        "why": "clean default-config point to resolve the 197-vs-216 band",
    },
    {
        # index 14 — profiler-free backward attribution (the --profile
        # trace is a documented wedge risk): times trunk-BN-A/B, fwd,
        # walled-grad, image-grad and full-grad programs (six compiles),
        # banking each row as it lands
        "name": "grad_breakdown_b16",
        "env": {},
        "cmd": [sys.executable, "benchmarks/grad_breakdown.py",
                "--batch-size", "16"],
        "success_key": "grad_full_ms",
        "why": "split backward into trunk/head and wgrad/dgrad on chip",
        "deadline": 1800,
    },
    {
        # index 15 — the BN-density hypothesis' structural lever
        # (STAGE_BREAKDOWN.md): frozen BN turns every trunk/tail BN into
        # a fusable affine. vs the default-config point (experiment 13)
        # this isolates what train-mode BN costs the whole step.
        # NOTE on the A/B: exp 13's BENCH_BATCH=16 is per-device while
        # --batch-size 16 here is global — identical ONLY on the 1-chip
        # relay host this queue targets; on a multi-chip host pass
        # per-device x n_dev instead
        "name": "flagship_b16_frozen_bn",
        "env": {},
        "args": ["--frozen-bn", "--batch-size", "16"],
        "why": "price train-mode BN: the cross-config gap ranking tracks BN density",
    },
    {
        # index 16 — on-chip cost of the device-side scale-jitter
        # resample (ops/image.py): vs experiment 13 this prices the
        # fused input-pipeline gather inside the timed step (expected
        # ~negligible next to the conv stack; host-side the same jitter
        # costs 27 ms/sample). Same single-chip batch note as exp 15.
        "name": "flagship_b16_device_jitter",
        "env": {},
        "args": ["--augment-scale", "0.75", "1.25",
                 "--augment-scale-device", "--batch-size", "16"],
        "why": "price the on-chip jitter gather vs the 27 ms/sample host resample",
    },
    {
        # index 17 — the BN-free structural point on the BN-density axis
        # (STAGE_BREAKDOWN.md): exp 15 (frozen-BN) prices train-mode
        # batch-stats reductions; this removes BN entirely (GroupNorm(32),
        # per-sample, no mutable state). Together the three points
        # (batch / frozen / group) attribute the BN share of the 4.6x
        # gap over the tiling ceiling.
        "name": "flagship_b16_groupnorm",
        "env": {},
        "args": ["--norm", "group", "--batch-size", "16"],
        "why": "GroupNorm backbone: the BN-free point on the BN-density axis",
    },
    {
        # index 18 — the device-resident feed (round 5,
        # data/device_cache.py): same fed loop as experiments 8/9 but the
        # dataset lives in HBM and the host ships only indices per step.
        # The triple (fed, ram-cached, device-cached) in one record
        # attributes the fed loop's gap to the host->device transfer.
        "name": "loader_trainer_600_devcache",
        "env": {"LOADER_BENCH_U8": "1", "LOADER_BENCH_DEVICE_CACHE": "1"},
        "cmd": [sys.executable, "benchmarks/loader_throughput.py"],
        "success_key": "trainer_loop_device_cache",
        "require_backend": "tpu",
        "why": "device-cache fed trainer at 600x600 vs the 11 img/s host feed",
        "deadline": 2400,
    },
]

# Queue order by wedge risk (VERDICT round 5, item 5): round 5 lost the
# devcache leg, FPN re-verify, the trace, and all the A/Bs to a
# transfer-stress leg that ran before them. Safe validations go first
# (re-records, sweeps, devcache, A/Bs, first-records), then the known
# wedge classes in increasing blast order: FPN init compile, the
# profiler trace, and the u8/transfer-stress legs dead last. Values are
# indices into EXPERIMENTS — positions stay stable, new experiments
# append and must be slotted here by risk class.
DEFAULT_ORDER = [
    13, 0,       # flagship re-records (default pair, top_k)
    2, 3,        # NMS tile sweeps
    4,           # mu-dtype A/B
    6,           # eval throughput
    18,          # device-cache fed trainer (safe validation)
    15, 16, 17,  # trunk-BN A/Bs: frozen-BN, device-jitter, GroupNorm
    10, 11,      # first on-chip records: voc12_align, coco_resnet50
    14,          # grad breakdown
    12,          # pallas in-step tombstone
    1, 5,        # FPN legs (compile-heavy, the observed wedge trigger)
    7,           # profiler trace (documented wedge risk)
    8, 9,        # u8/transfer-stress legs dead last (round-5 wedge)
]
assert sorted(DEFAULT_ORDER) == list(range(len(EXPERIMENTS)))


def _relay_alive() -> bool:
    r = subprocess.run(["pgrep", "-f", "[r]elay.py"], capture_output=True)
    return r.returncode == 0


def _append(record) -> None:
    data = {"experiments": []}
    if os.path.exists(OUT):
        with open(OUT) as f:
            data = json.load(f)
    data["experiments"].append(record)
    with open(OUT, "w") as f:
        json.dump(data, f, indent=2)


def run_one(exp, deadline: float) -> bool:
    """Launch one bench in a detached process; poll its log for the JSON
    line. True = got a measurement. On deadline the process is ABANDONED
    (left running, per the no-SIGKILL-mid-RPC rule) and False returned."""
    log = os.path.join("/tmp", f"mfu_{exp['name']}.log")
    env = dict(os.environ)
    env.update(exp.get("env", {}))
    env["BENCH_NO_FALLBACK"] = "1"  # an experiment wants TPU or nothing
    cmd = exp.get("cmd")
    if cmd is None:
        cmd = [sys.executable, "-m", "replication_faster_rcnn_tpu.cli", "bench"]
        cmd += exp.get("args", [])
    with open(log, "w") as lf:
        proc = subprocess.Popen(
            cmd, stdout=lf, stderr=subprocess.STDOUT, env=env, cwd=REPO,
            start_new_session=True,
        )
    t0 = time.time()
    while time.time() - t0 < deadline:
        time.sleep(10)
        rc = proc.poll()
        with open(log) as f:
            lines = [ln for ln in f.read().splitlines() if ln.startswith("{")]
        if lines:
            try:
                rec = json.loads(lines[-1])
            except json.JSONDecodeError:
                rec = None
            key = exp.get("success_key", "value")
            got = rec.get(key) if rec is not None else None
            if got and got != "pending":
                want = exp.get("require_backend")
                if want and (
                    not isinstance(got, dict) or got.get("backend") != want
                ):
                    _append(
                        {
                            "name": exp["name"],
                            "why": exp["why"],
                            "error": "measured on backend "
                            f"{got.get('backend') if isinstance(got, dict) else got!r}"
                            f", required {want} — relay likely died mid-suite",
                            "result": rec,
                            "log": log,
                        }
                    )
                    print(f"[{exp['name']}] WRONG BACKEND (wanted {want})")
                    return False
                _append(
                    {
                        "name": exp["name"],
                        "why": exp["why"],
                        "env": exp.get("env", {}),
                        "args": exp.get("args", []),
                        **(
                            {"cmd": [os.path.basename(cmd[0])] + cmd[1:]}
                            if exp.get("cmd")
                            else {}
                        ),
                        "result": rec,
                        "wall_s": round(time.time() - t0, 1),
                        "recorded_utc": time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                        ),
                    }
                )
                print(f"[{exp['name']}] {rec.get(key)} {rec.get('unit', '')}")
                return True
        if rc is not None:
            _append(
                {
                    "name": exp["name"],
                    "why": exp["why"],
                    "error": f"bench exited rc={rc} without a measurement",
                    "log": log,
                }
            )
            print(f"[{exp['name']}] FAILED rc={rc} (see {log})")
            return False
    _append(
        {
            "name": exp["name"],
            "why": exp["why"],
            "error": f"no measurement within {deadline:.0f}s; process "
            f"pid={proc.pid} ABANDONED (not killed: SIGKILL mid-RPC wedges "
            "the service), runner stopped",
            "log": log,
        }
    )
    print(f"[{exp['name']}] STALLED — abandoning pid {proc.pid}, stopping runner")
    return False


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", default=None,
                    help="comma-separated experiment indices (0-based)")
    ap.add_argument("--deadline", type=float, default=1500,
                    help="per-experiment seconds before abandoning")
    args = ap.parse_args()

    if not _relay_alive():
        print("relay is DEAD — refusing to run (verify SKILL.md discipline)")
        sys.exit(3)

    # no --only: run everything in the wedge-risk order, not list order
    todo = [EXPERIMENTS[i] for i in DEFAULT_ORDER]
    if args.only:
        idx = [int(i) for i in args.only.split(",")]
        todo = [EXPERIMENTS[i] for i in idx]
    for exp in todo:
        deadline = exp.get("deadline", args.deadline)
        ok = run_one(exp, deadline)
        if not ok:
            # a failure may mean a wedged service; stop rather than risk
            # taking the tunnel down with queued compiles
            print("stopping after failure — re-run with --only to resume")
            sys.exit(1)
        if exp.get("post_trace"):
            # best-effort decoration: the measurement is already recorded;
            # a summarizer failure must not abort the remaining queue
            out_json = os.path.join(
                REPO, "benchmarks", f"{exp['name']}_ops.json"
            )
            try:
                r = subprocess.run(
                    [sys.executable, "-m",
                     "replication_faster_rcnn_tpu.cli", "trace-summary",
                     exp["post_trace"], "--top", "40", "--json", out_json],
                    cwd=REPO, timeout=300,
                )
                if r.returncode == 0:
                    print(f"trace op table -> {out_json}")
                else:
                    print(f"trace-summary exited rc={r.returncode} (non-fatal)")
            except Exception as e:  # noqa: BLE001 — post-processing only
                print(f"trace-summary failed (non-fatal): {e!r}")
    print(f"all done; results in {OUT}")


if __name__ == "__main__":
    main()
