"""COCO-format trained-mAP evidence + the mini gate (VERDICT r3 #7).

Two modes share one synthetic-COCO writer (real COCO-2017 disk layout:
JPEG images + ``annotations/instances_{split}2017.json`` with sparse
category ids, exercising the id remap of `data/coco.py`):

* **full** (default, slow, manual): `cli train --dataset coco` smoke
  leg + a resnet18@128 Trainer run to convergence, reporting the COCO
  metric sweep (mAP@[.50:.95] + mAP@0.5) on train and disjoint val
  splits. Writes benchmarks/coco_overfit_result.json.

* **--mini** (the gated A/B): three small resnet18@64 legs on CPU —
  single-scale random sampling, 2-bucket multi-scale
  (data.train_resolutions), and topk_iou region sampling
  (arXiv:1702.02138) — each writing an mAP@[.50:.95] curve to
  benchmarks/coco_overfit_curve_mini_{leg}.jsonl, plus the ISSUE-17
  quantization A/B on the single leg's checkpoint (f32 eval vs the
  PTQ int8 serving compute; the drop must stay within
  QUANT_MAP_DROP_PT mAP points). Before any training
  the run must pass (a) hand-computed COCO-evaluator oracles *exactly*
  and (b) a per-bucket-program presence check against the committed
  fingerprint bank. The result is compared against the banked record
  (benchmarks/records/coco_overfit_mini_cpu.json): any leg under the
  pinned mAP floor, or 2-bucket throughput more than 15% below the
  single-bucket leg, exits 1. ``--mini --update`` re-banks.

The model is resnet18 at small pixels for CPU tractability — the point
is the COCO data path + COCO metric + the three config axes end to end,
not the backbone (the coco_vgg16/coco_resnet50 presets share every
component downstream of the trunk). Reference: the original COCO
py-faster-rcnn recipe the reference documents but never implements
(`/root/reference/reference/train_frcnn.prototxt:410-417`).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# sparse ids with gaps, like real COCO's 1..90-with-holes
CAT_IDS = [3, 7, 11, 18, 25, 44, 61, 88]

RECORDS_DIR = os.path.join(REPO, "benchmarks", "records")
RECORD_PATH = os.path.join(RECORDS_DIR, "coco_overfit_mini_cpu.json")
BANK_PATH = os.path.join(
    REPO, "replication_faster_rcnn_tpu", "analysis", "fingerprints",
    "ci_cpu.json",
)
# 2-bucket leg must keep >= 85% of the single-bucket leg's images/sec
# (a >15% multi-scale dispatch overhead fails the run)
THROUGHPUT_RATIO_FLOOR = 0.85
MINI_BUCKETS = ((32, 32), (64, 64))
# int8 PTQ may cost at most this many mAP@[.50:.95] points vs the same
# checkpoint's f32 eval (ISSUE-17 acceptance)
QUANT_MAP_DROP_PT = 0.3


def write_synthetic_coco(root: str, split: str, n_images: int,
                         image_size: int, seed: int) -> None:
    """Planted-rectangle JPEGs + COCO instances JSON under ``root``.

    Same object statistics as data/synthetic.py (class-colored blocks on
    dark noise, 1..4 objects of h/8..h/2 extent) so a detector can
    genuinely fit the data; bbox is COCO xywh in original pixel coords.
    """
    import numpy as np
    from PIL import Image

    img_dir = os.path.join(root, split)
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(os.path.join(root, "annotations"), exist_ok=True)

    images, annotations = [], []
    ann_id = 1
    h = w = image_size
    for idx in range(n_images):
        rng = np.random.RandomState(seed + idx)
        arr = (rng.uniform(0.0, 0.15, (h, w, 3)) * 255).astype("uint8")
        n_obj = rng.randint(1, 5)
        for _ in range(n_obj):
            bh = rng.randint(h // 8, h // 2)
            bw = rng.randint(w // 8, w // 2)
            r1 = rng.randint(0, h - bh)
            c1 = rng.randint(0, w - bw)
            k = rng.randint(0, len(CAT_IDS))
            cls = k + 1  # contiguous label the model sees after remap
            color = 0.3 + 0.7 * np.asarray(
                [(cls % 3) / 2.0, ((cls // 3) % 3) / 2.0,
                 ((cls // 9) % 3) / 2.0]
            )
            block = color * 255 + rng.uniform(-12, 12, (bh, bw, 3))
            arr[r1:r1 + bh, c1:c1 + bw] = np.clip(block, 0, 255).astype(
                "uint8"
            )
            annotations.append({
                "id": ann_id,
                "image_id": idx,
                "category_id": CAT_IDS[k],
                "bbox": [float(c1), float(r1), float(bw), float(bh)],
                "area": float(bw * bh),
                "iscrowd": 0,
            })
            ann_id += 1
        fname = f"{idx:012d}.jpg"
        Image.fromarray(arr).save(
            os.path.join(img_dir, fname), quality=95
        )
        images.append(
            {"id": idx, "file_name": fname, "height": h, "width": w}
        )

    ann = {
        "images": images,
        "annotations": annotations,
        "categories": [
            {"id": cid, "name": f"thing{cid}"} for cid in CAT_IDS
        ],
    }
    with open(
        os.path.join(root, "annotations", f"instances_{split}.json"), "w"
    ) as f:
        json.dump(ann, f)


# ---------------------------------------------------------------- mini gate


def oracle_check() -> list:
    """Hand-computed COCO-protocol oracles the evaluator must hit
    *exactly* (same cases tests/test_eval.py pins; re-run here so a
    gate run can never bank numbers from a drifted evaluator). Returns
    failure strings; empty means exact."""
    import numpy as np

    from replication_faster_rcnn_tpu.eval.coco_eval import coco_summary

    def det(boxes, scores, classes):
        return {"boxes": np.asarray(boxes, float).reshape(-1, 4),
                "scores": np.asarray(scores, float),
                "classes": np.asarray(classes, int)}

    def gt(boxes, labels, ignore=None):
        g = {"boxes": np.asarray(boxes, float).reshape(-1, 4),
             "labels": np.asarray(labels, int)}
        if ignore is not None:
            g["ignore"] = np.asarray(ignore, bool)
        return g

    fails = []

    def expect(name, got, want):
        if not math.isclose(got, want, rel_tol=0, abs_tol=1e-12):
            fails.append(f"oracle {name}: got {got!r}, want {want!r}")

    # 1) perfect detections: a small gt (area 100) and a medium gt
    # (area 1600) each matched exactly -> every aggregate 1.0 except the
    # empty large slice (-1.0)
    r = coco_summary(
        [det([[0, 0, 10, 10]], [0.9], [1]),
         det([[0, 0, 40, 40]], [0.8], [2])],
        [gt([[0, 0, 10, 10]], [1]), gt([[0, 0, 40, 40]], [2])],
        num_classes=3,
    )
    for k, want in [("mAP", 1.0), ("AP50", 1.0), ("AP75", 1.0),
                    ("AP_small", 1.0), ("AP_medium", 1.0),
                    ("AP_large", -1.0)]:
        expect(f"perfect/{k}", float(r[k]), want)

    # 2) IoU exactly 0.6: matches thresholds {.50,.55,.60} only -> 3/10
    r = coco_summary(
        [det([[0, 0, 10, 6]], [0.9], [1])],
        [gt([[0, 0, 10, 10]], [1])],
        num_classes=2,
    )
    expect("iou0.6/mAP", float(r["mAP"]), 3.0 / 10.0)

    # 3) 101-point interpolation: TP(.9), FP(.8), TP(.7) over 2 gts ->
    # envelope 1.0 up to recall .5 (51 grid points), 2/3 after (50)
    r = coco_summary(
        [det([[0, 0, 10, 10], [50, 50, 60, 60], [20, 20, 30, 30]],
             [0.9, 0.8, 0.7], [1, 1, 1])],
        [gt([[0, 0, 10, 10], [20, 20, 30, 30]], [1, 1])],
        num_classes=2, iou_thresholds=[0.5],
    )
    expect("interp/mAP", float(r["mAP"]),
           (51 * 1.0 + 50 * (2.0 / 3.0)) / 101.0)

    # 4) an ignored gt absorbs exactly ONE detection (COCOeval, unlike
    # the VOC-devkit rule): second det on it is a plain FP, the real gt
    # stays unmatched -> AP 0
    r = coco_summary(
        [det([[0, 0, 10, 10], [0, 0, 10, 10]], [0.9, 0.8], [1, 1])],
        [gt([[0, 0, 10, 10], [50, 50, 60, 60]], [1, 1],
            ignore=[True, False])],
        num_classes=2,
    )
    expect("ignored-absorbs-one/mAP", float(r["mAP"]), 0.0)

    # 5) empty inputs -> -1.0 everywhere (JSON-safe no-gt convention)
    r = coco_summary([], [], num_classes=2)
    expect("empty/mAP", float(r["mAP"]), -1.0)
    return fails


def expected_bucket_programs() -> list:
    """The per-bucket train programs the audited config compiles —
    these must all be present in the committed fingerprint bank."""
    from replication_faster_rcnn_tpu.analysis.hlolint import (
        AUDIT_FEEDS, AUDIT_KS, audit_config,
    )
    from replication_faster_rcnn_tpu.train.warmup import (
        bucket_train_program_names,
    )

    return sorted(bucket_train_program_names(
        audit_config(), feeds=AUDIT_FEEDS, ks=AUDIT_KS
    ))


def bank_bucket_check(bank_path: str = BANK_PATH) -> list:
    """Failure strings for bucket programs missing from the committed
    fingerprint bank (empty when the bank covers multi-scale)."""
    if not os.path.exists(bank_path):
        return [f"fingerprint bank missing: {bank_path}"]
    with open(bank_path) as f:
        banked = set(json.load(f).get("programs", {}))
    return [
        f"bucket program not in fingerprint bank: {name}"
        for name in expected_bucket_programs() if name not in banked
    ]


def curve_throughput(curve_path: str) -> float:
    """Steady-state images/sec from a curve's per-epoch rows: median
    over epochs >= 2 (the first epochs pay compiles — the bucketed leg
    compiles one program per resolution as buckets first occur)."""
    import numpy as np

    rates = []
    with open(curve_path) as f:
        for line in f:
            row = json.loads(line)
            if "images_per_sec" in row and row.get("epoch", 0) >= 2:
                rates.append(row["images_per_sec"])
    return float(np.median(rates)) if rates else 0.0


def check_gate(record: dict, banked: dict) -> tuple:
    """Compare a fresh mini record against the banked one. Returns
    (fails, warns) string lists; any fail should exit 1. Pure on dicts
    so tests can drive it with synthetic records."""
    fails, warns = [], []
    if record.get("oracle_fails"):
        fails += [str(s) for s in record["oracle_fails"]]
    if record.get("missing_bucket_programs"):
        fails += [str(s) for s in record["missing_bucket_programs"]]

    floor = float(banked.get("map_floor", 0.0))
    for leg, res in record.get("legs", {}).items():
        if float(res.get("train_mAP", -1.0)) < floor:
            fails.append(
                f"leg {leg}: train mAP@[.50:.95] "
                f"{res.get('train_mAP'):.4f} under banked floor "
                f"{floor:.4f}"
            )

    quant = record.get("quant") or {}
    drop = quant.get("map_drop_pt")
    if drop is None:
        fails.append("record has no quantization mAP A/B (quant leg)")
    elif float(drop) > QUANT_MAP_DROP_PT:
        fails.append(
            f"int8 PTQ costs {float(drop):.3f} mAP points "
            f"(f32 {quant.get('f32_mAP'):.4f} -> int8 "
            f"{quant.get('int8_mAP'):.4f}); budget is "
            f"{QUANT_MAP_DROP_PT} pt"
        )

    legs = record.get("legs", {})
    single = float(legs.get("single", {}).get("images_per_sec", 0.0))
    buckets = float(legs.get("buckets", {}).get("images_per_sec", 0.0))
    if single > 0:
        ratio = buckets / single
        if ratio < THROUGHPUT_RATIO_FLOOR:
            fails.append(
                f"2-bucket throughput {buckets:.3f} img/s is "
                f"{ratio:.2f}x the single-bucket {single:.3f} img/s "
                f"(floor {THROUGHPUT_RATIO_FLOOR})"
            )
    else:
        fails.append("single leg has no throughput measurement")

    for leg, res in legs.items():
        old = banked.get("legs", {}).get(leg, {}).get("images_per_sec")
        new = res.get("images_per_sec")
        if old and new and new < 0.5 * old:
            warns.append(
                f"leg {leg}: {new:.3f} img/s is under half the banked "
                f"{old:.3f} img/s (timing only — not gated)"
            )
    return fails, warns


def _quant_leg(args) -> dict:
    """ISSUE-17 quantization A/B on the single leg's checkpoint: the
    f32 eval vs the quantized serving compute (PTQ calibration on the
    train split, the sensitivity sweep's per-group plan, then
    `quant/apply.py` reconstruction — dequantized weights + the
    QuantDense int8 head GEMMs — through the SAME Evaluator protocol).
    Gated: the mAP@[.50:.95] drop must stay within QUANT_MAP_DROP_PT."""
    from replication_faster_rcnn_tpu import quant
    from replication_faster_rcnn_tpu.data import make_dataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.serving.engine import _plain_dicts
    from replication_faster_rcnn_tpu.train.trainer import load_eval_variables

    cfg = _mini_config(args)
    model, variables = load_eval_variables(
        cfg, os.path.join(args.workdir, "single")
    )
    variables = _plain_dicts(variables)
    train_ds = make_dataset(cfg.data, "train")
    ev = Evaluator(cfg, model)

    def eval_map(v) -> float:
        return float(ev.evaluate(v, train_ds, batch_size=args.batch)["mAP"])

    batches = quant.dataset_calibration_batches(
        train_ds, batches=cfg.quant.calib_batches,
        batch_size=cfg.quant.calib_batch_size,
    )
    artifact = quant.calibrate(model, variables, batches, cfg)
    artifact = quant.sweep(
        model, variables, artifact, batches, cfg, eval_fn=eval_map
    )
    infer_vars = quant.build_infer_variables(
        quant.quantize_variables(variables, artifact), cfg
    )
    f32_map = eval_map(variables)
    int8_map = eval_map(infer_vars)
    leg = {
        "f32_mAP": f32_map,
        "int8_mAP": int8_map,
        "map_drop_pt": round(100.0 * (f32_map - int8_map), 4),
        "plan": dict(artifact["plan"]),
        "recon_rel_err": {
            g: s["recon_rel_err"]
            for g, s in artifact.get("sensitivity", {}).items()
            if "recon_rel_err" in s
        },
    }
    print(f"leg quant: {json.dumps(leg)}", flush=True)
    return leg


def _mini_config(args, buckets=(), sampling="random"):
    """One mini leg's config: resnet18@64, num_classes=9, COCO metric;
    ``buckets`` sets data.train_resolutions, ``sampling`` the
    train.sampling_strategy axis."""
    import dataclasses

    from replication_faster_rcnn_tpu.config import (
        DataConfig, EvalConfig, MeshConfig, TrainConfig, get_config,
    )

    size = (args.image_size, args.image_size)
    base = get_config("voc_resnet18")
    return base.replace(
        # anchors 8..32 px on the stride-16 trunk, matching the planted
        # h/8..h/2 objects at 64 px (see map_overfit.py for the idiom)
        anchors=dataclasses.replace(
            base.anchors, scales=(0.5, 1.0, 2.0)
        ),
        model=dataclasses.replace(
            base.model, roi_op="align", compute_dtype="float32",
            num_classes=len(CAT_IDS) + 1,
        ),
        # n_sample=16 makes the head sampler genuinely selective: at
        # 64 px the candidate pool (~144 anchors pre-NMS) never fills
        # the default 128-roi budget, so random and topk_iou would keep
        # the SAME mask and the A/B legs would be bitwise identical.
        roi_targets=dataclasses.replace(base.roi_targets, n_sample=16),
        data=DataConfig(
            dataset="coco", root_dir=args.data_root, image_size=size,
            max_boxes=8, train_resolutions=tuple(buckets),
        ),
        eval=EvalConfig(metric="coco"),
        train=TrainConfig(
            batch_size=args.batch, n_epoch=args.epochs, lr=args.lr,
            eval_every_epochs=args.eval_every,
            checkpoint_every_epochs=max(args.epochs, 1),
            sampling_strategy=sampling, seed=0,
        ),
        mesh=MeshConfig(num_data=1),
    )


def _mini_leg(name: str, cfg, args) -> dict:
    """Train one leg from scratch, write its curve jsonl, return the
    leg record: final train-split mAP@[.50:.95] sweep + steady-state
    images/sec."""
    from replication_faster_rcnn_tpu.data import make_dataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train.trainer import Trainer

    workdir = os.path.join(args.workdir, name)
    if os.path.exists(workdir):
        shutil.rmtree(workdir)
    curve_path = os.path.join(
        REPO, "benchmarks", f"coco_overfit_curve_mini_{name}.jsonl"
    )
    if os.path.exists(curve_path):
        os.remove(curve_path)

    train_ds = make_dataset(cfg.data, "train")
    trainer = Trainer(cfg, workdir=workdir, dataset=train_ds)
    trainer.logger.jsonl_path = curve_path
    t0 = time.time()
    trainer.train(log_every=5)
    train_s = time.time() - t0

    variables = {
        "params": trainer.state.params,
        "batch_stats": trainer.state.batch_stats,
    }
    res = Evaluator(cfg, trainer.model).evaluate(
        variables, train_ds, batch_size=args.batch
    )
    leg = {
        "train_mAP": float(res["mAP"]),
        "train_AP50": float(res.get("AP50", float("nan"))),
        "train_AP75": float(res.get("AP75", float("nan"))),
        "images_per_sec": curve_throughput(curve_path),
        "train_seconds": round(train_s, 1),
        "curve": os.path.relpath(curve_path, REPO),
    }
    print(f"leg {name}: {json.dumps(leg)}", flush=True)
    return leg


def mini_main(args) -> int:
    """The gated mini A/B: oracle + bank preflight, three legs, record
    vs bank (or --update re-bank). Returns the process exit code."""
    oracle_fails = oracle_check()
    for s in oracle_fails:
        print(f"FAIL {s}", flush=True)
    if oracle_fails:
        # never train (let alone bank) on a drifted evaluator
        return 1
    print("evaluator oracles: exact", flush=True)

    missing = bank_bucket_check()
    for s in missing:
        print(f"FAIL {s}", flush=True)

    import jax

    jax.config.update("jax_platforms", "cpu")

    if os.path.exists(args.data_root):
        shutil.rmtree(args.data_root)
    write_synthetic_coco(
        args.data_root, "train2017", args.images, args.image_size, seed=0
    )
    write_synthetic_coco(
        args.data_root, "val2017", args.images, args.image_size,
        seed=1 << 20,
    )

    legs = {
        "single": _mini_leg("single", _mini_config(args), args),
        "buckets": _mini_leg(
            "buckets", _mini_config(args, buckets=MINI_BUCKETS), args
        ),
        "topk": _mini_leg(
            "topk", _mini_config(args, sampling="topk_iou"), args
        ),
    }
    quant_leg = _quant_leg(args)
    record = {
        "schema": 1,
        "config": "coco-format resnet18@64 mini A/B (num_classes=9): "
                  "single-scale random / 2-bucket multi-scale / "
                  "topk_iou sampling",
        "platform": jax.default_backend(),
        "measured": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "epochs": args.epochs,
        "images": args.images,
        "batch": args.batch,
        "lr": args.lr,
        "buckets": [list(b) for b in MINI_BUCKETS],
        "oracle_fails": oracle_fails,
        "bucket_programs": expected_bucket_programs(),
        "missing_bucket_programs": missing,
        "legs": legs,
        "quant": quant_leg,
    }

    if args.update:
        fails, _ = check_gate(record, {"map_floor": 0.0})
        if fails:
            for s in fails:
                print(f"FAIL {s}", flush=True)
            print("refusing to bank a failing record", flush=True)
            return 1
        # pin the floor at half the worst leg (CPU reruns jitter; the
        # floor catches a broken axis, not a slow machine)
        worst = min(leg["train_mAP"] for leg in legs.values())
        record["map_floor"] = round(0.5 * worst, 4)
        os.makedirs(RECORDS_DIR, exist_ok=True)
        with open(RECORD_PATH, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
        print(f"banked {RECORD_PATH} (map_floor={record['map_floor']})",
              flush=True)
        return 0

    if not os.path.exists(RECORD_PATH):
        print(f"FAIL no banked record at {RECORD_PATH} "
              "(run with --mini --update)", flush=True)
        return 1
    with open(RECORD_PATH) as f:
        banked = json.load(f)
    fails, warns = check_gate(record, banked)
    for s in warns:
        print(f"WARN {s}", flush=True)
    for s in fails:
        print(f"FAIL {s}", flush=True)
    if not fails:
        print("coco_overfit mini gate: OK", flush=True)
    return 1 if fails else 0


# ---------------------------------------------------------------- full mode


def full_main(args) -> None:
    for d in (args.data_root, args.workdir):
        if os.path.exists(d):
            shutil.rmtree(d)

    write_synthetic_coco(
        args.data_root, "train2017", args.images, args.image_size, seed=0
    )
    write_synthetic_coco(
        args.data_root, "val2017", args.val_images, args.image_size,
        seed=1 << 20,
    )

    # leg 1 — the user-facing surface: `cli train --dataset coco` must
    # read the on-disk COCO layout and run real jitted steps
    cli_leg = None
    if not args.skip_cli_leg:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "-m", "replication_faster_rcnn_tpu.cli",
             "train", "--dataset", "coco", "--data-root", args.data_root,
             "--steps", "2", "--image-size", str(args.image_size),
             "--batch-size", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=1800,
            env={**os.environ, "PALLAS_AXON_POOL_IPS": "",
                 "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise RuntimeError(f"cli train leg failed:\n{proc.stderr[-2000:]}")
        cli_leg = {"steps": 2, "seconds": round(time.time() - t0, 1),
                   "ok": True}
        print(f"cli-train-on-coco leg ok ({cli_leg['seconds']}s)")

    # leg 2 — full Trainer to convergence + COCO metric sweep.
    # CPU by design (resnet18@128 exists for CPU tractability): force the
    # CPU backend before any device op so running this script in the
    # TPU-driver env can neither hang on a wedged relay nor push a
    # multi-epoch compile at the fragile tunnel (verify SKILL.md). Safe
    # here: no backend has been initialized in-process yet (leg 1 is a
    # subprocess).
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")

    from replication_faster_rcnn_tpu.config import (
        DataConfig, EvalConfig, MeshConfig, TrainConfig, get_config,
    )
    from replication_faster_rcnn_tpu.data import make_dataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train.trainer import Trainer

    size = (args.image_size, args.image_size)
    base = get_config("voc_resnet18")
    cfg = base.replace(
        # (1,2,4) anchor scales: 16..64 px anchors matching the planted
        # h/8..h/2 objects at this small image size (see map_overfit.py)
        anchors=dataclasses.replace(base.anchors, scales=(1.0, 2.0, 4.0)),
        model=dataclasses.replace(
            base.model, roi_op="align", compute_dtype="float32",
            num_classes=len(CAT_IDS) + 1,
        ),
        data=DataConfig(dataset="coco", root_dir=args.data_root,
                        image_size=size, max_boxes=8,
                        augment_hflip=args.augment_hflip),
        eval=EvalConfig(metric="coco"),
        train=TrainConfig(
            batch_size=args.batch, n_epoch=args.epochs, lr=args.lr,
            eval_every_epochs=args.eval_every,
            checkpoint_every_epochs=max(args.epochs // 2, 1), seed=0,
        ),
        mesh=MeshConfig(num_data=1),
    )

    train_ds = make_dataset(cfg.data, "train")
    assert len(train_ds) == args.images
    trainer = Trainer(cfg, workdir=args.workdir, dataset=train_ds)
    t0 = time.time()
    trainer.train(log_every=5)
    train_s = time.time() - t0

    variables = {
        "params": trainer.state.params,
        "batch_stats": trainer.state.batch_stats,
    }
    evaluator = Evaluator(cfg, trainer.model)
    train_res = evaluator.evaluate(
        variables, train_ds, batch_size=args.batch
    )
    val_res = evaluator.evaluate(
        variables, make_dataset(cfg.data, "val"), batch_size=args.batch
    )

    result = {
        "metric": "coco mAP@[.50:.95]",
        "train_coco_mAP": float(train_res["mAP"]),
        "train_AP50": float(train_res.get("AP50", float("nan"))),
        "val_coco_mAP": float(val_res["mAP"]),
        "val_AP50": float(val_res.get("AP50", float("nan"))),
        "val_images": args.val_images,
        "cli_train_on_coco_leg": cli_leg,
        "config": "coco-format resnet18@128 (num_classes=9, sparse cat "
                  "ids remapped)",
        "epochs": args.epochs,
        "images": args.images,
        "batch": args.batch,
        "lr": args.lr,
        "train_seconds": round(train_s, 1),
        "backend": __import__("jax").default_backend(),
        "augment_hflip": args.augment_hflip,
    }
    out = os.path.join(
        REPO, "benchmarks",
        "coco_overfit_result_aug.json" if args.augment_hflip
        else "coco_overfit_result.json",
    )
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mini", action="store_true",
                    help="run the gated three-leg A/B instead of the "
                    "full convergence run")
    ap.add_argument("--update", action="store_true",
                    help="with --mini: re-bank "
                    "benchmarks/records/coco_overfit_mini_cpu.json")
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--images", type=int, default=None)
    ap.add_argument("--val-images", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--eval-every", type=int, default=None)
    ap.add_argument("--data-root", default="/tmp/coco_synth")
    ap.add_argument("--workdir", default="/tmp/coco_overfit_ckpts")
    ap.add_argument("--skip-cli-leg", action="store_true")
    ap.add_argument("--augment-hflip", action="store_true",
                    help="train with the 50%% flip; results go to "
                    "coco_overfit_result_aug.json so the aug-off row is "
                    "kept for comparison (COCO-side counterpart of the "
                    "VOC evidence that flipped the preset default)")
    args = ap.parse_args()

    # mode-dependent defaults: the mini A/B is sized for a CPU gate run,
    # the full mode keeps the original convergence recipe
    mini_defaults = dict(epochs=30, images=8, image_size=64, batch=4,
                         lr=1e-3, eval_every=10)
    full_defaults = dict(epochs=30, images=32, image_size=128, batch=8,
                         lr=3e-4, eval_every=5)
    for k, v in (mini_defaults if args.mini else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    if args.mini:
        sys.exit(mini_main(args))
    full_main(args)


if __name__ == "__main__":
    main()
