"""COCO-format trained-mAP evidence (VERDICT r3 #7).

`coco_vgg16` has an on-chip throughput record but the overfit evidence
harness (`benchmarks/map_overfit.py`) is VOC/synthetic-only — no COCO
config ever produced end-to-end trained-mAP numbers. This script closes
that: it writes a small synthetic dataset in the REAL COCO-2017 disk
layout (JPEG images + ``annotations/instances_{split}2017.json`` with
sparse category ids, exercising the id remap of `data/coco.py:42-44`),
drives a few `cli train` steps over it (the user-facing surface reads
COCO from disk), then runs the full Trainer to convergence and reports
the COCO metric sweep (mAP@[.50:.95] + mAP@0.5) on train and disjoint
val splits through the real eval path.

The model is resnet18-at-128px for CPU tractability — the point is the
COCO data path + COCO metric end to end, not the backbone (the
coco_vgg16/coco_resnet50 presets share every component downstream of the
trunk). Reference: the original COCO py-faster-rcnn recipe the
reference documents but never implements
(`/root/reference/reference/train_frcnn.prototxt:410-417`).

Writes benchmarks/coco_overfit_result.json.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# sparse ids with gaps, like real COCO's 1..90-with-holes
CAT_IDS = [3, 7, 11, 18, 25, 44, 61, 88]


def write_synthetic_coco(root: str, split: str, n_images: int,
                         image_size: int, seed: int) -> None:
    """Planted-rectangle JPEGs + COCO instances JSON under ``root``.

    Same object statistics as data/synthetic.py (class-colored blocks on
    dark noise, 1..4 objects of h/8..h/2 extent) so a detector can
    genuinely fit the data; bbox is COCO xywh in original pixel coords.
    """
    import numpy as np
    from PIL import Image

    img_dir = os.path.join(root, split)
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(os.path.join(root, "annotations"), exist_ok=True)

    images, annotations = [], []
    ann_id = 1
    h = w = image_size
    for idx in range(n_images):
        rng = np.random.RandomState(seed + idx)
        arr = (rng.uniform(0.0, 0.15, (h, w, 3)) * 255).astype("uint8")
        n_obj = rng.randint(1, 5)
        for _ in range(n_obj):
            bh = rng.randint(h // 8, h // 2)
            bw = rng.randint(w // 8, w // 2)
            r1 = rng.randint(0, h - bh)
            c1 = rng.randint(0, w - bw)
            k = rng.randint(0, len(CAT_IDS))
            cls = k + 1  # contiguous label the model sees after remap
            color = 0.3 + 0.7 * np.asarray(
                [(cls % 3) / 2.0, ((cls // 3) % 3) / 2.0,
                 ((cls // 9) % 3) / 2.0]
            )
            block = color * 255 + rng.uniform(-12, 12, (bh, bw, 3))
            arr[r1:r1 + bh, c1:c1 + bw] = np.clip(block, 0, 255).astype(
                "uint8"
            )
            annotations.append({
                "id": ann_id,
                "image_id": idx,
                "category_id": CAT_IDS[k],
                "bbox": [float(c1), float(r1), float(bw), float(bh)],
                "area": float(bw * bh),
                "iscrowd": 0,
            })
            ann_id += 1
        fname = f"{idx:012d}.jpg"
        Image.fromarray(arr).save(
            os.path.join(img_dir, fname), quality=95
        )
        images.append(
            {"id": idx, "file_name": fname, "height": h, "width": w}
        )

    ann = {
        "images": images,
        "annotations": annotations,
        "categories": [
            {"id": cid, "name": f"thing{cid}"} for cid in CAT_IDS
        ],
    }
    with open(
        os.path.join(root, "annotations", f"instances_{split}.json"), "w"
    ) as f:
        json.dump(ann, f)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--images", type=int, default=32)
    ap.add_argument("--val-images", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--eval-every", type=int, default=5)
    ap.add_argument("--data-root", default="/tmp/coco_synth")
    ap.add_argument("--workdir", default="/tmp/coco_overfit_ckpts")
    ap.add_argument("--skip-cli-leg", action="store_true")
    ap.add_argument("--augment-hflip", action="store_true",
                    help="train with the 50%% flip; results go to "
                    "coco_overfit_result_aug.json so the aug-off row is "
                    "kept for comparison (COCO-side counterpart of the "
                    "VOC evidence that flipped the preset default)")
    args = ap.parse_args()

    for d in (args.data_root, args.workdir):
        if os.path.exists(d):
            shutil.rmtree(d)

    write_synthetic_coco(
        args.data_root, "train2017", args.images, args.image_size, seed=0
    )
    write_synthetic_coco(
        args.data_root, "val2017", args.val_images, args.image_size,
        seed=1 << 20,
    )

    # leg 1 — the user-facing surface: `cli train --dataset coco` must
    # read the on-disk COCO layout and run real jitted steps
    cli_leg = None
    if not args.skip_cli_leg:
        t0 = time.time()
        proc = subprocess.run(
            [sys.executable, "-m", "replication_faster_rcnn_tpu.cli",
             "train", "--dataset", "coco", "--data-root", args.data_root,
             "--steps", "2", "--image-size", str(args.image_size),
             "--batch-size", "2"],
            cwd=REPO, capture_output=True, text=True, timeout=1800,
            env={**os.environ, "PALLAS_AXON_POOL_IPS": "",
                 "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode != 0:
            raise RuntimeError(f"cli train leg failed:\n{proc.stderr[-2000:]}")
        cli_leg = {"steps": 2, "seconds": round(time.time() - t0, 1),
                   "ok": True}
        print(f"cli-train-on-coco leg ok ({cli_leg['seconds']}s)")

    # leg 2 — full Trainer to convergence + COCO metric sweep.
    # CPU by design (resnet18@128 exists for CPU tractability): force the
    # CPU backend before any device op so running this script in the
    # TPU-driver env can neither hang on a wedged relay nor push a
    # multi-epoch compile at the fragile tunnel (verify SKILL.md). Safe
    # here: no backend has been initialized in-process yet (leg 1 is a
    # subprocess).
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")

    from replication_faster_rcnn_tpu.config import (
        DataConfig, EvalConfig, MeshConfig, TrainConfig, get_config,
    )
    from replication_faster_rcnn_tpu.data import make_dataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train.trainer import Trainer

    size = (args.image_size, args.image_size)
    base = get_config("voc_resnet18")
    cfg = base.replace(
        # (1,2,4) anchor scales: 16..64 px anchors matching the planted
        # h/8..h/2 objects at this small image size (see map_overfit.py)
        anchors=dataclasses.replace(base.anchors, scales=(1.0, 2.0, 4.0)),
        model=dataclasses.replace(
            base.model, roi_op="align", compute_dtype="float32",
            num_classes=len(CAT_IDS) + 1,
        ),
        data=DataConfig(dataset="coco", root_dir=args.data_root,
                        image_size=size, max_boxes=8,
                        augment_hflip=args.augment_hflip),
        eval=EvalConfig(metric="coco"),
        train=TrainConfig(
            batch_size=args.batch, n_epoch=args.epochs, lr=args.lr,
            eval_every_epochs=args.eval_every,
            checkpoint_every_epochs=max(args.epochs // 2, 1), seed=0,
        ),
        mesh=MeshConfig(num_data=1),
    )

    train_ds = make_dataset(cfg.data, "train")
    assert len(train_ds) == args.images
    trainer = Trainer(cfg, workdir=args.workdir, dataset=train_ds)
    t0 = time.time()
    trainer.train(log_every=5)
    train_s = time.time() - t0

    variables = {
        "params": trainer.state.params,
        "batch_stats": trainer.state.batch_stats,
    }
    evaluator = Evaluator(cfg, trainer.model)
    train_res = evaluator.evaluate(
        variables, train_ds, batch_size=args.batch
    )
    val_res = evaluator.evaluate(
        variables, make_dataset(cfg.data, "val"), batch_size=args.batch
    )

    result = {
        "metric": "coco mAP@[.50:.95]",
        "train_coco_mAP": float(train_res["mAP"]),
        "train_AP50": float(train_res.get("AP50", float("nan"))),
        "val_coco_mAP": float(val_res["mAP"]),
        "val_AP50": float(val_res.get("AP50", float("nan"))),
        "val_images": args.val_images,
        "cli_train_on_coco_leg": cli_leg,
        "config": "coco-format resnet18@128 (num_classes=9, sparse cat "
                  "ids remapped)",
        "epochs": args.epochs,
        "images": args.images,
        "batch": args.batch,
        "lr": args.lr,
        "train_seconds": round(train_s, 1),
        "backend": __import__("jax").default_backend(),
        "augment_hflip": args.augment_hflip,
    }
    out = os.path.join(
        REPO, "benchmarks",
        "coco_overfit_result_aug.json" if args.augment_hflip
        else "coco_overfit_result.json",
    )
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
