"""Backward-pass cost attribution WITHOUT the profiler.

The r3 VERDICT asks for an op-level account of the dominant backward
slice (46-52 ms of the ~74 ms v5e b16 step vs a 7.5 ms conv-FLOP
floor). The intended tool — a tunnel-side ``jax.profiler`` trace —
blocked from its first RPC and wedged the remote service
(verify SKILL.md incident 2026-08-01 ~08:48Z), so this script derives
the same attribution from wall-times of jitted grad VARIANTS instead:

  trunk_train  forward trunk only, train-mode BN (batch-stats
               reductions computed) — paired with trunk_eval this is
               the BN-density A/B from layer_cost_table /
               STAGE_BREAKDOWN: eval-mode BN is a fusable affine, so
               the delta is the price of train-mode BN on the trunk
  trunk_eval   forward trunk only, eval-mode BN
  fwd        forward + 4 losses (no grad)
  grad_wall  value_and_grad with ``features_wall=True`` — gradients stop
             at the trunk/neck features, so the program runs the full
             forward but only the RPN/targets/head backward
  grad_imgs  grad w.r.t. the INPUT IMAGES with params closed over — the
             full dgrad (activation-gradient) chain through head and
             trunk, but no wgrads (no parameter gradients anywhere)
  grad_full  the real thing: value_and_grad w.r.t. all params, gradient
             norm consumed (identical structure to the train step's)

Attribution (differences of separately compiled programs; each is a
fusion-boundary estimate, same caveat as ``_stage_breakdown``):

  trunk backward  = grad_full - grad_wall   (trunk dgrad + trunk wgrad)
  head+rpn bwd    = grad_wall - fwd
  all wgrads      = grad_full - grad_imgs
  trunk wgrad     ~ (grad_full - grad_wall) - (grad_imgs - fwd_trunk_dgrad)
                    -- not separable without more programs; the three
                    rows above already say where the milliseconds live.

Run ON THE CHIP (six programs, each a fresh compile of a
resnet18-class program — the historically safe compile class; the two
trunk-only programs are small, the four loss/grad variants ~40 s each):

    python benchmarks/grad_breakdown.py [--config voc_resnet18]
                                        [--batch-size 16]

Writes ``benchmarks/grad_breakdown.json``. Refuses to run on a
non-TPU backend unless ``GRAD_BREAKDOWN_CPU=1`` (the CPU path exists
for the unit test, at tiny shapes only).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmarks/grad_breakdown.py` from anywhere
    sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "benchmarks", "grad_breakdown.json")


def build(config_name: str, batch_size: int, image_size=None):
    import dataclasses

    from replication_faster_rcnn_tpu.config import get_config
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.train import (
        create_train_state,
        make_optimizer,
    )

    cfg = get_config(config_name)
    cfg = cfg.replace(
        data=dataclasses.replace(
            cfg.data,
            dataset="synthetic",
            **({"image_size": tuple(image_size)} if image_size else {}),
        ),
        train=dataclasses.replace(cfg.train, batch_size=batch_size),
    )
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)
    ds = SyntheticDataset(cfg.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])
    device_batch = jax.tree_util.tree_map(jnp.asarray, batch)
    return model, cfg, state, device_batch


def timed(fn, *args, n=5):
    for _ in range(2):  # compile + stabilize
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / n * 1e3


def make_programs(model, cfg, state, batch):
    from replication_faster_rcnn_tpu.train.train_step import compute_losses

    rng = jax.random.fold_in(state.rng, state.step)

    def _trunk(train):
        @jax.jit
        def t(params, batch):
            v = {"params": params, "batch_stats": state.batch_stats}
            feat, _ = model.apply(
                v, batch["image"], train, method="extract_features",
                mutable=["batch_stats"],
            )
            feats = feat if isinstance(feat, (list, tuple)) else [feat]
            return sum(f.astype(jnp.float32).sum() for f in feats)

        return t

    @jax.jit
    def fwd(params, batch):
        total, _ = compute_losses(
            model, cfg, params, state.batch_stats, batch, rng, True
        )
        return total

    def _grad_of(wall):
        @jax.jit
        def g(params, batch):
            def loss_fn(p):
                return compute_losses(
                    model, cfg, p, state.batch_stats, batch, rng, True,
                    features_wall=wall,
                )

            (total, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params
            )
            # consume every gradient exactly as the train step does
            return total + optax.global_norm(grads)

        return g

    @jax.jit
    def grad_imgs(params, batch):
        def loss_fn(images):
            return compute_losses(
                model, cfg, params, state.batch_stats,
                dict(batch, image=images), rng, True,
            )

        (total, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
            batch["image"].astype(jnp.float32)
        )
        return total + jnp.sqrt((g.astype(jnp.float32) ** 2).sum())

    return fwd, _grad_of(True), _grad_of(False), grad_imgs, _trunk(True), _trunk(False)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="voc_resnet18")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-size", type=int, nargs=2, default=None)
    args = ap.parse_args()

    backend = jax.default_backend()
    if backend not in ("tpu",) and not os.environ.get("GRAD_BREAKDOWN_CPU"):
        raise SystemExit(
            f"backend is {backend!r}; this attribution is meaningful on the "
            "chip only (GRAD_BREAKDOWN_CPU=1 overrides for tiny-shape tests)"
        )

    model, cfg, state, batch = build(
        args.config, args.batch_size, args.image_size
    )
    fwd, grad_wall, grad_full, grad_imgs, trunk_train, trunk_eval = (
        make_programs(model, cfg, state, batch)
    )

    rows = {}
    # cheap-to-expensive, and bank each row as it lands: every new compile
    # through the tunnel is potentially the session's last. The trunk
    # train/eval A/B tests the BN-density hypothesis from
    # layer_cost_table (STAGE_BREAKDOWN.md): eval-mode BN is a fusable
    # affine; train-mode adds the batch-stats reductions
    for name, fn in (
        ("trunk_train_ms", trunk_train),
        ("trunk_eval_ms", trunk_eval),
        ("fwd_ms", fwd),
        ("grad_wall_ms", grad_wall),
        ("grad_imgs_ms", grad_imgs),
        ("grad_full_ms", grad_full),
    ):
        rows[name] = round(timed(fn, state.params, batch), 2)
        print(f"{name}: {rows[name]}", flush=True)
        _write(args, backend, rows)

    rows["attrib_trunk_backward_ms"] = round(
        rows["grad_full_ms"] - rows["grad_wall_ms"], 2
    )
    rows["attrib_rpn_head_backward_ms"] = round(
        rows["grad_wall_ms"] - rows["fwd_ms"], 2
    )
    rows["attrib_all_wgrads_ms"] = round(
        rows["grad_full_ms"] - rows["grad_imgs_ms"], 2
    )
    _write(args, backend, rows)
    print(json.dumps(rows))


def _write(args, backend, rows) -> None:
    with open(OUT, "w") as f:
        json.dump(
            {
                "config": args.config,
                "batch_size": args.batch_size,
                "image_size": args.image_size,
                "backend": backend,
                "rows": rows,
                "recorded_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                "note": (
                    "differences of separately jitted programs (fusion "
                    "boundaries differ; small negatives are noise floors). "
                    "grad_wall stops gradients at the trunk features "
                    "(compute_losses features_wall); grad_imgs "
                    "differentiates w.r.t. images with params closed over "
                    "(full dgrad chain, zero wgrads); trunk_train/"
                    "trunk_eval are the forward trunk with train-/eval-"
                    "mode BN — their delta prices the train-mode "
                    "batch-stats reductions (the BN-density hypothesis, "
                    "STAGE_BREAKDOWN.md)"
                ),
            },
            f,
            indent=1,
        )


if __name__ == "__main__":
    main()
