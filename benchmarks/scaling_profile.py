"""Scale-out profile: ZeRO-1 memory/collective/throughput gate, banked.

One command measures what the ZeRO-1 optimizer-state sharding
(`parallel/zero.py` + the sharded branch of `parallel/spmd.py`) actually
buys on a data mesh, and fails loudly when the win rots:

* **per-device optimizer-state bytes** — read from the placed arrays'
  ``addressable_shards`` (what the runtime committed to memory, not what
  a sharding annotation promised), for the replicated baseline and the
  ZeRO placement of the SAME train state. The gate: the ZeRO placement
  must hold at most ``1/N + slack`` of the replicated bytes per device,
  i.e. the (N−1)/N reduction the partitioning exists for.
* **collective inventory** — `analysis.fingerprint.parse_collectives`
  over both lowered step programs: the replicated step must be psum
  all_reduces only, the ZeRO step must add reduce_scatter (gradient
  exchange) and all_gather (param reassembly) and nothing else. The
  structural contract also lives in hlolint HX003; repeating it here
  keeps this harness self-contained for off-CI runs.
* **throughput** — images/sec through both compiled steps; the ZeRO
  number is checked against the committed record for the same
  (config, platform, n_dev) under ``benchmarks/records/`` exactly like
  benchmarks/step_profile.py checks the single-step profile:

      python benchmarks/scaling_profile.py            # check
      python benchmarks/scaling_profile.py --update   # re-bank

The memory and collective gates are structural and run on EVERY
invocation (bank or no bank); only the throughput comparison needs a
banked record. Cross-platform comparisons are skipped.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
SCHEMA = "scaling_profile/v1"
DEFAULT_TOL = 0.15

# per-device ZeRO opt-state bytes may exceed the ideal replicated/N by
# this relative slack (leaves with no dimension divisible by N stay
# replicated — scalars, odd-shaped biases) before the memory gate fails
OPT_BYTES_SLACK = 0.5

GATE_KEY = "images_per_sec_zero"


# ---------------------------------------------------------------------------
# pure record logic (no jax): unit-testable without placing anything


def record_key(config_token: str, platform: str, n_dev: int) -> str:
    """Identity of a banked record. The backend is always spmd (ZeRO-1
    only exists there); the device count is part of the identity because
    the sharding factor IS the measurement."""
    return f"{config_token}_{platform}_n{n_dev}"


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"scaling_profile_{key}.json")


def check_structural(record, slack: float = OPT_BYTES_SLACK):
    """The bank-free gates: memory reduction and collective inventory.

    Returns a list of human-readable failures (empty = pass)."""
    failures = []
    n = int(record.get("n_dev", 1))
    repl = float(record.get("opt_bytes_per_device_replicated", 0))
    zero = float(record.get("opt_bytes_per_device_zero", 0))
    if repl <= 0 or zero <= 0:
        failures.append("opt-state byte measurement missing or zero")
        return failures
    frac = zero / repl
    ceiling = (1.0 / n) * (1.0 + slack)
    if frac > ceiling:
        failures.append(
            f"per-device opt-state not sharded: ZeRO holds {frac:.1%} of "
            f"the replicated bytes (ceiling {ceiling:.1%} = 1/{n} "
            f"+ {slack:.0%} slack) — the (N-1)/N reduction is gone"
        )
    coll_zero = record.get("collectives_zero") or {}
    coll_repl = record.get("collectives_replicated") or {}
    required = {"all_reduce", "reduce_scatter", "all_gather"}
    missing = sorted(required - set(coll_zero))
    if missing:
        failures.append(
            f"ZeRO step is missing collective kinds {missing} — the "
            "reduce-scatter/all-gather pattern of parallel/spmd.py is gone"
        )
    extra = sorted(set(coll_zero) - required)
    if extra:
        failures.append(f"ZeRO step emits unexpected collective kinds {extra}")
    repl_extra = sorted(set(coll_repl) - {"all_reduce"})
    if repl_extra:
        failures.append(
            f"replicated step emits unexpected collective kinds {repl_extra}"
        )
    return failures


def check_regression(current, banked, tol: float = DEFAULT_TOL):
    """Throughput comparison against the banked record.

    Returns (failures, warnings)."""
    failures, warnings = [], []
    if banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, "
            f"expected {SCHEMA!r}; skipping comparison"
        )
        return failures, warnings
    for key in (GATE_KEY, "images_per_sec_replicated"):
        old = banked.get(key)
        new = current.get(key)
        if not old or not new:
            continue
        drop = 1.0 - new / old
        if drop > tol:
            failures.append(
                f"{key} regressed {drop:+.1%}: {new:.3f} vs banked "
                f"{old:.3f} (tolerance {tol:.0%})"
            )
        elif drop > tol / 2:
            warnings.append(
                f"{key} within tolerance but slipping {drop:+.1%}: "
                f"{new:.3f} vs banked {old:.3f}"
            )
    old_frac = banked.get("opt_bytes_frac")
    new_frac = current.get("opt_bytes_frac")
    if old_frac and new_frac and new_frac > old_frac * (1.0 + tol):
        failures.append(
            f"opt_bytes_frac grew: {new_frac:.4f} vs banked {old_frac:.4f} "
            "— the ZeRO placement is holding more than it used to"
        )
    return failures, warnings


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# measurement


def _per_device_bytes(tree) -> int:
    """Bytes the FIRST local device holds for a placed pytree — summed
    over leaves from ``addressable_shards`` (committed layout, including
    any replicated leaves the sharder left whole)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = [s for s in leaf.addressable_shards if s.index is not None]
        first = min(shards, key=lambda s: s.device.id)
        total += first.data.nbytes
    return total


def profile(cfg, config_token: str, n_steps: int = 5):
    """Measure one config's scale-out profile; returns the record dict.

    ``cfg`` must be an spmd-backend config; the ZeRO variant is derived
    by flipping ``train.shard_opt_state`` so both placements price the
    same model/optimizer."""
    import copy
    import dataclasses

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu import parallel
    from replication_faster_rcnn_tpu.analysis.fingerprint import (
        parse_collectives,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.parallel import zero as pzero
    from replication_faster_rcnn_tpu.parallel.spmd import (
        make_shard_map_train_step,
    )
    from replication_faster_rcnn_tpu.train.train_step import (
        create_train_state,
        make_optimizer,
    )

    cfg_zero = cfg.replace(
        train=dataclasses.replace(
            cfg.train, backend="spmd", shard_opt_state=True
        )
    )
    cfg_repl = cfg_zero.replace(
        train=dataclasses.replace(cfg_zero.train, shard_opt_state=False)
    )

    mesh = parallel.make_mesh(cfg.mesh)
    n_shards = mesh.shape["data"]
    tx, _ = make_optimizer(cfg_zero, steps_per_epoch=100)
    model, state = create_train_state(cfg_zero, jax.random.PRNGKey(0), tx)
    host_state = jax.device_get(state)

    shardings = pzero.train_state_shardings(state, mesh, cfg.mesh, True)
    # independent host copies: both placements get private buffers, so the
    # donating steps can't invalidate each other's state mid-measurement
    state_repl = parallel.replicate_tree(copy.deepcopy(host_state), mesh)
    state_zero = pzero.place_train_state(copy.deepcopy(host_state), shardings)

    opt_repl = _per_device_bytes(state_repl.opt_state)
    opt_zero = _per_device_bytes(state_zero.opt_state)

    step_repl, _ = make_shard_map_train_step(cfg_repl, tx, mesh)
    step_zero, _ = make_shard_map_train_step(
        cfg_zero, tx, mesh, state_template=state
    )

    batch_size = cfg.train.batch_size
    ds = SyntheticDataset(cfg.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])

    def staged():
        return parallel.shard_batch(
            {k: np.array(v) for k, v in batch.items()}, mesh, cfg.mesh
        )

    coll = {}
    for name, step, st in (
        ("replicated", step_repl, state_repl),
        ("zero", step_zero, state_zero),
    ):
        text = step.lower(st, staged()).as_text()
        coll[name] = parse_collectives(text)

    def timed(step, st):
        # donation consumes the placed state every dispatch; threading the
        # returned state through mirrors the trainer's loop
        st, metrics = step(st, staged())  # compile + stabilize
        jax.device_get(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(n_steps):
            st, metrics = step(st, staged())
        jax.device_get(metrics["loss"])
        wall = time.perf_counter() - t0
        return st, batch_size * n_steps / wall, wall / n_steps * 1e3

    state_repl, ips_repl, ms_repl = timed(step_repl, state_repl)
    state_zero, ips_zero, ms_zero = timed(step_zero, state_zero)

    dev = jax.devices()[0]
    return {
        "schema": SCHEMA,
        "config": config_token,
        "backend": "spmd",
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "n_dev": jax.device_count(),
        "n_shards": int(n_shards),
        "batch_size": batch_size,
        "image_size": list(cfg.data.image_size),
        "n_steps_timed": n_steps,
        "opt_bytes_per_device_replicated": int(opt_repl),
        "opt_bytes_per_device_zero": int(opt_zero),
        "opt_bytes_frac": round(opt_zero / opt_repl, 6) if opt_repl else None,
        "opt_bytes_ideal_frac": round(1.0 / n_shards, 6),
        "collectives_replicated": coll["replicated"],
        "collectives_zero": coll["zero"],
        "step_ms_replicated": round(ms_repl, 3),
        "step_ms_zero": round(ms_zero, 3),
        "images_per_sec_replicated": round(ips_repl, 3),
        "images_per_sec_zero": round(ips_zero, 3),
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


# ---------------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument(
        "--devices",
        type=int,
        default=8,
        help="host-platform device count to force when jax is not yet "
        "imported and no accelerator is attached (CPU CI)",
    )
    p.add_argument("--steps", type=int, default=5, help="timed dispatches")
    p.add_argument(
        "--update", action="store_true", help="write/overwrite the banked record"
    )
    p.add_argument(
        "--no-check", action="store_true", help="measure + print only"
    )
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--slack", type=float, default=OPT_BYTES_SLACK)
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    if args.devices > 1 and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={args.devices}"
            ).strip()

    from benchmarks.step_profile import tiny_config

    cfg = tiny_config(
        batch_size=args.batch_size, image_size=args.image_size, backend="spmd"
    )
    import dataclasses

    from replication_faster_rcnn_tpu.config import MeshConfig

    cfg = cfg.replace(mesh=MeshConfig(num_data=args.devices))
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, grad_allreduce_dtype="bfloat16")
    )
    token = f"tiny{args.image_size}b{args.batch_size}"

    record = profile(cfg, token, n_steps=args.steps)
    key = record_key(token, record["platform"], record["n_dev"])
    path = record_path(key, args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    structural = check_structural(record, slack=args.slack)
    for f in structural:
        print(f"scaling_profile: FAIL {f}", file=sys.stderr)
    if structural:
        return 1

    if args.update:
        save_record(record, path)
        print(f"scaling_profile: banked {path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    if not os.path.exists(path):
        print(
            f"scaling_profile: no banked record at {path} — run with "
            "--update to create one (not checking)",
            file=sys.stderr,
        )
        return 0
    failures, warnings = check_regression(record, load_record(path), tol=args.tol)
    for w in warnings:
        print(f"scaling_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"scaling_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"scaling_profile: REGRESSION vs {path} — if intentional, "
            "re-bank with --update",
            file=sys.stderr,
        )
        return 1
    print(f"scaling_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
