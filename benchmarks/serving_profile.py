"""Serving load-generator benchmark + regression gate.

Prices the serving engine's amortization claim: continuous micro-batched
serving (serving/engine.py) vs the sequential one-image-per-dispatch
loop that `predict_image` used to be, at the SAME bucket shape, on the
same host. Batching wins by splitting the per-dispatch fixed cost
(Python dispatch, program launch, device_put/get, host assembly) across
the flush — which is exactly the regime of the tiny CI shape on a
single-core CPU host, where fixed cost dominates per-image compute.

Measured legs (serving/loadgen.py):
  * sequential — Evaluator.predict_batch, batch 1, one dispatch per
    image: the baseline `predict_image` pays.
  * engine closed-loop per compiled batch size — saturation capacity and
    latency (p50/p99) with flushes at full bucket batch.
  * engine open-loop at ~70% of measured capacity — the latency a user
    sees at a sane traffic level, queueing included.

Banked under benchmarks/records/ (step_profile.py conventions: atomic
save, --update to re-bank, --no-check to just measure). The gate fails
(exit 1) when engine capacity regresses >tol vs the banked record or
when the batched/sequential speedup falls below --min-speedup (default
2.0, the PR-7 acceptance floor).

--quant runs the ISSUE-17 quantized leg instead: bf16 residency vs int8
residency (PTQ sidecar calibrated in-process), gated on a
**memory-budget-matched** capacity comparison. Framing, in full: the
deployment budget M is fixed at what the bf16 leg needs for its
smallest compiled batch (bf16 resident params + that batch's
activations + input); each mode then serves at the LARGEST ladder batch
whose (resident params + activations + input) fits M. Quantization
shrinks residency ~2x vs bf16 (~4x vs f32), and the freed bytes buy
batch — which is where the throughput comes from: per-op, XLA:CPU's
int8/bf16 lowerings are no faster than f32 (the same-batch capacity
ratio is banked alongside as `matched_batch.speedup`, informational,
~1x on this host). The gated number is each mode's **deployment
capacity**: the compiled bucket program's steady-state images/sec at
that mode's budget batch, interleaved best-of-N direct dispatch. The
engine closed loop is banked alongside (informational): its per-request
Python path costs the same in every mode and, on a 1-core host, that
mode-independent overhead compresses the batch-amortization signal the
budget framing prices. The gate is ``int8 capacity @ its budget batch
>= --min-quant-speedup x bf16 capacity @ its budget batch`` (default
1.5, the ISSUE-17 acceptance floor), same bucket, same host.
Activation bytes come from the compiled program's ``memory_analysis()``
(temp + output; XLA:CPU reports temp as 0) plus the explicit f32
image-input bytes.

Usage:
  python benchmarks/serving_profile.py            # measure + gate
  python benchmarks/serving_profile.py --update   # re-bank
  python benchmarks/serving_profile.py --quant    # quantized leg gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
SCHEMA = "serving_profile/v1"
DEFAULT_TOL = 0.15
DEFAULT_MIN_SPEEDUP = 2.0
# the gate: engine capacity at the largest compiled batch
GATE_KEY = "engine_images_per_sec"

# --quant leg (ISSUE 17): int8-vs-bf16 under a matched memory budget
QUANT_SCHEMA = "serving_profile_quant/v1"
DEFAULT_MIN_QUANT_SPEEDUP = 1.5
QUANT_GATE_KEY = "int8_images_per_sec"
# compiled-batch ladder the budget search walks (capped at --max-batch)
BATCH_LADDER = (1, 2, 4, 8, 16, 32)


def record_key(config_token: str, platform: str) -> str:
    return f"{config_token}_{platform}"


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"serving_profile_{key}.json")


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def check_regression(
    current,
    banked,
    tol: float = DEFAULT_TOL,
    min_speedup: float = DEFAULT_MIN_SPEEDUP,
):
    """(failures, warnings) — pure, unit-testable. Failures: engine
    capacity >tol below the banked record, or the measured batched-vs-
    sequential speedup below the acceptance floor."""
    failures, warnings = [], []
    if banked is not None and banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, expected "
            f"{SCHEMA!r}; skipping comparison"
        )
        banked = None
    if banked is not None:
        old = banked.get(GATE_KEY)
        new = current.get(GATE_KEY)
        if old and new:
            drop = 1.0 - new / old
            if drop > tol:
                failures.append(
                    f"{GATE_KEY} regressed {drop:+.1%}: {new:.3f} vs banked "
                    f"{old:.3f} (tolerance {tol:.0%})"
                )
            elif drop > tol / 2:
                warnings.append(
                    f"{GATE_KEY} within tolerance but slipping {drop:+.1%}: "
                    f"{new:.3f} vs banked {old:.3f}"
                )
        old_p99 = (banked.get("engine") or {}).get("p99_ms")
        new_p99 = (current.get("engine") or {}).get("p99_ms")
        if old_p99 and new_p99:
            growth = new_p99 / old_p99 - 1.0
            if growth > 4 * tol:  # latency tails are noisy; warn only
                warnings.append(
                    f"engine p99 latency grew {growth:+.1%}: {new_p99:.1f} ms "
                    f"vs banked {old_p99:.1f} ms"
                )
    speedup = current.get("speedup")
    if speedup is not None and speedup < min_speedup:
        failures.append(
            f"batched/sequential speedup {speedup:.2f}x below the "
            f"{min_speedup:.1f}x acceptance floor (engine "
            f"{current.get(GATE_KEY)} img/s vs sequential "
            f"{current.get('sequential_images_per_sec')} img/s)"
        )
    return failures, warnings


def check_quant_regression(
    current,
    banked,
    tol: float = DEFAULT_TOL,
    min_quant_speedup: float = DEFAULT_MIN_QUANT_SPEEDUP,
):
    """(failures, warnings) for the --quant leg — pure, unit-testable.

    Failures: the budget-matched int8/bf16 capacity ratio below the
    acceptance floor, or that ratio >tol below the banked one. The
    regression gate runs on the RATIO, not the absolute capacities: the
    legs are interleaved, so host-speed drift (which swings absolute
    img/s by >20% run to run on a shared 1-core box) cancels out of it;
    absolute capacity drops only warn. The matched-batch (same-batch)
    ratio is informational — on hosts whose int8 contractions are no
    faster than f32 (XLA:CPU) it sits near 1x by design and is never
    gated.
    """
    failures, warnings = [], []
    if banked is not None and banked.get("schema") != QUANT_SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, expected "
            f"{QUANT_SCHEMA!r}; skipping comparison"
        )
        banked = None
    if banked is not None:
        old = banked.get("quant_speedup")
        new = current.get("quant_speedup")
        if old and new:
            drop = 1.0 - new / old
            if drop > tol:
                failures.append(
                    f"quant_speedup regressed {drop:+.1%}: {new:.3f}x vs "
                    f"banked {old:.3f}x (tolerance {tol:.0%})"
                )
            elif drop > tol / 2:
                warnings.append(
                    f"quant_speedup within tolerance but slipping "
                    f"{drop:+.1%}: {new:.3f}x vs banked {old:.3f}x"
                )
        old_cap = banked.get(QUANT_GATE_KEY)
        new_cap = current.get(QUANT_GATE_KEY)
        if old_cap and new_cap and new_cap < (1.0 - 2 * tol) * old_cap:
            warnings.append(
                f"{QUANT_GATE_KEY} {new_cap:.3f} img/s is "
                f"{1.0 - new_cap / old_cap:.0%} below the banked "
                f"{old_cap:.3f} (host drift or a real slowdown — "
                "absolute capacity is not gated)"
            )
    speedup = current.get("quant_speedup")
    if speedup is None:
        failures.append("record has no quant_speedup measurement")
    elif speedup < min_quant_speedup:
        failures.append(
            f"budget-matched int8/bf16 capacity ratio {speedup:.2f}x below "
            f"the {min_quant_speedup:.1f}x acceptance floor (int8 "
            f"{current.get(QUANT_GATE_KEY)} img/s @ batch "
            f"{current.get('int8_budget_batch')} vs bf16 "
            f"{current.get('bf16_images_per_sec')} img/s @ batch "
            f"{current.get('bf16_budget_batch')})"
        )
    return failures, warnings


# ---------------------------------------------------------------------------
# measurement


def serving_config(
    image_size: int = 16,
    max_batch: int = 32,
    batch_sizes=None,
    params_dtype: str = "float32",
):
    """Trimmed-budget serving config: synthetic resnet18 with ONE serving
    bucket at ``image_size`` and compiled batches (1, max_batch), so the
    sequential and batched legs run the identical per-image math and the
    comparison isolates dispatch amortization.

    The defaults put the per-image forward in the overhead-bound regime
    where micro-batching pays on a CPU host: at 16x16 the convs and the
    per-ROI tail are dominated by per-op fixed cost, not FLOPs, so a
    batch-32 flush amortizes it ~2.6x (measured raw on a 1-core CPU:
    16.5 ms/img at batch 1 vs 6.4 at batch 32). At 32x32 with the
    default NMS budgets the ResNet tail over 16 ROIs is compute-bound at
    ~60 ms/image and batching is a wash (~1.1x) — use
    --image-size/--max-batch to measure that regime explicitly."""
    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        EvalConfig,
        FasterRCNNConfig,
        MeshConfig,
        ModelConfig,
        ProposalConfig,
        ROITargetConfig,
        ServingConfig,
        TrainConfig,
    )

    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic",
            image_size=(image_size, image_size),
            max_boxes=8,
        ),
        train=TrainConfig(batch_size=1, n_epoch=1),
        mesh=MeshConfig(num_data=1),
        proposals=ProposalConfig(
            pre_nms_train=128,
            post_nms_train=32,
            pre_nms_test=16,
            post_nms_test=2,
        ),
        roi_targets=ROITargetConfig(n_sample=8),
        eval=EvalConfig(max_detections=2),
        serving=ServingConfig(
            resolutions=((image_size, image_size),),
            batch_sizes=tuple(batch_sizes) if batch_sizes else (1, max_batch),
            # deadline >= a full flush's drain time: on a 1-core host the
            # producer thread refills the queue while the worker computes,
            # and a short deadline would cut partial flushes whose
            # pad-to-bucket slots burn throughput
            max_delay_ms=50.0,
            queue_depth=64,
            params_dtype=params_dtype,
        ),
    )


def profile(cfg, config_token: str, n_requests: int = 64):
    import time

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu.eval.evaluator import Evaluator
    from replication_faster_rcnn_tpu.models.faster_rcnn import init_variables
    from replication_faster_rcnn_tpu.serving import loadgen
    from replication_faster_rcnn_tpu.serving.engine import InferenceEngine

    h, w = cfg.serving.bucket_resolutions(cfg.data.image_size)[0]
    rng = np.random.RandomState(0)
    # preprocessed float32 images at the bucket shape: both legs skip the
    # host resize so the comparison is pure dispatch-path
    images = [
        rng.rand(h, w, 3).astype(np.float32) * 2.0 - 1.0 for _ in range(8)
    ]
    model, variables = init_variables(cfg, jax.random.PRNGKey(0))

    # -- sequential baseline: one dispatch per image, batch 1 — what the
    # old predict_image loop paid per call, minus file I/O
    def sequential_rep():
        lat = []
        t0 = time.monotonic()
        for i in range(n_requests):
            t1 = time.monotonic()
            ev.predict_batch(variables, images[i % len(images)][None])
            lat.append(time.monotonic() - t1)
        wall = time.monotonic() - t0
        return {
            "n_requests": n_requests,
            "wall_s": round(wall, 4),
            "images_per_sec": round(n_requests / wall, 3),
            "p50_ms": round(loadgen.percentile_ms(lat, 50), 3),
            "p99_ms": round(loadgen.percentile_ms(lat, 99), 3),
        }

    ev = Evaluator(cfg, model)
    ev.predict_batch(variables, images[0][None])  # compile outside timing

    engine = InferenceEngine(cfg, model, variables, warmup=True)
    try:
        loadgen.run_closed_loop(engine, images, 8)  # warm the queue path
        # Interleave the legs and keep each leg's fastest rep: host speed
        # on a shared single-core box drifts on a seconds scale, and
        # measuring the legs back-to-back would fold that drift into the
        # speedup ratio. Alternating reps samples both legs across the
        # same conditions; best-of-N is the standard throughput anti-noise
        # idiom.
        seq_reps, closed_reps = [], []
        for _ in range(3):
            seq_reps.append(sequential_rep())
            closed_reps.append(
                loadgen.run_closed_loop(engine, images, n_requests)
            )
        sequential = max(seq_reps, key=lambda r: r["images_per_sec"])
        closed = max(closed_reps, key=lambda r: r["images_per_sec"])
        offered = max(1.0, 0.7 * closed["images_per_sec"])
        open_loop = loadgen.run_open_loop(
            engine, images, offered_rate=offered, n_requests=n_requests
        )
        flush_sizes = [n for _, n in engine._batcher.flush_log]
        per_batch = {
            str(bn): flush_sizes.count(bn) for bn in engine.batch_sizes
        }
        stats = dict(engine.stats)
        compile_seconds = dict(engine.compile_seconds)
    finally:
        engine.close()

    speedup = (
        round(closed["images_per_sec"] / sequential["images_per_sec"], 3)
        if sequential["images_per_sec"]
        else None
    )
    return {
        "schema": SCHEMA,
        "config": config_token,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "bucket": [h, w],
        "batch_sizes": list(engine.batch_sizes),
        "max_delay_ms": cfg.serving.max_delay_ms,
        "sequential": sequential,
        "sequential_images_per_sec": sequential["images_per_sec"],
        "engine": closed,
        GATE_KEY: closed["images_per_sec"],
        "engine_open_loop": open_loop,
        "flushes_by_size": per_batch,
        "engine_stats": stats,
        "compile_seconds": compile_seconds,
        "speedup": speedup,
        "measured": True,
    }


# ---------------------------------------------------------------------------
# --quant: int8 vs bf16 under a matched memory budget (ISSUE 17)


def activation_bytes(engine, h: int, w: int, n: int) -> int:
    """Per-dispatch working bytes of one bucket program at batch ``n``:
    the compiled program's temp + output allocations (memory_analysis;
    XLA:CPU reports temp as 0) plus the f32 NHWC image input. The
    resident variables argument is deliberately excluded — residency is
    priced separately as ``engine.params_bytes``."""
    ma = engine._program(engine._serve_name(h, w, n)).memory_analysis()
    return int(
        ma.temp_size_in_bytes + ma.output_size_in_bytes + n * h * w * 3 * 4
    )


def budget_batch(ladder, params_bytes: int, act_by_batch, budget: int) -> int:
    """Largest ladder batch whose residency + working set fits the
    budget (the smallest ladder batch when none does). Pure — tests
    drive it with synthetic tables."""
    fit = [b for b in ladder if params_bytes + act_by_batch[b] <= budget]
    return max(fit) if fit else min(ladder)


def program_capacity(engine, h: int, w: int, n: int, images, reps: int = 5):
    """Steady-state capacity of one compiled bucket program at batch
    ``n``: best-of-``reps`` direct dispatch (device-resident input,
    block on the output), no queue in the loop. This is the number a
    deployment's flush worker can sustain when the submit path runs
    elsewhere — the gated quantity of the --quant leg."""
    import time

    import jax
    import numpy as np

    prog = engine._program(engine._serve_name(h, w, n))
    batch = jax.device_put(
        np.stack([images[i % len(images)] for i in range(n)])
    )
    block = lambda out: jax.tree_util.tree_map(  # noqa: E731
        lambda x: x.block_until_ready(), out
    )
    block(prog(engine._variables, batch))  # ensure compiled + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        block(prog(engine._variables, batch))
        best = min(best, time.perf_counter() - t0)
    return {
        "batch": n,
        "ms_per_flush": round(best * 1000, 3),
        "images_per_sec": round(n / best, 3),
    }


def profile_quant(
    image_size: int, max_batch: int, config_token: str, n_requests: int = 64
):
    import shutil
    import tempfile

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu import quant
    from replication_faster_rcnn_tpu.models.faster_rcnn import init_variables
    from replication_faster_rcnn_tpu.serving import loadgen
    from replication_faster_rcnn_tpu.serving.engine import InferenceEngine

    ladder = tuple(b for b in BATCH_LADDER if b <= max_batch)
    cfgs = {
        mode: serving_config(
            image_size, max_batch, batch_sizes=ladder, params_dtype=mode
        )
        for mode in ("bfloat16", "int8")
    }
    h, w = cfgs["bfloat16"].serving.bucket_resolutions(
        cfgs["bfloat16"].data.image_size
    )[0]
    rng = np.random.RandomState(0)
    images = [
        rng.rand(h, w, 3).astype(np.float32) * 2.0 - 1.0 for _ in range(8)
    ]
    # one checkpoint feeds both legs (PRNGKey(0)) so the comparison is
    # residency-dtype only
    model, variables = init_variables(cfgs["bfloat16"], jax.random.PRNGKey(0))
    f32_params_bytes = int(
        sum(x.nbytes for x in jax.tree_util.tree_leaves(variables))
    )

    # the int8 leg's sidecar, calibrated in-process on the synthetic
    # distribution the legs serve (the `frcnn quantize` path end to end)
    tmpdir = tempfile.mkdtemp(prefix="serving_profile_quant_")
    engines = {}
    try:
        artifact = quant.calibrate(
            model,
            variables,
            quant.synthetic_calibration_batches(
                cfgs["int8"], batches=4, batch_size=2
            ),
            cfgs["int8"],
        )
        artifact_path = quant.save_artifact(
            os.path.join(tmpdir, "quant_artifact.json"), artifact
        )

        def make_engine(mode, batch_sizes):
            cfg = serving_config(
                image_size, max_batch, batch_sizes=batch_sizes,
                params_dtype=mode,
            )
            return InferenceEngine(
                cfg, model, variables,
                artifact_path=artifact_path if mode == "int8" else None,
            )

        engines = {mode: make_engine(mode, ladder) for mode in cfgs}

        # -- the budget: what the bf16 leg needs at its smallest batch
        act = {
            mode: {b: activation_bytes(eng, h, w, b) for b in ladder}
            for mode, eng in engines.items()
        }
        params_bytes = {m: engines[m].params_bytes for m in engines}
        budget = params_bytes["bfloat16"] + act["bfloat16"][min(ladder)]
        bb = {
            mode: budget_batch(ladder, params_bytes[mode], act[mode], budget)
            for mode in engines
        }

        # -- the gated capacities (each mode's program at ITS budget
        # batch) and the informational matched-batch capacities (both
        # modes at the full ladder batch), interleaved across modes so
        # host-speed drift lands on both legs alike; keep each cell's
        # best round
        cap, matched_cap = {}, {}
        for _ in range(2):
            for mode, eng in engines.items():
                for store, b in ((cap, bb[mode]), (matched_cap, ladder[-1])):
                    c = program_capacity(eng, h, w, b, images)
                    prev = store.get(mode)
                    if prev is None or c["images_per_sec"] > prev[
                        "images_per_sec"
                    ]:
                        store[mode] = c

        # -- informational: the engine closed loop at each mode's budget
        # batch — the full per-request path (queue, futures, metrics),
        # which costs the same in every mode and is never gated. A mode
        # whose budget batch is the full ladder reuses its probe engine;
        # a capped mode gets a fresh engine whose batcher flushes at the
        # budget batch.
        engine_loop = {}
        for mode in ("bfloat16", "int8"):
            if bb[mode] == ladder[-1]:
                eng = engines[mode]
            else:
                capped = tuple(b for b in ladder if b <= bb[mode])
                # keyed into `engines` so the finally-close sweep owns it
                engines[f"{mode}@b{bb[mode]}"] = eng = make_engine(
                    mode, capped
                )
            loadgen.run_closed_loop(eng, images, 8)  # warm the queue path
            engine_loop[mode] = loadgen.run_closed_loop(
                eng, images, n_requests
            )
    finally:
        for eng in engines.values():
            eng.close()
        shutil.rmtree(tmpdir, ignore_errors=True)

    bf16_ips = cap["bfloat16"]["images_per_sec"]
    int8_ips = cap["int8"]["images_per_sec"]
    matched_bf16 = matched_cap["bfloat16"]["images_per_sec"]
    matched_int8 = matched_cap["int8"]["images_per_sec"]
    return {
        "schema": QUANT_SCHEMA,
        "config": config_token,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "bucket": [h, w],
        "batch_ladder": list(ladder),
        "params_bytes": {
            "float32": f32_params_bytes,
            "bfloat16": params_bytes["bfloat16"],
            "int8": params_bytes["int8"],
        },
        "residency_ratio_vs_bf16": round(
            params_bytes["bfloat16"] / params_bytes["int8"], 3
        ),
        "residency_ratio_vs_f32": round(
            f32_params_bytes / params_bytes["int8"], 3
        ),
        "activation_bytes": {
            m: {str(b): act[m][b] for b in ladder} for m in act
        },
        "memory_budget_bytes": budget,
        "bf16_budget_batch": bb["bfloat16"],
        "int8_budget_batch": bb["int8"],
        "bf16": cap["bfloat16"],
        "int8": cap["int8"],
        "bf16_images_per_sec": bf16_ips,
        QUANT_GATE_KEY: int8_ips,
        "quant_speedup": (
            round(int8_ips / bf16_ips, 3) if bf16_ips else None
        ),
        "matched_batch": {
            "batch": ladder[-1],
            "bf16_images_per_sec": matched_bf16,
            "int8_images_per_sec": matched_int8,
            "speedup": (
                round(matched_int8 / matched_bf16, 3) if matched_bf16 else None
            ),
        },
        "engine_closed_loop": engine_loop,
        "plan": dict(artifact["plan"]),
        "measured": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--requests", type=int, default=96)
    p.add_argument("--update", action="store_true",
                   help="write/overwrite the banked record")
    p.add_argument("--no-check", action="store_true",
                   help="measure + print only")
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--min-speedup", type=float, default=DEFAULT_MIN_SPEEDUP,
                   help="fail when batched/sequential speedup is below "
                        "this floor (PR acceptance: 2.0)")
    p.add_argument("--quant", action="store_true",
                   help="run the quantized leg instead: int8 vs bf16 "
                        "residency under a matched memory budget")
    p.add_argument("--min-quant-speedup", type=float,
                   default=DEFAULT_MIN_QUANT_SPEEDUP,
                   help="with --quant: fail when the budget-matched "
                        "int8/bf16 speedup is below this floor "
                        "(ISSUE-17 acceptance: 1.5)")
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    if args.quant:
        token = f"quant{args.image_size}b{args.max_batch}"
        record = profile_quant(
            args.image_size, args.max_batch, token, n_requests=args.requests
        )
    else:
        cfg = serving_config(args.image_size, args.max_batch)
        token = f"tiny{args.image_size}b{args.max_batch}"
        record = profile(cfg, token, n_requests=args.requests)
    path = record_path(record_key(token, record["platform"]), args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    if args.update:
        save_record(record, path)
        print(f"serving_profile: banked {path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    banked = load_record(path) if os.path.exists(path) else None
    if banked is None:
        print(
            f"serving_profile: no banked record at {path} — run with "
            "--update to create one (still enforcing the speedup floor)",
            file=sys.stderr,
        )
    if args.quant:
        failures, warnings = check_quant_regression(
            record, banked, tol=args.tol,
            min_quant_speedup=args.min_quant_speedup,
        )
    else:
        failures, warnings = check_regression(
            record, banked, tol=args.tol, min_speedup=args.min_speedup
        )
    for w in warnings:
        print(f"serving_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"serving_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"serving_profile: REGRESSION vs {path} — if intentional, "
            "re-bank with --update",
            file=sys.stderr,
        )
        return 1
    print(f"serving_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
