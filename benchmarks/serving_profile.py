"""Serving load-generator benchmark + regression gate.

Prices the serving engine's amortization claim: continuous micro-batched
serving (serving/engine.py) vs the sequential one-image-per-dispatch
loop that `predict_image` used to be, at the SAME bucket shape, on the
same host. Batching wins by splitting the per-dispatch fixed cost
(Python dispatch, program launch, device_put/get, host assembly) across
the flush — which is exactly the regime of the tiny CI shape on a
single-core CPU host, where fixed cost dominates per-image compute.

Measured legs (serving/loadgen.py):
  * sequential — Evaluator.predict_batch, batch 1, one dispatch per
    image: the baseline `predict_image` pays.
  * engine closed-loop per compiled batch size — saturation capacity and
    latency (p50/p99) with flushes at full bucket batch.
  * engine open-loop at ~70% of measured capacity — the latency a user
    sees at a sane traffic level, queueing included.

Banked under benchmarks/records/ (step_profile.py conventions: atomic
save, --update to re-bank, --no-check to just measure). The gate fails
(exit 1) when engine capacity regresses >tol vs the banked record or
when the batched/sequential speedup falls below --min-speedup (default
2.0, the PR-7 acceptance floor).

Usage:
  python benchmarks/serving_profile.py            # measure + gate
  python benchmarks/serving_profile.py --update   # re-bank
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
SCHEMA = "serving_profile/v1"
DEFAULT_TOL = 0.15
DEFAULT_MIN_SPEEDUP = 2.0
# the gate: engine capacity at the largest compiled batch
GATE_KEY = "engine_images_per_sec"


def record_key(config_token: str, platform: str) -> str:
    return f"{config_token}_{platform}"


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"serving_profile_{key}.json")


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def check_regression(
    current,
    banked,
    tol: float = DEFAULT_TOL,
    min_speedup: float = DEFAULT_MIN_SPEEDUP,
):
    """(failures, warnings) — pure, unit-testable. Failures: engine
    capacity >tol below the banked record, or the measured batched-vs-
    sequential speedup below the acceptance floor."""
    failures, warnings = [], []
    if banked is not None and banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, expected "
            f"{SCHEMA!r}; skipping comparison"
        )
        banked = None
    if banked is not None:
        old = banked.get(GATE_KEY)
        new = current.get(GATE_KEY)
        if old and new:
            drop = 1.0 - new / old
            if drop > tol:
                failures.append(
                    f"{GATE_KEY} regressed {drop:+.1%}: {new:.3f} vs banked "
                    f"{old:.3f} (tolerance {tol:.0%})"
                )
            elif drop > tol / 2:
                warnings.append(
                    f"{GATE_KEY} within tolerance but slipping {drop:+.1%}: "
                    f"{new:.3f} vs banked {old:.3f}"
                )
        old_p99 = (banked.get("engine") or {}).get("p99_ms")
        new_p99 = (current.get("engine") or {}).get("p99_ms")
        if old_p99 and new_p99:
            growth = new_p99 / old_p99 - 1.0
            if growth > 4 * tol:  # latency tails are noisy; warn only
                warnings.append(
                    f"engine p99 latency grew {growth:+.1%}: {new_p99:.1f} ms "
                    f"vs banked {old_p99:.1f} ms"
                )
    speedup = current.get("speedup")
    if speedup is not None and speedup < min_speedup:
        failures.append(
            f"batched/sequential speedup {speedup:.2f}x below the "
            f"{min_speedup:.1f}x acceptance floor (engine "
            f"{current.get(GATE_KEY)} img/s vs sequential "
            f"{current.get('sequential_images_per_sec')} img/s)"
        )
    return failures, warnings


# ---------------------------------------------------------------------------
# measurement


def serving_config(image_size: int = 16, max_batch: int = 32):
    """Trimmed-budget serving config: synthetic resnet18 with ONE serving
    bucket at ``image_size`` and compiled batches (1, max_batch), so the
    sequential and batched legs run the identical per-image math and the
    comparison isolates dispatch amortization.

    The defaults put the per-image forward in the overhead-bound regime
    where micro-batching pays on a CPU host: at 16x16 the convs and the
    per-ROI tail are dominated by per-op fixed cost, not FLOPs, so a
    batch-32 flush amortizes it ~2.6x (measured raw on a 1-core CPU:
    16.5 ms/img at batch 1 vs 6.4 at batch 32). At 32x32 with the
    default NMS budgets the ResNet tail over 16 ROIs is compute-bound at
    ~60 ms/image and batching is a wash (~1.1x) — use
    --image-size/--max-batch to measure that regime explicitly."""
    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        EvalConfig,
        FasterRCNNConfig,
        MeshConfig,
        ModelConfig,
        ProposalConfig,
        ROITargetConfig,
        ServingConfig,
        TrainConfig,
    )

    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic",
            image_size=(image_size, image_size),
            max_boxes=8,
        ),
        train=TrainConfig(batch_size=1, n_epoch=1),
        mesh=MeshConfig(num_data=1),
        proposals=ProposalConfig(
            pre_nms_train=128,
            post_nms_train=32,
            pre_nms_test=16,
            post_nms_test=2,
        ),
        roi_targets=ROITargetConfig(n_sample=8),
        eval=EvalConfig(max_detections=2),
        serving=ServingConfig(
            resolutions=((image_size, image_size),),
            batch_sizes=(1, max_batch),
            # deadline >= a full flush's drain time: on a 1-core host the
            # producer thread refills the queue while the worker computes,
            # and a short deadline would cut partial flushes whose
            # pad-to-bucket slots burn throughput
            max_delay_ms=50.0,
            queue_depth=64,
            params_dtype="float32",
        ),
    )


def profile(cfg, config_token: str, n_requests: int = 64):
    import time

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu.eval.evaluator import Evaluator
    from replication_faster_rcnn_tpu.models.faster_rcnn import init_variables
    from replication_faster_rcnn_tpu.serving import loadgen
    from replication_faster_rcnn_tpu.serving.engine import InferenceEngine

    h, w = cfg.serving.bucket_resolutions(cfg.data.image_size)[0]
    rng = np.random.RandomState(0)
    # preprocessed float32 images at the bucket shape: both legs skip the
    # host resize so the comparison is pure dispatch-path
    images = [
        rng.rand(h, w, 3).astype(np.float32) * 2.0 - 1.0 for _ in range(8)
    ]
    model, variables = init_variables(cfg, jax.random.PRNGKey(0))

    # -- sequential baseline: one dispatch per image, batch 1 — what the
    # old predict_image loop paid per call, minus file I/O
    def sequential_rep():
        lat = []
        t0 = time.monotonic()
        for i in range(n_requests):
            t1 = time.monotonic()
            ev.predict_batch(variables, images[i % len(images)][None])
            lat.append(time.monotonic() - t1)
        wall = time.monotonic() - t0
        return {
            "n_requests": n_requests,
            "wall_s": round(wall, 4),
            "images_per_sec": round(n_requests / wall, 3),
            "p50_ms": round(loadgen.percentile_ms(lat, 50), 3),
            "p99_ms": round(loadgen.percentile_ms(lat, 99), 3),
        }

    ev = Evaluator(cfg, model)
    ev.predict_batch(variables, images[0][None])  # compile outside timing

    engine = InferenceEngine(cfg, model, variables, warmup=True)
    try:
        loadgen.run_closed_loop(engine, images, 8)  # warm the queue path
        # Interleave the legs and keep each leg's fastest rep: host speed
        # on a shared single-core box drifts on a seconds scale, and
        # measuring the legs back-to-back would fold that drift into the
        # speedup ratio. Alternating reps samples both legs across the
        # same conditions; best-of-N is the standard throughput anti-noise
        # idiom.
        seq_reps, closed_reps = [], []
        for _ in range(3):
            seq_reps.append(sequential_rep())
            closed_reps.append(
                loadgen.run_closed_loop(engine, images, n_requests)
            )
        sequential = max(seq_reps, key=lambda r: r["images_per_sec"])
        closed = max(closed_reps, key=lambda r: r["images_per_sec"])
        offered = max(1.0, 0.7 * closed["images_per_sec"])
        open_loop = loadgen.run_open_loop(
            engine, images, offered_rate=offered, n_requests=n_requests
        )
        flush_sizes = [n for _, n in engine._batcher.flush_log]
        per_batch = {
            str(bn): flush_sizes.count(bn) for bn in engine.batch_sizes
        }
        stats = dict(engine.stats)
        compile_seconds = dict(engine.compile_seconds)
    finally:
        engine.close()

    speedup = (
        round(closed["images_per_sec"] / sequential["images_per_sec"], 3)
        if sequential["images_per_sec"]
        else None
    )
    return {
        "schema": SCHEMA,
        "config": config_token,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "bucket": [h, w],
        "batch_sizes": list(engine.batch_sizes),
        "max_delay_ms": cfg.serving.max_delay_ms,
        "sequential": sequential,
        "sequential_images_per_sec": sequential["images_per_sec"],
        "engine": closed,
        GATE_KEY: closed["images_per_sec"],
        "engine_open_loop": open_loop,
        "flushes_by_size": per_batch,
        "engine_stats": stats,
        "compile_seconds": compile_seconds,
        "speedup": speedup,
        "measured": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--image-size", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--requests", type=int, default=96)
    p.add_argument("--update", action="store_true",
                   help="write/overwrite the banked record")
    p.add_argument("--no-check", action="store_true",
                   help="measure + print only")
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--min-speedup", type=float, default=DEFAULT_MIN_SPEEDUP,
                   help="fail when batched/sequential speedup is below "
                        "this floor (PR acceptance: 2.0)")
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    cfg = serving_config(args.image_size, args.max_batch)
    token = f"tiny{args.image_size}b{args.max_batch}"
    record = profile(cfg, token, n_requests=args.requests)
    path = record_path(record_key(token, record["platform"]), args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    if args.update:
        save_record(record, path)
        print(f"serving_profile: banked {path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    banked = load_record(path) if os.path.exists(path) else None
    if banked is None:
        print(
            f"serving_profile: no banked record at {path} — run with "
            "--update to create one (still enforcing the speedup floor)",
            file=sys.stderr,
        )
    failures, warnings = check_regression(
        record, banked, tol=args.tol, min_speedup=args.min_speedup
    )
    for w in warnings:
        print(f"serving_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"serving_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"serving_profile: REGRESSION vs {path} — if intentional, "
            "re-bank with --update",
            file=sys.stderr,
        )
        return 1
    print(f"serving_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
