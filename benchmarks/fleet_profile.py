"""Fleet availability benchmark + regression gate.

Prices the tentpole claim of the serving fleet (serving/fleet/): a
health-checked router with failover, circuit breakers and hedging keeps
serving through a replica death — availability >= 99.9% over a load run
with a seeded mid-run replica kill — while aggregating replica capacity
(>= 2x a single replica's throughput, the fleet acceptance floor).

Replicas are simulated single-slot services: each models its capacity
with a virtual busy-until queue (arrival waits for the slot, then
sleeps the service time OUTSIDE any lock), so one replica tops out at
~1/service_time regardless of client concurrency and N replicas
genuinely aggregate — sleeps release the GIL, which is what makes the
>=2x gate measurable on the single-core CI host where the real engine
could never show fleet parallelism.  Everything above the client is the
production stack: ReplicaRegistry + Prober (lease staleness),
FleetRouter (consistent hashing, breakers, failover, hedging), and
serving/loadgen.py's fleet loop.

Measured legs:
  * single   — closed loop against a 1-replica fleet: the baseline
    capacity one replica offers.
  * fleet    — the same load over 3 replicas with a seeded
    ``router.dispatch`` drop at ~3/4 of the run: the router's kill hook
    makes the selected replica actually die, failover + breakers absorb
    it, and after the run the prober must notice the death (lease
    expiry -> dead) and re-admit the revived replica (rejoin probes) —
    the full self-healing loop, asserted structurally.  The leg runs
    under a SpanTracer, producing the merged Chrome trace the PR 16
    observability contract requires: at least one failed-over request
    whose attempt spans touch two distinct replicas under one trace id.
  * slo      — the router's attempt-level burn-rate tracker (windows
    shrunk to benchmark scale) must ALARM (burn > 1 on both windows)
    right after the kill window, and clear (burn < 1) after the victim
    rejoins and a clean burst ages the errors out.
  * hedge    — a fast/slow replica pair under tight hedge clamps: the
    p99-derived hedge must fire and win at least once (tail tolerance
    failover alone cannot see).
  * mixed    — the dtype-heterogeneous fleet a quantized rollout
    creates: one int8 replica beside two bf16 replicas.  The router
    must hold the availability floor over the full load, the int8
    replica must actually serve traffic, and each replica's resident
    params dtype must be observable both in ``router.snapshot()`` (the
    /stats "registry" view, fed by /healthz probes) and as the
    ``fleet_replica_params_dtype`` info gauge in the Prometheus
    /metrics exposition — the ISSUE 17 observability contract: you can
    always tell which replicas serve quantized weights.
  * rollout  — two full rolling weight rollouts driven by the REAL
    RolloutController (serving/rollout/) against versioned replicas
    (each wraps a real MicroBatcher whose flush key is
    ``(model_version, bucket)`` — the engine's hot-swap keying) while
    background load keeps hitting the router.  Wave one promotes; wave
    two rolls to a version whose flushes fail, the canary's private
    burn-rate tracker alarms, the router auto-demotes it, and the
    controller reverse-rolls the fleet.  Gated: availability >= the
    floor through BOTH waves, zero version-mixed batches (structural —
    every flush's admitted-item versions are recorded), version skew
    observed in the /stats registry view mid-wave, and the
    ``fleet_replica_model_version`` info gauge present in /metrics.

Banked under benchmarks/records/ (step_profile.py conventions: atomic
save, --update to re-bank, --no-check to just measure). The gate fails
(exit 1) when availability drops below --min-availability (0.999),
fleet/single speedup falls below --min-speedup (2.0), the self-healing
structure breaks (no kill, no failover, no death detection, no rejoin,
no hedge win), the burn-rate alarm fails to fire through the kill or to
clear after rejoin, the merged trace lacks cross-replica failover
evidence, or fleet throughput regresses >tol vs the banked record.

Usage:
  python benchmarks/fleet_profile.py            # measure + gate
  python benchmarks/fleet_profile.py --update   # re-bank
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
# v3: adds the mixed-precision (int8 + bf16) dtype-observability leg
# v4: adds the rolling-rollout leg (hot-swap under load, gated promote,
#     auto-rollback on a burn-rate alarm, zero version-mixed batches)
SCHEMA = "fleet_profile/v4"
DEFAULT_TOL = 0.25  # sleep-paced throughput is steadier than compute,
#                     but the CI host still jitters thread wakeups
DEFAULT_MIN_SPEEDUP = 2.0
DEFAULT_MIN_AVAILABILITY = 0.999
# the gate: fleet capacity through the kill
GATE_KEY = "fleet_images_per_sec"
# the benchmark is pure host threading — no accelerator in the loop —
# so records are keyed by a constant platform token
PLATFORM = "sim"


def record_key(config_token: str, platform: str = PLATFORM) -> str:
    return f"{config_token}_{platform}"


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"fleet_profile_{key}.json")


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def check_regression(
    current,
    banked,
    tol: float = DEFAULT_TOL,
    min_speedup: float = DEFAULT_MIN_SPEEDUP,
    min_availability: float = DEFAULT_MIN_AVAILABILITY,
):
    """(failures, warnings) — pure, unit-testable.  Failures: the
    availability floor, the fleet/single speedup floor, any broken
    self-healing structure, or fleet capacity >tol below the banked
    record."""
    failures, warnings = [], []
    if banked is not None and banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, expected "
            f"{SCHEMA!r}; skipping comparison"
        )
        banked = None
    if banked is not None:
        old = banked.get(GATE_KEY)
        new = current.get(GATE_KEY)
        if old and new:
            drop = 1.0 - new / old
            if drop > tol:
                failures.append(
                    f"{GATE_KEY} regressed {drop:+.1%}: {new:.3f} vs banked "
                    f"{old:.3f} (tolerance {tol:.0%})"
                )
            elif drop > tol / 2:
                warnings.append(
                    f"{GATE_KEY} within tolerance but slipping {drop:+.1%}: "
                    f"{new:.3f} vs banked {old:.3f}"
                )

    availability = current.get("availability")
    if availability is not None and availability < min_availability:
        failures.append(
            f"availability {availability:.4%} below the "
            f"{min_availability:.2%} floor through the replica kill "
            f"({current.get('fleet', {}).get('errors')} failed, "
            f"{current.get('fleet', {}).get('n_requests')} offered)"
        )
    speedup = current.get("speedup")
    if speedup is not None and speedup < min_speedup:
        failures.append(
            f"fleet/single speedup {speedup:.2f}x below the "
            f"{min_speedup:.1f}x acceptance floor (fleet "
            f"{current.get(GATE_KEY)} img/s vs single "
            f"{current.get('single_images_per_sec')} img/s)"
        )
    # the self-healing structure: each False is a dead subsystem even
    # when the headline numbers survive
    for key, what in (
        ("victim_killed", "the seeded router.dispatch drop never killed "
                          "a replica"),
        ("victim_dead_after_run", "the prober never lease-expired the "
                                  "killed replica"),
        ("victim_rejoined", "the revived replica never re-entered "
                            "rotation"),
    ):
        if current.get(key) is False:
            failures.append(f"{key}: {what}")
    if current.get("failovers", 0) < 1:
        failures.append(
            "no failover recorded — the kill was not absorbed by "
            "re-dispatch"
        )
    hedge = current.get("hedge") or {}
    if hedge and hedge.get("hedge_wins", 0) < 1:
        failures.append(
            "hedge leg recorded no hedge win against the slow replica"
        )
    # the SLO engine: the burn-rate alarm must FIRE while the kill's
    # failed attempts sit in both windows, and CLEAR once the victim
    # rejoined and a clean burst aged them out
    slo = current.get("slo") or {}
    if slo:
        if not slo.get("alarm_during_kill"):
            failures.append(
                "slo: burn-rate alarm did not fire during the kill window "
                f"(burn short={slo.get('burn_during_kill', {}).get('short')} "
                f"long={slo.get('burn_during_kill', {}).get('long')})"
            )
        if not slo.get("cleared_after_rejoin"):
            failures.append(
                "slo: burn rate did not drop below 1 after the victim "
                "rejoined and the clean burst ran "
                f"(burn short={slo.get('burn_after_rejoin', {}).get('short')} "
                f"long={slo.get('burn_after_rejoin', {}).get('long')})"
            )
    # mixed-precision leg: availability floor, dtype observability on
    # both surfaces, and the int8 replica genuinely in rotation
    mixed = current.get("mixed") or {}
    if mixed:
        mixed_avail = mixed.get("availability")
        if mixed_avail is not None and mixed_avail < min_availability:
            failures.append(
                f"mixed: availability {mixed_avail:.4%} below the "
                f"{min_availability:.2%} floor with an int8 replica in "
                "rotation"
            )
        dtypes = set((mixed.get("replica_dtypes") or {}).values())
        if not {"int8", "bfloat16"} <= dtypes:
            failures.append(
                "mixed: registry snapshot does not report both int8 and "
                f"bfloat16 replica dtypes (got {sorted(map(str, dtypes))})"
            )
        if not mixed.get("metrics_dtype_gauge"):
            failures.append(
                "mixed: fleet_replica_params_dtype info gauge missing "
                "from the Prometheus exposition"
            )
        if mixed.get("int8_requests_ok", 0) < 1:
            failures.append(
                "mixed: the int8 replica served no successful request — "
                "it never entered rotation"
            )
    # rollout leg: both waves must land (one promoted, one rolled back
    # by the injected burn-rate alarm), availability must hold through
    # them, no flush may ever mix model versions, and the skew must be
    # observable while a wave is in flight
    rollout = current.get("rollout") or {}
    if rollout:
        roll_avail = rollout.get("availability")
        if roll_avail is not None and roll_avail < min_availability:
            failures.append(
                f"rollout: availability {roll_avail:.4%} below the "
                f"{min_availability:.2%} floor through the two rollout "
                "waves"
            )
        if not rollout.get("promoted_ok"):
            failures.append(
                "rollout: the good-version wave did not finish promoted "
                f"(outcome {rollout.get('promote_outcome')!r})"
            )
        if not rollout.get("rolled_back_ok"):
            failures.append(
                "rollout: the bad-version wave was not auto-rolled-back "
                "by the burn-rate alarm (outcome "
                f"{rollout.get('rollback_outcome')!r})"
            )
        if rollout.get("version_mixed_batches", 0) != 0:
            failures.append(
                f"rollout: {rollout.get('version_mixed_batches')} flushes "
                "mixed model versions — the (version, bucket) batch "
                "keying is broken"
            )
        if not rollout.get("skew_observed"):
            failures.append(
                "rollout: version skew was never visible in the /stats "
                "registry view while a wave was in flight"
            )
        if not rollout.get("metrics_version_gauge"):
            failures.append(
                "rollout: fleet_replica_model_version info gauge missing "
                "from the Prometheus exposition"
            )
    # tracing: the merged Chrome trace must show one failed-over request
    # whose attempt spans touch >= 2 replicas under a single trace id
    if current.get("trace_failover_evidence") is False:
        failures.append(
            "trace: no request in the merged trace failed on one replica "
            "and succeeded on another under a single trace id"
        )
    return failures, warnings


# ---------------------------------------------------------------------------
# simulated replicas


def make_sim_replica(
    replica_id: str, service_s: float, params_dtype: str = None
):
    """A single-slot replica: capacity 1/service_s regardless of caller
    concurrency.  The slot is a virtual busy-until queue — arrival
    reserves the next free interval under the lock, then sleeps out its
    own completion time outside it (never sleep while holding a lock).
    ``params_dtype`` makes /healthz report a resident dtype the way a
    real engine replica does — the registry tracks it and the router
    exposes it (mixed leg)."""
    from replication_faster_rcnn_tpu.serving.fleet.client import (
        LocalReplicaClient,
    )

    lock = threading.Lock()
    busy_until = [0.0]

    def predict(payload):
        with lock:
            start = max(time.monotonic(), busy_until[0])
            done = start + service_s
            busy_until[0] = done
        delay = done - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        return {"replica": replica_id, "payload": payload}

    def health():
        return {"ok": True, "params_dtype": params_dtype}

    return LocalReplicaClient(
        replica_id, predict, health if params_dtype is not None else None
    )


def make_versioned_sim_replica(
    replica_id: str, service_s: float, version: str = "1", bad_versions=()
):
    """A rollout-capable sim replica: requests flow through a REAL
    MicroBatcher whose flush key is ``(model_version, bucket)`` — the
    engine's hot-swap keying — and every flush records the admitted
    items' versions, so "zero version-mixed batches" is checked
    structurally, not assumed.  ``swap()`` flips the admission version
    (in-flight entries keep their old key and flush separately, exactly
    like the engine).  Flushes at a version in ``bad_versions`` raise —
    the bad-build stand-in the auto-rollback wave needs.

    Returns ``(client, state)``; ``state['flushes']`` is the
    ``(key_version, sorted(item_versions))`` log and ``state['close']``
    drains the batcher."""
    from replication_faster_rcnn_tpu.serving.batcher import MicroBatcher
    from replication_faster_rcnn_tpu.serving.fleet.client import (
        LocalReplicaClient,
    )

    lock = threading.Lock()
    state = {"version": str(version), "flushes": []}

    def flush(key, items):
        key_version = key[0]
        admitted = sorted({v for _, v in items})
        with lock:
            state["flushes"].append((key_version, admitted))
        if key_version in bad_versions:
            raise RuntimeError(
                f"replica {replica_id!r}: version {key_version} cannot "
                "serve (bad build)"
            )
        time.sleep(service_s)
        return [{"replica": replica_id, "version": key_version,
                 "payload": p} for p, _ in items]

    batcher = MicroBatcher(
        flush, max_batch=4, max_delay_s=service_s,
        name=f"rollout-sim-{replica_id}",
    )

    def predict(payload):
        with lock:
            v = state["version"]
        return batcher.submit((v, "b"), (payload, v)).result(timeout=10.0)

    def health():
        with lock:
            v = state["version"]
        return {
            "ok": True,
            "model_version": v,
            "bucket_queue_depths": {
                str(k): n for k, n in batcher.key_depths().items()
            },
        }

    def swap(new_version):
        with lock:
            state["version"] = str(new_version)

    state["close"] = batcher.close
    client = LocalReplicaClient(replica_id, predict, health, swap_fn=swap)
    return client, state


def build_fleet(clients, cfg):
    """(registry, prober, router) over ``clients`` — replicas are
    probed into rotation before the router sees traffic."""
    from replication_faster_rcnn_tpu.serving.fleet.registry import (
        Prober,
        ReplicaRegistry,
    )
    from replication_faster_rcnn_tpu.serving.fleet.router import FleetRouter

    registry = ReplicaRegistry(cfg)
    for rid, client in clients.items():
        registry.add(rid, client)
    for _ in range(cfg.rejoin_probes):  # admit synchronously
        registry.probe_once()
    router = FleetRouter(
        registry, cfg, kill_hook=lambda rid: clients[rid].kill()
    )
    prober = Prober(registry, interval_s=cfg.probe_interval_s).start()
    return registry, prober, router


def _failover_trace_evidence(events):
    """The trace id of one failed-over request in the merged Chrome
    trace: its ``fleet/attempt`` spans touch >= 2 distinct replicas,
    with at least one failed and one successful attempt — the
    observability acceptance evidence.  None when no request qualifies."""
    by_trace = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != "fleet/attempt":
            continue
        args = ev.get("args") or {}
        if args.get("trace_id"):
            by_trace.setdefault(args["trace_id"], []).append(args)
    for trace_id, attempts in sorted(by_trace.items()):
        replicas = {a.get("replica") for a in attempts}
        if (
            len(replicas) >= 2
            and any(a.get("ok") for a in attempts)
            and any(not a.get("ok") for a in attempts)
        ):
            return trace_id
    return None


def _wait_for(predicate, timeout_s: float = 10.0) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return bool(predicate())


# ---------------------------------------------------------------------------
# measurement


def profile(
    config_token: str,
    n_requests: int = 240,
    service_ms: float = 4.0,
    concurrency: int = 6,
    seed: int = 0,
):
    import dataclasses
    import tempfile

    from replication_faster_rcnn_tpu.config import FleetConfig
    from replication_faster_rcnn_tpu.faultlib import failpoints
    from replication_faster_rcnn_tpu.serving import loadgen
    from replication_faster_rcnn_tpu.serving.fleet.router import content_key
    from replication_faster_rcnn_tpu.telemetry import spans as tspans
    from replication_faster_rcnn_tpu.telemetry.report import load_trace_events

    service_s = service_ms / 1000.0
    cfg = FleetConfig(
        probe_interval_s=0.05,
        lease_timeout_s=0.2,
        rejoin_probes=2,
        breaker_threshold=3,
        breaker_cooldown_s=0.5,
        max_attempts=3,
        request_timeout_s=10.0,
        cache_entries=0,  # unique hashes anyway — measure replicas, not LRU
        canary_fraction=0.0,
        # clamp hedging above the healthy tail: a dead replica fails
        # fast (failover handles it), so hedges stay out of the
        # throughput measurement; the hedge leg prices them separately
        hedge=True,
        hedge_floor_ms=100.0,
        hedge_ceiling_ms=400.0,
        # shrink the SLO windows to benchmark scale so the burn-rate
        # alarm can fire during the kill window AND age back out within
        # one run (production defaults are 5 m / 1 h)
        slo_short_window_s=0.4,
        slo_long_window_s=1.2,
    )
    # unique content per request: every dispatch must reach a replica
    requests = [
        (f"img-{i:04d}", content_key(f"img-{i:04d}".encode()))
        for i in range(n_requests)
    ]

    # -- single-replica baseline: one slot's capacity under full load
    clients = {"r0": make_sim_replica("r0", service_s)}
    registry, prober, router = build_fleet(clients, cfg)
    try:
        single = loadgen.run_fleet_loop(
            router.dispatch, requests, concurrency=concurrency
        )
    finally:
        prober.stop()
        router.close()

    # -- fleet leg: 3 replicas, seeded kill at ~2/3 of the run; traced,
    # so the merged Chrome trace must show a failed-over request's
    # spans crossing the router and two replicas under one trace id
    clients = {
        rid: make_sim_replica(rid, service_s) for rid in ("r0", "r1", "r2")
    }
    registry, prober, router = build_fleet(clients, cfg)
    kill_at = max(1, (3 * n_requests) // 4)
    failpoints.configure(
        [
            failpoints.Rule(
                "router.dispatch", "drop", 1.0, seed,
                max_fires=1, after=kill_at,
            )
        ]
    )
    trace_dir = tempfile.mkdtemp(prefix="fleet_profile_trace_")
    trace_path = os.path.join(trace_dir, "trace.json")
    tracer = tspans.SpanTracer(trace_path)
    tspans.set_tracer(tracer)
    try:
        fleet = loadgen.run_fleet_loop(
            router.dispatch, requests, concurrency=concurrency
        )
        # sample the burn rate NOW, while the kill's failed attempts
        # still sit inside both windows — the alarm must be firing
        slo_during = router.slo.snapshot()
        victims = [rid for rid, c in clients.items() if c.killed]
        victim = victims[0] if victims else None
        # self-healing, second half: the prober lease-expires the dead
        # replica, then readmits it after revival
        dead_seen = victim is not None and _wait_for(
            lambda: registry.state_of(victim) == "dead"
        )
        if victim is not None:
            clients[victim].revive()
        rejoined = victim is not None and _wait_for(
            lambda: victim in registry.in_rotation()
        )
        # clean burst + window turnover: with the victim back, the burn
        # rate must drop below 1 on both windows (the alarm clears)
        clean = loadgen.run_fleet_loop(
            router.dispatch, requests, concurrency=concurrency
        )
        cleared = _wait_for(
            lambda: max(router.slo.burn_rates().values()) < 1.0
        )
        slo_after = router.slo.snapshot()
        router_stats = router.snapshot()["router"]
    finally:
        failpoints.disarm()
        prober.stop()
        router.close()
        tracer.flush()
        tspans.set_tracer(tspans.NULL_TRACER)
    failover_trace = _failover_trace_evidence(load_trace_events(trace_path))

    # -- hedge leg: fast/slow pair, tight clamps — the hedge must win
    hedge_cfg = dataclasses.replace(
        cfg, hedge_floor_ms=8.0, hedge_ceiling_ms=8.0, cache_entries=0
    )
    clients = {
        "fast": make_sim_replica("fast", service_s / 2),
        "slow": make_sim_replica("slow", 15 * service_s),
    }
    registry, prober, router = build_fleet(clients, hedge_cfg)
    try:
        hedge_run = loadgen.run_fleet_loop(
            router.dispatch, requests[:32], concurrency=2
        )
        hedge_stats = router.snapshot()["router"]
    finally:
        prober.stop()
        router.close()

    # -- mixed-precision leg: one int8 replica beside two bf16 replicas.
    # No kill here — the fleet leg already prices self-healing; this leg
    # prices the quantized-rollout contract: heterogeneous dtypes hold
    # the availability floor, and every replica's resident dtype is
    # observable in /stats (registry snapshot) and /metrics (the
    # fleet_replica_params_dtype info gauge).
    replica_dtypes_cfg = {"b0": "bfloat16", "b1": "bfloat16", "q0": "int8"}
    clients = {
        rid: make_sim_replica(rid, service_s, params_dtype=dt)
        for rid, dt in replica_dtypes_cfg.items()
    }
    registry, prober, router = build_fleet(clients, cfg)
    try:
        mixed_run = loadgen.run_fleet_loop(
            router.dispatch, requests, concurrency=concurrency
        )
        mixed_snap = router.snapshot()
        mixed_prom = router.metrics.render_prometheus()
    finally:
        prober.stop()
        router.close()
    replica_dtypes = {
        rid: info.get("params_dtype")
        for rid, info in mixed_snap["registry"].items()
    }
    dtype_gauge_lines = sorted(
        line
        for line in mixed_prom.splitlines()
        if line.startswith("fleet_replica_params_dtype{")
    )
    int8_ok = sum(
        stats.get("ok", 0)
        for rid, stats in mixed_snap["replicas"].items()
        if replica_dtypes.get(rid) == "int8"
    )

    # -- rollout leg: two rolling rollouts mid-load through the REAL
    # controller.  Wave "2" promotes; wave "3" fails its flushes on the
    # canary, the burn-rate alarm demotes it, and the controller
    # reverse-rolls the fleet back to "2".  Load never stops.
    from replication_faster_rcnn_tpu.config import (
        FasterRCNNConfig,
        RolloutConfig,
    )
    from replication_faster_rcnn_tpu.serving.rollout import RolloutController

    rollout_fleet_cfg = dataclasses.replace(
        cfg,
        hedge=False,          # sequential failover: canary misses fall
        #                       through to the serving walk in-thread
        canary_fraction=0.4,  # a wide slice so the canary accumulates
        #                       CANARY_SLO_MIN_SAMPLES within the hold
        cache_entries=0,
    )
    full_cfg = FasterRCNNConfig().replace(
        fleet=rollout_fleet_cfg,
        rollout=RolloutConfig(
            drain_timeout_s=1.0,
            swap_timeout_s=5.0,
            rejoin_timeout_s=5.0,
            canary_hold_s=0.6,
            canary_min_requests=5,
        ),
    )
    versioned = {
        rid: make_versioned_sim_replica(
            rid, service_s, version="1", bad_versions=("3",)
        )
        for rid in ("v0", "v1", "v2")
    }
    clients = {rid: client for rid, (client, _) in versioned.items()}
    registry, prober, router = build_fleet(clients, rollout_fleet_cfg)
    controller = RolloutController(registry, router, full_cfg)
    stop = threading.Event()
    skew_samples = []
    load_counts = []

    def _load_loop(worker: int) -> None:
        counts = {"ok": 0, "fail": 0}
        load_counts.append(counts)
        i = 0
        while not stop.is_set():
            payload = f"roll-{worker}-{i:05d}"
            try:
                router.dispatch(payload, content_hash=content_key(payload.encode()))
                counts["ok"] += 1
            except Exception:  # noqa: BLE001 - the availability ledger
                counts["fail"] += 1
            i += 1

    def _skew_sampler() -> None:
        # the /stats registry view: distinct reported versions per poll
        while not stop.is_set():
            snap = router.snapshot()["registry"]
            versions_now = {
                info.get("model_version")
                for info in snap.values()
                if info.get("model_version")
            }
            skew_samples.append(sorted(versions_now))
            time.sleep(0.02)

    threads = [
        threading.Thread(target=_load_loop, args=(w,), daemon=False)
        for w in range(concurrency // 2 or 1)
    ] + [threading.Thread(target=_skew_sampler, daemon=False)]
    try:
        for t in threads:
            t.start()
        wave_promote = controller.rollout("2")
        wave_rollback = controller.rollout("3")
        rollout_prom = router.metrics.render_prometheus()
        rollout_snap = router.snapshot()
        rollout_registry = rollout_snap["registry"]
        router_stats_rollout = rollout_snap["router"]
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        prober.stop()
        router.close()
        for _, (_, st) in versioned.items():
            st["close"]()
    flush_log = [
        entry for _, (_, st) in versioned.items() for entry in st["flushes"]
    ]
    mixed_batches = sum(
        1
        for key_version, admitted in flush_log
        if len(admitted) != 1 or admitted[0] != key_version
    )
    roll_ok = sum(c["ok"] for c in load_counts)
    roll_fail = sum(c["fail"] for c in load_counts)
    roll_avail = (
        roll_ok / (roll_ok + roll_fail) if (roll_ok + roll_fail) else None
    )
    final_versions = {
        rid: info.get("model_version")
        for rid, info in rollout_registry.items()
    }

    speedup = (
        round(fleet["images_per_sec"] / single["images_per_sec"], 3)
        if single["images_per_sec"]
        else None
    )
    return {
        "schema": SCHEMA,
        "config": config_token,
        "platform": PLATFORM,
        "service_ms": service_ms,
        "n_requests": n_requests,
        "concurrency": concurrency,
        "seed": seed,
        "kill_after_attempts": kill_at,
        "single": single,
        "single_images_per_sec": single["images_per_sec"],
        "fleet": fleet,
        GATE_KEY: fleet["images_per_sec"],
        "availability": fleet["availability"],
        "speedup": speedup,
        "victim": victim,
        "victim_killed": victim is not None,
        "victim_dead_after_run": dead_seen,
        "victim_rejoined": rejoined,
        "failovers": router_stats["failovers"],
        "router_stats": router_stats,
        "slo": {
            "short_window_s": cfg.slo_short_window_s,
            "long_window_s": cfg.slo_long_window_s,
            "availability_target": cfg.slo_availability_target,
            "burn_during_kill": slo_during["burn_rates"],
            "alarm_during_kill": slo_during["alarm"],
            "burn_after_rejoin": slo_after["burn_rates"],
            "cleared_after_rejoin": cleared,
            "clean_burst_availability": clean["availability"],
        },
        "trace_failover_evidence": failover_trace is not None,
        "failover_trace_id": failover_trace,
        "hedge": {
            "p99_ms": hedge_run["p99_ms"],
            "availability": hedge_run["availability"],
            "hedges": hedge_stats["hedges"],
            "hedge_wins": hedge_stats["hedge_wins"],
        },
        "mixed": {
            "availability": mixed_run["availability"],
            "images_per_sec": mixed_run["images_per_sec"],
            "replica_dtypes": replica_dtypes,
            "int8_requests_ok": int(int8_ok),
            "metrics_dtype_gauge": bool(dtype_gauge_lines),
            "metrics_dtype_gauge_lines": dtype_gauge_lines,
        },
        "rollout": {
            "availability": roll_avail,
            "requests_ok": roll_ok,
            "requests_failed": roll_fail,
            "promote_outcome": wave_promote.outcome,
            "promoted_ok": wave_promote.outcome == "promoted",
            "rollback_outcome": wave_rollback.outcome,
            "rollback_reason": wave_rollback.reason,
            "rolled_back_ok": wave_rollback.outcome == "rolled_back",
            "flushes": len(flush_log),
            "version_mixed_batches": int(mixed_batches),
            "skew_observed": any(len(s) > 1 for s in skew_samples),
            "skew_samples": len(skew_samples),
            "final_versions": final_versions,
            "canary_demotions": router_stats_rollout["canary_demotions"],
            "metrics_version_gauge": bool(
                [
                    line
                    for line in rollout_prom.splitlines()
                    if line.startswith("fleet_replica_model_version{")
                ]
            ),
        },
        "measured": True,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=240)
    p.add_argument("--service-ms", type=float, default=4.0)
    p.add_argument("--concurrency", type=int, default=6)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--update", action="store_true",
                   help="write/overwrite the banked record")
    p.add_argument("--no-check", action="store_true",
                   help="measure + print only")
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--min-speedup", type=float, default=DEFAULT_MIN_SPEEDUP,
                   help="fail when fleet/single throughput is below this "
                        "floor (PR acceptance: 2.0)")
    p.add_argument("--min-availability", type=float,
                   default=DEFAULT_MIN_AVAILABILITY,
                   help="fail when availability through the replica kill "
                        "is below this floor (PR acceptance: 0.999)")
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    token = f"sim3r{args.requests}s{args.service_ms:g}"
    record = profile(
        token,
        n_requests=args.requests,
        service_ms=args.service_ms,
        concurrency=args.concurrency,
        seed=args.seed,
    )
    path = record_path(record_key(token), args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    if args.update:
        save_record(record, path)
        print(f"fleet_profile: banked {path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    banked = load_record(path) if os.path.exists(path) else None
    if banked is None:
        print(
            f"fleet_profile: no banked record at {path} — run with "
            "--update to create one (still enforcing the availability "
            "and speedup floors)",
            file=sys.stderr,
        )
    failures, warnings = check_regression(
        record,
        banked,
        tol=args.tol,
        min_speedup=args.min_speedup,
        min_availability=args.min_availability,
    )
    for w in warnings:
        print(f"fleet_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"fleet_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"fleet_profile: REGRESSION vs {path} — if intentional, "
            "re-bank with --update",
            file=sys.stderr,
        )
        return 1
    print(f"fleet_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
