"""Bank fresh on-chip bench results into ``bench_v5e_round2.json``.

``bench.py``'s CPU-fallback line surfaces ``last_recorded_tpu`` from
``benchmarks/bench_v5e_round2.json`` ONLY — but live captures land in
``benchmarks/mfu_experiments.json`` (the queue runner) and
``benchmarks/bench_r05_{early,late}.json`` (the relay watcher's banked
bench lines). If the relay revives mid-session and dies again before the
driver's end-of-round bench, those fresh numbers would be invisible to
the line of record. This script normalizes and appends them (deduped on
the ``measured`` stamp); the watcher runs it after every capture phase,
and it is safe to run any number of times.

    python benchmarks/bank_records.py [--dry-run]
"""

from __future__ import annotations

import argparse
import json
import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks")
CANON = os.path.join(BENCH, "bench_v5e_round2.json")


def _config_string(exp: dict) -> str:
    """First word must be the preset name (bench.py's same-config match
    keys on it); the rest is a human-readable flag summary."""
    args = exp.get("args", [])
    preset = "voc_resnet18"
    if "--config" in args:
        preset = args[args.index("--config") + 1]
    extras = " ".join(
        a for a in args if a != "--config" and a != preset
    )
    env = exp.get("env", {})
    envs = " ".join(f"{k}={v}" for k, v in env.items() if k != "BENCH_WATCHDOG_S")
    parts = [preset, "600x600", extras, envs,
             f"(queue experiment {exp['name']})", "one v5e chip"]
    return " ".join(p for p in parts if p)


def _bench_line_records(path: str, label: str):
    """A watcher-banked raw bench.py JSON line -> record, unless it was a
    CPU fallback."""
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            line = json.load(f)
    except (OSError, json.JSONDecodeError):
        return []
    if line.get("fallback_backend") or not line.get("value"):
        return []
    # lead with an ISO UTC stamp (the banked file's mtime = capture time):
    # benchmark.py picks the most recent record by lexicographic compare
    # of this field, so a non-timestamp prefix would win forever
    stamp = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path))
    )
    measured = (
        f"{stamp} banked from {os.path.basename(path)} ({label}, round 5)"
    )
    rec = {
        "value": line["value"],
        "vs_baseline": line.get("vs_baseline"),
        "config": "voc_resnet18 600x600 batch 16, bench.py defaults, one v5e chip",
        "metric": line.get("metric"),
        "measured": measured,
    }
    for k in ("flops_per_step", "mfu"):
        if line.get(k) is not None:
            rec[k] = line[k]
    if isinstance(line.get("breakdown"), dict):
        rec["breakdown_ms"] = line["breakdown"]
    return [rec]


def collect_new(since: str):
    out = []
    mfu_path = os.path.join(BENCH, "mfu_experiments.json")
    if os.path.exists(mfu_path):
        with open(mfu_path) as f:
            for exp in json.load(f).get("experiments", []):
                res = exp.get("result")
                when = exp.get("recorded_utc")
                if not (isinstance(res, dict) and when):
                    continue
                if when < since:  # ISO strings compare chronologically
                    continue
                # bench-format results only (fed-trainer/grad legs have
                # their own evidence files and aren't throughput records)
                if not (res.get("metric") and res.get("value")):
                    continue
                if res.get("fallback_backend"):
                    continue
                rec = {
                    "value": res["value"],
                    "vs_baseline": res.get("vs_baseline"),
                    "config": _config_string(exp),
                    "metric": res["metric"],
                    "measured": f"{when} by mfu_experiments queue on the "
                                f"real chip ({exp['name']})",
                }
                for k in ("flops_per_step", "mfu"):
                    if res.get(k) is not None:
                        rec[k] = res[k]
                if isinstance(res.get("breakdown"), dict):
                    rec["breakdown_ms"] = res["breakdown"]
                out.append(rec)
    out += _bench_line_records(
        os.path.join(BENCH, "bench_r05_early.json"), "bench-of-record early"
    )
    out += _bench_line_records(
        os.path.join(BENCH, "bench_r05_late.json"), "bench-late"
    )
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dry-run", action="store_true")
    ap.add_argument(
        "--since", default="2026-08-01T21:00:00Z",
        help="only bank queue records stamped at/after this UTC instant "
        "(default: the round-5 session start — earlier measurements were "
        "curated by hand, often under a differently formatted stamp)")
    args = ap.parse_args()

    with open(CANON) as f:
        canon = json.load(f)
    # dedup on the measured stamp: a genuine re-measurement that lands on
    # an identical rounded value (queue exp 13 exists to re-record) must
    # still bank; the --since cutoff keeps hand-curated history out
    have = {r.get("measured") for r in canon.get("records", [])}
    fresh = [
        r for r in collect_new(args.since) if r["measured"] not in have
    ]
    if not fresh:
        print("nothing new to bank")
        return
    for r in fresh:
        print(f"banking: {r['metric']} = {r['value']} ({r['measured']})")
    if args.dry_run:
        return
    canon["records"].extend(fresh)
    canon.setdefault("notes", [])
    if isinstance(canon["notes"], list):
        canon["notes"].append(
            f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}: "
            f"bank_records.py appended {len(fresh)} round-5 record(s)"
        )
    tmp = CANON + ".tmp"
    with open(tmp, "w") as f:
        json.dump(canon, f, indent=1)
    os.replace(tmp, CANON)  # atomic: a kill mid-write can't truncate CANON
    print(f"appended {len(fresh)} record(s) to {CANON}")


if __name__ == "__main__":
    main()
