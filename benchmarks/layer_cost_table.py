"""Per-conv cost table + MXU-utilization ceiling model (host-side, exact).

The r3 VERDICT asks that ResNet-config MFU either reach >=0.25 or be
bounded by an analysis naming the irreducible costs. The tunnel-side
profiler is a documented wedge risk (verify SKILL.md incident
2026-08-01), so this is the static half of that analysis (the dynamic
half is `benchmarks/grad_breakdown.py`): enumerate every
`conv_general_dilated` in the model's own jaxpr (exact traced shapes —
no hand-maintained table) and bound each pass's achievable MXU
utilization from the systolic array's tiling:

  The v5e MXU multiplies 128x128 tiles. A matmul with contraction size
  K and output-channel size M runs at an efficiency ceiling of
  (K / 128ceil(K)) * (M / 128ceil(M)): padding to the tile is wasted
  lanes. Per pass the (K, M) roles are:
    forward   K = Cin*kh*kw,  M = Cout
    dgrad     K = Cout*kh*kw, M = Cin   (skipped for the stem: dx of
                                         the input image is never used)
    wgrad     K = N*OH*OW,    M = Cout  (x Cin*kh*kw output rows; the
                                         huge spatial contraction makes
                                         K-padding negligible)

  A 64-channel layer therefore cannot exceed 50% MXU utilization on its
  forward/wgrad output lanes no matter what the compiler does — that is
  the "irreducible" part; the rest of the gap between the ceiling floor
  and a measured step is XLA scheduling/fusion/HBM, attributable on
  chip by grad_breakdown.

Writes ``benchmarks/layer_cost_table.json``:
  per-conv rows (shapes, per-pass GFLOPs and efficiency ceilings) and
  aggregates: plain compute floor (all FLOPs at peak), ceiling-adjusted
  floor (FLOPs / (peak * eff)), and the implied MFU ceiling for a
  measured step time.

Run (CPU is fine and intended — jaxpr tracing only, nothing executes):
    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      python benchmarks/layer_cost_table.py [--config voc_resnet18]
      [--batch-size 16] [--measured-step-ms 74.0]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "benchmarks", "layer_cost_table.json")

# single source for the v5e roofline constant (namespace-package import;
# benchmark.py's _peak_flops_per_sec uses the same figure per device)
from benchmarks.backward_analysis import V5E_PEAK_BF16_FLOPS as PEAK_BF16  # noqa: E402

TILE = 128


def _eff(k: int, m: int) -> float:
    """Tiling efficiency ceiling of a (K contraction, M output-lane)
    matmul on a TILE x TILE systolic array."""
    kp = TILE * math.ceil(k / TILE)
    mp = TILE * math.ceil(m / TILE)
    return (k / kp) * (m / mp)


def collect_convs(config_name: str, batch_size: int, image_size=None):
    import jax

    jax.config.update("jax_platforms", "cpu")  # pure trace; never touch a chip

    from replication_faster_rcnn_tpu.benchmark import abstract_step_inputs
    from replication_faster_rcnn_tpu.config import get_config
    from replication_faster_rcnn_tpu.train.train_step import (
        compute_losses,
        make_optimizer,
    )

    import dataclasses

    cfg = get_config(config_name)
    cfg = cfg.replace(
        data=dataclasses.replace(
            cfg.data,
            dataset="synthetic",
            **({"image_size": tuple(image_size)} if image_size else {}),
        ),
        train=dataclasses.replace(cfg.train, batch_size=batch_size),
    )
    tx, _ = make_optimizer(cfg, 100)
    # the bench's shared abstract fixture: shapes only, no arrays, no
    # param-init program — this table can never trace different shapes
    # than the flops_per_step it is reconciled against
    model, state_abs, batch_abs = abstract_step_inputs(cfg, tx)

    def loss(params, batch_stats, rng, step, batch):
        total, _ = compute_losses(
            model, cfg, params, batch_stats, batch,
            jax.random.fold_in(rng, step), True,
        )
        return total

    jaxpr = jax.make_jaxpr(loss)(
        state_abs.params, state_abs.batch_stats, state_abs.rng,
        state_abs.step, batch_abs,
    )

    convs = []

    def walk(jp):
        for eqn in jp.eqns:
            if eqn.primitive.name == "conv_general_dilated":
                lhs = tuple(eqn.invars[0].aval.shape)
                rhs = tuple(eqn.invars[1].aval.shape)
                out = tuple(eqn.outvars[0].aval.shape)
                convs.append((lhs, rhs, out))
            for sub in eqn.params.values():
                subs = sub if isinstance(sub, (list, tuple)) else (sub,)
                for s in subs:
                    if hasattr(s, "jaxpr"):
                        walk(s.jaxpr)

    walk(jaxpr.jaxpr)
    return cfg, convs


def analyze(convs):
    rows = []
    tot = {"fwd": 0.0, "dgrad": 0.0, "wgrad": 0.0}
    eff_tot = {"fwd": 0.0, "dgrad": 0.0, "wgrad": 0.0}  # flops / eff
    for i, (lhs, rhs, out) in enumerate(convs):
        # NHWC lhs, HWIO rhs, NHWC out (flax convention)
        kh, kw, cin, cout = rhs
        n = lhs[0]
        spatial = out[1] * out[2] if len(out) == 4 else out[1]
        flops = 2.0 * n * spatial * cout * cin * kh * kw
        # accumulate with the UNROUNDED efficiencies (rounding is for the
        # output rows only; a sub-0.0005 efficiency would otherwise
        # divide by zero and the stem's small values would skew the
        # weighted ceiling)
        e_fwd = _eff(cin * kh * kw, cout)
        e_dgrad = _eff(cout * kh * kw, cin)
        e_wgrad = _eff(n * spatial, cout)
        row = {
            "lhs": lhs,
            "rhs": rhs,
            "out": out,
            "gflops_fwd": round(flops / 1e9, 2),
            "eff_fwd": round(e_fwd, 3),
            "eff_dgrad": round(e_dgrad, 3),
            "eff_wgrad": round(e_wgrad, 3),
        }
        stem = i == 0 and cin <= 4  # image input: dx never needed
        row["dgrad_skipped"] = stem
        rows.append(row)
        tot["fwd"] += flops
        eff_tot["fwd"] += flops / e_fwd
        if not stem:
            tot["dgrad"] += flops
            eff_tot["dgrad"] += flops / e_dgrad
        tot["wgrad"] += flops
        eff_tot["wgrad"] += flops / e_wgrad
    return rows, tot, eff_tot


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="voc_resnet18")
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--image-size", type=int, nargs=2, default=None)
    ap.add_argument(
        "--measured-step-ms", type=float, default=None,
        help="measured on-chip step time; adds implied-MFU-ceiling rows",
    )
    args = ap.parse_args()

    out_path = OUT
    if args.config != "voc_resnet18":  # flagship keeps the unsuffixed name
        out_path = OUT.replace(".json", f"_{args.config}.json")

    cfg, convs = collect_convs(args.config, args.batch_size, args.image_size)
    rows, tot, eff_tot = analyze(convs)

    conv_flops = sum(tot.values())
    floor_ms = conv_flops / PEAK_BF16 * 1e3
    ceil_ms = sum(eff_tot.values()) / PEAK_BF16 * 1e3
    agg = {
        "n_convs": len(rows),
        "conv_gflops": {k: round(v / 1e9, 2) for k, v in tot.items()},
        "conv_gflops_total": round(conv_flops / 1e9, 2),
        "weighted_eff_ceiling": {
            k: round(tot[k] / eff_tot[k], 3) for k in tot if eff_tot[k]
        },
        "compute_floor_ms_at_peak": round(floor_ms, 2),
        "compute_floor_ms_at_tiling_ceiling": round(ceil_ms, 2),
    }
    # even a perfect schedule cannot beat the tiling ceiling: this is
    # the conv-MFU bound the architecture's channel widths impose
    agg["best_achievable_conv_mfu"] = round(floor_ms / ceil_ms, 3)
    if args.measured_step_ms:
        agg["measured_step_ms"] = args.measured_step_ms
        agg["gap_vs_tiling_ceiling"] = round(
            args.measured_step_ms / ceil_ms, 2
        )

    out = {
        "config": args.config,
        "batch_size": args.batch_size,
        "peak_bf16_flops": PEAK_BF16,
        "mxu_tile": TILE,
        "aggregate": agg,
        "convs": rows,
        "recorded_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "note": (
            "conv primitives enumerated from the model's own jaxpr (exact "
            "shapes); efficiency ceilings are the 128x128-tile padding "
            "bound per pass — what no compiler schedule can exceed, not a "
            "prediction of what XLA achieves. dgrad of the image-input "
            "stem is skipped (its dx is unused). Non-conv FLOPs (head "
            "matmuls, NMS, targets) are excluded here; bench.py's "
            "flops_per_step covers the whole program. CONVENTION: this "
            "table counts the full kh*kw taps per output position (the "
            "work the MXU actually performs on the padded im2col, and the "
            "fvcore/industry convention behind quoted MFU numbers); "
            "XLA's HloCostAnalysis — the basis of bench.py's "
            "flops_per_step — excludes border padding taps (measured: "
            "-30.5% on the ROI head's 4x4x3x3 SAME convs, (10/12)^2 "
            "exactly; -1.4% on the 300x300 stem), so bench.py's mfu is "
            "systematically CONSERVATIVE: flagship b16 forward convs are "
            "902 GFLOP full-tap vs ~791 border-exact for forward+loss, "
            "and the 0.153 record corresponds to ~0.186 full-tap."
        ),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"aggregate": agg}))


if __name__ == "__main__":
    main()
