"""Host input-pipeline throughput vs 8-chip demand (SURVEY.md §7 hard
part #4: the ≥6x target assumes the chips are never input-bound).

Builds a synthetic VOC devkit (typical-VOC-sized JPEGs + XML annotations)
in /tmp, then measures the real ingest path — PIL JPEG decode -> native
C++ fused resize+normalize (`native/frcnn_native.cpp`, numpy fallback) ->
XML parse -> pad-to-max_boxes -> collate — three ways:

  * one-sample __getitem__ rate (the per-core ceiling),
  * DataLoader end-to-end (prefetch thread + worker pool),
  * the resize+normalize kernel alone, native vs numpy fallback.

Demand model: measured per-chip train images/sec x 8 chips (the v5e-8
north-star topology). The verdict records how many CPU cores/hosts at the
measured per-core rate would be needed — this 1-core container cannot
feed 8 chips, and the number quantifies exactly what can.

Writes benchmarks/loader_throughput.json; prints it.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# measured on the real chip (b16 600x600 with tiled NMS, 2026-07-31,
# benchmarks/bench_v5e_round2.json); overridable once a newer number exists
PER_CHIP_IMG_S = float(os.environ.get("LOADER_DEMAND_PER_CHIP", "210"))
N_CHIPS = 8


def _build_devkit(root: str, n_images: int) -> None:
    from PIL import Image

    rng = np.random.RandomState(0)
    os.makedirs(os.path.join(root, "ImageSets", "Main"), exist_ok=True)
    os.makedirs(os.path.join(root, "JPEGImages"), exist_ok=True)
    os.makedirs(os.path.join(root, "Annotations"), exist_ok=True)
    ids = [f"{i:06d}" for i in range(n_images)]
    with open(os.path.join(root, "ImageSets", "Main", "train.txt"), "w") as f:
        f.write("\n".join(ids) + "\n")
    for i, img_id in enumerate(ids):
        w, h = 500, 375  # typical VOC photo size
        arr = rng.randint(0, 255, (h, w, 3), np.uint8)
        Image.fromarray(arr).save(
            os.path.join(root, "JPEGImages", img_id + ".jpg"), quality=85
        )
        objs = []
        for _ in range(rng.randint(1, 5)):
            x1, y1 = rng.randint(0, w - 60), rng.randint(0, h - 60)
            bw, bh = rng.randint(30, 60), rng.randint(30, 60)
            objs.append(
                "<object><name>car</name><difficult>0</difficult>"
                f"<bndbox><xmin>{x1}</xmin><ymin>{y1}</ymin>"
                f"<xmax>{x1+bw}</xmax><ymax>{y1+bh}</ymax></bndbox></object>"
            )
        with open(os.path.join(root, "Annotations", img_id + ".xml"), "w") as f:
            f.write(
                f"<annotation><size><width>{w}</width><height>{h}</height>"
                f"</size>{''.join(objs)}</annotation>"
            )


def main() -> None:
    from replication_faster_rcnn_tpu.config import DataConfig
    from replication_faster_rcnn_tpu.data import native_ops
    from replication_faster_rcnn_tpu.data.loader import DataLoader
    from replication_faster_rcnn_tpu.data.voc import VOCDataset

    n_images = int(os.environ.get("LOADER_BENCH_IMAGES", "64"))
    root = "/tmp/loader_bench_voc"
    if os.path.exists(root):
        shutil.rmtree(root)
    _build_devkit(root, n_images)

    cfg = DataConfig(root_dir=root, dataset="voc", image_size=(600, 600))
    ds = VOCDataset(cfg, "train")

    # per-sample rate (single-threaded ceiling); warm one sample first
    ds[0]
    t0 = time.time()
    for i in range(n_images):
        ds[i]
    per_sample_s = (time.time() - t0) / n_images
    single_rate = 1.0 / per_sample_s

    def _loader_rate(warm_epochs: int = 0, dataset=None, **kw):
        loader = DataLoader(
            dataset if dataset is not None else ds,
            batch_size=8, shuffle=True, prefetch=2, **kw,
        )
        for epoch in range(warm_epochs):
            loader.set_epoch(epoch)
            for _ in loader:
                pass
        n = 0
        t0 = time.time()
        for epoch in range(warm_epochs, warm_epochs + 3):
            loader.set_epoch(epoch)
            for batch in loader:
                n += batch["image"].shape[0]
        return n / (time.time() - t0)

    # DataLoader end-to-end: thread workers (native decode releases the
    # GIL) and fork-process workers (VERDICT r2 item 4; on this 1-core
    # container processes timeshare one core, so the row records overhead,
    # not scaling — the scaling claim is the per-core rate x worker count)
    loader_rate = _loader_rate(num_workers=4)
    # the process path needs >= 2 workers (the loader runs num_workers<=1
    # serially in-process — a "process mode" label on that would lie)
    mp_workers = max(2, int(os.environ.get("LOADER_BENCH_MP_WORKERS", "2")))
    loader_rate_mp = _loader_rate(num_workers=mp_workers, worker_mode="process")
    # RAM-cache steady state (data/cache.py): epoch 0 decodes into the
    # cache untimed, epochs 1-3 measure the memcpy path — the single-core
    # answer to keeps_up_one_chip=false
    loader_rate_cached = _loader_rate(
        warm_epochs=1, num_workers=1, cache_ram=True
    )
    # uint8 samples (device_normalize): 4x smaller cache entries and 4x
    # less collate memcpy — the steady-state ceiling for the fed trainer's
    # host side when normalization runs on-chip
    import dataclasses as _dc

    ds_u8 = VOCDataset(_dc.replace(cfg, device_normalize=True), "train")
    loader_rate_cached_u8 = _loader_rate(
        warm_epochs=1, dataset=ds_u8, num_workers=1, cache_ram=True
    )

    # the fused resize+normalize kernel alone: native C++ vs numpy fallback
    arr = np.random.RandomState(1).randint(0, 255, (375, 500, 3), np.uint8)
    mean = np.asarray(cfg.pixel_mean, np.float32)
    std = np.asarray(cfg.pixel_std, np.float32)
    reps = 20

    def _rate(fn):
        fn()  # warm
        t0 = time.time()
        for _ in range(reps):
            fn()
        return reps / (time.time() - t0)

    kernel = {
        "native": (
            _rate(lambda: native_ops.resize_normalize(arr, (600, 600), mean, std))
            if native_ops.native_available()
            else None
        ),
        "numpy": _rate(
            lambda: native_ops._resize_normalize_numpy(arr, (600, 600), mean, std)
        ),
    }

    # write the loader rows NOW — the trainer leg below may touch a
    # wedged TPU tunnel, and a hang there must not lose these
    demand = PER_CHIP_IMG_S * N_CHIPS
    path = os.path.join(REPO, "benchmarks", "loader_throughput.json")

    def _emit(extra):
        out = {
            "single_thread_images_per_sec": round(single_rate, 2),
            "loader_images_per_sec": round(loader_rate, 2),
            "loader_process_mode_images_per_sec": round(loader_rate_mp, 2),
            "loader_process_mode_workers": mp_workers,
            "loader_cached_images_per_sec": round(loader_rate_cached, 2),
            "loader_cached_u8_images_per_sec": round(loader_rate_cached_u8, 2),
            "resize_normalize_native_per_sec": (
                round(kernel["native"], 2) if kernel.get("native") else None
            ),
            "resize_normalize_numpy_per_sec": round(kernel["numpy"], 2),
            "demand_v5e8_images_per_sec": demand,
            "per_chip_images_per_sec": PER_CHIP_IMG_S,
            "workers_needed_for_v5e8": round(demand / max(single_rate, 1e-9), 1),
            "host_cpu_count": os.cpu_count(),
            "n_images": n_images,
            "keeps_up": max(loader_rate, loader_rate_mp) >= demand,
            "keeps_up_one_chip": max(loader_rate, loader_rate_mp)
            >= PER_CHIP_IMG_S,
            "keeps_up_one_chip_cached": loader_rate_cached >= PER_CHIP_IMG_S,
            "notes": "1-core container; neither threads nor fork workers "
            "can exceed the single-core decode rate here — "
            "workers_needed_for_v5e8 is the per-host worker budget "
            "(threads for the GIL-releasing native decode, processes for "
            "Python-bound work) a real v5e-8 host needs",
            **extra,
        }
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
        return out

    _emit({"trainer_loop": "pending"})

    # trainer-loop throughput: real Trainer epochs through the
    # loader + shard_batch/device_put path (NOT pre-staged tensors like
    # bench.py) on the synthetic dataset. Shape adapts to the backend:
    # full 600x600 on TPU, the CPU-feasible 128px otherwise — the JSON
    # records which one ran. TPU liveness is probed in a subprocess first
    # (a wedged tunnel blocks device ops forever); dead -> CPU leg.
    trainer_rec = None
    if os.environ.get("LOADER_BENCH_TRAINER", "1") == "1":
        import jax

        from replication_faster_rcnn_tpu.benchmark import _probe_subprocess
        from replication_faster_rcnn_tpu.config import (
            MeshConfig,
            TrainConfig,
            get_config,
        )
        from replication_faster_rcnn_tpu.data import SyntheticDataset
        from replication_faster_rcnn_tpu.train.trainer import Trainer

        if not _probe_subprocess(120.0):
            # wedged/dead tunnel: no jax backend has been initialized in
            # this process yet (the loader legs are pure numpy), so the
            # CPU switch still takes effect
            jax.config.update("jax_platforms", "cpu")
        on_tpu = jax.default_backend() == "tpu"
        size = (600, 600) if on_tpu else (128, 128)
        batch = 16 if on_tpu else 4
        n_epoch = 3
        # LOADER_BENCH_U8=1: run the fed legs on the uint8/device-normalize
        # path — 4x less host->device bytes per step, the honest
        # counterpart measurement for --device-normalize
        u8_feed = os.environ.get("LOADER_BENCH_U8", "0") == "1"
        tcfg = get_config("voc_resnet18").replace(
            data=DataConfig(
                dataset="synthetic", image_size=size, max_boxes=8,
                device_normalize=u8_feed,
            ),
            train=TrainConfig(batch_size=batch, n_epoch=n_epoch),
            mesh=MeshConfig(num_data=1),
        )
        tds = SyntheticDataset(tcfg.data, "train", length=8 * batch)
        trainer = Trainer(tcfg, workdir="/tmp/loader_bench_trainer", dataset=tds)
        trainer.train_one_batch(  # compile outside the timed window
            next(iter(trainer.loader))
        )
        t0 = time.time()
        seen = 0
        for ep in range(n_epoch):
            trainer.loader.set_epoch(ep)
            for b in trainer.loader:
                jax.block_until_ready(trainer.train_one_batch(b)["loss"])
                seen += batch
        trainer_rec = {
            "images_per_sec": round(seen / (time.time() - t0), 3),
            "backend": jax.default_backend(),
            "image_size": list(size),
            "batch": batch,
            "path": "Trainer.train_one_batch through DataLoader + "
            "shard_batch (host->device each step)",
            "u8_feed": u8_feed,
        }

    # same fed loop with the RAM cache on: epoch 0 fills the cache
    # untimed (the jitted step is already compiled from the leg above —
    # identical shapes), then timed epochs measure what the chip sees
    # when the host serves from memory
    trainer_cached_rec = None
    if trainer_rec is not None and os.environ.get(
        "LOADER_BENCH_TRAINER_CACHE", "1"
    ) == "1":
        import jax  # noqa: F811 — bound above inside the trainer leg

        from replication_faster_rcnn_tpu.data.loader import (
            DataLoader as _DL,
        )

        cached_loader = _DL(
            tds, batch_size=batch, shuffle=True,
            seed=tcfg.train.seed, prefetch=2, num_workers=1,
            cache_ram=True,
        )
        cached_loader.set_epoch(0)
        for b in cached_loader:  # fill the cache, untimed
            pass
        t0 = time.time()
        seen = 0
        for ep in range(1, 1 + n_epoch):
            cached_loader.set_epoch(ep)
            for b in cached_loader:
                jax.block_until_ready(trainer.train_one_batch(b)["loss"])
                seen += batch
        trainer_cached_rec = {
            "images_per_sec": round(seen / (time.time() - t0), 3),
            "backend": jax.default_backend(),
            "image_size": list(size),
            "batch": batch,
            "path": "same fed loop, loader cache_ram steady state",
            "u8_feed": u8_feed,
        }

    # the device-resident feed (data/device_cache.py): dataset uploaded to
    # HBM once, per-step host traffic is the index selection only. The
    # delta vs trainer_loop measures exactly what the per-step
    # host->device image transfer costs the fed loop.
    trainer_devcache_rec = None
    if trainer_rec is not None and os.environ.get(
        "LOADER_BENCH_DEVICE_CACHE", "0"
    ) == "1":
        import dataclasses

        import jax  # noqa: F811 — bound above inside the trainer leg

        dc_cfg = tcfg.replace(
            data=dataclasses.replace(tcfg.data, cache_device=True)
        )
        dc_trainer = Trainer(
            dc_cfg, workdir="/tmp/loader_bench_trainer_dc", dataset=tds
        )
        dc_trainer.train_one_batch(  # compile outside the timed window
            next(iter(dc_trainer.sampler))
        )
        t0 = time.time()
        seen = 0
        for ep in range(n_epoch):
            dc_trainer.sampler.set_epoch(ep)
            for s in dc_trainer.sampler:
                # sync by host transfer, NOT block_until_ready: the remote
                # plugin returns from the latter before execution finishes
                # (benchmark.py's ~100x inflation note), and this leg has
                # no big host->device transfer to mask the early return
                jax.device_get(dc_trainer.train_one_batch(s)["loss"])
                seen += batch
        trainer_devcache_rec = {
            "images_per_sec": round(seen / (time.time() - t0), 3),
            "backend": jax.default_backend(),
            "image_size": list(size),
            "batch": batch,
            "path": "Trainer cache_device: HBM-resident dataset, "
            "index-only feed, gather+augment inside the jitted step",
            "u8_feed": u8_feed,
            "cache_bytes": dc_trainer.device_cache.nbytes,
        }

    out = _emit(
        {
            "trainer_loop": trainer_rec,
            "trainer_loop_cached": trainer_cached_rec,
            "trainer_loop_device_cache": trainer_devcache_rec,
        }
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
