"""2D-mesh profile: (dp, mp) model-parallel memory/collective/throughput gate.

One command measures what the model-axis parameter sharding
(`--mesh-shape DP,MP`; `parallel/zero.py::compose_spec` +
`parallel/plan.py` pjit plans) actually buys on a 2D device mesh, and
fails loudly when the win rots:

* **per-device param bytes** — read from the placed arrays'
  ``addressable_shards`` (what the runtime committed to memory, not what
  a sharding annotation promised), for the replicated dp-only placement
  and the mp-sharded placement of the SAME train state. The gate: the mp
  placement must hold at most ``1/mp + slack`` of the replicated bytes
  per device — the whole point of naming a model axis.
* **collective inventory** — `analysis.fingerprint.
  parse_partitioned_collectives` over both COMPILED step programs (the
  mp exchange is GSPMD-inserted post-partitioning, invisible in lowered
  StableHLO): the mp step must carry model-axis all-gathers (weight
  reassembly), the dp-only step must carry zero model-axis collectives.
  The structural contract also lives in hlolint HX003; repeating it here
  keeps this harness self-contained for off-CI runs.
* **throughput** — images/sec through both compiled steps; the mp number
  is checked against the committed record for the same
  (config, mesh, platform) under ``benchmarks/records/`` exactly like
  benchmarks/scaling_profile.py checks the ZeRO profile:

      python benchmarks/mesh_profile.py            # check
      python benchmarks/mesh_profile.py --update   # re-bank

The memory and collective gates are structural and run on EVERY
invocation (bank or no bank); only the throughput comparison needs a
banked record. Cross-platform comparisons are skipped.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
SCHEMA = "mesh_profile/v1"
DEFAULT_TOL = 0.15

# per-device mp param bytes may exceed the ideal replicated/mp by this
# relative slack (leaves with no dimension divisible by mp stay
# replicated — scalars, odd-shaped biases) before the memory gate fails
PARAM_BYTES_SLACK = 0.5

GATE_KEY = "images_per_sec_mp"


# ---------------------------------------------------------------------------
# pure record logic (no jax): unit-testable without placing anything


def record_key(config_token: str, platform: str, dp: int, mp: int) -> str:
    """Identity of a banked record. The mesh shape is part of the
    identity because the sharding factor IS the measurement."""
    return f"{config_token}_{platform}_mesh{dp}x{mp}"


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"mesh_profile_{key}.json")


def check_structural(record, slack: float = PARAM_BYTES_SLACK):
    """The bank-free gates: per-device param-memory reduction and the
    model-axis collective inventory.

    Returns a list of human-readable failures (empty = pass)."""
    failures = []
    mp = int(record.get("mesh_mp", 1))
    repl = float(record.get("param_bytes_per_device_replicated", 0))
    shrd = float(record.get("param_bytes_per_device_mp", 0))
    if repl <= 0 or shrd <= 0:
        failures.append("param byte measurement missing or zero")
        return failures
    frac = shrd / repl
    ceiling = (1.0 / mp) * (1.0 + slack)
    if frac > ceiling:
        failures.append(
            f"per-device params not sharded: mp placement holds {frac:.1%} "
            f"of the replicated bytes (ceiling {ceiling:.1%} = 1/{mp} "
            f"+ {slack:.0%} slack) — the model-axis split is gone"
        )

    def _model_ops(inventory):
        return {
            kind: entry.get("axes", {}).get("model", 0)
            for kind, entry in (inventory or {}).items()
            if entry.get("axes", {}).get("model", 0)
        }

    mp_ops = _model_ops(record.get("collectives_mp"))
    if not mp_ops.get("all-gather"):
        failures.append(
            "mp step compiled without model-axis all-gathers — GSPMD "
            f"emitted no weight exchange (model-axis ops: {mp_ops or 'none'})"
        )
    dp_ops = _model_ops(record.get("collectives_dp"))
    if dp_ops:
        failures.append(
            f"dp-only step emits model-axis collectives {dp_ops} — the "
            "baseline is supposed to leave the model axis idle"
        )
    return failures


def check_regression(current, banked, tol: float = DEFAULT_TOL):
    """Throughput comparison against the banked record.

    Returns (failures, warnings)."""
    failures, warnings = [], []
    if banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, "
            f"expected {SCHEMA!r}; skipping comparison"
        )
        return failures, warnings
    for key in (GATE_KEY, "images_per_sec_dp"):
        old = banked.get(key)
        new = current.get(key)
        if not old or not new:
            continue
        drop = 1.0 - new / old
        if drop > tol:
            failures.append(
                f"{key} regressed {drop:+.1%}: {new:.3f} vs banked "
                f"{old:.3f} (tolerance {tol:.0%})"
            )
        elif drop > tol / 2:
            warnings.append(
                f"{key} within tolerance but slipping {drop:+.1%}: "
                f"{new:.3f} vs banked {old:.3f}"
            )
    old_frac = banked.get("param_bytes_frac")
    new_frac = current.get("param_bytes_frac")
    if old_frac and new_frac and new_frac > old_frac * (1.0 + tol):
        failures.append(
            f"param_bytes_frac grew: {new_frac:.4f} vs banked {old_frac:.4f} "
            "— the mp placement is holding more than it used to"
        )
    return failures, warnings


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# measurement


def _per_device_bytes(tree) -> int:
    """Bytes the FIRST local device holds for a placed pytree — summed
    over leaves from ``addressable_shards`` (committed layout, including
    any replicated leaves the sharder left whole)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = [s for s in leaf.addressable_shards if s.index is not None]
        first = min(shards, key=lambda s: s.device.id)
        total += first.data.nbytes
    return total


def profile(cfg_mp, config_token: str, n_steps: int = 5):
    """Measure one config's 2D-mesh profile; returns the record dict.

    ``cfg_mp`` must be an auto-backend config with
    ``mesh.param_sharding`` on and ``mesh.num_model > 1``; the dp-only
    baseline is derived by flattening the mesh onto the data axis so both
    placements price the same model/optimizer."""
    import copy
    import dataclasses

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu import parallel
    from replication_faster_rcnn_tpu.analysis.fingerprint import (
        parse_partitioned_collectives,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.parallel import zero as pzero
    from replication_faster_rcnn_tpu.parallel.plan import (
        Plan,
        compile_step_with_plan,
    )
    from replication_faster_rcnn_tpu.train.train_step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    dp = cfg_mp.mesh.num_data
    mp = cfg_mp.mesh.num_model
    cfg_dp = cfg_mp.replace(
        mesh=dataclasses.replace(
            cfg_mp.mesh, num_data=dp * mp, num_model=1, param_sharding=False
        )
    )

    mesh_mp = parallel.make_mesh(cfg_mp.mesh)
    mesh_dp = parallel.make_mesh(cfg_dp.mesh)
    tx, _ = make_optimizer(cfg_mp, steps_per_epoch=100)
    model, state = create_train_state(cfg_mp, jax.random.PRNGKey(0), tx)
    host_state = jax.device_get(state)

    sh_mp = pzero.train_state_shardings(
        state, mesh_mp, cfg_mp.mesh, cfg_mp.train.shard_opt_state
    )
    sh_dp = pzero.train_state_shardings(state, mesh_dp, cfg_dp.mesh, False)
    # independent host copies: both placements get private buffers, so the
    # donating steps can't invalidate each other's state mid-measurement
    state_mp = pzero.place_train_state(copy.deepcopy(host_state), sh_mp)
    state_dp = pzero.place_train_state(copy.deepcopy(host_state), sh_dp)

    bytes_mp = _per_device_bytes(state_mp.params)
    bytes_dp = _per_device_bytes(state_dp.params)

    step_mp = compile_step_with_plan(
        make_train_step(model, cfg_mp, tx),
        Plan(mesh=mesh_mp, donate_argnums=(0,), out_shardings=(sh_mp, None)),
    )
    step_dp = compile_step_with_plan(
        make_train_step(model, cfg_dp, tx),
        Plan(mesh=mesh_dp, donate_argnums=(0,), out_shardings=(sh_dp, None)),
    )

    batch_size = cfg_mp.train.batch_size
    ds = SyntheticDataset(cfg_mp.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])

    def staged(mesh, mesh_cfg):
        return parallel.shard_batch(
            {k: np.array(v) for k, v in batch.items()}, mesh, mesh_cfg
        )

    coll = {}
    for name, step, st, mesh, mesh_cfg in (
        ("mp", step_mp, state_mp, mesh_mp, cfg_mp.mesh),
        ("dp", step_dp, state_dp, mesh_dp, cfg_dp.mesh),
    ):
        compiled = step.lower(st, staged(mesh, mesh_cfg)).compile()
        try:
            text = compiled.as_text()
        except Exception:  # pragma: no cover - some backends hide HLO text
            text = ""
        coll[name] = parse_partitioned_collectives(text, dict(mesh.shape))

    def timed(step, st, mesh, mesh_cfg):
        # donation consumes the placed state every dispatch; threading the
        # returned state through mirrors the trainer's loop
        st, metrics = step(st, staged(mesh, mesh_cfg))  # compile + stabilize
        jax.device_get(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(n_steps):
            st, metrics = step(st, staged(mesh, mesh_cfg))
        jax.device_get(metrics["loss"])
        wall = time.perf_counter() - t0
        return st, batch_size * n_steps / wall, wall / n_steps * 1e3

    state_mp, ips_mp, ms_mp = timed(step_mp, state_mp, mesh_mp, cfg_mp.mesh)
    state_dp, ips_dp, ms_dp = timed(step_dp, state_dp, mesh_dp, cfg_dp.mesh)

    dev = jax.devices()[0]
    return {
        "schema": SCHEMA,
        "config": config_token,
        "backend": cfg_mp.train.backend,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "n_dev": jax.device_count(),
        "mesh_dp": int(dp),
        "mesh_mp": int(mp),
        "batch_size": batch_size,
        "image_size": list(cfg_mp.data.image_size),
        "n_steps_timed": n_steps,
        "param_bytes_per_device_replicated": int(bytes_dp),
        "param_bytes_per_device_mp": int(bytes_mp),
        "param_bytes_frac": round(bytes_mp / bytes_dp, 6) if bytes_dp else None,
        "param_bytes_ideal_frac": round(1.0 / mp, 6),
        "collectives_mp": coll["mp"],
        "collectives_dp": coll["dp"],
        "step_ms_mp": round(ms_mp, 3),
        "step_ms_dp": round(ms_dp, 3),
        "images_per_sec_mp": round(ips_mp, 3),
        "images_per_sec_dp": round(ips_dp, 3),
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


# ---------------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument(
        "--mesh-shape",
        default="2,4",
        metavar="DP,MP",
        help="2D device mesh: DP-way data x MP-way model parallelism",
    )
    p.add_argument(
        "--devices",
        type=int,
        default=8,
        help="host-platform device count to force when jax is not yet "
        "imported and no accelerator is attached (CPU CI)",
    )
    p.add_argument("--steps", type=int, default=5, help="timed dispatches")
    p.add_argument(
        "--update", action="store_true", help="write/overwrite the banked record"
    )
    p.add_argument(
        "--no-check", action="store_true", help="measure + print only"
    )
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--slack", type=float, default=PARAM_BYTES_SLACK)
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    try:
        dp, mp = (int(t) for t in args.mesh_shape.split(","))
    except ValueError:
        raise SystemExit(
            f"--mesh-shape expects 'DP,MP', got {args.mesh_shape!r}"
        )
    if mp < 2:
        raise SystemExit("--mesh-shape needs MP >= 2 (nothing to measure)")

    if args.devices > 1 and "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={args.devices}"
            ).strip()

    import dataclasses

    from benchmarks.step_profile import tiny_config
    from replication_faster_rcnn_tpu.config import MeshConfig

    cfg = tiny_config(
        batch_size=args.batch_size, image_size=args.image_size, backend="auto"
    )
    cfg = cfg.replace(
        mesh=MeshConfig(num_data=dp, num_model=mp, param_sharding=True)
    )
    token = f"tiny{args.image_size}b{args.batch_size}"

    record = profile(cfg, token, n_steps=args.steps)
    key = record_key(token, record["platform"], dp, mp)
    path = record_path(key, args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    structural = check_structural(record, slack=args.slack)
    for f in structural:
        print(f"mesh_profile: FAIL {f}", file=sys.stderr)
    if structural:
        return 1

    if args.update:
        save_record(record, path)
        print(f"mesh_profile: banked {path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    if not os.path.exists(path):
        print(
            f"mesh_profile: no banked record at {path} — run with "
            "--update to create one (not checking)",
            file=sys.stderr,
        )
        return 0
    failures, warnings = check_regression(record, load_record(path), tol=args.tol)
    for w in warnings:
        print(f"mesh_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"mesh_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"mesh_profile: REGRESSION vs {path} — if intentional, "
            "re-bank with --update",
            file=sys.stderr,
        )
        return 1
    print(f"mesh_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
