"""Static cost attribution of the train step (VERDICT r3 #2, CPU half).

The on-chip breakdown now splits backward_ms vs opt_update_ms (
`benchmark.py::_stage_breakdown`); this script supplies the structural
side that needs no chip: XLA HloCostAnalysis FLOPs and bytes-accessed of
three nested programs at the flagship operating point —

    forward   = losses only                  (what _stage_breakdown's
                                              forward_fn times)
    grad      = value_and_grad + grad_norm   (grad_fn)
    step      = grad + Adam update           (the real train step)

Successive differences attribute backward FLOPs (grad − forward) and
optimizer FLOPs (step − grad), and the bytes-accessed deltas bound the
HBM traffic each phase moves — enough to say, before any trace lands,
whether the measured 40.7 ms b16 backward+update lump is compute-bound
(FLOPs/peak) or bandwidth-bound (bytes/BW). Abstract lowering only: no
arrays are allocated, nothing compiles, safe on any backend host (the
analysis itself forces the CPU backend, the same discipline as
`benchmark.py::_step_flops`).

Reference: `/root/reference/train.py:126-127` (`total_loss.backward()` +
`optimizer.step()` — the lump being attributed).

Writes benchmarks/backward_analysis.json.
"""

from __future__ import annotations

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# v5e single-chip roofline constants (same source as benchmark.py's MFU)
V5E_PEAK_BF16_FLOPS = 197e12
V5E_HBM_GBPS = 819e9


def main() -> None:
    import dataclasses

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import optax

    from replication_faster_rcnn_tpu.benchmark import (
        abstract_step_inputs,
        lowered_cost,
    )
    from replication_faster_rcnn_tpu.config import get_config
    from replication_faster_rcnn_tpu.train import (
        make_optimizer,
        make_train_step,
    )
    from replication_faster_rcnn_tpu.train.train_step import compute_losses

    batch_size = int(os.environ.get("BA_BATCH", "16"))
    cfg = get_config(os.environ.get("BA_CONFIG", "voc_resnet18"))
    # the same abstract fixture the bench's FLOPs counter uses, at the
    # requested batch (dataset field irrelevant: only shapes are read)
    cfg = cfg.replace(
        train=dataclasses.replace(cfg.train, batch_size=batch_size)
    )

    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state_abs, batch_abs = abstract_step_inputs(cfg, tx)

    def forward(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)
        total, _ = compute_losses(
            model, cfg, state.params, state.batch_stats, batch, rng, True
        )
        return total

    def grad(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            return compute_losses(
                model, cfg, params, state.batch_stats, batch, rng, True
            )

        (total, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        return total + optax.global_norm(grads)

    step = make_train_step(model, cfg, tx)

    fwd = lowered_cost(forward, state_abs, batch_abs)
    grd = lowered_cost(grad, state_abs, batch_abs)
    stp = lowered_cost(step, state_abs, batch_abs)

    n_params = sum(
        int(np.prod(lf.shape))
        for lf in jax.tree_util.tree_leaves(state_abs.params)
    )

    def _phase(name, flops, bytes_):
        return {
            "phase": name,
            "flops": flops,
            # pre-fusion HLO operand+result bytes: every op counted as if
            # it round-tripped HBM. Real post-fusion traffic is far lower
            # (an UPPER BOUND, kept only to compare phases structurally)
            "hlo_bytes_upper_bound": bytes_,
            "v5e_compute_floor_ms": round(
                flops / V5E_PEAK_BF16_FLOPS * 1e3, 3
            ),
        }

    phases = [
        _phase("forward_loss", fwd["flops"], fwd["bytes_accessed"]),
        _phase(
            "backward (grad - forward)",
            grd["flops"] - fwd["flops"],
            grd["bytes_accessed"] - fwd["bytes_accessed"],
        ),
        _phase(
            "optimizer_update (step - grad)",
            stp["flops"] - grd["flops"],
            stp["bytes_accessed"] - grd["bytes_accessed"],
        ),
        _phase("full_step", stp["flops"], stp["bytes_accessed"]),
    ]

    # the optimizer update's REAL traffic is computable from first
    # principles (it is purely elementwise over the param-shaped trees):
    # read grad+param+mu+nu, write param+mu+nu = 7 f32 passes; bf16 mu
    # (--mu-dtype bfloat16) halves the two mu passes
    adam_bytes_f32 = n_params * 7 * 4
    adam_bytes_bf16mu = n_params * (5 * 4 + 2 * 2)
    optimizer_analytic = {
        "adam_hbm_bytes_f32": adam_bytes_f32,
        "adam_hbm_bytes_bf16_mu": adam_bytes_bf16mu,
        "v5e_memory_floor_ms_f32": round(
            adam_bytes_f32 / V5E_HBM_GBPS * 1e3, 3
        ),
        "v5e_memory_floor_ms_bf16_mu": round(
            adam_bytes_bf16mu / V5E_HBM_GBPS * 1e3, 3
        ),
        "reading": "if the measured opt_update_ms is far above this "
        "floor, the update is fusion/launch-bound, not bandwidth-bound, "
        "and bf16-mu's ~14% traffic cut will not show; at the floor, it "
        "will",
    }

    out = {
        "config": cfg.name if hasattr(cfg, "name") else "voc_resnet18",
        "batch_size": batch_size,
        "image_size": list(cfg.data.image_size),
        "n_params": n_params,
        "phases": phases,
        "backward_over_forward_flops": round(
            (grd["flops"] - fwd["flops"]) / fwd["flops"], 3
        ),
        "optimizer_analytic": optimizer_analytic,
        "note": "HloCostAnalysis on the abstract CPU lowering — model "
        "FLOPs, not a measurement; compute floors assume v5e-1 peak "
        "197 TFLOP/s bf16. hlo_bytes are pre-fusion upper bounds. Pair "
        "with the on-chip breakdown's backward_ms/opt_update_ms once "
        "measured: step compute floor vs the measured step time bounds "
        "achievable MFU headroom.",
    }
    path = os.path.join(REPO, "benchmarks", "backward_analysis.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
