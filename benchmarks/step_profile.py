"""Step-profile regression harness: per-phase time + cost records, banked.

One command measures a config's train step, attributes wall time to the
pipeline phases (``dispatch`` floor, ``fwd``, ``bwd``, ``update``) via
the telemetry span tracer (`telemetry/spans.py`), attaches the analytic
per-phase FLOPs/bytes from XLA's HloCostAnalysis of the same lowered
programs (`benchmark.lowered_cost`), computes MFU against the measured
host peak (`telemetry/mfu.py`), and checks the result against the
committed record for the same (config, backend, platform) under
``benchmarks/records/``:

    python benchmarks/step_profile.py --preset tiny            # check
    python benchmarks/step_profile.py --preset tiny --update   # re-bank

A run whose throughput lands >15% below the banked value on the SAME
backend+platform exits nonzero with a loud report — a perf regression
fails like a test failure instead of rotting silently in a JSON nobody
rereads. Cross-platform comparisons are skipped (a CPU run can never
"regress" a TPU record). ``benchmarks/bank_records.py`` stays the home
of raw throughput history; this file owns the per-phase shape of a step.

Why spans and not bare ``time.time()``: the trainer's own hot loop is
instrumented with the same tracer (``step/dispatch``, ``step/sync``), so
profiling through spans keeps one timing vocabulary across the trainer,
the telemetry report CLI, and this harness — the record's ``spans``
table is exactly `telemetry.report.phase_table` output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

RECORDS_DIR = os.path.join(_REPO, "benchmarks", "records")
SCHEMA = "step_profile/v1"
OPS_SCHEMA = "ops_profile/v1"
DEFAULT_TOL = 0.15

# throughput is the hard gate; phase means on a shared CPU jitter well
# past 15%, so per-phase regressions are reported but only fail under
# --strict-phases
GATE_KEY = "images_per_sec"

# on-device augmentation gate (ISSUE 19): host staging must stay flat
# (≤1.1×) as the augment op count scales 0→3 — the transforms run inside
# the jitted step, so their cost lands in device dispatch, never on the
# host feed thread. The absolute floor keeps a sub-ms CPU staging
# baseline from turning quotient-of-noise into a failure.
AUGMENT_STAGE_TOL = 0.10
AUGMENT_STAGE_FLOOR_MS = 0.3


# ---------------------------------------------------------------------------
# pure record logic (no jax): unit-testable without timing anything


def record_key(config_token: str, backend: str, platform: str, k: int = 1) -> str:
    """Identity of a banked record: what must match for a comparison to
    be meaningful. ``k`` is train.steps_per_dispatch — a fused-dispatch
    profile is a different record, not a regression of the k=1 one."""
    token = f"{config_token}_{backend}_{platform}"
    if k > 1:
        token += f"_k{k}"
    return token


def record_path(key: str, records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(records_dir, f"step_profile_{key}.json")


def check_regression(current, banked, tol: float = DEFAULT_TOL,
                     strict_phases: bool = False):
    """Compare a fresh profile against its banked record.

    Returns (failures, warnings): lists of human-readable strings. A
    failure means the harness must exit nonzero. Only records with the
    same key are comparable — the caller guarantees that by construction
    (the banked record is looked up BY key)."""
    failures, warnings = [], []
    if banked.get("schema") != SCHEMA:
        warnings.append(
            f"banked record has schema {banked.get('schema')!r}, "
            f"expected {SCHEMA!r}; skipping comparison"
        )
        return failures, warnings

    old = banked.get(GATE_KEY)
    new = current.get(GATE_KEY)
    if old and new:
        drop = 1.0 - new / old
        if drop > tol:
            failures.append(
                f"{GATE_KEY} regressed {drop:+.1%}: {new:.3f} vs banked "
                f"{old:.3f} (tolerance {tol:.0%})"
            )
        elif drop > tol / 2:
            warnings.append(
                f"{GATE_KEY} within tolerance but slipping {drop:+.1%}: "
                f"{new:.3f} vs banked {old:.3f}"
            )
    # overlap gate (PR 4): the double-buffered device feed must keep
    # hiding staging behind dispatch. overlap_fraction is "how much of the
    # synchronous staging cost the stager hid" — a >tol relative drop means
    # the producer thread stopped overlapping and fails like a throughput
    # regression. Only enforced when the banked fraction is substantial:
    # where staging is a millisecond or two (CPU feeds), the fraction is
    # quotient-of-noise and a relative rule would flap. Records from
    # before the overlap section skip the check entirely.
    old_ov = ((banked.get("overlap") or {}).get("overlap_fraction"))
    new_ov = ((current.get("overlap") or {}).get("overlap_fraction"))
    if old_ov and old_ov >= 0.3 and new_ov is not None:
        ov_drop = 1.0 - new_ov / old_ov
        if ov_drop > tol:
            failures.append(
                f"overlap_fraction regressed {ov_drop:+.1%}: {new_ov:.3f} vs "
                f"banked {old_ov:.3f} (tolerance {tol:.0%})"
            )
    # absolute arm of the same gate — the acceptance number itself: feed
    # time paid on the dispatch thread must stay under 10% of dispatch
    # wall (with tol headroom over the banked value for noisy hosts)
    old_frac = ((banked.get("overlap") or {}).get("host_blocked_frac_of_dispatch"))
    new_frac = ((current.get("overlap") or {}).get("host_blocked_frac_of_dispatch"))
    if old_frac is not None and new_frac is not None:
        ceiling = max(old_frac * (1.0 + tol), 0.10)
        if new_frac > ceiling:
            failures.append(
                f"host_blocked_frac_of_dispatch {new_frac:.3f} exceeds "
                f"{ceiling:.3f} (banked {old_frac:.3f} + {tol:.0%}, floor 0.10)"
            )
    for phase, row in (banked.get("phases") or {}).items():
        old_ms = (row or {}).get("mean_ms")
        new_ms = ((current.get("phases") or {}).get(phase) or {}).get("mean_ms")
        if not old_ms or not new_ms:
            continue
        growth = new_ms / old_ms - 1.0
        if growth > tol:
            msg = (
                f"phase {phase!r} slowed {growth:+.1%}: {new_ms:.2f} ms vs "
                f"banked {old_ms:.2f} ms"
            )
            (failures if strict_phases else warnings).append(msg)
    # augmentation flatness gate (ISSUE 19). Two arms: the in-run one
    # (every level's host stage vs this run's own 0-op baseline) is the
    # acceptance number itself; the vs-banked one catches a slow creep
    # where every level degrades together. Records banked before the
    # augment section simply skip the second arm.
    aug_levels = (current.get("augment") or {}).get("levels") or []
    if len(aug_levels) >= 2:
        base_ms = aug_levels[0].get("host_stage_ms") or 0.0
        worst = max(lv.get("host_stage_ms") or 0.0 for lv in aug_levels)
        ceiling = (
            base_ms * (1.0 + AUGMENT_STAGE_TOL) + AUGMENT_STAGE_FLOOR_MS
        )
        if worst > ceiling:
            failures.append(
                f"augment host_stage_ms not flat: worst level {worst:.3f} ms"
                f" vs 0-op baseline {base_ms:.3f} ms (ceiling {ceiling:.3f}"
                f" = baseline × {1.0 + AUGMENT_STAGE_TOL:.2f} + "
                f"{AUGMENT_STAGE_FLOOR_MS} ms floor)"
            )
        banked_levels = (banked.get("augment") or {}).get("levels") or []
        if banked_levels:
            old_worst = max(
                lv.get("host_stage_ms") or 0.0 for lv in banked_levels
            )
            b_ceiling = (
                old_worst * (1.0 + AUGMENT_STAGE_TOL) + AUGMENT_STAGE_FLOOR_MS
            )
            if worst > b_ceiling:
                failures.append(
                    f"augment host_stage_ms {worst:.3f} exceeds banked worst "
                    f"{old_worst:.3f} × {1.0 + AUGMENT_STAGE_TOL:.2f} + "
                    f"{AUGMENT_STAGE_FLOOR_MS} ms floor ({b_ceiling:.3f})"
                )
    return failures, warnings


def load_record(path: str):
    with open(path) as f:
        return json.load(f)


def save_record(record, path: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# measurement


def tiny_config(batch_size: int = 2, image_size: int = 64, backend: str = "auto",
                steps_per_dispatch: int = 1):
    """The trimmed-budget profile config: same shape family the fast test
    tier compiles (64x64 synthetic, pre_nms 128 / post_nms 32 / n_sample
    8), so a committed CPU record prices the exact graphs CI exercises."""
    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        FasterRCNNConfig,
        MeshConfig,
        ModelConfig,
        ProposalConfig,
        ROITargetConfig,
        TrainConfig,
    )

    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic", image_size=(image_size, image_size), max_boxes=8
        ),
        train=TrainConfig(
            batch_size=batch_size,
            n_epoch=4,
            backend=backend,
            steps_per_dispatch=steps_per_dispatch,
        ),
        mesh=MeshConfig(num_data=1),
        proposals=ProposalConfig(pre_nms_train=128, post_nms_train=32),
        roi_targets=ROITargetConfig(n_sample=8),
    )


def _phase_fns(model, cfg, tx):
    """The four jitted phase programs. fwd/grad mirror the bench's stage
    prefixes (`benchmark._stage_breakdown`) so the two harnesses can never
    attribute different pipelines; update/null run on materialized grads."""
    import jax
    import jax.numpy as jnp
    import optax

    from replication_faster_rcnn_tpu.train.train_step import compute_losses

    @jax.jit
    def fwd_fn(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)
        total, _ = compute_losses(
            model, cfg, state.params, state.batch_stats, batch, rng, True
        )
        return total

    @jax.jit
    def grad_fn(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            return compute_losses(
                model, cfg, params, state.batch_stats, batch, rng, True
            )

        (total, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        return total + optax.global_norm(grads)

    @jax.jit
    def update_fn(state, grads):
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        return optax.apply_updates(state.params, updates), opt_state

    @jax.jit
    def null_fn(state, grads):
        # dispatch + completion-sync floor: same inputs, near-empty program
        return jax.tree_util.tree_leaves(grads)[0].ravel()[0] + jnp.float32(
            state.step
        )

    return fwd_fn, grad_fn, update_fn, null_fn


def _measure_overlap(step, state, batch, n_dispatches: int = 8,
                     prefetch_depth: int = 2):
    """Host-blocked time per dispatch, with and without the device stager.

    Two loops over identical host batches through the SAME compiled step:

    * synchronous — collate copy + ``device_put`` + wait on the consumer
      thread before every dispatch (the pre-PR-4 feed), giving
      ``host_stage_ms``;
    * overlapped — a :class:`DevicePrefetcher` producer thread stages
      batch K+1 while dispatch K runs; the consumer's only feed cost is
      the queue wait, giving ``host_blocked_ms``.

    ``overlap_fraction`` = share of the synchronous staging cost the
    stager hid; ``host_blocked_frac_of_dispatch`` is the acceptance
    number (host-blocked time as a fraction of dispatch wall)."""
    import jax
    import numpy as np

    from replication_faster_rcnn_tpu.data.prefetch_device import (
        DevicePrefetcher,
    )

    feed = [batch for _ in range(n_dispatches)]
    wait_transfer = jax.default_backend() != "cpu"

    def stage(bs):
        # the trainer's feed work per dispatch: the collate/stack host
        # copy (fresh arrays — an already-resident buffer would
        # short-circuit the transfer) plus the device_put. Only off-CPU
        # do we wait for the transfer itself: XLA:CPU retires transfer
        # completion on the compute stream, so block_until_ready there
        # measures whatever dispatches are in flight, not the feed.
        collated = {key: np.array(v) for key, v in bs[0].items()}
        staged = jax.device_put(collated)
        if wait_transfer:
            for leaf in jax.tree_util.tree_leaves(staged):
                leaf.block_until_ready()
        return staged

    def drain(out):
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])

    # synchronous baseline
    stage_s = 0.0
    out = None
    t_wall = time.perf_counter()
    for b in feed:
        t0 = time.perf_counter()
        staged = stage([b])
        stage_s += time.perf_counter() - t0
        out = step(state, staged)
    drain(out)
    sync_wall_s = time.perf_counter() - t_wall

    # overlapped: consumer pays only the queue wait
    stager = DevicePrefetcher(
        iter(feed), stage, depth=prefetch_depth, chunk=1
    )
    blocked_s = 0.0
    out = None
    t_wall = time.perf_counter()
    try:
        while True:
            t0 = time.perf_counter()
            try:
                item = next(stager)
            except StopIteration:
                break
            blocked_s += time.perf_counter() - t0
            out = step(state, item[1])
    finally:
        stager.close()
    drain(out)
    overlap_wall_s = time.perf_counter() - t_wall

    n = float(n_dispatches)
    host_stage_ms = stage_s / n * 1e3
    host_blocked_ms = blocked_s / n * 1e3
    dispatch_wall_ms = overlap_wall_s / n * 1e3
    overlap_fraction = (
        max(0.0, 1.0 - host_blocked_ms / host_stage_ms)
        if host_stage_ms > 0 else None
    )
    return {
        "prefetch_depth": prefetch_depth,
        "n_dispatches": n_dispatches,
        "host_stage_ms": round(host_stage_ms, 3),
        "host_blocked_ms": round(host_blocked_ms, 3),
        "overlap_fraction": (
            round(overlap_fraction, 4) if overlap_fraction is not None else None
        ),
        "sync_wall_ms": round(sync_wall_s / n * 1e3, 3),
        "dispatch_wall_ms": round(dispatch_wall_ms, 3),
        "host_blocked_frac_of_dispatch": (
            round(host_blocked_ms / dispatch_wall_ms, 4)
            if dispatch_wall_ms > 0 else None
        ),
    }


def _measure_augment(cfg, n_dispatches: int = 12, n_steps: int = 5):
    """Host-stage flatness as on-device augmentation ops scale 0→3.

    With ``data.augment_device`` the host loader ships pixels untouched
    plus a 2-int32 ``aug`` tag per row; every transform (hflip, scale
    jitter, translation jitter) runs inside the jitted train step. So
    the host staging cost — the same collate copy + device_put the
    trainer pays per dispatch — must stay FLAT as the op count grows,
    and the augmentation milliseconds must show up in the device step
    wall instead. One level per op count, each compiling the step that
    traces exactly that level's transforms."""
    import dataclasses

    import jax
    import numpy as np

    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.train.train_step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    batch_size = cfg.train.batch_size
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)
    ds = SyntheticDataset(cfg.data, length=batch_size)
    base = collate([ds[i] for i in range(batch_size)])
    # the loader's AugmentTagView tag: (dataset idx, epoch) per row
    aug_tag = np.stack(
        [np.asarray([i, 0], np.int32) for i in range(batch_size)]
    )

    LEVELS = (
        (),
        ("hflip",),
        ("hflip", "scale"),
        ("hflip", "scale", "translate"),
    )
    wait_transfer = jax.default_backend() != "cpu"
    levels = []
    for ops_on in LEVELS:
        dcfg = dataclasses.replace(
            cfg.data,
            augment_device=bool(ops_on),
            augment_hflip="hflip" in ops_on,
            augment_scale=((0.75, 1.25) if "scale" in ops_on else None),
            augment_translate=(0.1 if "translate" in ops_on else 0.0),
        )
        vcfg = cfg.replace(data=dcfg)
        batch = dict(base)
        if ops_on:
            batch["aug"] = aug_tag
        step = jax.jit(make_train_step(model, vcfg, tx))

        # the trainer's per-dispatch feed work (same stage as
        # _measure_overlap): fresh collate copy + device_put. Median, not
        # mean — a single scheduler hiccup must not fake a slope.
        stage_ms = []
        staged = None
        for _ in range(n_dispatches):
            t0 = time.perf_counter()
            collated = {key: np.array(v) for key, v in batch.items()}
            staged = jax.device_put(collated)
            if wait_transfer:
                for leaf in jax.tree_util.tree_leaves(staged):
                    leaf.block_until_ready()
            stage_ms.append((time.perf_counter() - t0) * 1e3)

        out = step(state, staged)  # compile + stabilize
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        t0 = time.perf_counter()
        for _ in range(n_steps):
            out = step(state, staged)
            jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        step_ms = (time.perf_counter() - t0) / n_steps * 1e3

        levels.append({
            "n_ops": len(ops_on),
            "ops": list(ops_on),
            "host_stage_ms": round(float(np.median(stage_ms)), 3),
            "step_ms": round(step_ms, 3),
        })

    base_stage = levels[0]["host_stage_ms"]
    ratio = (
        max(lv["host_stage_ms"] / base_stage for lv in levels)
        if base_stage > 0
        else None
    )
    return {
        "levels": levels,
        "host_stage_ratio_max": (
            round(ratio, 4) if ratio is not None else None
        ),
        # the transforms' cost, attributed where it belongs: the device
        # step wall of the 3-op level over the 0-op level (raw — small
        # negatives are CPU timing noise, not a speedup claim)
        "device_augment_ms": round(
            levels[-1]["step_ms"] - levels[0]["step_ms"], 3
        ),
    }


def _measure_async_save(step, state, batch_staged, n_saves: int = 3):
    """Trainer-side checkpoint cost, synchronous vs background writer.

    The "save" is the manifest half of the real pipeline (host snapshot +
    per-leaf CRC + atomic manifest rename via ``fault.write_manifest`` —
    the same function the trainer's writer runs); orbax serialization is
    skipped to keep the harness's disk footprint tiny, so these numbers
    are a floor on the real win, not the whole of it. ``save_blocked_ms``
    is what the trainer pays per scheduled save with the writer on: the
    device_get snapshot plus the submit (a dispatch runs between saves,
    so the previous write has compute to hide behind, as in training)."""
    import shutil
    import tempfile

    import jax

    from replication_faster_rcnn_tpu.train import fault
    from replication_faster_rcnn_tpu.train.async_checkpoint import (
        AsyncCheckpointWriter,
    )

    tmp = tempfile.mkdtemp(prefix="step_profile_ckpt_")
    try:
        def work(i, host):
            fault.write_manifest(
                tmp, i, host, None, kind="scheduled", writer="profile"
            )

        def run_between_saves():
            # the dispatches that separate two checkpoint boundaries in a
            # real run — drained, so each timed save starts from the same
            # quiescent point and a background write has the same compute
            # wall to hide behind that it gets in training
            out = step(state, batch_staged)
            jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])

        sync_s = 0.0
        for i in range(n_saves):
            run_between_saves()
            t0 = time.perf_counter()
            work(i, jax.device_get(state))
            sync_s += time.perf_counter() - t0

        writer = AsyncCheckpointWriter()
        blocked_s = 0.0
        for i in range(n_saves):
            run_between_saves()
            t0 = time.perf_counter()
            host = jax.device_get(state)
            writer.submit(100 + i, lambda i=i, h=host: work(100 + i, h))
            blocked_s += time.perf_counter() - t0
        writer.wait()
        return {
            "n_saves": n_saves,
            "save_sync_ms": round(sync_s / n_saves * 1e3, 3),
            "save_blocked_ms": round(blocked_s / n_saves * 1e3, 3),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def profile(cfg, config_token: str, n_steps: int = 5):
    """Measure one config's step profile; returns the record dict."""
    import jax
    import numpy as np  # noqa: F401 — keeps parity with bench imports

    from replication_faster_rcnn_tpu.benchmark import (
        abstract_step_inputs,
        lowered_cost,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.telemetry.mfu import (
        compute_mfu,
        peak_flops_per_sec,
    )
    from replication_faster_rcnn_tpu.telemetry.report import phase_table
    from replication_faster_rcnn_tpu.telemetry.spans import SpanTracer
    from replication_faster_rcnn_tpu.train.train_step import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    batch_size = cfg.train.batch_size
    k = max(1, cfg.train.steps_per_dispatch)
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)
    ds = SyntheticDataset(cfg.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])

    step = make_train_step(model, cfg, tx)
    if k > 1:
        from replication_faster_rcnn_tpu.train.train_step import build_multi_step

        step = build_multi_step(step, k)
        batch = {key: np.stack([v] * k) for key, v in batch.items()}
    step = jax.jit(step)

    fwd_fn, grad_fn, update_fn, null_fn = _phase_fns(model, cfg, tx)
    phase_batch = collate([ds[i] for i in range(batch_size)])

    # materialized grads for the update/null programs: one grad_fn's worth
    # of real values, shaped like params
    grads = jax.tree_util.tree_map(lambda p: jax.numpy.ones_like(p), state.params)

    tracer = SpanTracer()

    def timed(name, fn, *args):
        for _ in range(2):  # compile + stabilize, outside any span
            out = fn(*args)
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        for _ in range(n_steps):
            with tracer.span(f"profile/{name}", cat="profile"):
                out = fn(*args)
                jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])

    timed("dispatch", null_fn, state, grads)
    timed("fwd", fwd_fn, state, phase_batch)
    timed("grad", grad_fn, state, phase_batch)
    timed("update", update_fn, state, grads)
    timed("step", step, state, batch)

    table = {row["name"]: row for row in phase_table(tracer.to_dict()["traceEvents"])}

    def mean_ms(name):
        row = table.get(f"profile/{name}")
        return float(row["mean_ms"]) if row else None

    dispatch_ms = mean_ms("dispatch")
    fwd_ms = mean_ms("fwd")
    grad_ms = mean_ms("grad")
    update_ms = mean_ms("update")
    step_ms = mean_ms("step") / k  # per TRAIN step under fused dispatch
    bwd_ms = max(0.0, grad_ms - fwd_ms) if grad_ms and fwd_ms else None

    images_per_sec = batch_size / (step_ms / 1e3)

    # analytic per-phase cost: HloCostAnalysis of the SAME programs,
    # lowered on abstract inputs. Safe in-process only on a non-plugin
    # backend (the axon TPU tunnel wedges inside cost_analysis).
    analytic = None
    flops_per_step = None
    if jax.default_backend() == "cpu":
        _, state_abs, batch_abs = abstract_step_inputs(cfg, tx)
        grads_abs = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), state_abs.params
        )
        fwd_cost = lowered_cost(fwd_fn, state_abs, batch_abs)
        grad_cost = lowered_cost(grad_fn, state_abs, batch_abs)
        update_cost = lowered_cost(update_fn, state_abs, grads_abs)
        analytic = {
            "fwd": fwd_cost,
            "bwd": {
                key: max(0.0, grad_cost[key] - fwd_cost[key]) for key in fwd_cost
            },
            "update": update_cost,
        }
        flops_per_step = grad_cost["flops"] + update_cost["flops"]
    else:
        from replication_faster_rcnn_tpu.benchmark import _step_flops

        flops_per_step = _step_flops(cfg, batch_size)

    # critical-path overlap: feed-blocked + checkpoint-blocked host time
    # through the PR 4 machinery (data/prefetch_device.py,
    # train/async_checkpoint.py), same compiled step as the timings above
    overlap = _measure_overlap(step, state, batch)
    overlap.update(_measure_async_save(step, state, jax.device_put(batch)))

    # on-device augmentation flatness: host staging vs augment op count
    augment = _measure_augment(cfg)

    peak, basis = peak_flops_per_sec(jax.device_count())
    mfu = compute_mfu(flops_per_step, images_per_sec / batch_size, peak)
    if mfu is None or basis is None:
        raise SystemExit(
            "step_profile: could not derive a non-null MFU "
            f"(flops={flops_per_step}, peak={peak}, basis={basis}) — "
            "refusing to bank a record with an MFU hole"
        )

    dev = jax.devices()[0]
    record = {
        "schema": SCHEMA,
        "config": config_token,
        "backend": cfg.train.backend,
        "steps_per_dispatch": k,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "n_dev": jax.device_count(),
        "batch_size": batch_size,
        "image_size": list(cfg.data.image_size),
        "n_steps_timed": n_steps,
        "step_ms": round(step_ms, 3),
        "images_per_sec": round(images_per_sec, 3),
        "phases": {
            "dispatch": {"mean_ms": round(dispatch_ms, 3)},
            "fwd": {"mean_ms": round(fwd_ms, 3)},
            "bwd": {"mean_ms": round(bwd_ms, 3)},
            "update": {"mean_ms": round(update_ms, 3)},
        },
        "analytic": analytic,
        "overlap": overlap,
        "augment": augment,
        "flops_per_step": flops_per_step,
        "mfu": round(mfu, 4),
        "mfu_basis": basis,
        "spans": sorted(table.values(), key=lambda r: r["name"]),
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    return record


# ---------------------------------------------------------------------------
# per-op backend profile (ISSUE 13): the detection hot ops, timed through
# the SAME dispatch seams the train/serve programs use, once per ops
# backend. On CPU the pallas rows run in interpret mode — structurally
# faithful (the exact kernels tier 1 gates) but not a perf signal, so the
# banked record is a coverage artifact there, never a regression gate;
# on a real TPU the same command prices the Mosaic kernels for real.


def ops_profile_path(config_token: str, platform: str,
                     records_dir: str = RECORDS_DIR) -> str:
    return os.path.join(
        records_dir, f"ops_profile_{config_token}_{platform}.json"
    )


def ops_profile(cfg, config_token: str, n_reps: int = 10):
    """Per-op (nms / roi_align / iou_match) × backend (xla / pallas)
    timings on this config's shapes; returns the ``ops_profile/v1``
    record. Each row names the backend it REQUESTED and the path that
    actually executed (`executed`), so a silent pallas→xla fallback is
    visible in the banked artifact."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from replication_faster_rcnn_tpu import ops as ops_pkg
    from replication_faster_rcnn_tpu.ops import boxes as box_ops
    from replication_faster_rcnn_tpu.ops import roi_ops
    from replication_faster_rcnn_tpu.ops.nms_tiled import nms_fixed_tiled

    rng = np.random.default_rng(0)
    h, w = cfg.data.image_size
    pre_nms = cfg.proposals.pre_nms_train
    post_nms = cfg.proposals.post_nms_train
    n_sample = cfg.roi_targets.n_sample
    n_gt = cfg.data.max_boxes
    # the RPN's anchor count at trunk stride 16, K=9 — same grid the
    # target-assignment seam matches against
    n_anchor = (h // 16) * (w // 16) * 9
    fh, fw, c = h // 16, w // 16, 256

    def boxes_of(n):
        tl = rng.uniform(0, 0.7 * h, (n, 2)).astype(np.float32)
        wh = rng.uniform(1.0, 0.3 * h, (n, 2)).astype(np.float32)
        return jnp.asarray(np.concatenate([tl, tl + wh], axis=1))

    nms_boxes = boxes_of(pre_nms)
    nms_scores = jnp.asarray(rng.uniform(size=pre_nms).astype(np.float32))
    anchors = boxes_of(n_anchor)
    gt = boxes_of(n_gt)
    gt_mask = jnp.asarray(np.arange(n_gt) < max(1, n_gt // 2))
    feat = jnp.asarray(rng.standard_normal((fh, fw, c)).astype(np.float32))
    rois = boxes_of(n_sample) * (min(fh, fw) / float(h))

    interpret = ops_pkg.interpret_mode()

    def xla_match(a, g, m):
        ious = jnp.where(m[None, :], box_ops.iou(a, g), -1.0)
        return ious, jnp.argmax(ious, 1), jnp.max(jnp.maximum(ious, 0.0), 1)

    def build(op, backend):
        """(callable, args, executed-path label) for one (op, backend)
        cell — pallas cells go through the real kernels, falling back to
        the xla row's callable when the kernels can't import."""
        if op == "nms":
            if backend == "pallas" and ops_pkg.pallas_available("nms"):
                from replication_faster_rcnn_tpu.ops.pallas import (
                    nms_fixed_pallas,
                )

                fn = jax.jit(
                    lambda b, s: nms_fixed_pallas(
                        b, s, 0.7, post_nms, interpret=interpret
                    )
                )
                return fn, (nms_boxes, nms_scores), _pallas_label(interpret)
            fn = jax.jit(lambda b, s: nms_fixed_tiled(b, s, 0.7, post_nms))
            return fn, (nms_boxes, nms_scores), "xla"
        if op == "roi_align":
            if backend == "pallas" and ops_pkg.pallas_available("roi_align"):
                fn = jax.jit(
                    lambda f, r: roi_ops.roi_align(f, r, method="pallas")
                )
                return fn, (feat, rois), _pallas_label(interpret)
            fn = jax.jit(lambda f, r: roi_ops.roi_align(f, r, method="einsum"))
            return fn, (feat, rois), "xla"
        if op == "iou_match":
            if backend == "pallas" and ops_pkg.pallas_available("anchor_match"):
                from replication_faster_rcnn_tpu.ops.pallas import (
                    match_boxes_pallas,
                )

                fn = jax.jit(
                    lambda a, g, m: match_boxes_pallas(
                        a, g, m, interpret=interpret
                    )
                )
                return fn, (anchors, gt, gt_mask), _pallas_label(interpret)
            return jax.jit(xla_match), (anchors, gt, gt_mask), "xla"
        raise ValueError(op)

    shapes = {
        "nms": {"n_boxes": pre_nms, "max_out": post_nms},
        "roi_align": {"feat": [fh, fw, c], "n_rois": n_sample, "out": 7},
        "iou_match": {"n_anchors": n_anchor, "n_gt": n_gt},
    }
    ops: dict = {}
    for op in ("nms", "roi_align", "iou_match"):
        ops[op] = dict(shapes[op])
        for backend in ("xla", "pallas"):
            fn, args, executed = build(op, backend)
            out = fn(*args)
            jax.tree_util.tree_map(
                lambda x: x.block_until_ready(), out
            )  # compile
            t0 = time.perf_counter()
            for _ in range(n_reps):
                out = fn(*args)
            jax.tree_util.tree_map(lambda x: x.block_until_ready(), out)
            ops[op][backend] = {
                "mean_ms": round(
                    (time.perf_counter() - t0) / n_reps * 1e3, 4
                ),
                "executed": executed,
            }

    dev = jax.devices()[0]
    return {
        "schema": OPS_SCHEMA,
        "config": config_token,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", None),
        "interpret": interpret,
        "n_reps": n_reps,
        "ops": ops,
        "measured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def _pallas_label(interpret: bool) -> str:
    return "pallas_interpret" if interpret else "pallas"


def check_ops_record(current, banked):
    """Structural gate over the banked ops record: same schema, same
    (op × backend) matrix, and every pallas row still executes a pallas
    path (a row that silently degraded to 'xla' means the kernels
    stopped importing — that fails like a regression). Timings are never
    compared: the pallas rows are interpret-mode on CPU."""
    failures = []
    if banked.get("schema") != OPS_SCHEMA:
        failures.append(
            f"banked ops record has schema {banked.get('schema')!r}, "
            f"expected {OPS_SCHEMA!r}"
        )
        return failures
    cur_ops, bank_ops = current.get("ops", {}), banked.get("ops", {})
    if sorted(cur_ops) != sorted(bank_ops):
        failures.append(
            f"ops matrix changed: {sorted(cur_ops)} vs banked "
            f"{sorted(bank_ops)}"
        )
        return failures
    for op, row in sorted(cur_ops.items()):
        for backend in ("xla", "pallas"):
            if backend not in row:
                failures.append(f"ops.{op} lost its {backend} row")
                continue
            executed = row[backend].get("executed", "")
            if backend == "pallas" and not executed.startswith("pallas"):
                failures.append(
                    f"ops.{op} pallas row executed {executed!r} — the "
                    "pallas kernels fell back to xla"
                )
    return failures


# ---------------------------------------------------------------------------
# CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--preset",
        default="tiny",
        help="'tiny' (trimmed CI-shape config) or a name from config.CONFIGS",
    )
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--image-size", type=int, default=64)
    p.add_argument("--backend", default="auto", choices=["auto", "spmd"])
    p.add_argument("--steps-per-dispatch", type=int, default=1)
    p.add_argument("--steps", type=int, default=5, help="timed reps per phase")
    p.add_argument(
        "--update", action="store_true", help="write/overwrite the banked record"
    )
    p.add_argument(
        "--no-check", action="store_true", help="measure + print only"
    )
    p.add_argument(
        "--strict-phases",
        action="store_true",
        help="per-phase slowdowns >tol fail too (default: warn)",
    )
    p.add_argument("--tol", type=float, default=DEFAULT_TOL)
    p.add_argument("--records-dir", default=RECORDS_DIR)
    args = p.parse_args(argv)

    if args.preset == "tiny":
        cfg = tiny_config(
            batch_size=args.batch_size,
            image_size=args.image_size,
            backend=args.backend,
            steps_per_dispatch=args.steps_per_dispatch,
        )
        token = f"tiny{args.image_size}b{args.batch_size}"
    else:
        import dataclasses

        from replication_faster_rcnn_tpu.config import CONFIGS

        if args.preset not in CONFIGS:
            p.error(f"unknown preset {args.preset!r}; have {sorted(CONFIGS)}")
        cfg = CONFIGS[args.preset]
        cfg = cfg.replace(
            data=dataclasses.replace(
                cfg.data,
                dataset="synthetic",
                image_size=(args.image_size, args.image_size),
            ),
            train=dataclasses.replace(
                cfg.train,
                batch_size=args.batch_size,
                backend=args.backend,
                steps_per_dispatch=args.steps_per_dispatch,
            ),
        )
        token = f"{args.preset}{args.image_size}b{args.batch_size}"

    record = profile(cfg, token, n_steps=args.steps)
    key = record_key(
        token, record["backend"], record["platform"], record["steps_per_dispatch"]
    )
    path = record_path(key, args.records_dir)
    print(json.dumps(record, indent=1, sort_keys=True))

    ops_record = ops_profile(cfg, token)
    ops_path = ops_profile_path(token, record["platform"], args.records_dir)
    print(json.dumps(ops_record, indent=1, sort_keys=True))

    if args.update:
        save_record(record, path)
        save_record(ops_record, ops_path)
        print(f"step_profile: banked {path}", file=sys.stderr)
        print(f"step_profile: banked {ops_path}", file=sys.stderr)
        return 0
    if args.no_check:
        return 0
    if not os.path.exists(path):
        print(
            f"step_profile: no banked record at {path} — run with --update "
            "to create one (not checking)",
            file=sys.stderr,
        )
        return 0
    failures, warnings = check_regression(
        record, load_record(path), tol=args.tol, strict_phases=args.strict_phases
    )
    if os.path.exists(ops_path):
        failures.extend(
            f"ops: {m}"
            for m in check_ops_record(ops_record, load_record(ops_path))
        )
    for w in warnings:
        print(f"step_profile: WARN {w}", file=sys.stderr)
    for f in failures:
        print(f"step_profile: FAIL {f}", file=sys.stderr)
    if failures:
        print(
            f"step_profile: REGRESSION vs {path} — if intentional, re-bank "
            "with --update",
            file=sys.stderr,
        )
        return 1
    print(f"step_profile: OK vs {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
