"""mAP evidence run: full-Trainer mini-training to mAP@0.5 >= 0.9.

No VOC/COCO exists in this image (zero egress), so the strongest available
evidence for the BASELINE "mAP@0.5 parity" north star is end-to-end: the
full Trainer (ONE jitted SPMD train step, orbax checkpointing, per-epoch
in-training eval through the real eval path `eval/detect` ->
`eval/voc_eval`) trained on planted-rectangle synthetic data
(`data/synthetic.py` — class-colored rectangles a detector can genuinely
learn) until the evaluator reports high mAP. The reference cannot run this
check at all: its eval was never written (`/root/reference/test_eval.py`
is empty, SURVEY.md §2.1 #15).

What this proves: the whole train->checkpoint->restore->decode->mAP chain
is correct and can drive a detector to high mAP on data it has learned.
What remains for the full parity claim (PARITY.md §"mAP parity status"):
pointing `--dataset voc --data-root <VOC2007>` at a real devkit and
training the voc_resnet18 preset to compare mAP@0.5 against a reference
run — blocked only on dataset availability, not on framework capability.

Writes:
  benchmarks/map_overfit_curve.jsonl  — per-step losses + per-epoch val mAP
  benchmarks/map_overfit_result.json  — summary incl. restored-checkpoint
                                        consistency check and train-set mAP
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python benchmarks/map_overfit.py` from anywhere
    sys.path.insert(0, REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=60)
    ap.add_argument("--images", type=int, default=48)
    ap.add_argument("--final-val-images", type=int, default=256,
                    help="disjoint val-split size for the final "
                    "generalization mAP (VERDICT r2 item 9: a 48-image "
                    "val split makes val mAP look like noise)")
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--eval-every", type=int, default=2)
    ap.add_argument("--num-data", type=int, default=1,
                    help="data-parallel mesh width (1 = single device)")
    ap.add_argument("--dtype", default="float32",
                    help="compute dtype: float32 on CPU, bfloat16 on TPU")
    ap.add_argument("--workdir", default="/tmp/map_overfit_ckpts")
    ap.add_argument("--augment-hflip", action="store_true",
                    help="50%% horizontal-flip train augmentation; results "
                    "go to map_overfit_result*_aug.json so the aug-off "
                    "baseline row is kept for comparison (VERDICT r3 #5)")
    ap.add_argument("--augment-scale", type=float, nargs=2, default=None,
                    metavar=("LO", "HI"),
                    help="scale-jitter augmentation; with it on, results "
                    "go to map_overfit_result*_scale.json")
    ap.add_argument("--augment-scale-device", action="store_true",
                    help="run the jitter resample on device (host ships "
                    "boxes + geometry); results go to *_scale_dev.json")
    ap.add_argument(
        "--norm", default="batch", choices=["batch", "group"],
        help="backbone normalization; 'group' trains the GroupNorm(32) "
        "variant (results go to *_gn.json) — quality evidence for the "
        "BN-free MFU lever")
    ap.add_argument(
        "--tta", dest="tta", action="store_true", default=None,
        help="run the flip-TTA eval leg on the large val split (defaults "
        "on only when augmentation flags are set — the TTA leg roughly "
        "doubles final-eval wall time)")
    ap.add_argument("--no-tta", dest="tta", action="store_false")
    ap.add_argument(
        "--config", default="voc_resnet18",
        choices=["voc_resnet18", "voc_resnet50_fpn"],
        help="preset to train: the flagship, or the FPN config (#3 in "
        "BASELINE) — FPN keeps its per-level single anchor scale, so "
        "--anchor-scales should be ONE value (e.g. 2 -> 8..128 px over "
        "strides 4..64, matching small planted objects)")
    ap.add_argument(
        "--anchor-scales", type=float, nargs="+", default=[1.0, 2.0, 4.0],
        help="anchor scales x base 16 px. The VOC default (8,16,32) targets "
        "600x600 objects; at this script's small image sizes those anchors "
        "(128-512 px) dwarf every planted object (h/8..h/2), leaving only "
        "force-positive RPN matches and capping achievable localization. "
        "(1,2,4) -> 16/32/64 px anchors matching the object range.")
    args = ap.parse_args()

    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        MeshConfig,
        TrainConfig,
        get_config,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train.trainer import Trainer

    import dataclasses

    size = (args.image_size, args.image_size)
    if args.augment_scale_device and not args.augment_scale:
        ap.error("--augment-scale-device requires --augment-scale LO HI")
    base = get_config(args.config)
    if base.model.fpn and len(args.anchor_scales) != 1:
        ap.error(
            "FPN uses one anchor scale per level (the preset's "
            f"scales={base.anchors.scales}); pass exactly one "
            f"--anchor-scales value, got {args.anchor_scales}"
        )
    # replace() so every preset field not explicitly overridden is kept —
    # rebuilding the config dataclasses from scratch would silently reset
    # preset-specific fields (num_classes, fpn_channels, ...) to defaults
    cfg = base.replace(
        anchors=dataclasses.replace(
            base.anchors, scales=tuple(args.anchor_scales)
        ),
        model=dataclasses.replace(
            base.model, roi_op="align", compute_dtype=args.dtype,
            norm=args.norm,
        ),
        data=DataConfig(dataset="synthetic", image_size=size, max_boxes=8,
                        augment_hflip=args.augment_hflip,
                        augment_scale=tuple(args.augment_scale)
                        if args.augment_scale else None,
                        augment_scale_device=args.augment_scale_device),
        train=TrainConfig(
            batch_size=args.batch,
            n_epoch=args.epochs,
            lr=args.lr,
            eval_every_epochs=args.eval_every,
            checkpoint_every_epochs=max(args.epochs // 4, 1),
            seed=0,
        ),
        mesh=MeshConfig(num_data=args.num_data),
    )

    # a stale workdir would defeat the restore-consistency leg below:
    # Trainer.save() dedups on latest_step(), so a rerun with identical
    # step counts but different hyperparameters would silently keep (and
    # then "restore") the previous run's checkpoints
    if os.path.exists(args.workdir):
        import shutil

        shutil.rmtree(args.workdir)

    train_ds = SyntheticDataset(cfg.data, "train", length=args.images)
    trainer = Trainer(cfg, workdir=args.workdir, dataset=train_ds)
    suffix = "" if args.config == "voc_resnet18" else "_fpn"
    if args.augment_hflip:
        suffix += "_aug"
    if args.augment_scale:
        suffix += "_scale"
    if args.augment_scale_device:
        suffix += "_dev"
    if args.norm == "group":
        suffix += "_gn"
    curve_path = os.path.join(
        REPO, "benchmarks", f"map_overfit_curve{suffix}.jsonl"
    )
    if os.path.exists(curve_path):
        os.remove(curve_path)
    trainer.logger.jsonl_path = curve_path

    t0 = time.time()
    last = trainer.train(log_every=5)
    train_s = time.time() - t0
    trainer.save()  # final state, whatever the epoch cadence saved last

    # the in-training eval used the val split (disjoint synthetic stream):
    # generalization mAP. Also measure memorization mAP on the train set.
    variables = {
        "params": trainer.state.params,
        "batch_stats": trainer.state.batch_stats,
    }
    evaluator = Evaluator(cfg, trainer.model)
    train_map = float(
        evaluator.evaluate(variables, train_ds, batch_size=args.batch)["mAP"]
    )

    # checkpoint/resume leg: a FRESH trainer restoring the final checkpoint
    # must reproduce the same val mAP (exercises orbax save->restore on the
    # exact state the curve ends on).
    # the reference value is a FRESH eval of the final state (last.get
    # ("mAP") can be stale: the in-training eval only fires on eval-every
    # boundaries, while save() checkpoints the true final epoch)
    final_map = float(trainer.evaluate()["mAP"])

    trainer2 = Trainer(cfg, workdir=args.workdir, dataset=train_ds)
    restored_step = trainer2.restore()
    restored_map = float(trainer2.evaluate()["mAP"])
    if abs(restored_map - final_map) > 1e-9:
        raise AssertionError(
            f"restored checkpoint mAP {restored_map} != final mAP {final_map}"
        )

    # large disjoint val split: the in-training val stream is small (the
    # default synthetic val split), so its mAP is high-variance
    big_val = SyntheticDataset(cfg.data, "val", length=args.final_val_images)
    big_val_map = float(
        evaluator.evaluate(variables, big_val, batch_size=args.batch)["mAP"]
    )

    # flip-TTA leg on the same split/state: what the mirrored second
    # forward + merged NMS buys at eval time (eval/detect.py TTA path).
    # Runs only for augmentation studies (or explicit --tta): it roughly
    # doubles final-eval wall time, so baseline runs skip it.
    run_tta = args.tta
    if run_tta is None:
        run_tta = bool(
            args.augment_hflip or args.augment_scale is not None
        )
    big_val_map_tta = None
    if run_tta:
        tta_cfg = cfg.replace(
            eval=dataclasses.replace(cfg.eval, tta_hflip=True)
        )
        big_val_map_tta = float(
            Evaluator(tta_cfg, trainer.model)
            .evaluate(variables, big_val, batch_size=args.batch)["mAP"]
        )

    result = {
        "final_val_mAP": final_map,
        "val_mAP_large_split": big_val_map,
        "val_mAP_large_split_tta": big_val_map_tta,
        "val_images_large_split": args.final_val_images,
        "last_intraining_val_mAP": last.get("mAP"),
        "train_set_mAP": train_map,
        "restored_step": restored_step,
        "restored_val_mAP": restored_map,
        "config": args.config,
        "epochs": args.epochs,
        "images": args.images,
        "image_size": args.image_size,
        "batch": args.batch,
        "lr": args.lr,
        "dtype": args.dtype,
        "augment_hflip": args.augment_hflip,
        "augment_scale": args.augment_scale,
        "augment_scale_device": args.augment_scale_device,
        "norm": args.norm,
        "train_seconds": round(train_s, 1),
        "backend": __import__("jax").default_backend(),
    }
    out_path = os.path.join(
        REPO, "benchmarks", f"map_overfit_result{suffix}.json"
    )
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
