"""Head-to-head mAP: the PyTorch reference vs this framework, same data.

VERDICT r2 missing item #1: until now the mAP parity case was
ingredient-parity plus our-model-only overfits — nobody had ever scored
the reference's own trained output. This script closes that: it trains
the REFERENCE trainer (`/root/reference/train.py:153-161`, run verbatim
through `benchmarks/reference_baseline.py`'s dependency stand-ins) on the
exact planted-rectangle synthetic dataset `benchmarks/map_overfit.py`
uses, decodes its head outputs with the reference's own `reg2bbox`
semantics, and scores BOTH models' detections with the same evaluator
(`eval/voc_eval.voc_ap`).

Fairness provisions for the reference:
  * identical images/boxes/labels, identical train/val splits (our
    `SyntheticDataset` streams, converted to the reference's sample
    format: CHW tensors, (y1,x1,y2,x2) boxes padded with -1 — the same
    layout its own `utils/data_loader.py:56-117` emits);
  * the same small-object anchor scales our overfit run uses (its
    default 128-512 px anchors dwarf every planted object at 128 px
    images; `RPN.base_anchor` is rebuilt with the reference's own
    `generate_anchor_base`);
  * its own hyperparameters where it has them (Adam + weight_decay 5e-6,
    cosine schedule per `train.py:139-140`) with the lr chosen by a
    short sweep rather than its VOC default (0.01 diverges here);
  * decode uses its train-mode proposal budget (600 rois) — more
    proposals than our eval path keeps, never fewer.

The reference has no decode/eval path of its own (`test_eval.py` is
empty), so the decode glue below is written in THIS repo's style against
the reference's conventions: class-c deltas un-normalized by the
ProposalTargetCreator std (0.1, 0.1, 0.2, 0.2) (`utils/utils.py:216`),
boxes via its `reg2bbox`, per-class NMS at 0.3, score > 0.05.

Writes benchmarks/head_to_head_map.json with {ours, reference} blocks.

Run: python benchmarks/head_to_head_map.py [--epochs N] [--images N]
     (add --skip-ours to reuse a committed map_overfit result)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _reference_samples(ds):
    """Convert our SyntheticDataset samples to the reference's format.

    Ours: image HWC float32 normalized (same ImageNet mean/std the
    reference's transform applies), boxes (y1,x1,y2,x2) float padded -1,
    labels int padded -1 — semantically identical content, so the
    conversion is a transpose plus dtype casts.
    """
    import numpy as np
    import torch

    out = []
    for i in range(len(ds)):
        s = ds[i]
        image = torch.as_tensor(s["image"].transpose(2, 0, 1))[None]  # [1,C,H,W]
        boxes = np.full((1, s["boxes"].shape[0], 4), -1.0, np.float32)
        labels = np.full((1, s["labels"].shape[0]), -1.0, np.float32)
        m = s["labels"] >= 0
        boxes[0, m] = s["boxes"][m]
        labels[0, m] = s["labels"][m].astype(np.float32)
        out.append((image, boxes, labels))
    return out


def _gt_list(ds):
    import numpy as np

    gts = []
    for i in range(len(ds)):
        s = ds[i]
        m = s["labels"] >= 0
        gts.append(
            {
                "boxes": np.asarray(s["boxes"][m], np.float32),
                "labels": np.asarray(s["labels"][m], np.int32),
            }
        )
    return gts


def _decode_reference(net, image, score_thresh=0.05, nms_iou=0.3, max_det=100):
    """Detections from the reference net on one image, its conventions.

    Returns {'boxes' [D,4] (y1,x1,y2,x2), 'scores' [D], 'classes' [D]}.
    """
    import numpy as np
    import torch

    from replication_faster_rcnn_tpu.data import native_ops
    from utils.utils import reg2bbox  # the reference's own decode

    _, _, img_h, img_w = image.shape
    with torch.no_grad():
        features = net.backbone(image.float())
        # rpn takes (width, height) per train.py:65
        _, _, rois, roi_inds, _ = net.rpn(features, img_w, img_h)
        cls_out, reg_out = net.head(features, rois, roi_inds, img_h, img_w)
        # cls_out [1, 21, R], reg_out [1, R, 21*4]
        probs = torch.softmax(cls_out[0], dim=0).numpy()  # [21, R]
        reg = reg_out[0].numpy()  # [R, 84]
        rois_np = rois.numpy()  # [R, 4]

    # ProposalTargetCreator normalizes reg targets by this std
    # (utils/utils.py:216); invert it before reg2bbox
    std = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    boxes_all, scores_all, classes_all = [], [], []
    n_classes = probs.shape[0]
    for c in range(1, n_classes):
        deltas = torch.as_tensor(reg[:, 4 * c : 4 * c + 4] * std)
        bbox = reg2bbox(torch.as_tensor(rois_np), deltas).numpy()
        bbox[:, 0::2] = np.clip(bbox[:, 0::2], 0, img_h)
        bbox[:, 1::2] = np.clip(bbox[:, 1::2], 0, img_w)
        score = probs[c]
        keep = score > score_thresh
        if not keep.any():
            continue
        b, s = bbox[keep], score[keep]
        order = native_ops.nms(b, s, float(nms_iou))
        boxes_all.append(b[order])
        scores_all.append(s[order])
        classes_all.append(np.full(len(order), c, np.int32))
    if not boxes_all:
        return {
            "boxes": np.zeros((0, 4), np.float32),
            "scores": np.zeros((0,), np.float32),
            "classes": np.zeros((0,), np.int32),
        }
    boxes = np.concatenate(boxes_all)
    scores = np.concatenate(scores_all)
    classes = np.concatenate(classes_all)
    order = np.argsort(-scores)[:max_det]
    return {"boxes": boxes[order], "scores": scores[order], "classes": classes[order]}


def _batch(samples, batch_size):
    """Group per-image reference samples into train_step batches (the
    reference's own DataLoader default is batch 2, frcnn.py:19)."""
    import numpy as np
    import torch

    out = []
    for i in range(0, len(samples), batch_size):
        chunk = samples[i : i + batch_size]
        out.append(
            (
                torch.cat([c[0] for c in chunk], dim=0),
                np.concatenate([c[1] for c in chunk], axis=0),
                np.concatenate([c[2] for c in chunk], axis=0),
            )
        )
    return out


def _train_reference(samples, epochs, lr, anchor_scales, log_every=20):
    """Build the reference trainer and run its own train_step over the
    sample list for `epochs` passes, with its published optimizer recipe
    (train.py:139-140: Adam + wd 5e-6 + cosine)."""
    import numpy as np
    import torch

    from benchmarks.reference_baseline import _install_stubs, _prepare_workdir

    _install_stubs()
    tmp = "/tmp/head_to_head_ref_workdir"
    os.makedirs(tmp, exist_ok=True)
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        _prepare_workdir(tmp)
        from train import trainer  # the reference trainer

        torch.manual_seed(0)
        np.random.seed(0)
        t = trainer()
        # small-object anchors, built with the reference's own generator
        # (its VOC default 128-512 px anchors cannot match 16-64 px
        # planted objects at these image sizes — same adjustment our
        # overfit run makes via --anchor-scales)
        from utils.anchors import generate_anchor_base

        t.model.net.rpn.base_anchor = generate_anchor_base(
            ratios=[0.5, 1.0, 2.0], anchor_scales=list(anchor_scales)
        )
        t.optimizer = torch.optim.Adam(
            t.model.net.parameters(), lr=lr, weight_decay=5e-6
        )
        scheduler = torch.optim.lr_scheduler.CosineAnnealingLR(t.optimizer, epochs)

        import contextlib
        import io

        t.model.net.train()
        step = 0
        for ep in range(epochs):
            for image, boxes, labels in samples:
                # train_step prints five loss lines per call; keep the log
                # readable by sampling them
                buf = io.StringIO()
                with contextlib.redirect_stdout(buf):
                    t.train_step(image, boxes, labels)
                if step % log_every == 0:
                    first = buf.getvalue().splitlines()[:1]
                    print(f"ref epoch {ep} step {step}: {first[0] if first else ''}")
                    sys.stdout.flush()
                step += 1
            scheduler.step()
        t.model.net.eval()
        return t
    finally:
        os.chdir(cwd)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=60)
    ap.add_argument("--images", type=int, default=48)
    ap.add_argument("--val-images", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=128)
    ap.add_argument("--ref-lr", type=float, default=3e-4)
    ap.add_argument("--ref-batch", type=int, default=2)
    ap.add_argument("--anchor-scales", type=float, nargs="+", default=[1.0, 2.0, 4.0])
    ap.add_argument(
        "--skip-ours",
        action="store_true",
        help="reuse benchmarks/map_overfit_result.json for our side "
        "(same dataset parameters) instead of retraining",
    )
    ap.add_argument("--ref-only", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    from replication_faster_rcnn_tpu.config import DataConfig
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.eval.voc_eval import voc_ap

    size = (args.image_size, args.image_size)
    dcfg = DataConfig(dataset="synthetic", image_size=size, max_boxes=8)
    train_ds = SyntheticDataset(dcfg, "train", length=args.images)
    val_ds = SyntheticDataset(dcfg, "val", length=args.val_images)

    # ---- reference: train + decode + score
    ref_samples = _batch(_reference_samples(train_ds), args.ref_batch)
    t0 = time.time()
    t = _train_reference(ref_samples, args.epochs, args.ref_lr, args.anchor_scales)
    ref_train_s = time.time() - t0

    import torch

    def ref_score(ds):
        dets = [
            _decode_reference(
                t.model.net,
                torch.as_tensor(ds[i]["image"].transpose(2, 0, 1))[None],
            )
            for i in range(len(ds))
        ]
        return float(voc_ap(dets, _gt_list(ds), num_classes=21)["mAP"])

    ref_train_map = ref_score(train_ds)
    ref_val_map = ref_score(val_ds)

    result = {
        "data": {
            "images": args.images,
            "val_images": args.val_images,
            "image_size": args.image_size,
            "epochs": args.epochs,
            "dataset": "planted-rectangle synthetic (data/synthetic.py), "
            "identical streams for both frameworks",
        },
        "reference": {
            "train_set_mAP@0.5": ref_train_map,
            "val_mAP@0.5": ref_val_map,
            "lr": args.ref_lr,
            "batch": args.ref_batch,
            "optimizer": "Adam wd=5e-6 + cosine (reference train.py:139-140)",
            "anchor_scales": args.anchor_scales,
            "train_seconds": round(ref_train_s, 1),
            "decode": "train-mode proposals (600), reference reg2bbox, "
            "per-class NMS 0.3, score>0.05",
        },
    }

    if not args.ref_only:
        if args.skip_ours:
            with open(os.path.join(REPO, "benchmarks", "map_overfit_result.json")) as f:
                ours = json.load(f)
            assert ours["images"] == args.images and ours["image_size"] == args.image_size, (
                "committed map_overfit_result.json used different dataset "
                "parameters; rerun without --skip-ours"
            )
            result["ours"] = {
                "train_set_mAP@0.5": ours["train_set_mAP"],
                "val_mAP@0.5": ours["final_val_mAP"],
                "source": "benchmarks/map_overfit_result.json (same dataset params)",
            }
        else:
            # run our side fresh through the same entry point map_overfit uses
            import subprocess

            env = dict(os.environ)
            env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
            r = subprocess.run(
                [
                    sys.executable,
                    os.path.join(REPO, "benchmarks", "map_overfit.py"),
                    "--epochs",
                    str(args.epochs),
                    "--images",
                    str(args.images),
                    "--image-size",
                    str(args.image_size),
                ],
                env=env,
                cwd=REPO,
                capture_output=True,
                text=True,
            )
            if r.returncode != 0:
                raise RuntimeError(f"our-side training failed:\n{r.stderr[-2000:]}")
            ours = json.loads(r.stdout.strip().splitlines()[-1])
            result["ours"] = {
                "train_set_mAP@0.5": ours["train_set_mAP"],
                "val_mAP@0.5": ours["final_val_mAP"],
                "source": "fresh map_overfit.py run (same epochs/images/size)",
            }

    out = os.path.join(REPO, "benchmarks", "head_to_head_map.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
