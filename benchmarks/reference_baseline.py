"""Measure the PyTorch reference's training throughput on CPU.

BASELINE.md: the reference publishes no numbers, so the 6x target needs a
measured torch-CPU baseline. This script runs the REFERENCE code itself
(`/root/reference/train.py` ``trainer.train_step``) on synthetic tensors and
records images/sec into ``benchmarks/baseline_measured.json``.

The image lacks three of the reference's dependencies, so minimal stand-ins
are injected via sys.modules BEFORE importing it:
  * ``skimage`` / ``xmltodict`` — only touched by the data loader, which
    this benchmark bypasses (synthetic tensors); stubs are import-only.
  * ``torchvision`` — the reference's NMS/RoIPool kernels (SURVEY.md §2.3).
    Stand-ins are vectorized torch implementations below; they are a small
    fraction of step time (the ResNet conv stacks via genuine ATen
    dominate), so the baseline remains representative. matmul threads: the
    host has 1 core, matching BASELINE.json's "single-host CPU" framing.

Run: python benchmarks/reference_baseline.py [--steps N] [--batch B]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"


def _install_stubs() -> None:
    import numpy as np
    import torch

    # ---- skimage (data-loader only; never exercised here)
    skimage = types.ModuleType("skimage")
    skimage_io = types.ModuleType("skimage.io")
    skimage_io.imread = lambda p: np.zeros((600, 600, 3), np.uint8)
    skimage_tr = types.ModuleType("skimage.transform")
    skimage_tr.resize = lambda img, size: np.zeros((*size, 3), np.float64)
    skimage.io = skimage_io
    skimage.transform = skimage_tr
    sys.modules["skimage"] = skimage
    sys.modules["skimage.io"] = skimage_io
    sys.modules["skimage.transform"] = skimage_tr

    # ---- xmltodict (data-loader only)
    xmltodict = types.ModuleType("xmltodict")
    xmltodict.parse = lambda s: {}
    sys.modules["xmltodict"] = xmltodict

    # ---- torchvision: nms / roi_pool / transforms used by the reference.
    # NMS routes to this repo's native C++ greedy NMS (same semantics as
    # torchvision's C++ kernel) so the baseline isn't slowed by a Python
    # stand-in; numpy fallback inside native_ops covers a missing .so.
    sys.path.insert(0, REPO)
    from replication_faster_rcnn_tpu.data import native_ops

    def nms(boxes: "torch.Tensor", scores: "torch.Tensor", iou_threshold: float):
        keep = native_ops.nms(
            boxes.detach().cpu().numpy(),
            scores.detach().cpu().numpy(),
            float(iou_threshold),
        )
        return torch.as_tensor(np.asarray(keep), dtype=torch.long)

    import torch.nn.functional as F

    def roi_pool(features, boxes, output_size, spatial_scale=1.0):
        # torchvision.ops.roi_pool semantics: round the scaled roi, then
        # max-pool over floor/ceil bin boundaries over rh=r2-r1+1 rows
        # (computed from the UNclamped corners) — which is
        # adaptive_max_pool2d over the (inclusive) region, with rows/cols
        # outside the feature map treated as absent (bins that fall
        # entirely outside stay 0). Out-of-range margins on any side are
        # modeled by -inf padding to the full rh x rw extent, then
        # zeroing any all-padding bins. One fused pool per roi instead of
        # oh*ow Python-level bins: the original triple loop took
        # ~20s/step at 128px images (it dominated any small-shape run of
        # the reference; at 600x600 the conv stacks dominate either way).
        if isinstance(output_size, int):
            output_size = (output_size, output_size)
        oh, ow = output_size
        n, c, h, w = features.shape
        out = features.new_zeros(len(boxes), c, oh, ow)
        neg_inf = float("-inf")
        for k in range(len(boxes)):
            b = int(boxes[k, 0])
            r1, c1, r2, c2 = [
                int(round(float(v) * spatial_scale)) for v in boxes[k, 1:]
            ]
            rh = max(r2 - r1 + 1, 1)
            rw = max(c2 - c1 + 1, 1)
            rs, cs = max(r1, 0), max(c1, 0)
            region = features[b, :, rs : max(min(r1 + rh, h), rs), cs : max(min(c1 + rw, w), cs)]
            pad_top = rs - r1
            pad_left = cs - c1
            pad_bottom = rh - pad_top - region.shape[1]
            pad_right = rw - pad_left - region.shape[2]
            padded = pad_top or pad_left or pad_bottom or pad_right
            if padded:
                region = F.pad(
                    region,
                    (pad_left, pad_right, pad_top, pad_bottom),
                    value=neg_inf,
                )
            pooled = F.adaptive_max_pool2d(region, (oh, ow))
            if padded:
                pooled = torch.where(
                    pooled == neg_inf, torch.zeros_like(pooled), pooled
                )
            out[k] = pooled
        return out

    torchvision = types.ModuleType("torchvision")
    tv_ops = types.ModuleType("torchvision.ops")
    tv_ops.nms = nms
    tv_ops.roi_pool = roi_pool
    tv_ops_roi = types.ModuleType("torchvision.ops.roi_pool")
    tv_ops_roi.roi_pool = roi_pool
    tv_transforms = types.ModuleType("torchvision.transforms")

    class _Compose:
        def __init__(self, fs):
            self.fs = fs

        def __call__(self, x):
            for f in self.fs:
                x = f(x)
            return x

    tv_transforms.Compose = _Compose
    tv_transforms.ToTensor = lambda: (lambda x: torch.as_tensor(x))
    tv_transforms.Normalize = lambda m, s: (lambda x: x)
    tv_datasets = types.ModuleType("torchvision.datasets")
    torchvision.ops = tv_ops
    torchvision.transforms = tv_transforms
    torchvision.datasets = tv_datasets
    sys.modules["torchvision"] = torchvision
    sys.modules["torchvision.ops"] = tv_ops
    sys.modules["torchvision.ops.roi_pool"] = tv_ops_roi
    sys.modules["torchvision.transforms"] = tv_transforms
    sys.modules["torchvision.datasets"] = tv_datasets


def _prepare_workdir(tmp: str) -> None:
    """The reference hard-codes relative paths: a resnet18 .pth at
    data/resnet/ (`nets/resnet_torch.py:394`) and a VOC imageset list
    (`utils/data_loader.py:48`). Create both so its constructors run."""
    import torch

    os.makedirs(os.path.join(tmp, "data/resnet"), exist_ok=True)
    vocdir = os.path.join(tmp, "data/voc/VOCdevkit/VOC2012")
    os.makedirs(os.path.join(vocdir, "ImageSets/Main"), exist_ok=True)
    with open(os.path.join(vocdir, "ImageSets/Main/aeroplane_train.txt"), "w") as f:
        f.write("fake_000001 1\n")

    sys.path.insert(0, REFERENCE)
    from nets.resnet_torch import resnet18  # reference's own definition

    model = resnet18()
    torch.save(model.state_dict(), os.path.join(tmp, "data/resnet/resnet18-5c106cde.pth"))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--batch", type=int, default=2)  # reference default
    args = ap.parse_args()

    import numpy as np
    import torch

    _install_stubs()
    tmp = "/tmp/reference_baseline_workdir"
    os.makedirs(tmp, exist_ok=True)
    cwd = os.getcwd()
    os.chdir(tmp)
    try:
        _prepare_workdir(tmp)
        from train import trainer  # the reference trainer

        t = trainer()
        t.optimizer = torch.optim.Adam(t.model.net.parameters(), lr=1e-4)

        rng = np.random.RandomState(0)
        image = torch.as_tensor(
            rng.uniform(-1, 1, (args.batch, 3, 600, 600)).astype(np.float32)
        )
        # boxes/labels as numpy: the reference's target creators call numpy
        # reductions on them (utils/utils.py:116), which numpy 2.x no longer
        # accepts on torch tensors; its own loader yields numpy-backed
        # tensors under the older numpy it was written against.
        boxes = np.full((args.batch, 32, 4), -1.0, np.float32)
        labels = np.full((args.batch, 32), -1.0, np.float32)
        for i in range(args.batch):
            boxes[i, 0] = [100.0, 120.0, 300.0, 350.0]
            labels[i, 0] = 7
            boxes[i, 1] = [50.0, 400.0, 200.0, 550.0]
            labels[i, 1] = 12

        for _ in range(args.warmup):
            t.train_step(image, boxes, labels)
        t0 = time.time()
        for _ in range(args.steps):
            t.train_step(image, boxes, labels)
        dt = time.time() - t0
        ips = args.steps * args.batch / dt
    finally:
        os.chdir(cwd)

    out = {
        "torch_cpu_images_per_sec": round(ips, 4),
        "sec_per_step": round(dt / args.steps, 3),
        "batch_size": args.batch,
        "steps": args.steps,
        "torch_version": torch.__version__,
        "cpu_count": os.cpu_count(),
        "notes": "reference train_step on synthetic 600x600 tensors; "
        "torchvision nms/roi_pool stand-ins (not installed in image)",
    }
    path = os.path.join(REPO, "benchmarks", "baseline_measured.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
