"""Micro-benchmark: the three NMS backends at the training budget.

Run on a healthy TPU (check the relay first — see
.claude/skills/verify/SKILL.md "TPU tunnel fragility"):

    python benchmarks/nms_backends.py [--batch 8] [--n 12000] [--out 600]

Prints ms/call for the XLA selection loop (`ops/nms.py`), the tiled
exact algorithm (`ops/nms_tiled.py`), and the rebuilt Pallas kernel
(`ops/pallas/nms_kernel.py` — ISSUE 13; the round-5 removal's successor,
now CPU-validatable in interpret mode and compiled only through the
warmup registry), plus a selection-parity check — all three must select
identically. Each row names the path that actually EXECUTED: off-TPU the
pallas row runs the interpreter, so its time is a correctness artifact,
not a perf number; on a real chip it prices the Mosaic kernel (the
removed round-5 kernel measured 3.2x the loop standalone on v5e).
CPU reference numbers (1 core, 12k->600, batch 1): loop 88.6ms,
tiled 8.2ms (identical selections).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _rand(batch: int, n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    ctr = rng.uniform(0, 600, (batch, n, 2))
    wh = rng.uniform(16, 120, (batch, n, 2))
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], -1).astype(np.float32)
    scores = rng.uniform(0, 1, (batch, n)).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(scores)


def _time(fn, boxes, scores, reps: int = 10):
    idx, valid = fn(boxes, scores)
    jax.device_get(idx)  # sync (block_until_ready lies on the remote plugin)
    t0 = time.time()
    for _ in range(reps):
        idx, valid = fn(boxes, scores)
    jax.device_get(idx)
    return (time.time() - t0) / reps * 1000, idx, valid


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--n", type=int, default=12000)
    ap.add_argument("--out", type=int, default=600)
    ap.add_argument("--thresh", type=float, default=0.7)
    args = ap.parse_args(argv)

    from replication_faster_rcnn_tpu import ops as ops_pkg
    from replication_faster_rcnn_tpu.ops.nms import nms_fixed
    from replication_faster_rcnn_tpu.ops.nms_tiled import nms_fixed_tiled

    boxes, scores = _rand(args.batch, args.n)
    backends = {
        "loop": jax.jit(jax.vmap(lambda b, s: nms_fixed(b, s, args.thresh, args.out))),
        "tiled": jax.jit(
            jax.vmap(lambda b, s: nms_fixed_tiled(b, s, args.thresh, args.out))
        ),
    }
    executed = {"loop": "xla", "tiled": "xla"}
    if ops_pkg.pallas_available("nms"):
        from replication_faster_rcnn_tpu.ops.pallas import nms_fixed_pallas

        interpret = ops_pkg.interpret_mode()
        backends["pallas"] = jax.jit(
            jax.vmap(
                lambda b, s: nms_fixed_pallas(
                    b, s, args.thresh, args.out, interpret=interpret
                )
            )
        )
        executed["pallas"] = "pallas_interpret" if interpret else "pallas"
    else:
        print(" pallas: unavailable (ops/pallas failed to import) — skipped")
    results = {}
    for name, fn in backends.items():
        ms, idx, valid = _time(fn, boxes, scores)
        results[name] = (ms, np.asarray(idx), np.asarray(valid))
        print(f"{name:>7}: {ms:8.2f} ms/call  "
              f"(batch {args.batch}, {args.n}->{args.out})  "
              f"[executed: {executed[name]}]")

    ref_idx, ref_val = results["loop"][1], results["loop"][2]
    for name, (_, idx, valid) in results.items():
        if name == "loop":
            continue
        ok = bool((idx == ref_idx).all() and (valid == ref_val).all())
        print(f"{name:>7}: selections {'IDENTICAL to' if ok else 'DIFFER from'} loop")
        if not ok:
            return 1

    # the proposal-path tail, both ways (round 4: models/rpn.py sorts
    # once and passes assume_sorted): top_k + internally-sorting NMS vs
    # one argsort + assume_sorted NMS. Outputs live in truncated-candidate
    # index space, so they compare to each other, not to the raw loop.
    pre = min(args.n - args.n // 16, args.n)  # ~top-k keeps most, as in RPN

    def _pipe_topk(b, s):
        ts, ti = jax.lax.top_k(s, pre)
        tb = b[ti]
        return nms_fixed_tiled(
            tb, ts, args.thresh, args.out, mask=jnp.isfinite(ts)
        )

    def _pipe_single_sort(b, s):
        order = jnp.argsort(-s)
        ti = jax.lax.slice_in_dim(order, 0, pre)
        ts = s[ti]
        tb = b[ti]
        return nms_fixed_tiled(
            tb, ts, args.thresh, args.out, mask=jnp.isfinite(ts),
            assume_sorted=True,
        )

    ms_a, idx_a, val_a = _time(jax.jit(jax.vmap(_pipe_topk)), boxes, scores)
    ms_b, idx_b, val_b = _time(
        jax.jit(jax.vmap(_pipe_single_sort)), boxes, scores
    )
    same = bool(
        (np.asarray(idx_a) == np.asarray(idx_b)).all()
        and (np.asarray(val_a) == np.asarray(val_b)).all()
    )
    print(f"proposal tail topk+sort: {ms_a:8.2f} ms/call")
    print(f"proposal tail one-sort : {ms_b:8.2f} ms/call "
          f"({ms_a / max(ms_b, 1e-9):.2f}x; selections "
          f"{'IDENTICAL' if same else 'DIFFER'})")
    return 0 if same else 1


if __name__ == "__main__":
    sys.exit(main())
