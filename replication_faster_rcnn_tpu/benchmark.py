"""Benchmark: jitted train-step throughput on the flagship config.

(Importable package module; the repo-root ``bench.py`` is a thin shim so
the driver can run it from the checkout root.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: VOC-shaped (600x600, synthetic tensors — dataset-independent)
training images/sec on the available device(s). ``vs_baseline`` is the
ratio against the measured single-host PyTorch-CPU reference throughput
(BASELINE.md: the reference publishes no numbers, so the baseline is
measured by benchmarks/reference_baseline.py and cached in
benchmarks/baseline_measured.json; target is >= 6x).
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import numpy as np


def _wedge_exit(reason: str):
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_600x600",
                "value": 0.0,
                "unit": "images/sec",
                "vs_baseline": None,
                "error": reason,
            }
        ),
        flush=True,
    )
    os._exit(2)


def _arm_watchdog() -> threading.Timer:
    """Print a diagnostic JSON line and exit if the measurement wedges.

    The remote-TPU tunnel in this image can hang indefinitely inside a
    compile (no Python-level interrupt possible); without this the driver
    would record nothing at all. BENCH_WATCHDOG_S overrides the budget.
    Returns the timer; cancel it once the measurement completes.
    """
    budget = float(os.environ.get("BENCH_WATCHDOG_S", "1500"))

    def fire():
        _wedge_exit(
            f"watchdog: device wedged >{budget:.0f}s (remote compile tunnel hang)"
        )

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()
    return t


def _probe_device() -> None:
    """Fail fast if the device tunnel is already wedged.

    A wedged remote-TPU service blocks even a trivial op forever, and a
    blocked device call cannot be interrupted from Python — so a short
    side watchdog reports the wedge in minutes instead of burning the
    full measurement budget before saying anything.
    """
    import jax.numpy as jnp

    budget = float(os.environ.get("BENCH_PROBE_S", "180"))
    t = threading.Timer(
        budget,
        lambda: _wedge_exit(
            f"probe: device unresponsive >{budget:.0f}s before compile "
            "(tunnel wedged at start)"
        ),
    )
    t.daemon = True
    t.start()
    try:
        jax.device_get(jnp.ones((8, 128)).sum())
    finally:
        t.cancel()


def main(config=None, profile_dir=None) -> None:
    """Measure the jitted train step of ``config`` (default: the flagship
    voc_resnet18 at 600x600, batch 8/device) on all available devices.
    ``profile_dir`` wraps the timed loop in a jax.profiler trace."""
    watchdog = _arm_watchdog()
    try:
        _probe_device()
        _measure(config, profile_dir)
    finally:
        # a raised exception must not leave the timer alive to later print a
        # bogus zero-metric line and os._exit a host process
        watchdog.cancel()


def _measure(config, profile_dir=None) -> None:
    import dataclasses

    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        MeshConfig,
        TrainConfig,
        get_config,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.parallel import (
        make_mesh,
        shard_batch,
        validate_parallel,
    )
    from replication_faster_rcnn_tpu.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    n_dev = len(jax.devices())
    if config is None:
        batch_size = 8 * n_dev
        cfg = get_config("voc_resnet18").replace(
            data=DataConfig(dataset="synthetic", image_size=(600, 600), max_boxes=32),
            train=TrainConfig(batch_size=batch_size),
            mesh=MeshConfig(num_data=n_dev),
        )
    else:
        # honor the caller's model/image/batch/mesh choices (incl. a model
        # axis and spatial partitioning); force synthetic data
        # (dataset-independent measurement) and fill every device
        n_model = max(1, config.mesh.num_model)
        n_data = max(1, n_dev // n_model)
        cfg = config.replace(
            data=dataclasses.replace(config.data, dataset="synthetic"),
            mesh=dataclasses.replace(config.mesh, num_data=n_data),
        )
        batch_size = cfg.train.batch_size
        if batch_size % n_data != 0:
            batch_size = max(1, batch_size // n_data) * n_data
            cfg = cfg.replace(
                train=dataclasses.replace(cfg.train, batch_size=batch_size)
            )
    validate_parallel(cfg)
    mesh = make_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)

    from replication_faster_rcnn_tpu.parallel.zero import (
        place_train_state,
        train_state_shardings,
    )

    shardings = train_state_shardings(
        state, mesh, cfg.mesh, cfg.train.shard_opt_state
    )
    state = place_train_state(state, shardings)

    ds = SyntheticDataset(cfg.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])
    device_batch = shard_batch(batch, mesh, cfg.mesh)

    if cfg.train.backend == "spmd":
        # measure the explicit shard_map backend (already jitted + donated)
        from replication_faster_rcnn_tpu.parallel import make_shard_map_train_step

        step, _ = make_shard_map_train_step(cfg, tx, mesh)
    else:
        step = jax.jit(
            make_train_step(model, cfg, tx),
            donate_argnums=(0,),
            out_shardings=(shardings, None),
        )

    # warmup (compile) + 2 steps to stabilize. NOTE: sync via device_get of
    # the scalar metrics, not block_until_ready — the remote-TPU plugin in
    # this image returns from block_until_ready before execution finishes,
    # which inflated throughput ~100x; a host transfer genuinely waits.
    for _ in range(3):
        state, metrics = step(state, device_batch)
    jax.device_get(metrics)

    from replication_faster_rcnn_tpu.utils.profiling import trace

    n_steps = 10
    t0 = time.time()
    with trace(profile_dir):
        for _ in range(n_steps):
            state, metrics = step(state, device_batch)
        jax.device_get(metrics)  # forces the whole dependency chain
    dt = time.time() - t0
    images_per_sec = n_steps * batch_size / dt

    baseline_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
        "baseline_measured.json",
    )
    vs_baseline = float("nan")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        ref = baseline.get("torch_cpu_images_per_sec")
        if ref:
            vs_baseline = images_per_sec / ref

    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_600x600",
                "value": round(images_per_sec, 3),
                "unit": "images/sec",
                "vs_baseline": round(vs_baseline, 3) if np.isfinite(vs_baseline) else None,
            }
        )
    )


if __name__ == "__main__":
    main()
