"""Benchmark: jitted train-step throughput on the flagship config.

(Importable package module; the repo-root ``bench.py`` is a thin shim so
the driver can run it from the checkout root.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", plus
"flops_per_step"/"mfu" and — unless BENCH_BREAKDOWN=0 — a per-stage
"breakdown"}.

Metric: VOC-shaped (600x600, synthetic tensors — dataset-independent)
training images/sec on the available device(s). ``vs_baseline`` is the
ratio against the measured single-host PyTorch-CPU reference throughput
(BASELINE.md: the reference publishes no numbers, so the baseline is
measured by benchmarks/reference_baseline.py and cached in
benchmarks/baseline_measured.json; target is >= 6x).

MFU: ``achieved_flops / (time x peak_bf16_flops)``. The step's FLOP count
comes from XLA's own HloCostAnalysis on the *lowered* (pre-compile) module
— a host-side analysis that never touches the device, so it is safe even
through the fragile remote-TPU tunnel; it undercounts post-fusion FLOPs by
a few percent, which makes the reported MFU slightly conservative. Peak is
per-chip bf16 (v5e: 197 TFLOP/s) x mesh size on TPU, or a measured-matmul
host peak on CPU (telemetry/mfu.py); "mfu_basis" labels which regime a
number came from so a CPU-fallback MFU can't be mistaken for chip MFU.

Stage breakdown (SURVEY.md §5 tracing plan): wall-time of jitted prefixes
of the step — trunk, +RPN heads, +proposal NMS, full forward+loss — whose
successive differences attribute time to trunk / rpn_heads / proposal_nms
/ targets_head_loss / backward_update. Differences of separately-jitted
programs (XLA fuses differently per program), so treat small negative
deltas as noise floors, not measurement bugs.
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import numpy as np


# failure-path metric label; refined to the actual mode/shape as soon as the
# measurement resolves its config, so a wedge report never mislabels an eval
# or non-600 run as the train 600x600 number
_METRIC = "train_images_per_sec_600x600"


def _wedge_exit(reason: str):
    print(
        json.dumps(
            {
                "metric": _METRIC,
                "value": 0.0,
                "unit": "images/sec",
                "vs_baseline": None,
                "error": reason,
            }
        ),
        flush=True,
    )
    os._exit(2)


def _cpu_fallback(reason: str, config=None) -> None:
    """Measure on a scrubbed-env CPU subprocess instead of recording 0.0.

    When the remote-TPU tunnel is wedged (round-1 failure mode: the
    official number of record became 0.0 despite a working framework),
    a JAX-CPU measurement against the torch-CPU baseline is still an
    honest single-core apples-to-apples number. The child gets a fresh
    interpreter with the axon plugin suppressed, a small batch (CPU
    steps are seconds, not milliseconds) and few steps; the printed line
    carries ``fallback_backend``/``fallback_reason`` so nobody mistakes
    it for a TPU number. Never returns.
    """
    import dataclasses
    import subprocess
    import sys

    try:
        env = dict(os.environ)
        env.update(
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            BENCH_NO_FALLBACK="1",
            BENCH_BATCH=os.environ.get("BENCH_FALLBACK_BATCH", "2"),
            BENCH_STEPS="3",
            BENCH_BREAKDOWN="0",
            BENCH_WATCHDOG_S="1100",
        )
        env.pop("JAX_PLATFORM_NAME", None)
        payload = ""
        if config is not None:
            env["BENCH_CONFIG_STDIN"] = "1"
            cpu_cfg = config.replace(
                train=dataclasses.replace(
                    config.train,
                    batch_size=min(config.train.batch_size, 2),
                )
            )
            payload = json.dumps(dataclasses.asdict(cpu_cfg))
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                "from replication_faster_rcnn_tpu.benchmark import main; main()",
            ],
            input=payload,
            text=True,
            capture_output=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=1300,
        )
        obj = json.loads(r.stdout.strip().splitlines()[-1])
        if not obj.get("value"):
            raise RuntimeError(f"fallback produced no throughput: {obj}")
        # A fallback record must never ship "mfu": null silently again
        # (pre-telemetry binaries did): the child derives it on-host
        # (telemetry/mfu.py, cpu_measured_matmul basis). If it could not,
        # keep the honest throughput line but fail the process loudly so
        # the driver sees a broken record, not a quiet hole.
        if obj.get("mfu") is None or not obj.get("mfu_basis"):
            obj["mfu_error"] = "fallback child produced no MFU/basis"
            obj["fallback_backend"] = "cpu"
            obj["fallback_reason"] = reason
            print(json.dumps(obj), flush=True)
            os._exit(3)
        obj["fallback_backend"] = "cpu"
        obj["fallback_reason"] = reason
        obj["last_recorded_tpu"] = _last_recorded_tpu(
            obj.get("metric", _METRIC), _config_token(config)
        )
        print(json.dumps(obj), flush=True)
        os._exit(0)
    except Exception as e:  # noqa: BLE001 — any failure -> the 0.0 record
        _wedge_exit(f"{reason}; cpu fallback failed: {e!r}")


def _config_token(config):
    """Identity token for the benched model, used to match committed
    on-chip records (whose "config" strings start with the preset name,
    e.g. "voc_resnet50_fpn 600x600 batch 8 ..."). Resolves the preset by
    comparing model sections; falls back to a backbone-derived token for
    non-preset configs. None config means the flagship bench default."""
    if config is None:
        return "voc_resnet18"
    try:
        from replication_faster_rcnn_tpu.config import CONFIGS

        for name, preset in CONFIGS.items():
            if preset.model == config.model:
                return name
        return config.model.backbone + ("_fpn" if config.model.fpn else "")
    except Exception:  # noqa: BLE001 — informational only
        return None


def _last_recorded_tpu(metric=None, config_token=None):
    """Most recent committed on-chip measurement matching ``metric``
    (default: the current _METRIC) from benchmarks/bench_v5e_round2.json.
    Prefers a record for the same model (``config_token`` == first word
    of the record's "config" string); only if none exists does it fall
    back to the latest record for the metric regardless of model, with
    "same_config": false so a hardware number can't be silently
    misattributed to a different config. A CPU-fallback line carries
    this (keyed on the metric the fallback child actually measured) so
    the reader still sees the real hardware number. Returns None when no
    matching record exists — the field is informational only."""
    if metric is None:
        metric = _METRIC
    try:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks",
            "bench_v5e_round2.json",
        )
        with open(path) as f:
            data = json.load(f)
        best = best_same = None
        for rec in data.get("records", []):
            if rec.get("metric", data.get("metric")) != metric:
                continue
            if best is None or rec.get("measured", "") > best.get("measured", ""):
                best = rec
            rec_token = (rec.get("config") or "").split(" ")[0]
            if config_token is not None and rec_token == config_token:
                if best_same is None or rec.get("measured", "") > best_same.get(
                    "measured", ""
                ):
                    best_same = rec
        chosen = best_same if best_same is not None else best
        if chosen is not None:
            out = {
                "value": chosen.get("value"),
                "vs_baseline": chosen.get("vs_baseline"),
                "config": chosen.get("config"),
                "measured": chosen.get("measured"),
                "same_config": chosen is best_same,
            }
            if chosen.get("provenance"):
                out["provenance"] = chosen["provenance"]
            return out
    except Exception:  # noqa: BLE001 — informational; never break the line
        return None
    return None


_fallback_lock = threading.Lock()
_fallback_started = False


def _maybe_fallback(reason: str, config=None) -> None:
    """Wedge handler: CPU-subprocess fallback unless this process IS the
    fallback child (BENCH_NO_FALLBACK=1 — then report the 0.0). Runs at
    most once per process: the probe-retry path and the watchdog can
    both reach it, and a second concurrent fallback child would race the
    first to stdout."""
    global _fallback_started
    with _fallback_lock:
        if _fallback_started:
            return
        _fallback_started = True
    if os.environ.get("BENCH_NO_FALLBACK") == "1":
        _wedge_exit(reason)
    _cpu_fallback(reason, config)


def _arm_watchdog(config=None) -> threading.Timer:
    """CPU-fallback (else print a diagnostic JSON line) and exit if the
    measurement wedges.

    The remote-TPU tunnel in this image can hang indefinitely inside a
    compile (no Python-level interrupt possible); without this the driver
    would record nothing at all. BENCH_WATCHDOG_S overrides the budget.
    Returns the timer; cancel it once the measurement completes.
    """
    budget = float(os.environ.get("BENCH_WATCHDOG_S", "1500"))

    def fire():
        _maybe_fallback(
            f"watchdog: device wedged >{budget:.0f}s (remote compile tunnel hang)",
            config,
        )

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()
    return t


def _relay_alive():
    """Liveness of this image's remote-TPU relay process — cheap (no RPC
    traffic against the fragile tunnel). Returns None when undeterminable
    (no pgrep, or a host without the relay script at all — there a dead
    "relay" must not suppress re-probing, since no orchestrator will ever
    start one), True/False otherwise."""
    import subprocess

    if not os.path.exists("/root/.relay.py"):
        return None
    try:
        r = subprocess.run(
            ["pgrep", "-f", "[r]elay.py"], capture_output=True, timeout=10
        )
        return r.returncode == 0
    except Exception:  # noqa: BLE001 — treat as unknown
        return None


def _probe_subprocess(timeout_s: float) -> bool:
    """Run one trivial device op in a fresh subprocess under the caller's
    environment. A healthy tunnel answers in seconds; a dead one errors
    fast (connection refused) or blocks until the timeout. Probing in a
    subprocess keeps this process's backend un-poisoned: an in-process op
    against a wedged tunnel blocks forever and cannot be interrupted."""
    import subprocess
    import sys

    code = "import jax, jax.numpy as jnp; jax.device_get(jnp.ones((8, 128)).sum())"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _cpu_pinned() -> bool:
    """True when this process is explicitly pinned to the CPU backend
    (jax.config jax_platforms, seeded by JAX_PLATFORMS=cpu in scrubbed
    children or set by tests/conftest.py) — no tunnel exists to probe."""
    return (
        getattr(jax.config, "jax_platforms", None) or ""
    ).split(",")[0] == "cpu"


def _probe_device(config=None) -> None:
    """Fail fast if the device tunnel is already wedged — but give a
    *recovering* relay a chance first.

    Stage 1: a subprocess probe (budget BENCH_PROBE_S, default 180s).
    Success means the tunnel answers; proceed to warm this process's
    backend (still under a side watchdog — the tunnel can die between
    the probe and the op).

    Stage 2 (new, VERDICT r2 item 3): if the probe fails, re-probe for up
    to BENCH_PROBE_RETRIES_S (default 420s, 0 disables) every
    BENCH_PROBE_RETRY_INTERVAL_S (default 30s) — two earlier rounds lost
    the official number to a relay that was dead at bench time but could
    have been restored minutes later by the orchestrator. Device probes
    are only issued while the relay process exists (`pgrep`), so a
    relay-less wait adds no RPC load; when relay liveness is
    undeterminable the probe itself is the check.

    Only then fall back to the CPU measurement.
    """
    import time

    import jax.numpy as jnp

    # a process pinned to CPU (jax.config jax_platforms — how tests and
    # the fallback child run) measures on CPU: there is no tunnel to
    # probe. Probing anyway is worse than useless — the probe SUBPROCESS
    # inherits the shell env (JAX_PLATFORMS=axon via sitecustomize), so
    # it would interrogate a TPU tunnel this process will never touch,
    # and a wedged tunnel then drags a pure-CPU bench through the full
    # probe+retry+fallback machinery (observed: os._exit killing a
    # pytest session 25 min in). Reading jax.config does NOT initialize
    # a backend, so this check is safe even when the tunnel is dead.
    if _cpu_pinned():
        jax.device_get(jnp.ones((8, 128)).sum())  # warm; instant on CPU
        return

    budget = float(os.environ.get("BENCH_PROBE_S", "180"))
    if not _probe_subprocess(budget):
        window = float(os.environ.get("BENCH_PROBE_RETRIES_S", "420"))
        interval = float(os.environ.get("BENCH_PROBE_RETRY_INTERVAL_S", "30"))
        deadline = time.monotonic() + window
        recovered = False
        while time.monotonic() < deadline:
            time.sleep(max(1.0, interval))
            alive = _relay_alive()
            if alive is False:
                continue  # no relay process — don't load the tunnel
            if _probe_subprocess(budget):
                recovered = True
                break
        if not recovered:
            _maybe_fallback(
                f"probe: device unresponsive >{budget:.0f}s and no recovery "
                f"within the {window:.0f}s retry window (tunnel wedged/dead "
                "at start)",
                config,
            )
            # _maybe_fallback returning means another thread (watchdog) is
            # already measuring the fallback; park until it exits the
            # process rather than poisoning this one on a dead backend.
            threading.Event().wait()
    # warm the in-process backend under a side timer: the tunnel can wedge
    # between the subprocess probe succeeding and this eager op
    t = threading.Timer(
        budget,
        lambda: _maybe_fallback(
            f"probe: in-process device op blocked >{budget:.0f}s after a "
            "successful subprocess probe (tunnel wedged mid-start)",
            config,
        ),
    )
    t.daemon = True
    t.start()
    try:
        jax.device_get(jnp.ones((8, 128)).sum())
    finally:
        t.cancel()


def main(config=None, profile_dir=None) -> None:
    """Measure the jitted train step of ``config`` (default: the flagship
    voc_resnet18 at 600x600, batch 16/device) on all available devices.
    ``profile_dir`` wraps the timed loop in a jax.profiler trace."""
    eval_mode = os.environ.get("BENCH_MODE", "train") == "eval"
    if config is None and os.environ.get("BENCH_CONFIG_STDIN") == "1":
        # the CPU-fallback child receives the parent's resolved config on
        # stdin so a wedged non-default run is re-measured, not replaced
        # by the flagship default
        import sys

        from replication_faster_rcnn_tpu.config import config_from_dict

        payload = sys.stdin.read().strip()
        if payload:
            config = config_from_dict(json.loads(payload))
    # label failure paths with the right mode AND shape even before the
    # measurement starts (a probe-stage wedge must not mislabel the run) —
    # set for BOTH modes so a prior in-process run's label can never go
    # stale, and read the caller's image size so a non-600 run that wedges
    # is never recorded against the flagship shape
    global _METRIC
    shape = "600x600" if config is None else "{}x{}".format(*config.data.image_size)
    _METRIC = ("eval" if eval_mode else "train") + f"_images_per_sec_{shape}"
    watchdog = _arm_watchdog(config)
    try:
        _probe_device(config)
        if eval_mode:
            _measure_eval(config, profile_dir, watchdog=watchdog)
        else:
            _measure(config, profile_dir, watchdog=watchdog)
    finally:
        # a raised exception must not leave the timer alive to later print a
        # bogus zero-metric line and os._exit a host process
        watchdog.cancel()


def _flagship_cfg(n_dev):
    """The bench default config: voc_resnet18 at 600x600 on synthetic
    tensors, data-parallel over every device. One definition shared by the
    train and eval measurements so the flagship shape cannot drift between
    the two metrics."""
    from replication_faster_rcnn_tpu.config import DataConfig, MeshConfig, get_config

    return get_config("voc_resnet18").replace(
        data=DataConfig(dataset="synthetic", image_size=(600, 600), max_boxes=32),
        mesh=MeshConfig(num_data=n_dev),
    )


def _capture_trace(profile_dir, step, state, device_batch, *,
                   images_per_sec, metric, n_steps=3) -> str:
    """Short jax.profiler capture of an already-warm program.

    Guarded: if start/step/stop wedges the remote tunnel (the round-4
    failure mode), a timer prints the primary metric as a bare JSON line
    (so queue runners still record the measurement) and hard-exits.
    Budget via BENCH_TRACE_S (default 300s). Returns "ok" or a reason.
    """
    budget = float(os.environ.get("BENCH_TRACE_S", "300"))
    guard = threading.Timer(
        budget,
        lambda: (
            print(
                json.dumps(
                    {
                        "metric": metric,
                        "value": round(images_per_sec, 3),
                        "unit": "images/sec",
                        "trace": f"wedged >{budget:.0f}s; metric saved, "
                                 "process exiting",
                    }
                ),
                flush=True,
            ),
            os._exit(0),
        ),
    )
    guard.daemon = True
    guard.start()
    try:
        from replication_faster_rcnn_tpu.utils.profiling import trace

        with trace(profile_dir):
            for _ in range(n_steps):
                state, metrics = step(state, device_batch)
            jax.device_get(metrics)
        return "ok"
    except Exception as e:  # trace is decoration; never lose the metric
        return f"failed: {e!r}"
    finally:
        guard.cancel()


def _measure(config, profile_dir=None, watchdog=None) -> None:
    import dataclasses

    from replication_faster_rcnn_tpu.config import TrainConfig
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.parallel import (
        make_mesh,
        shard_batch,
        shard_stacked_batch,
        validate_parallel,
    )
    from replication_faster_rcnn_tpu.train import (
        build_multi_step,
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    n_dev = len(jax.devices())
    if config is None:
        # 16/device is the measured best operating point on v5e with the
        # tiled-NMS default (210 img/s vs 186 at 8/device; with the old
        # loop NMS b16 was *slower* — 96 vs 124 — so this default is tied
        # to the tiled backend). BENCH_BATCH overrides per device. Do NOT
        # raise past 16: the batch-32 600x600 compile wedges this image's
        # remote-TPU service (verify SKILL.md gotchas).
        batch_size = int(os.environ.get("BENCH_BATCH", "16")) * n_dev
        cfg = _flagship_cfg(n_dev).replace(
            train=TrainConfig(batch_size=batch_size)
        )
    else:
        # honor the caller's model/image/batch/mesh choices (incl. a model
        # axis and spatial partitioning); force synthetic data
        # (dataset-independent measurement) and fill every device
        n_model = max(1, config.mesh.num_model)
        validate_parallel(config, n_dev)  # descriptive num_model/mesh-fit errors
        n_data = n_dev // n_model
        cfg = config.replace(
            data=dataclasses.replace(config.data, dataset="synthetic"),
            mesh=dataclasses.replace(config.mesh, num_data=n_data),
        )
        batch_size = cfg.train.batch_size
        if batch_size % n_data != 0:
            batch_size = max(1, batch_size // n_data) * n_data
            cfg = cfg.replace(
                train=dataclasses.replace(cfg.train, batch_size=batch_size)
            )
    global _METRIC
    _METRIC = "train_images_per_sec_{}x{}".format(*cfg.data.image_size)
    validate_parallel(cfg, n_dev)
    mesh = make_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)

    from replication_faster_rcnn_tpu.parallel.zero import (
        place_train_state,
        train_state_shardings,
    )

    shardings = train_state_shardings(
        state, mesh, cfg.mesh, cfg.train.shard_opt_state
    )
    state = place_train_state(state, shardings)

    ds = SyntheticDataset(cfg.data, length=batch_size)
    if cfg.data.augment_scale:
        # --augment-scale[-device] must change what the step RUNS, not
        # just the config label: the view attaches the 'jitter' geometry
        # (device mode — the on-chip resample becomes part of the timed
        # step) or pre-jitters on host (host mode; step unchanged but
        # the batch content matches training)
        from replication_faster_rcnn_tpu.data.augment import AugmentedView

        ds = AugmentedView(
            ds, seed=0, epoch=0, hflip=False,
            scale_range=cfg.data.augment_scale,
            scale_on_device=cfg.data.augment_scale_device,
        )
    batch = collate([ds[i] for i in range(batch_size)])
    device_batch = shard_batch(batch, mesh, cfg.mesh)

    # fused multi-step dispatch (train.steps_per_dispatch > 1): the timed
    # program scans K steps per jitted call. The fed/spmd paths stack the
    # same host batch K times on a new leading axis (identical per-step
    # work, 1/K the dispatches); the cache path pre-stages K distinct
    # selections. `device_batch` stays single-step for the stage breakdown.
    k = max(1, cfg.train.steps_per_dispatch)
    timed_batch = device_batch
    if k > 1 and not cfg.data.cache_device:
        chunk = {kk: np.stack([v] * k) for kk, v in batch.items()}
        timed_batch = shard_stacked_batch(chunk, mesh, cfg.mesh)

    if cfg.train.backend == "spmd":
        # measure the explicit shard_map backend (already jitted + donated)
        from replication_faster_rcnn_tpu.parallel import make_shard_map_train_step

        step, _ = make_shard_map_train_step(
            cfg, tx, mesh, steps_per_dispatch=k
        )
    elif cfg.data.cache_device:
        # --cache-device: the timed step is the CACHED one — on-device
        # gather + flip/jitter + train step; per-step host traffic is the
        # index selection only. (Without this branch the flag would
        # silently bench the plain fed step under a cache_device label.)
        from replication_faster_rcnn_tpu.data.device_cache import (
            CachedSampler,
            DeviceCache,
        )
        from replication_faster_rcnn_tpu.train import make_cached_train_step

        base_ds = SyntheticDataset(cfg.data, length=max(2 * batch_size, 64))
        cache = DeviceCache(base_ds, mesh=mesh)
        sampler = CachedSampler(
            len(base_ds), cache.image_hw, batch_size=batch_size, seed=0,
            hflip=cfg.data.augment_hflip, scale_range=cfg.data.augment_scale,
        )
        if k > 1:
            from replication_faster_rcnn_tpu.data.device_cache import (
                stack_selections,
            )
            from replication_faster_rcnn_tpu.train import (
                make_cached_multi_step,
            )

            sels = stack_selections([
                sampler.selection(
                    (np.arange(batch_size) + i * batch_size) % len(base_ds)
                )
                for i in range(k)
            ])
            sel = shard_stacked_batch(sels, mesh, cfg.mesh)
            cached = jax.jit(
                make_cached_multi_step(model, cfg, tx, k),
                donate_argnums=(0,),
                out_shardings=(shardings, None),
            )
        else:
            sel = shard_batch(
                sampler.selection(np.arange(batch_size) % len(base_ds)),
                mesh, cfg.mesh,
            )
            cached = jax.jit(
                make_cached_train_step(model, cfg, tx),
                donate_argnums=(0,),
                out_shardings=(shardings, None),
            )

        def step(state, _batch, _c=cached, _arrays=cache.arrays, _sel=sel):
            return _c(state, _arrays, _sel)

    else:
        base_step = make_train_step(model, cfg, tx)
        step = jax.jit(
            build_multi_step(base_step, k) if k > 1 else base_step,
            donate_argnums=(0,),
            out_shardings=(shardings, None),
        )

    # warmup (compile) + 2 steps to stabilize. NOTE: sync via device_get of
    # the scalar metrics, not block_until_ready — the remote-TPU plugin in
    # this image returns from block_until_ready before execution finishes,
    # which inflated throughput ~100x; a host transfer genuinely waits.
    for _ in range(3):
        state, metrics = step(state, timed_batch)
    jax.device_get(metrics)

    # BENCH_STEPS counts TRAIN steps; a fused program runs k per dispatch,
    # so round up to whole dispatches and report per-step throughput
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    n_dispatch = max(1, -(-n_steps // k))
    n_steps = n_dispatch * k
    t0 = time.time()
    for _ in range(n_dispatch):
        state, metrics = step(state, timed_batch)
    jax.device_get(metrics)  # forces the whole dependency chain
    dt = time.time() - t0
    images_per_sec = n_steps * batch_size / dt

    # Trace capture runs AFTER the primary measurement, never around it:
    # round 4's in-loop trace wedged at stop_trace (remote tunnel) and
    # lost the throughput number with it. Here a wedge can only cost the
    # trace — a guard prints the already-won metric and exits. The main
    # watchdog stands down FIRST: it must not fire mid-trace and discard
    # the won metric through the fallback path.
    trace_status = None
    if profile_dir is not None:
        if watchdog is not None:
            watchdog.cancel()
        trace_status = _capture_trace(
            profile_dir, step, state, timed_batch,
            images_per_sec=images_per_sec, metric=_METRIC,
        )

    baseline_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
        "baseline_measured.json",
    )
    vs_baseline = float("nan")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        ref = baseline.get("torch_cpu_images_per_sec")
        if ref:
            vs_baseline = images_per_sec / ref

    # the primary metric is won; the remaining work (FLOPs subprocess, up
    # to BENCH_FLOPS_TIMEOUT_S, and the breakdown's stage compiles) must
    # not let the main watchdog fire and discard it as a bogus wedge
    if watchdog is not None:
        watchdog.cancel()
    flops_per_step = _step_flops(cfg, batch_size)
    mfu = None
    mfu_basis = None
    if flops_per_step:
        from replication_faster_rcnn_tpu.telemetry.mfu import compute_mfu

        peak, mfu_basis = _peak_flops_per_sec(n_dev)
        mfu = compute_mfu(flops_per_step, images_per_sec / batch_size, peak)
        if mfu is None:
            mfu_basis = None

    out = {
        "metric": _METRIC,
        "value": round(images_per_sec, 3),
        "unit": "images/sec",
        "vs_baseline": round(vs_baseline, 3) if np.isfinite(vs_baseline) else None,
        "flops_per_step": flops_per_step,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_basis": mfu_basis,
    }
    if k > 1:
        out["steps_per_dispatch"] = k
    if trace_status is not None:
        out["trace"] = trace_status
    if out["mfu"] is None and jax.default_backend() == "cpu":
        # bench contract: a CPU-side record must carry MFU on the measured-
        # CPU-matmul basis or fail LOUDLY — "mfu": null with rc 0 is how
        # BENCH_r05.json shipped a silent hole past the fallback parent's
        # own check (the parent only vets the child it spawned; a directly-
        # run CPU bench had no enforcement). Same exit code (3) and
        # mfu_error key as the parent-side rule, so drivers see one shape.
        out["mfu_error"] = (
            "cpu record produced no MFU "
            "(flops estimate or measured matmul peak unavailable)"
        )
        print(json.dumps(out), flush=True)
        os._exit(3)
    if os.environ.get("BENCH_BREAKDOWN", "1") != "0":
        step_ms = dt / n_steps * 1e3
        # The breakdown is strictly optional decoration on an already-won
        # measurement: if one of its 6 extra stage compiles wedges the
        # remote tunnel (unkillable from Python), a side timer prints the
        # primary metric and exits instead of hanging forever; a plain
        # exception just annotates the JSON. The main watchdog already
        # stood down before _step_flops — the guard is the only failure
        # path from here on.
        budget = float(os.environ.get("BENCH_BREAKDOWN_S", "600"))
        guard = threading.Timer(
            budget,
            lambda: (
                print(
                    json.dumps(
                        {
                            **out,
                            "breakdown": {
                                "error": f"wedged >{budget:.0f}s; skipped"
                            },
                        }
                    ),
                    flush=True,
                ),
                os._exit(0),
            ),
        )
        guard.daemon = True
        guard.start()
        try:
            if cfg.data.cache_device:
                # the stage prefixes time the FED graph; under the cached
                # step they would misattribute the gather — skip honestly
                out["breakdown"] = {
                    "note": "skipped under --cache-device (stage prefixes "
                    "time the fed-step graph)"
                }
            else:
                out["breakdown"] = _stage_breakdown(
                    model, cfg, state, device_batch, step_ms, tx=tx
                )
        except Exception as e:  # never lose the primary metric
            out["breakdown"] = {"error": repr(e)}
        finally:
            guard.cancel()
    print(json.dumps(out))


def _measure_eval(config, profile_dir=None, watchdog=None) -> None:
    """``BENCH_MODE=eval``: jitted inference throughput — forward + fixed-
    shape decode + per-class NMS (`eval/detect.py`), data-parallel over all
    devices — on synthetic 600x600 tensors, images/sec.

    ``vs_baseline`` is null by design: the reference has NO inference/eval
    path to race against (`test_eval.py` is 0 bytes — SURVEY.md §2.1 #15);
    this metric exists because the eval path is new capability whose cost
    still needs a number of record."""
    import dataclasses

    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train import (
        create_train_state,
        make_optimizer,
    )

    n_dev = len(jax.devices())
    if config is None:
        cfg = _flagship_cfg(n_dev)
    else:
        cfg = config.replace(
            data=dataclasses.replace(config.data, dataset="synthetic")
        )
        if cfg.mesh.num_model > 1 or cfg.mesh.spatial:
            # the eval path is data-parallel only (Evaluator._eval_sharding
            # forces num_model=1): refuse rather than print a number
            # labeled as if the requested model-parallel layout ran
            raise ValueError(
                "BENCH_MODE=eval measures the data-parallel eval path only; "
                "drop --num-model/--spatial (got num_model="
                f"{cfg.mesh.num_model}, spatial={cfg.mesh.spatial})"
            )
        from replication_faster_rcnn_tpu.parallel import validate_parallel

        validate_parallel(cfg, n_dev)
    global _METRIC
    _METRIC = "eval_images_per_sec_{}x{}".format(*cfg.data.image_size)
    # batch precedence: BENCH_EVAL_BATCH env > the CLI/caller config's
    # train.batch_size > 8 per device; the JSON reports the effective value
    if "BENCH_EVAL_BATCH" in os.environ:
        batch_size = int(os.environ["BENCH_EVAL_BATCH"])
    elif config is not None:
        batch_size = cfg.train.batch_size
    else:
        batch_size = 8 * n_dev
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    _, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    ev = Evaluator(cfg)
    img_sharding, rep_sharding = ev._eval_sharding(batch_size)
    if rep_sharding is not None:
        variables = jax.device_put(variables, rep_sharding)
    ds = SyntheticDataset(cfg.data, length=batch_size)
    images = collate([ds[i] for i in range(batch_size)])["image"]
    # same sync discipline as the train measurement: upload once, queue all
    # jitted calls, one device_get of the final outputs at the end (the
    # per-call device_put/get inside Evaluator.predict_batch would add a
    # host round-trip per step — ruinous over the remote-TPU tunnel)
    images_dev = jax.device_put(np.asarray(images), img_sharding)
    for _ in range(3):
        out = ev._jit_infer(variables, images_dev)
    jax.device_get(out)
    n_steps = int(os.environ.get("BENCH_STEPS", "10"))
    t0 = time.time()
    for _ in range(n_steps):
        out = ev._jit_infer(variables, images_dev)
    jax.device_get(out)
    dt = time.time() - t0
    if watchdog is not None:
        watchdog.cancel()  # measurement won; only printing remains
    value = round(n_steps * batch_size / dt, 3)
    record = {
        "metric": _METRIC,
        "value": value,
        "unit": "images/sec",
        "vs_baseline": None,
        "batch_size": batch_size,
        "note": "reference has no eval/inference path (empty "
        "test_eval.py); no baseline ratio exists",
    }
    if profile_dir is not None:
        # post-measurement guarded capture; see _capture_trace
        record["trace"] = _capture_trace(
            profile_dir,
            lambda v, img: (v, ev._jit_infer(v, img)),
            variables,
            images_dev,
            images_per_sec=value,
            metric=_METRIC,
        )
    print(json.dumps(record))


def _step_flops(cfg, batch_size):
    """Global FLOPs of one train step (full ``batch_size``), from XLA's
    HloCostAnalysis of the step lowered for ONE CPU device in a
    scrubbed-env subprocess.

    Why a subprocess: the axon remote-TPU plugin routes ``cost_analysis``
    through the device tunnel and has been observed to block indefinitely
    (round-2 measurement), so the analysis must never run against the
    plugin backend. FLOP counts are backend-independent; the child only
    traces abstract values — it allocates no batch arrays and never
    compiles. The count is *model* FLOPs (1-device graph, no halo/collective
    duplication), the conventional MFU numerator. Returns None on any
    failure or after BENCH_FLOPS_TIMEOUT_S (default 420s)."""
    import dataclasses
    import subprocess
    import sys

    try:
        child_cfg = cfg.replace(
            mesh=dataclasses.replace(
                cfg.mesh, num_data=1, num_model=1, spatial=False
            ),
            train=dataclasses.replace(
                cfg.train, backend="auto", batch_size=batch_size
            ),
        )
        if jax.default_backend() == "cpu":
            # plain CPU backend (tests, CI): in-process analysis is safe
            # and skips a whole extra Python+JAX cold start
            flops = _flops_of_config(child_cfg)
            return flops if flops and flops > 0 else None
        payload = json.dumps(dataclasses.asdict(child_cfg))
        env = dict(os.environ)
        env.update(
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )
        r = subprocess.run(
            [
                sys.executable,
                "-c",
                "from replication_faster_rcnn_tpu.benchmark import "
                "_flops_child; _flops_child()",
            ],
            input=payload,
            text=True,
            capture_output=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=float(os.environ.get("BENCH_FLOPS_TIMEOUT_S", "420")),
        )
        flops = json.loads(r.stdout.strip().splitlines()[-1])["flops"]
        return flops if flops and flops > 0 else None
    except Exception:
        return None


def abstract_step_inputs(cfg, tx):
    """(model, state_abs, batch_abs): abstract fixtures of one train step
    — shapes/dtypes only, no arrays allocated, no param-init programs run
    (a pure trace). Shared by the bench's FLOPs counter and the static
    cost-attribution script (`benchmarks/backward_analysis.py`) so the
    two can never analyze different shapes."""
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
    from replication_faster_rcnn_tpu.train import create_train_state

    model = FasterRCNN(cfg)
    state_abs = jax.eval_shape(
        lambda rng: create_train_state(cfg, rng, tx)[1], jax.random.PRNGKey(0)
    )
    sample = collate([SyntheticDataset(cfg.data, length=1)[0]])
    b = cfg.train.batch_size
    batch_abs = {
        k: jax.ShapeDtypeStruct((b,) + v.shape[1:], v.dtype)
        for k, v in sample.items()
    }
    if cfg.data.augment_device and (
        cfg.data.augment_hflip
        or cfg.data.augment_scale
        or cfg.data.augment_translate
    ):
        # device-mode augmentation ships an int32 (idx, epoch) row per
        # sample (data/augment.py::AugmentTagView) — the fixture must
        # carry it so warmup/audit lower the runtime trace, not a twin
        batch_abs["aug"] = jax.ShapeDtypeStruct((b, 2), np.int32)
    return model, state_abs, batch_abs


def lowered_cost_analysis(lowered):
    """{flops, bytes_accessed} from an already-lowered program's
    HloCostAnalysis (no compile). Shared by the step-profile harness and
    the HLO auditor (analysis/fingerprint.py) so both price programs
    identically. Only safe on a non-plugin backend; callers guard."""
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return {
        "flops": float(ca.get("flops", 0.0)) if ca else 0.0,
        "bytes_accessed": float(ca.get("bytes accessed", 0.0)) if ca else 0.0,
    }


def lowered_cost(fn, *abstract_args):
    """{flops, bytes_accessed} of ``fn`` from HloCostAnalysis of its
    abstract lowering (no compile). Only safe on a non-plugin backend;
    callers guard (see :func:`_step_flops`)."""
    return lowered_cost_analysis(jax.jit(fn).lower(*abstract_args))


def _flops_of_config(cfg) -> float:
    """HloCostAnalysis FLOPs of one train step of ``cfg`` (abstract
    lowering — no batch arrays, no compile). Only safe on a non-plugin
    backend; callers guard (see :func:`_step_flops`)."""
    from replication_faster_rcnn_tpu.train import make_optimizer, make_train_step

    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state_abs, batch_abs = abstract_step_inputs(cfg, tx)
    return lowered_cost(
        make_train_step(model, cfg, tx), state_abs, batch_abs
    )["flops"]


def _flops_child():
    """Subprocess body for :func:`_step_flops`: stdin carries the config as
    ``dataclasses.asdict`` JSON; stdout's last line is ``{"flops": N}``.
    Must run with JAX_PLATFORMS=cpu (the parent scrubs the env)."""
    import sys

    from replication_faster_rcnn_tpu.config import config_from_dict

    cfg = config_from_dict(json.load(sys.stdin))
    print(json.dumps({"flops": _flops_of_config(cfg)}))


def _peak_flops_per_sec(n_dev: int):
    """(aggregate peak FLOP/s, basis label) for the current backend —
    thin wrapper over `telemetry.mfu.peak_flops_per_sec`, which owns the
    TPU datasheet table (device_kind-keyed, PALLAS_AXON_TPU_GEN fallback
    for opaque plugin backends) and the measured-matmul CPU peak that
    keeps MFU non-null on the CPU-fallback path."""
    from replication_faster_rcnn_tpu.telemetry.mfu import peak_flops_per_sec

    return peak_flops_per_sec(n_dev)


def _stage_breakdown(model, cfg, state, device_batch, step_ms: float, tx=None):
    """Wall-time attribution across the step's pipeline stages.

    Times six jitted prefixes of the step (each returning a scalar so the
    host sync transfers nothing but still waits on the full computation):
    trunk -> +rpn heads -> +proposal NMS -> +target creators -> full
    forward+loss -> +value_and_grad; successive differences plus the
    already-measured full-step time attribute the device-side label
    makers (`targets_ms`) and head (`head_loss_ms`) inside the old
    targets_head_loss lump, and backward (grad minus forward) vs the
    optimizer update (step minus grad) — the r3 VERDICT's "40.7 ms
    backward+update" lump, split on chip. One more jitted program (not a
    prefix) times the optimizer update directly on materialized
    gradients (`opt_update_direct_ms`). BENCH_BREAKDOWN=0 disables
    (7 extra stage compiles).
    """
    import jax.numpy as jnp
    import optax

    from replication_faster_rcnn_tpu.train.train_step import compute_losses

    h, w = cfg.data.image_size
    has_jitter = "jitter" in device_batch

    def _scalar(feat):
        # FPN's extract_features returns a list of levels
        feats = feat if isinstance(feat, (list, tuple)) else [feat]
        return sum(f.astype(jnp.float32).sum() for f in feats)

    def _images(batch):
        # under --augment-scale-device the real step's first on-device op
        # is the jitter resample gather (train_step.compute_losses); the
        # prefixes must run the same pipeline or the resample cost would
        # silently land in targets_ms while trunk_ms timed a pipeline the
        # step never runs
        if has_jitter:
            from replication_faster_rcnn_tpu.ops.image import (
                batched_scale_jitter,
            )

            return batched_scale_jitter(batch["image"], batch["jitter"])
        return batch["image"]

    def _features(state, batch):
        # train=True to match what the timed step executes (train-mode BN
        # computes batch statistics; eval-mode would misattribute that
        # cost to the forward_fn - propose_fn difference)
        v = {"params": state.params, "batch_stats": state.batch_stats}
        feat, _ = model.apply(
            v, _images(batch), True, method="extract_features",
            mutable=["batch_stats"],
        )
        return v, feat

    @jax.jit
    def jitter_fn(state, batch):
        del state
        return _images(batch).astype(jnp.float32).sum()

    @jax.jit
    def trunk_fn(state, batch):
        _, feat = _features(state, batch)
        return _scalar(feat)

    @jax.jit
    def rpn_fn(state, batch):
        v, feat = _features(state, batch)
        logits, deltas, _ = model.apply(v, feat, method="rpn_forward")
        return logits.astype(jnp.float32).sum() + deltas.astype(jnp.float32).sum()

    @jax.jit
    def propose_fn(state, batch):
        v, feat = _features(state, batch)
        logits, deltas, anchors = model.apply(v, feat, method="rpn_forward")
        rois, valid = model.apply(
            v, logits, deltas, anchors, float(h), float(w), True, method="propose"
        )
        return rois.sum() + valid.sum()

    @jax.jit
    def targets_fn(state, batch):
        # the real step's own prefix (trunk -> RPN -> propose -> both
        # target creators, no head): compute_losses' targets_only mode,
        # so this timed stage can never drift from what the step runs
        rng = jax.random.fold_in(state.rng, state.step)
        probe, _ = compute_losses(
            model, cfg, state.params, state.batch_stats, batch, rng, True,
            targets_only=True,
        )
        return probe

    @jax.jit
    def forward_fn(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)
        total, _ = compute_losses(
            model, cfg, state.params, state.batch_stats, batch, rng, True
        )
        return total

    @jax.jit
    def grad_fn(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(params):
            return compute_losses(
                model, cfg, params, state.batch_stats, batch, rng, True
            )

        (total, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        # the norm consumes every gradient (otherwise XLA would DCE the
        # whole backward) and is exactly what the real step computes for
        # its grad_norm metric, so the stage cost matches the step's
        return total + optax.global_norm(grads)

    @jax.jit
    def null_fn(state, grads):
        # near-empty program with the same on-device inputs and a scalar
        # output: times pure dispatch + completion-sync overhead. Over the
        # axon remote tunnel each standalone program execution pays an RPC
        # round-trip that a sub-millisecond op like the optimizer update
        # cannot amortize — this row is the floor to read
        # opt_update_direct_ms against (r4 VERDICT #1: 15-22 ms direct vs
        # ~0.4 ms analytic; if the floor is ~15 ms the "overhead" is the
        # measurement harness, matching the in-step subtraction's ~0)
        return jax.tree_util.tree_leaves(grads)[0].ravel()[0] + jnp.float32(
            state.step
        )

    @jax.jit
    def update_fn(state, grads):
        # the optimizer update ALONE, on materialized grads: a direct
        # measurement, unlike the step_ms - t_grad subtraction, whose
        # separately-jitted prefixes fuse differently and can report a
        # (noise-floor) NEGATIVE update cost — observed -4.27 ms on v5e
        # at b16 while the analytic HBM floor is ~0.4 ms
        # (benchmarks/backward_analysis.json). The updated trees are jit
        # OUTPUTS on purpose: an update whose results feed only a scalar
        # reduction can be fused into the reduce and never write the
        # params/mu/nu trees to HBM — eliding the very cost this row
        # measures.
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return params, opt_state

    def _sync_leaf(out):
        # wait for program completion without transferring the outputs:
        # fetching any one output buffer gates on the whole program, and
        # device_get of full param/opt trees over the remote tunnel would
        # swamp a sub-millisecond measurement
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])

    def timed(fn, *args, sync=jax.device_get):
        for _ in range(2):  # compile + 1 stabilizing run
            out = fn(*args)
        sync(out)
        n, t0 = 5, time.time()
        for _ in range(n):
            out = fn(*args)
        sync(out)
        return (time.time() - t0) / n * 1e3

    t_jitter = timed(jitter_fn, state, device_batch) if has_jitter else None
    t_trunk = timed(trunk_fn, state, device_batch)
    t_rpn = timed(rpn_fn, state, device_batch)
    t_prop = timed(propose_fn, state, device_batch)
    t_targets = timed(targets_fn, state, device_batch)
    t_fwd = timed(forward_fn, state, device_batch)
    t_grad = timed(grad_fn, state, device_batch)
    t_upd = t_floor = upd_err = floor_err = None
    if tx is not None:
        try:
            zero_grads = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            t_upd = timed(update_fn, state, zero_grads, sync=_sync_leaf)
        except Exception as e:  # noqa: BLE001 — direct row is best-effort
            upd_err = repr(e)
        if t_upd is not None:
            try:
                t_floor = timed(null_fn, state, zero_grads, sync=_sync_leaf)
            except Exception as e:  # noqa: BLE001 — floor row, same deal
                floor_err = repr(e)
    out = {
        **({"jitter_ms": round(t_jitter, 2)} if t_jitter is not None else {}),
        # successive-difference convention: when the jitter stage exists it
        # is the pipeline's first prefix, so trunk gets the difference
        "trunk_ms": round(t_trunk - (t_jitter or 0.0), 2),
        "rpn_heads_ms": round(t_rpn - t_trunk, 2),
        "proposal_nms_ms": round(t_prop - t_rpn, 2),
        "targets_ms": round(t_targets - t_prop, 2),
        "head_loss_ms": round(t_fwd - t_targets, 2),
        "targets_head_loss_ms": round(t_fwd - t_prop, 2),
        "backward_ms": round(t_grad - t_fwd, 2),
        "opt_update_ms": round(step_ms - t_grad, 2),
        "backward_update_ms": round(step_ms - t_fwd, 2),
        "step_ms": round(step_ms, 2),
    }
    if t_upd is not None:
        out["opt_update_direct_ms"] = round(t_upd, 2)
        if t_floor is not None:
            out["dispatch_floor_ms"] = round(t_floor, 2)
            # the update's cost net of the per-program dispatch/sync floor
            # — the number comparable to the ~0.4 ms analytic HBM bound
            out["opt_update_direct_adj_ms"] = round(max(0.0, t_upd - t_floor), 2)
        elif floor_err is not None:
            # a missing floor must be distinguishable from an older-binary
            # run: the round's central dispatch-floor question would
            # otherwise go silently unanswered
            out["dispatch_floor_error"] = floor_err
    elif upd_err is not None:
        out["opt_update_direct_error"] = upd_err
    return out


if __name__ == "__main__":
    main()
