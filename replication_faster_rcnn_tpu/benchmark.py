"""Benchmark: jitted train-step throughput on the flagship config.

(Importable package module; the repo-root ``bench.py`` is a thin shim so
the driver can run it from the checkout root.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", plus
"flops_per_step"/"mfu" and — unless BENCH_BREAKDOWN=0 — a per-stage
"breakdown"}.

Metric: VOC-shaped (600x600, synthetic tensors — dataset-independent)
training images/sec on the available device(s). ``vs_baseline`` is the
ratio against the measured single-host PyTorch-CPU reference throughput
(BASELINE.md: the reference publishes no numbers, so the baseline is
measured by benchmarks/reference_baseline.py and cached in
benchmarks/baseline_measured.json; target is >= 6x).

MFU: ``achieved_flops / (time x peak_bf16_flops)``. The step's FLOP count
comes from XLA's own HloCostAnalysis on the *lowered* (pre-compile) module
— a host-side analysis that never touches the device, so it is safe even
through the fragile remote-TPU tunnel; it undercounts post-fusion FLOPs by
a few percent, which makes the reported MFU slightly conservative. Peak is
per-chip bf16 (v5e: 197 TFLOP/s) x mesh size.

Stage breakdown (SURVEY.md §5 tracing plan): wall-time of jitted prefixes
of the step — trunk, +RPN heads, +proposal NMS, full forward+loss — whose
successive differences attribute time to trunk / rpn_heads / proposal_nms
/ targets_head_loss / backward_update. Differences of separately-jitted
programs (XLA fuses differently per program), so treat small negative
deltas as noise floors, not measurement bugs.
"""

from __future__ import annotations

import json
import os
import threading
import time

import jax
import numpy as np


def _wedge_exit(reason: str):
    print(
        json.dumps(
            {
                "metric": "train_images_per_sec_600x600",
                "value": 0.0,
                "unit": "images/sec",
                "vs_baseline": None,
                "error": reason,
            }
        ),
        flush=True,
    )
    os._exit(2)


def _arm_watchdog() -> threading.Timer:
    """Print a diagnostic JSON line and exit if the measurement wedges.

    The remote-TPU tunnel in this image can hang indefinitely inside a
    compile (no Python-level interrupt possible); without this the driver
    would record nothing at all. BENCH_WATCHDOG_S overrides the budget.
    Returns the timer; cancel it once the measurement completes.
    """
    budget = float(os.environ.get("BENCH_WATCHDOG_S", "1500"))

    def fire():
        _wedge_exit(
            f"watchdog: device wedged >{budget:.0f}s (remote compile tunnel hang)"
        )

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()
    return t


def _probe_device() -> None:
    """Fail fast if the device tunnel is already wedged.

    A wedged remote-TPU service blocks even a trivial op forever, and a
    blocked device call cannot be interrupted from Python — so a short
    side watchdog reports the wedge in minutes instead of burning the
    full measurement budget before saying anything.
    """
    import jax.numpy as jnp

    budget = float(os.environ.get("BENCH_PROBE_S", "180"))
    t = threading.Timer(
        budget,
        lambda: _wedge_exit(
            f"probe: device unresponsive >{budget:.0f}s before compile "
            "(tunnel wedged at start)"
        ),
    )
    t.daemon = True
    t.start()
    try:
        jax.device_get(jnp.ones((8, 128)).sum())
    finally:
        t.cancel()


def main(config=None, profile_dir=None) -> None:
    """Measure the jitted train step of ``config`` (default: the flagship
    voc_resnet18 at 600x600, batch 8/device) on all available devices.
    ``profile_dir`` wraps the timed loop in a jax.profiler trace."""
    watchdog = _arm_watchdog()
    try:
        _probe_device()
        _measure(config, profile_dir, watchdog=watchdog)
    finally:
        # a raised exception must not leave the timer alive to later print a
        # bogus zero-metric line and os._exit a host process
        watchdog.cancel()


def _measure(config, profile_dir=None, watchdog=None) -> None:
    import dataclasses

    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        MeshConfig,
        TrainConfig,
        get_config,
    )
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import collate
    from replication_faster_rcnn_tpu.parallel import (
        make_mesh,
        shard_batch,
        validate_parallel,
    )
    from replication_faster_rcnn_tpu.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )

    n_dev = len(jax.devices())
    if config is None:
        batch_size = 8 * n_dev
        cfg = get_config("voc_resnet18").replace(
            data=DataConfig(dataset="synthetic", image_size=(600, 600), max_boxes=32),
            train=TrainConfig(batch_size=batch_size),
            mesh=MeshConfig(num_data=n_dev),
        )
    else:
        # honor the caller's model/image/batch/mesh choices (incl. a model
        # axis and spatial partitioning); force synthetic data
        # (dataset-independent measurement) and fill every device
        n_model = max(1, config.mesh.num_model)
        validate_parallel(config, n_dev)  # descriptive num_model/mesh-fit errors
        n_data = n_dev // n_model
        cfg = config.replace(
            data=dataclasses.replace(config.data, dataset="synthetic"),
            mesh=dataclasses.replace(config.mesh, num_data=n_data),
        )
        batch_size = cfg.train.batch_size
        if batch_size % n_data != 0:
            batch_size = max(1, batch_size // n_data) * n_data
            cfg = cfg.replace(
                train=dataclasses.replace(cfg.train, batch_size=batch_size)
            )
    validate_parallel(cfg, n_dev)
    mesh = make_mesh(cfg.mesh)
    tx, _ = make_optimizer(cfg, steps_per_epoch=100)
    model, state = create_train_state(cfg, jax.random.PRNGKey(0), tx)

    from replication_faster_rcnn_tpu.parallel.zero import (
        place_train_state,
        train_state_shardings,
    )

    shardings = train_state_shardings(
        state, mesh, cfg.mesh, cfg.train.shard_opt_state
    )
    state = place_train_state(state, shardings)

    ds = SyntheticDataset(cfg.data, length=batch_size)
    batch = collate([ds[i] for i in range(batch_size)])
    device_batch = shard_batch(batch, mesh, cfg.mesh)

    if cfg.train.backend == "spmd":
        # measure the explicit shard_map backend (already jitted + donated)
        from replication_faster_rcnn_tpu.parallel import make_shard_map_train_step

        step, _ = make_shard_map_train_step(cfg, tx, mesh)
    else:
        step = jax.jit(
            make_train_step(model, cfg, tx),
            donate_argnums=(0,),
            out_shardings=(shardings, None),
        )

    # warmup (compile) + 2 steps to stabilize. NOTE: sync via device_get of
    # the scalar metrics, not block_until_ready — the remote-TPU plugin in
    # this image returns from block_until_ready before execution finishes,
    # which inflated throughput ~100x; a host transfer genuinely waits.
    for _ in range(3):
        state, metrics = step(state, device_batch)
    jax.device_get(metrics)

    from replication_faster_rcnn_tpu.utils.profiling import trace

    n_steps = 10
    t0 = time.time()
    with trace(profile_dir):
        for _ in range(n_steps):
            state, metrics = step(state, device_batch)
        jax.device_get(metrics)  # forces the whole dependency chain
    dt = time.time() - t0
    images_per_sec = n_steps * batch_size / dt

    baseline_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks",
        "baseline_measured.json",
    )
    vs_baseline = float("nan")
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        ref = baseline.get("torch_cpu_images_per_sec")
        if ref:
            vs_baseline = images_per_sec / ref

    flops_per_step = _step_flops(step, state, device_batch)
    if flops_per_step and cfg.train.backend == "spmd":
        # jit(shard_map(...)) lowers the body at per-shard shapes — the
        # batch is sharded over the DATA axis only — so the cost analysis
        # counts global/num_data FLOPs; scale by the data-axis width so
        # mfu is comparable with the auto-partitioning backend (whose
        # lowered module carries global shapes).
        flops_per_step *= mesh.shape[cfg.mesh.data_axis]
    mfu = None
    if flops_per_step:
        peak = _peak_flops_per_sec(n_dev)
        if peak:
            mfu = (flops_per_step * images_per_sec / batch_size) / peak

    out = {
        "metric": "train_images_per_sec_600x600",
        "value": round(images_per_sec, 3),
        "unit": "images/sec",
        "vs_baseline": round(vs_baseline, 3) if np.isfinite(vs_baseline) else None,
        "flops_per_step": flops_per_step,
        "mfu": round(mfu, 4) if mfu is not None else None,
    }
    if os.environ.get("BENCH_BREAKDOWN", "1") != "0":
        step_ms = dt / n_steps * 1e3
        # The breakdown is strictly optional decoration on an already-won
        # measurement: if one of its 4 extra stage compiles wedges the
        # remote tunnel (unkillable from Python), a side timer prints the
        # primary metric and exits instead of letting the main watchdog
        # report value=0; a plain exception just annotates the JSON. The
        # main watchdog (whose firing would discard the metric) stands
        # down first — from here on the guard is the only failure path.
        if watchdog is not None:
            watchdog.cancel()
        budget = float(os.environ.get("BENCH_BREAKDOWN_S", "600"))
        guard = threading.Timer(
            budget,
            lambda: (
                print(
                    json.dumps(
                        {
                            **out,
                            "breakdown": {
                                "error": f"wedged >{budget:.0f}s; skipped"
                            },
                        }
                    ),
                    flush=True,
                ),
                os._exit(0),
            ),
        )
        guard.daemon = True
        guard.start()
        try:
            out["breakdown"] = _stage_breakdown(
                model, cfg, state, device_batch, step_ms
            )
        except Exception as e:  # never lose the primary metric
            out["breakdown"] = {"error": repr(e)}
        finally:
            guard.cancel()
    print(json.dumps(out))


def _step_flops(step, state, device_batch):
    """One train step's FLOPs per XLA's HloCostAnalysis of the lowered
    (pre-compile) module. Host-side only — never touches the device (the
    remote-TPU tunnel in this image must not be asked to compile twice).
    Returns None when the analysis is unavailable on the backend."""
    try:
        ca = step.lower(state, device_batch).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
        return flops if flops > 0 else None
    except Exception:
        return None


def _peak_flops_per_sec(n_dev: int):
    """Aggregate peak bf16 FLOP/s of the mesh, or None off-TPU (an MFU
    against a CPU's peak would be meaningless for a TPU framework) or on an
    unrecognized TPU generation (a silently-wrong peak would distort MFU).

    The chip generation comes from the device's own ``device_kind``; the
    PALLAS_AXON_TPU_GEN env var is only a fallback for plugin backends
    whose device_kind string is opaque."""
    dev = jax.devices()[0]
    if dev.platform != "tpu":
        return None
    kind = getattr(dev, "device_kind", "").lower()
    if not any(g in kind for g in ("v4", "v5", "v6")):
        kind = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        peak = 197e12
    elif "v5p" in kind or "v5" in kind:
        peak = 459e12
    elif "v6 lite" in kind or "v6e" in kind:
        peak = 918e12
    elif "v4" in kind:
        peak = 275e12
    else:
        return None
    return peak * n_dev


def _stage_breakdown(model, cfg, state, device_batch, step_ms: float):
    """Wall-time attribution across the step's pipeline stages.

    Times four jitted prefixes of the step (each returning a scalar so the
    host sync transfers nothing but still waits on the full computation):
    trunk -> +rpn heads -> +proposal NMS -> full forward+loss; successive
    differences plus the already-measured full-step time attribute
    backward+update as the remainder. BENCH_BREAKDOWN=0 disables (4 extra
    stage compiles).
    """
    import jax.numpy as jnp

    from replication_faster_rcnn_tpu.train.train_step import compute_losses

    h, w = cfg.data.image_size
    images = device_batch["image"]

    def _scalar(feat):
        # FPN's extract_features returns a list of levels
        feats = feat if isinstance(feat, (list, tuple)) else [feat]
        return sum(f.astype(jnp.float32).sum() for f in feats)

    def _features(state, images):
        # train=True to match what the timed step executes (train-mode BN
        # computes batch statistics; eval-mode would misattribute that
        # cost to the forward_fn - propose_fn difference)
        v = {"params": state.params, "batch_stats": state.batch_stats}
        feat, _ = model.apply(
            v, images, True, method="extract_features", mutable=["batch_stats"]
        )
        return v, feat

    @jax.jit
    def trunk_fn(state, images):
        _, feat = _features(state, images)
        return _scalar(feat)

    @jax.jit
    def rpn_fn(state, images):
        v, feat = _features(state, images)
        logits, deltas, _ = model.apply(v, feat, method="rpn_forward")
        return logits.astype(jnp.float32).sum() + deltas.astype(jnp.float32).sum()

    @jax.jit
    def propose_fn(state, images):
        v, feat = _features(state, images)
        logits, deltas, anchors = model.apply(v, feat, method="rpn_forward")
        rois, valid = model.apply(
            v, logits, deltas, anchors, float(h), float(w), True, method="propose"
        )
        return rois.sum() + valid.sum()

    @jax.jit
    def forward_fn(state, batch):
        rng = jax.random.fold_in(state.rng, state.step)
        total, _ = compute_losses(
            model, cfg, state.params, state.batch_stats, batch, rng, True
        )
        return total

    def timed(fn, *args):
        for _ in range(2):  # compile + 1 stabilizing run
            out = fn(*args)
        jax.device_get(out)
        n, t0 = 5, time.time()
        for _ in range(n):
            out = fn(*args)
        jax.device_get(out)
        return (time.time() - t0) / n * 1e3

    t_trunk = timed(trunk_fn, state, images)
    t_rpn = timed(rpn_fn, state, images)
    t_prop = timed(propose_fn, state, images)
    t_fwd = timed(forward_fn, state, device_batch)
    return {
        "trunk_ms": round(t_trunk, 2),
        "rpn_heads_ms": round(t_rpn - t_trunk, 2),
        "proposal_nms_ms": round(t_prop - t_rpn, 2),
        "targets_head_loss_ms": round(t_fwd - t_prop, 2),
        "backward_update_ms": round(step_ms - t_fwd, 2),
        "step_ms": round(step_ms, 2),
    }


if __name__ == "__main__":
    main()
