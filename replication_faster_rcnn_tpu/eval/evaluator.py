"""Dataset evaluator: jitted inference sweep -> VOC or COCO mAP.

Completes the reference's missing eval path (`test_eval.py`, 0 bytes):
runs the combined FasterRCNN forward (test-mode NMS budgets 3000->300,
reference `nets/rpn.py:41-43`) + fixed-shape decode over a dataset and
reduces on host to mAP@EvalConfig.iou_thresh (metric="voc") or the full
COCO summary — mAP@[.50:.95], AP50/AP75 and the small/medium/large
area breakdown (metric="coco", eval/coco_eval.py). Inference is data-parallel:
eval batches shard over the mesh's data axis (largest divisor of
batch_size that fits the devices), the same SPMD layout as training.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.data import DataLoader
from replication_faster_rcnn_tpu.eval.detect import (
    batched_decode,
    batched_decode_tta,
)
from replication_faster_rcnn_tpu.eval.coco_eval import coco_summary
from replication_faster_rcnn_tpu.eval.voc_eval import voc_ap
from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN
from replication_faster_rcnn_tpu.telemetry import spans as tspans


def make_infer_fn(model: FasterRCNN, config: FasterRCNNConfig, image_size=None):
    """The inference program: combined forward + fixed-shape decode, as a
    pure ``(variables, images) -> detections`` function ready for jit.

    ``image_size`` overrides ``config.data.image_size`` — the serving
    engine compiles this same program once per resolution bucket, so the
    eval sweep and every serving bucket share one definition (and the
    eval program's audited fingerprint covers the serving math too)."""
    h, w = image_size if image_size is not None else config.data.image_size

    def _forward(variables: Any, images):
        logits, deltas, rois, valid, cls, reg, _ = model.apply(
            variables, images, train=False
        )
        return rois, valid, cls, reg

    def infer(variables: Any, images):
        plain = _forward(variables, images)
        if config.eval.tta_hflip:
            # second pass on the mirrored image; its candidates stay
            # in the mirrored frame until the decode reflects them
            mirrored = _forward(variables, images[:, :, ::-1, :])
            return batched_decode_tta(
                plain, mirrored, float(h), float(w),
                config.eval, config.roi_targets,
            )
        rois, valid, cls, reg = plain
        return batched_decode(
            rois, valid, cls, reg, float(h), float(w),
            config.eval, config.roi_targets,
        )

    return infer


def summary_scalars(
    result: Dict[str, Any], num_classes: int
) -> Dict[str, float]:
    """Flatten an ``evaluate()`` result into the flat float schema the
    step logger / `frcnn telemetry` consume, identical in shape for the
    VOC and COCO metrics: every scalar aggregate ('mAP', and for COCO
    'AP50'/'AP75'/'AP_small'/...) plus one ``AP/<class-name>`` entry per
    class that has ground truth. Class names resolve from the bundled
    VOC/COCO vocabularies when ``num_classes`` matches one, class
    indices otherwise."""
    from replication_faster_rcnn_tpu.config import COCO_CLASSES, VOC_CLASSES

    names = {
        len(VOC_CLASSES): VOC_CLASSES,
        len(COCO_CLASSES): COCO_CLASSES,
    }.get(num_classes, tuple(str(i) for i in range(num_classes)))
    out = {
        k: float(v)
        for k, v in result.items()
        if np.isscalar(v) or getattr(v, "ndim", None) == 0
    }
    aps = result.get("ap_per_class")
    if aps is not None:
        for c in range(1, num_classes):
            if np.isfinite(aps[c]):
                out[f"AP/{names[c]}"] = float(aps[c])
    return out


class Evaluator:
    def __init__(
        self,
        config: FasterRCNNConfig,
        model: Optional[FasterRCNN] = None,
        devices: Optional[list] = None,
    ):
        self.config = config
        self.model = model if model is not None else FasterRCNN(config)
        self.devices = devices

        infer = make_infer_fn(self.model, config)
        self._jit_infer = jax.jit(infer)

        def infer_cached(variables: Any, image_cache, idx):
            # device-resident val images (data/device_cache.py): gather
            # inside the compiled program; the host ships indices only
            return infer(variables, jnp.take(image_cache, idx, axis=0))

        self._jit_infer_cached = jax.jit(infer_cached)
        self._device_cache_base = None
        self._device_cache = None
        # optional strict-mode gate (analysis/strict.py): when set, every
        # infer dispatch runs under its per-program warmup/recompile check
        self.strict = None

    def _strict_dispatch(self, program: str, fn):
        if self.strict is None:
            return contextlib.nullcontext()
        return self.strict.dispatch(program, fn)

    def _eval_sharding(self, batch_size: int):
        """(image sharding, replicated sharding) for a data-parallel eval
        mesh, or (None, None) when only one device would be used."""
        from replication_faster_rcnn_tpu.parallel import (
            batch_sharding,
            fit_data_parallelism,
            make_mesh,
            replicated,
        )

        devices = self.devices if self.devices is not None else jax.devices()
        n_data = fit_data_parallelism(batch_size, len(devices))
        if n_data <= 1 and self.devices is None:
            return None, None  # default device, no sharding needed
        # an explicit device list must be honored even at parallelism 1 —
        # a 1-device mesh pins execution there instead of device 0
        mesh_cfg = dataclasses.replace(
            self.config.mesh, num_data=n_data, num_model=1, spatial=False
        )
        mesh = make_mesh(mesh_cfg, devices[:n_data])
        return batch_sharding(mesh, mesh_cfg), replicated(mesh)

    def predict_batch(
        self, variables: Any, images, sharding=None
    ) -> Dict[str, np.ndarray]:
        if sharding is not None:
            images = jax.device_put(np.asarray(images), sharding)
        elif not isinstance(images, jax.Array):
            # explicit staging: a host array passed straight to dispatch
            # would transfer implicitly (a strict-mode violation)
            images = jax.device_put(np.asarray(images))
        with self._strict_dispatch("eval_infer", self._jit_infer):
            out = self._jit_infer(variables, images)
        return jax.device_get(out)

    def _score(
        self,
        detections: List[Dict[str, np.ndarray]],
        gts: List[Dict[str, np.ndarray]],
    ) -> Dict[str, float]:
        if self.config.eval.metric == "coco":
            return coco_summary(
                detections,
                gts,
                self.config.model.num_classes,
                max_dets=self.config.eval.max_detections,
            )
        return voc_ap(
            detections,
            gts,
            self.config.model.num_classes,
            iou_thresh=self.config.eval.iou_thresh,
            use_07_metric=self.config.eval.use_07_metric,
        )

    def _evaluate_cached(
        self,
        variables: Any,
        dataset,
        batch_size: int,
        max_images: Optional[int],
    ) -> Dict[str, float]:
        """Device-resident val sweep: images uploaded to HBM once per
        dataset (reused across in-training eval epochs), each batch then
        costs the host an index vector instead of a decoded image batch.
        Ground truth comes from the cache's ``host_meta`` — mAP scoring
        runs on host and must not pay a second decode pass. Runs on the
        default device (no eval mesh): the feed savings, not eval data-
        parallelism, is what this path is for."""
        tracer = tspans.current_tracer()
        if self._device_cache_base is not dataset:
            from replication_faster_rcnn_tpu.data.device_cache import DeviceCache

            self._device_cache_base = dataset
            self._device_cache = DeviceCache(dataset, keep_host_meta=True)
        cache = self._device_cache
        meta = cache.host_meta
        images = cache.arrays["image"]
        detections: List[Dict[str, np.ndarray]] = []
        gts: List[Dict[str, np.ndarray]] = []
        seen = 0
        for start in range(0, len(cache), batch_size):
            idxs = np.arange(
                start, min(start + batch_size, len(cache)), dtype=np.int32
            )
            k = len(idxs)
            if k < batch_size:  # pad the tail to the compiled shape
                idxs = np.concatenate(
                    [idxs, np.full(batch_size - k, idxs[-1], np.int32)]
                )
            with tracer.span("eval/infer", cat="eval", feed="device_cache"):
                # device_put, not jnp.asarray: the index upload must be an
                # explicit transfer or strict mode's guard rejects it
                with self._strict_dispatch(
                    "eval_infer_cached", self._jit_infer_cached
                ):
                    raw = self._jit_infer_cached(
                        variables, images, jax.device_put(idxs)
                    )
                out = jax.device_get(raw)
            for i in range(k):
                j = start + i
                valid = out["valid"][i]
                detections.append(
                    {
                        "boxes": out["boxes"][i][valid],
                        "scores": out["scores"][i][valid],
                        "classes": out["classes"][i][valid],
                    }
                )
                lab = meta["labels"][j]
                diff = meta.get("difficult")
                diff = diff[j] if diff is not None else np.zeros_like(lab, bool)
                real = lab >= 0
                gts.append(
                    {
                        "boxes": meta["boxes"][j][real],
                        "labels": lab[real],
                        "ignore": diff[real],
                    }
                )
            seen += k
            if max_images is not None and seen >= max_images:
                break
        return self._score(detections, gts)

    def evaluate(
        self,
        variables: Any,
        dataset,
        batch_size: int = 8,
        max_images: Optional[int] = None,
    ) -> Dict[str, float]:
        if self.config.data.cache_device:
            return self._evaluate_cached(
                variables, dataset, batch_size, max_images
            )
        img_sharding, rep_sharding = self._eval_sharding(batch_size)
        if rep_sharding is not None:
            # device-side reshard (no host round-trip of the weights)
            variables = jax.device_put(variables, rep_sharding)
        # always thread workers here, even when training runs with
        # --loader-mode process: eval happens inside a TPU-attached,
        # multithreaded parent, and forking that process mid-training is
        # exactly the deadlock risk data/loader.py warns about. Cost: with
        # the native decode lib present threads lose nothing (the hot path
        # releases the GIL); on the PIL/numpy fallback path eval ingest is
        # GIL-bound at ~1 worker — accepted, eval is a small fraction of
        # a training run and a hung eval would stall the whole run.
        if self.config.data.loader_cache_ram:
            # the cache must outlive this call to save anything: in-training
            # eval calls evaluate() once per eval epoch with the same val
            # dataset, and a per-call CachedView would decode the whole
            # split every time for zero benefit
            if getattr(self, "_cached_base", None) is not dataset:
                from replication_faster_rcnn_tpu.data.cache import CachedView

                self._cached_base = dataset
                self._cached_view = CachedView(dataset)
            dataset = self._cached_view
        loader = DataLoader(
            dataset, batch_size=batch_size, shuffle=False, drop_last=False,
            prefetch=self.config.data.loader_prefetch,
            num_workers=self.config.data.loader_workers,
            worker_mode="thread",
        )
        tracer = tspans.current_tracer()
        detections: List[Dict[str, np.ndarray]] = []
        gts: List[Dict[str, np.ndarray]] = []
        seen = 0
        for batch in loader:
            n = batch["image"].shape[0]
            if n != batch_size:  # pad the tail batch to the compiled shape
                pad = batch_size - n
                batch = {
                    k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                    for k, v in batch.items()
                }
            with tracer.span("eval/infer", cat="eval", feed="loader"):
                out = self.predict_batch(
                    variables, batch["image"], img_sharding
                )
            for i in range(n):
                valid = out["valid"][i]
                detections.append(
                    {
                        "boxes": out["boxes"][i][valid],
                        "scores": out["scores"][i][valid],
                        "classes": out["classes"][i][valid],
                    }
                )
                # gt includes difficult objects flagged as ignore — the VOC
                # protocol scores them as neither TP nor FP
                lab = batch["labels"][i]
                diff = batch.get("difficult")
                diff = (
                    diff[i] if diff is not None else np.zeros_like(lab, bool)
                )
                real = lab >= 0
                gts.append(
                    {
                        "boxes": batch["boxes"][i][real],
                        "labels": lab[real],
                        "ignore": diff[real],
                    }
                )
            seen += n
            if max_images is not None and seen >= max_images:
                break
        return self._score(detections, gts)
