"""Dataset evaluator: jitted inference sweep -> VOC mAP.

Completes the reference's missing eval path (`test_eval.py`, 0 bytes):
runs the combined FasterRCNN forward (test-mode NMS budgets 3000->300,
reference `nets/rpn.py:41-43`) + fixed-shape decode over a dataset and
reduces to mAP@EvalConfig.iou_thresh on host.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig
from replication_faster_rcnn_tpu.data import DataLoader
from replication_faster_rcnn_tpu.eval.detect import batched_decode
from replication_faster_rcnn_tpu.eval.voc_eval import coco_map, voc_ap
from replication_faster_rcnn_tpu.models.faster_rcnn import FasterRCNN


class Evaluator:
    def __init__(self, config: FasterRCNNConfig, model: Optional[FasterRCNN] = None):
        self.config = config
        self.model = model if model is not None else FasterRCNN(config)
        h, w = config.data.image_size

        def infer(variables: Any, images):
            logits, deltas, rois, valid, cls, reg, _ = self.model.apply(
                variables, images, train=False
            )
            return batched_decode(
                rois, valid, cls, reg, float(h), float(w),
                config.eval, config.roi_targets,
            )

        self._jit_infer = jax.jit(infer)

    def predict_batch(self, variables: Any, images) -> Dict[str, np.ndarray]:
        return jax.device_get(self._jit_infer(variables, images))

    def evaluate(
        self,
        variables: Any,
        dataset,
        batch_size: int = 8,
        max_images: Optional[int] = None,
    ) -> Dict[str, float]:
        loader = DataLoader(
            dataset, batch_size=batch_size, shuffle=False, drop_last=False,
            prefetch=2,
        )
        detections: List[Dict[str, np.ndarray]] = []
        gts: List[Dict[str, np.ndarray]] = []
        seen = 0
        for batch in loader:
            n = batch["image"].shape[0]
            if n != batch_size:  # pad the tail batch to the compiled shape
                pad = batch_size - n
                batch = {
                    k: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
                    for k, v in batch.items()
                }
            out = self.predict_batch(variables, batch["image"])
            for i in range(n):
                valid = out["valid"][i]
                detections.append(
                    {
                        "boxes": out["boxes"][i][valid],
                        "scores": out["scores"][i][valid],
                        "classes": out["classes"][i][valid],
                    }
                )
                # gt includes difficult objects flagged as ignore — the VOC
                # protocol scores them as neither TP nor FP
                lab = batch["labels"][i]
                diff = batch.get("difficult")
                diff = (
                    diff[i] if diff is not None else np.zeros_like(lab, bool)
                )
                real = lab >= 0
                gts.append(
                    {
                        "boxes": batch["boxes"][i][real],
                        "labels": lab[real],
                        "ignore": diff[real],
                    }
                )
            seen += n
            if max_images is not None and seen >= max_images:
                break
        if self.config.eval.metric == "coco":
            return coco_map(detections, gts, self.config.model.num_classes)
        return voc_ap(
            detections,
            gts,
            self.config.model.num_classes,
            iou_thresh=self.config.eval.iou_thresh,
            use_07_metric=self.config.eval.use_07_metric,
        )
