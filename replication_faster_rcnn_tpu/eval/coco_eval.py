"""COCO-protocol detection evaluator (numpy, host-side, dependency-free).

pycocotools is not in this image (data/coco.py parses annotations with
stdlib json for the same reason), so this reimplements COCOeval's bbox
protocol from its published definition:

* AP is the mean of interpolated precision sampled at 101 recall points
  (np.linspace(0, 1, 101)), not the area under the raw PR curve that
  `voc_eval.coco_map` computes — the two differ by the sampling grid.
* mAP@[.5:.95] averages that AP over the 10 IoU thresholds .50:.05:.95.
* Per-detection matching is greedy in score order: the best-IoU
  *still-unmatched* gt above the threshold wins, non-ignored gts
  preferred over ignored ones; a detection whose only match is an
  ignored gt is excluded from the PR curve (neither TP nor FP).
* Area-range breakdowns (small < 32^2 <= medium < 96^2 <= large) reuse
  the same machinery with out-of-range gts marked ignored and unmatched
  out-of-range detections excluded — COCOeval's aRng ignore semantics.
  Areas are box areas in the evaluated coordinate frame (the resized
  canvas); COCO's own numbers use segmentation areas at native
  resolution, so absolute breakdowns shift, but the semantics are the
  COCO ones and self-consistent across runs.
* maxDets=100 detections per image per class (score-ranked) by default.

Aggregates mirror COCOeval's convention of -1 when a slice has no
ground truth at all (instead of NaN, which JSON records cannot hold);
per-class entries stay NaN so downstream consumers can mask them.

Matching semantics are pinned against hand-computed oracles in
tests/test_eval.py (TestCocoEval101), which is what "COCO-style" means
here — exact, not approximate.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from replication_faster_rcnn_tpu.eval.voc_eval import _iou_one_to_many

# the 10-threshold sweep .50:.05:.95 and the 101-point recall grid
IOU_THRESHOLDS: np.ndarray = np.linspace(0.5, 0.95, 10)
RECALL_POINTS: np.ndarray = np.linspace(0.0, 1.0, 101)
# (name, lo, hi): gt/detections with box area outside [lo, hi] are
# ignored for that slice (COCOeval areaRng, in resized-canvas pixels^2)
AREA_RANGES = (
    ("all", 0.0, float("inf")),
    ("small", 0.0, 32.0 ** 2),
    ("medium", 32.0 ** 2, 96.0 ** 2),
    ("large", 96.0 ** 2, float("inf")),
)


def _box_areas(boxes: np.ndarray) -> np.ndarray:
    if len(boxes) == 0:
        return np.zeros(0)
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def _gather_class(detections, ground_truths, cls: int, max_dets: int):
    """Per-image matching state for one class: the det x gt IoU matrix
    plus det scores/areas (score-sorted, top max_dets per image) and gt
    base-ignore flags/areas. Computed once per class; every (threshold,
    area range) cell re-runs only the greedy assignment over it."""
    per_img = []
    for d, g in zip(detections, ground_truths):
        dsel = d["classes"] == cls
        dbox = np.asarray(d["boxes"])[dsel]
        dsc = np.asarray(d["scores"])[dsel]
        order = np.argsort(-dsc, kind="stable")[:max_dets]
        dbox, dsc = dbox[order], dsc[order]
        gsel = g["labels"] == cls
        gbox = np.asarray(g["boxes"])[gsel]
        gig = np.asarray(
            g.get("ignore", np.zeros(len(g["labels"]), bool))
        )[gsel].astype(bool)
        if len(dbox) and len(gbox):
            iou = np.stack([_iou_one_to_many(b, gbox) for b in dbox])
        else:
            iou = np.zeros((len(dbox), len(gbox)))
        per_img.append(
            {
                "scores": dsc,
                "det_areas": _box_areas(dbox),
                "iou": iou,
                "gt_ignore": gig,
                "gt_areas": _box_areas(gbox),
            }
        )
    return per_img


def _match_class(per_img, iou_t: float, lo: float, hi: float):
    """COCOeval's per-image greedy assignment at one (threshold, area
    range): each detection takes the highest-IoU unmatched gt clearing
    the threshold, preferring non-ignored gts (never trading a found
    real match for an ignored one); unlike the VOC-devkit rule a gt is
    consumed even when ignored. Returns the concatenated (scores, tp,
    det_ignore) across images plus the non-ignored gt count."""
    all_scores: List[np.ndarray] = []
    all_tp: List[np.ndarray] = []
    all_ig: List[np.ndarray] = []
    n_gt = 0
    thresh = min(iou_t, 1.0 - 1e-10)
    for rec in per_img:
        gig = (
            rec["gt_ignore"]
            | (rec["gt_areas"] < lo)
            | (rec["gt_areas"] > hi)
        )
        n_gt += int((~gig).sum())
        gt_order = np.argsort(gig, kind="stable")  # real gts first
        n_d = len(rec["scores"])
        matched = np.zeros(len(gig), bool)
        d_tp = np.zeros(n_d, bool)
        d_ig = np.zeros(n_d, bool)
        for di in range(n_d):
            best, best_iou = -1, thresh
            for gi in gt_order:
                if matched[gi]:
                    continue
                if best >= 0 and not gig[best] and gig[gi]:
                    break  # a real match stands; ignored gts can't take it
                if rec["iou"][di, gi] < best_iou:
                    continue
                best_iou = rec["iou"][di, gi]
                best = gi
            if best >= 0:
                matched[best] = True
                if gig[best]:
                    d_ig[di] = True  # absorbed by an ignored gt
                else:
                    d_tp[di] = True
            else:
                # unmatched detection outside the area range: not this
                # slice's problem (it would be an FP only at "all")
                area = rec["det_areas"][di] if n_d else 0.0
                d_ig[di] = bool(area < lo or area > hi)
        all_scores.append(rec["scores"])
        all_tp.append(d_tp)
        all_ig.append(d_ig)
    return (
        np.concatenate(all_scores) if all_scores else np.zeros(0),
        np.concatenate(all_tp) if all_tp else np.zeros(0, bool),
        np.concatenate(all_ig) if all_ig else np.zeros(0, bool),
        n_gt,
    )


def _ap_101(scores, tp, det_ignore, n_gt) -> float:
    """101-point interpolated AP from one class's matched detections:
    global score sort, cumulate TP/FP over non-ignored detections, take
    the monotone precision envelope, sample it at RECALL_POINTS. NaN
    when the class has no (non-ignored) gt in this slice."""
    if n_gt == 0:
        return float("nan")
    keep = ~det_ignore
    order = np.argsort(-scores[keep], kind="stable")
    tp_sorted = tp[keep][order]
    if len(tp_sorted) == 0:
        return 0.0
    ctp = np.cumsum(tp_sorted)
    cfp = np.cumsum(~tp_sorted)
    recall = ctp / n_gt
    precision = ctp / np.maximum(ctp + cfp, 1e-9)
    for i in range(len(precision) - 1, 0, -1):
        if precision[i] > precision[i - 1]:
            precision[i - 1] = precision[i]
    idx = np.searchsorted(recall, RECALL_POINTS, side="left")
    q = np.zeros(len(RECALL_POINTS))
    hit = idx < len(precision)
    q[hit] = precision[idx[hit]]
    return float(q.mean())


def _agg(values: np.ndarray) -> float:
    """COCOeval summary rule: mean over finite entries, -1.0 when every
    entry is NaN (no gt anywhere in the slice)."""
    finite = np.isfinite(values)
    return float(values[finite].mean()) if finite.any() else -1.0


def coco_summary(
    detections: Sequence[Dict[str, np.ndarray]],
    ground_truths: Sequence[Dict[str, np.ndarray]],
    num_classes: int,
    iou_thresholds: Optional[Sequence[float]] = None,
    max_dets: int = 100,
) -> Dict[str, object]:
    """Full COCO-style summary over parallel per-image lists.

    Args:
      detections[i]: {'boxes' [D,4], 'scores' [D], 'classes' [D]}
      ground_truths[i]: {'boxes' [G,4], 'labels' [G], optional
        'ignore' [G]} — base ignores (VOC 'difficult') compose with the
        area-range ignores.
      num_classes: including background (class 0 is never scored).
      iou_thresholds: override the .50:.05:.95 sweep (tests use [0.5]).
      max_dets: score-ranked detection budget per image per class.

    Returns
      {'mAP', 'AP50', 'AP75', 'AP_small', 'AP_medium', 'AP_large':
       float (-1.0 where the slice has no gt),
       'ap_per_class': [num_classes] float (threshold-averaged, at area
       range "all"; NaN where the class has no gt)}.
    """
    thresholds = np.asarray(
        IOU_THRESHOLDS if iou_thresholds is None else iou_thresholds, float
    )
    n_cls = num_classes - 1
    # ap[area, threshold, class]
    ap = np.full((len(AREA_RANGES), len(thresholds), n_cls), np.nan)
    for ci, cls in enumerate(range(1, num_classes)):
        per_img = _gather_class(detections, ground_truths, cls, max_dets)
        for ai, (_, lo, hi) in enumerate(AREA_RANGES):
            for ti, t in enumerate(thresholds):
                ap[ai, ti, ci] = _ap_101(
                    *_match_class(per_img, float(t), lo, hi)
                )

    out: Dict[str, object] = {"mAP": _agg(ap[0])}
    for ti, t in enumerate(thresholds):
        if abs(float(t) - 0.5) < 1e-9:
            out["AP50"] = _agg(ap[0, ti])
        if abs(float(t) - 0.75) < 1e-9:
            out["AP75"] = _agg(ap[0, ti])
    for ai, (name, _, _) in enumerate(AREA_RANGES):
        if name != "all":
            out[f"AP_{name}"] = _agg(ap[ai])
    # a class's NaN-ness at "all" is threshold-independent (no gt), so
    # the plain threshold mean is exact: all-NaN or all-finite columns
    ap_per_class = np.full(num_classes, np.nan)
    if n_cls:
        ap_per_class[1:] = ap[0].mean(axis=0)
    out["ap_per_class"] = ap_per_class
    return out
