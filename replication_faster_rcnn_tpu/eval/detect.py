"""Inference decode — proposals + head outputs -> final detections.

The reference never wrote this path (`test_eval.py` is empty; the combined
forward is broken — SURVEY.md §3.2), so the decode is designed from the
Faster R-CNN paper + the reference's training-time conventions:

  * head reg outputs were trained against targets normalized by
    ``roi_targets.reg_std`` (reference `utils/utils.py:216,271-272`), so
    predictions are de-normalized with the same std/mean before decoding.
  * class-specific boxes: class c uses deltas [4c:4c+4] (the gather
    semantics of reference `train.py:112-117`).
  * scores are softmax over 21 classes; background (class 0) is dropped.
  * score threshold, per-class NMS (class-offset trick), top
    ``max_detections`` kept — all fixed-shape with validity masks.

Everything is jit/vmap-safe; the batch decode is one XLA program.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.config import EvalConfig, ROITargetConfig
from replication_faster_rcnn_tpu.ops import boxes as box_ops
from replication_faster_rcnn_tpu.ops import nms as nms_ops

Array = jnp.ndarray


def _class_boxes_scores(
    rois: Array,
    cls_logits: Array,
    reg_out: Array,
    img_h: float,
    img_w: float,
    roi_cfg: ROITargetConfig,
) -> Tuple[Array, Array]:
    """Pre-NMS stage: (probs [R, C], clipped class boxes [R, C, 4])."""
    r = rois.shape[0]
    c = cls_logits.shape[-1]
    probs = jax.nn.softmax(cls_logits, axis=-1)  # [R, C]

    # de-normalize all class deltas and decode each class's box
    mean = jnp.asarray(roi_cfg.reg_mean, jnp.float32)
    std = jnp.asarray(roi_cfg.reg_std, jnp.float32)
    deltas = reg_out.reshape(r, c, 4) * std + mean  # [R, C, 4]
    boxes = box_ops.decode(rois[:, None, :], deltas)  # [R, C, 4]
    return probs, box_ops.clip(boxes, img_h, img_w)


def _nms_tail(
    boxes: Array,
    probs: Array,
    roi_valid: Array,
    eval_cfg: EvalConfig,
) -> Dict[str, Array]:
    """Shared decode tail: flatten (roi, class>0) pairs, score-threshold,
    per-class NMS, fixed D = eval_cfg.max_detections outputs."""
    r, c = probs.shape
    flat_boxes = boxes.reshape(r * c, 4)
    flat_scores = probs.reshape(r * c)
    class_ids = jnp.tile(jnp.arange(c, dtype=jnp.int32), (r,))
    fg = (class_ids > 0) & jnp.repeat(roi_valid, c)
    fg &= flat_scores >= eval_cfg.score_thresh

    idx, valid = nms_ops.batched_nms_fixed(
        flat_boxes,
        flat_scores,
        class_ids,
        eval_cfg.nms_thresh,
        eval_cfg.max_detections,
        mask=fg,
    )
    return {
        "boxes": flat_boxes[idx] * valid[:, None],
        "scores": jnp.where(valid, flat_scores[idx], 0.0),
        "classes": jnp.where(valid, class_ids[idx], 0).astype(jnp.int32),
        "valid": valid,
    }


def decode_detections(
    rois: Array,
    roi_valid: Array,
    cls_logits: Array,
    reg_out: Array,
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """Per-image decode.

    Args:
      rois: [R, 4]; roi_valid: [R]; cls_logits: [R, C]; reg_out: [R, C*4].

    Returns dict with 'boxes' [D, 4], 'scores' [D], 'classes' [D] int32,
    'valid' [D] bool, D = eval_cfg.max_detections.
    """
    probs, boxes = _class_boxes_scores(
        rois, cls_logits, reg_out, img_h, img_w, roi_cfg
    )
    return _nms_tail(boxes, probs, roi_valid, eval_cfg)


def decode_detections_tta(
    rois: Array,
    roi_valid: Array,
    cls_logits: Array,
    reg_out: Array,
    rois_f: Array,
    roi_valid_f: Array,
    cls_logits_f: Array,
    reg_out_f: Array,
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """Flip test-time augmentation: merge the plain pass with a pass run
    on the horizontally mirrored image (``*_f`` arrays, still in the
    MIRRORED frame). Each pass decodes class boxes in its own frame;
    the mirrored boxes are reflected back (x -> W - x, the train-time
    ``hflip_sample`` convention) and the union of 2R candidates runs one
    shared per-class NMS — so duplicates across passes suppress each
    other instead of surviving two independent NMS rounds. The reference
    has no eval path at all (`test_eval.py` empty); TTA is a
    capability-plus over the paper recipe."""
    probs_a, boxes_a = _class_boxes_scores(
        rois, cls_logits, reg_out, img_h, img_w, roi_cfg
    )
    probs_b, boxes_b = _class_boxes_scores(
        rois_f, cls_logits_f, reg_out_f, img_h, img_w, roi_cfg
    )
    # reflect mirrored-frame boxes back: [y1, x1, y2, x2] row-major
    boxes_b = jnp.stack(
        [
            boxes_b[..., 0],
            img_w - boxes_b[..., 3],
            boxes_b[..., 2],
            img_w - boxes_b[..., 1],
        ],
        axis=-1,
    )
    probs = jnp.concatenate([probs_a, probs_b], axis=0)  # [2R, C]
    boxes = jnp.concatenate([boxes_a, boxes_b], axis=0)  # [2R, C, 4]
    valid = jnp.concatenate([roi_valid, roi_valid_f], axis=0)
    return _nms_tail(boxes, probs, valid, eval_cfg)


def batched_decode(
    rois: Array,
    roi_valid: Array,
    cls_logits: Array,
    reg_out: Array,
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """vmap over the batch: rois [N, R, 4] -> dict of [N, D, ...]."""
    return jax.vmap(
        lambda r, v, cl, rg: decode_detections(
            r, v, cl, rg, img_h, img_w, eval_cfg, roi_cfg
        )
    )(rois, roi_valid, cls_logits, reg_out)


def batched_decode_tta(
    plain: Tuple[Array, Array, Array, Array],
    mirrored: Tuple[Array, Array, Array, Array],
    img_h: float,
    img_w: float,
    eval_cfg: EvalConfig,
    roi_cfg: ROITargetConfig,
) -> Dict[str, Array]:
    """vmap of :func:`decode_detections_tta` over the batch."""
    return jax.vmap(
        lambda r, v, cl, rg, rf, vf, clf, rgf: decode_detections_tta(
            r, v, cl, rg, rf, vf, clf, rgf, img_h, img_w, eval_cfg, roi_cfg
        )
    )(*plain, *mirrored)
