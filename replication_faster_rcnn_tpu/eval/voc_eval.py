"""VOC-style mAP@IoU evaluator (numpy, host-side).

The reference contains no evaluation at all (SURVEY.md §2.1 #15), so this
implements the standard Pascal VOC protocol from its published definition:
per-class ranked matching of detections to gt at an IoU threshold, each gt
matched at most once, precision/recall curve summarized either by the
VOC2007 11-point interpolation or the VOC2010+ area-under-curve (both
offered; EvalConfig.use_07_metric selects).

Inputs are plain numpy accumulated across the eval set — metric math stays
off-device (tiny, branchy, once per epoch).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def _ap_from_pr(recall: np.ndarray, precision: np.ndarray, use_07: bool) -> float:
    if use_07:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11.0
        return float(ap)
    # VOC2010+: area under the monotonically-decreasing precision envelope
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    changed = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[changed + 1] - mrec[changed]) * mpre[changed + 1]))


def _iou_one_to_many(box: np.ndarray, boxes: np.ndarray) -> np.ndarray:
    tl = np.maximum(box[:2], boxes[:, :2])
    br = np.minimum(box[2:], boxes[:, 2:])
    wh = np.clip(br - tl, 0, None)
    inter = wh[:, 0] * wh[:, 1]
    a = (box[2] - box[0]) * (box[3] - box[1])
    b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    union = a + b - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-9), 0.0)


def _class_iou_rows(detections, ground_truths, cls):
    """Per-class matching state shared by both metrics: score-sorted
    [(score, img_i, iou_row)] with the FULL IoU vector against that image's
    gts kept per detection, plus per-image ignore masks and the non-ignored
    gt count. The VOC devkit path freezes each detection's argmax from the
    row; the COCO sweep re-matches per threshold."""
    gt_boxes = []
    gt_ignore = []
    n_gt = 0
    for g in ground_truths:
        sel = g["labels"] == cls
        ig = np.asarray(g.get("ignore", np.zeros(len(g["labels"]), bool)))[sel]
        gt_boxes.append(g["boxes"][sel])
        gt_ignore.append(ig)
        n_gt += int((~ig).sum())

    recs = []
    for img_i, d in enumerate(detections):
        sel = d["classes"] == cls
        for b, s in zip(d["boxes"][sel], d["scores"][sel]):
            gts = gt_boxes[img_i]
            iou_row = _iou_one_to_many(b, gts) if len(gts) else np.zeros(0)
            recs.append((float(s), img_i, iou_row))
    recs.sort(key=lambda t: -t[0])
    return recs, n_gt, gt_ignore


def _pr_tail(tp, fp, n_gt, use_07_metric):
    ctp = np.cumsum(tp)
    cfp = np.cumsum(fp)
    recall = ctp / n_gt
    precision = ctp / np.maximum(ctp + cfp, 1e-9)
    return _ap_from_pr(recall, precision, use_07_metric)


def _ap_devkit(recs, n_gt, gt_ignore, iou_thresh, use_07_metric):
    """AP at one threshold with VOC-devkit semantics: each detection is
    pinned to its argmax-IoU gt; if that gt clears the threshold it is a TP
    once and an FP on re-detection; ignored (difficult) gt -> neither."""
    if n_gt == 0:
        return np.nan
    if not recs:
        return 0.0
    matched = [np.zeros(len(ig), bool) for ig in gt_ignore]
    tp = np.zeros(len(recs))
    fp = np.zeros(len(recs))
    for k, (_, img_i, iou_row) in enumerate(recs):
        j = int(iou_row.argmax()) if len(iou_row) else -1
        if j >= 0 and iou_row[j] >= iou_thresh:
            if gt_ignore[img_i][j]:
                pass  # difficult gt: neither TP nor FP
            elif not matched[img_i][j]:
                tp[k] = 1
                matched[img_i][j] = True
            else:
                fp[k] = 1
        else:
            fp[k] = 1
    return _pr_tail(tp, fp, n_gt, use_07_metric)


def voc_ap(
    detections: Sequence[Dict[str, np.ndarray]],
    ground_truths: Sequence[Dict[str, np.ndarray]],
    num_classes: int,
    iou_thresh: float = 0.5,
    use_07_metric: bool = False,
) -> Dict[str, float]:
    """Compute per-class AP and mAP.

    Args (parallel lists over images):
      detections[i]: {'boxes' [D,4], 'scores' [D], 'classes' [D]} (valid only)
      ground_truths[i]: {'boxes' [G,4], 'labels' [G], optional 'ignore' [G]}
        — 'ignore' marks VOC "difficult" objects: excluded from the gt count
        and detections matching them score as neither TP nor FP (official
        devkit semantics).

    Returns {'mAP': float, 'ap_per_class': [num_classes] (nan where no gt)}.
    """
    aps = np.full(num_classes, np.nan)
    for cls in range(1, num_classes):
        recs, n_gt, gt_ignore = _class_iou_rows(detections, ground_truths, cls)
        aps[cls] = _ap_devkit(recs, n_gt, gt_ignore, iou_thresh, use_07_metric)

    valid = ~np.isnan(aps[1:])
    m_ap = float(aps[1:][valid].mean()) if valid.any() else 0.0
    return {"mAP": m_ap, "ap_per_class": aps}


def _ap_greedy(recs, n_gt, gt_ignore, iou_thresh, use_07_metric):
    """AP at one threshold with pycocotools matching semantics: each
    detection (in score order) takes the highest-IoU *still-unmatched,
    non-ignored* gt with IoU >= t; if none, an ignored gt with IoU >= t
    absorbs it (neither TP nor FP, and ignored gts may absorb several);
    otherwise FP."""
    if n_gt == 0:
        return np.nan
    if not recs:
        return 0.0
    matched = [np.zeros(len(ig), bool) for ig in gt_ignore]
    tp, fp = [], []
    for score, img_i, iou_row in recs:
        ok = iou_row >= iou_thresh
        real = ok & ~gt_ignore[img_i] & ~matched[img_i]
        if real.any():
            j = int(np.where(real, iou_row, -1.0).argmax())
            matched[img_i][j] = True
            tp.append(1.0)
            fp.append(0.0)
        elif (ok & gt_ignore[img_i]).any():
            continue  # matched an ignored gt: excluded from the PR curve
        else:
            tp.append(0.0)
            fp.append(1.0)
    return _pr_tail(np.asarray(tp), np.asarray(fp), n_gt, use_07_metric)


def coco_map(
    detections: Sequence[Dict[str, np.ndarray]],
    ground_truths: Sequence[Dict[str, np.ndarray]],
    num_classes: int,
    iou_thresholds: Optional[Sequence[float]] = None,
) -> Dict[str, float]:
    """COCO-style mAP: mean AP over IoU thresholds .50:.05:.95 (for the
    COCO-2017 config, BASELINE.json #5). Per-class IoU rows are computed
    once; each threshold re-runs the greedy best-unmatched-gt assignment
    (pycocotools semantics — a detection may match different gts at
    different thresholds, unlike the VOC devkit's frozen argmax)."""
    if iou_thresholds is None:
        iou_thresholds = np.arange(0.5, 1.0, 0.05)
    per_class = {
        cls: _class_iou_rows(detections, ground_truths, cls)
        for cls in range(1, num_classes)
    }
    per_thresh = []
    per_thresh_cls = []
    for t in iou_thresholds:
        aps = np.asarray(
            [
                _ap_greedy(*per_class[cls], float(t), False)
                for cls in range(1, num_classes)
            ]
        )
        per_thresh_cls.append(aps)
        valid = ~np.isnan(aps)
        per_thresh.append(float(aps[valid].mean()) if valid.any() else 0.0)
    out = {"mAP": float(np.mean(per_thresh))}
    # per-class AP averaged over the threshold sweep. A class's AP is NaN
    # iff it has no gt, which is threshold-independent, so plain mean is
    # exact: columns are either all-NaN (propagates) or all-finite.
    ap_per_class = np.full(num_classes, np.nan)
    ap_per_class[1:] = np.stack(per_thresh_cls).mean(axis=0)
    out["ap_per_class"] = ap_per_class
    for t, v in zip(iou_thresholds, per_thresh):
        if abs(t - 0.5) < 1e-9:
            out["AP50"] = v
        if abs(t - 0.75) < 1e-9:
            out["AP75"] = v
    return out
