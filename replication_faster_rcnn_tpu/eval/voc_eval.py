"""VOC-style mAP@IoU evaluator (numpy, host-side).

The reference contains no evaluation at all (SURVEY.md §2.1 #15), so this
implements the standard Pascal VOC protocol from its published definition:
per-class ranked matching of detections to gt at an IoU threshold, each gt
matched at most once, precision/recall curve summarized either by the
VOC2007 11-point interpolation or the VOC2010+ area-under-curve (both
offered; EvalConfig.use_07_metric selects).

Inputs are plain numpy accumulated across the eval set — metric math stays
off-device (tiny, branchy, once per epoch).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np


def _ap_from_pr(recall: np.ndarray, precision: np.ndarray, use_07: bool) -> float:
    if use_07:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = precision[recall >= t].max() if (recall >= t).any() else 0.0
            ap += p / 11.0
        return float(ap)
    # VOC2010+: area under the monotonically-decreasing precision envelope
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    for i in range(len(mpre) - 2, -1, -1):
        mpre[i] = max(mpre[i], mpre[i + 1])
    changed = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[changed + 1] - mrec[changed]) * mpre[changed + 1]))


def _iou_one_to_many(box: np.ndarray, boxes: np.ndarray) -> np.ndarray:
    tl = np.maximum(box[:2], boxes[:, :2])
    br = np.minimum(box[2:], boxes[:, 2:])
    wh = np.clip(br - tl, 0, None)
    inter = wh[:, 0] * wh[:, 1]
    a = (box[2] - box[0]) * (box[3] - box[1])
    b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    union = a + b - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-9), 0.0)


def voc_ap(
    detections: Sequence[Dict[str, np.ndarray]],
    ground_truths: Sequence[Dict[str, np.ndarray]],
    num_classes: int,
    iou_thresh: float = 0.5,
    use_07_metric: bool = False,
) -> Dict[str, float]:
    """Compute per-class AP and mAP.

    Args (parallel lists over images):
      detections[i]: {'boxes' [D,4], 'scores' [D], 'classes' [D]} (valid only)
      ground_truths[i]: {'boxes' [G,4], 'labels' [G], optional 'ignore' [G]}
        — 'ignore' marks VOC "difficult" objects: excluded from the gt count
        and detections matching them score as neither TP nor FP (official
        devkit semantics).

    Returns {'mAP': float, 'ap_per_class': [num_classes] (nan where no gt)}.
    """
    aps = np.full(num_classes, np.nan)
    for cls in range(1, num_classes):
        # gather this class's gt per image
        gt_boxes: List[np.ndarray] = []
        gt_ignore: List[np.ndarray] = []
        n_gt = 0
        for g in ground_truths:
            sel = g["labels"] == cls
            ig = np.asarray(
                g.get("ignore", np.zeros(len(g["labels"]), bool))
            )[sel]
            gt_boxes.append(g["boxes"][sel])
            gt_ignore.append(ig)
            n_gt += int((~ig).sum())

        # flatten detections of this class across images
        recs = []
        for img_i, d in enumerate(detections):
            sel = d["classes"] == cls
            for b, s in zip(d["boxes"][sel], d["scores"][sel]):
                recs.append((float(s), img_i, b))
        if n_gt == 0:
            continue  # AP undefined with no gt of this class
        if not recs:
            aps[cls] = 0.0
            continue

        recs.sort(key=lambda t: -t[0])
        matched = [np.zeros(len(b), bool) for b in gt_boxes]
        tp = np.zeros(len(recs))
        fp = np.zeros(len(recs))
        for k, (_, img_i, box) in enumerate(recs):
            gts = gt_boxes[img_i]
            if len(gts) == 0:
                fp[k] = 1
                continue
            ious = _iou_one_to_many(box, gts)
            j = int(ious.argmax())
            if ious[j] >= iou_thresh:
                if gt_ignore[img_i][j]:
                    pass  # difficult gt: neither TP nor FP
                elif not matched[img_i][j]:
                    tp[k] = 1
                    matched[img_i][j] = True
                else:
                    fp[k] = 1
            else:
                fp[k] = 1

        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        recall = ctp / n_gt
        precision = ctp / np.maximum(ctp + cfp, 1e-9)
        aps[cls] = _ap_from_pr(recall, precision, use_07_metric)

    valid = ~np.isnan(aps[1:])
    m_ap = float(aps[1:][valid].mean()) if valid.any() else 0.0
    return {"mAP": m_ap, "ap_per_class": aps}
