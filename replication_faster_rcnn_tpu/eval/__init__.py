from replication_faster_rcnn_tpu.eval.coco_eval import coco_summary  # noqa: F401
from replication_faster_rcnn_tpu.eval.detect import batched_decode, decode_detections  # noqa: F401
from replication_faster_rcnn_tpu.eval.evaluator import Evaluator, summary_scalars  # noqa: F401
from replication_faster_rcnn_tpu.eval.voc_eval import coco_map, voc_ap  # noqa: F401
