"""Single-image prediction — the user-facing inference path the reference
planned but never wrote (`test_eval.py` empty, `readme.md:7`).

Loads an image, runs the combined forward + decode at the configured input
size, maps boxes back to original-image coordinates, and optionally draws
them (PIL) to an output file.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from replication_faster_rcnn_tpu.config import FasterRCNNConfig, VOC_CLASSES
from replication_faster_rcnn_tpu.eval.evaluator import Evaluator

# one-entry Evaluator cache for repeated predict_image calls on the same
# (config, model): the Evaluator holds the jitted inference function, so a
# fresh instance per call re-traced and re-compiled the whole forward pass
# for every image — image 2..N each paid image 1's compile
_cached_evaluator: Optional[Evaluator] = None
_cached_key = None


def get_evaluator(config: FasterRCNNConfig, model) -> Evaluator:
    """The cached Evaluator for (config, model), built on first use.
    Config is a frozen dataclass (value-hashable); the model is keyed by
    identity — a new model instance gets a fresh Evaluator."""
    global _cached_evaluator, _cached_key
    key = (config, id(model))
    if _cached_evaluator is None or _cached_key != key:
        _cached_evaluator = Evaluator(config, model)
        _cached_key = key
    return _cached_evaluator


def predict_image(
    config: FasterRCNNConfig,
    model,
    variables: Any,
    image_path: str,
    score_thresh: Optional[float] = None,
    evaluator: Optional[Evaluator] = None,
) -> List[Dict[str, Any]]:
    """-> list of {'box' [4] in original image coords (row-major),
    'score', 'class_id', 'class_name'} sorted by score.

    ``evaluator`` reuses a caller-owned Evaluator (its jitted inference
    fn stays warm); otherwise the module-level cache supplies one."""
    from replication_faster_rcnn_tpu.data.voc import _load_image

    h, w = config.data.image_size
    image, orig_h, orig_w = _load_image(
        image_path, (h, w), config.data.pixel_mean, config.data.pixel_std
    )
    ev = evaluator if evaluator is not None else get_evaluator(config, model)
    out = ev.predict_batch(variables, image[None])
    thresh = config.eval.score_thresh if score_thresh is None else score_thresh

    names = (
        VOC_CLASSES
        if config.model.num_classes == len(VOC_CLASSES)
        else [str(i) for i in range(config.model.num_classes)]
    )
    back = np.asarray([orig_h / h, orig_w / w, orig_h / h, orig_w / w])
    results = []
    for i in range(out["valid"].shape[1]):
        if not out["valid"][0, i] or out["scores"][0, i] < thresh:
            continue
        cls = int(out["classes"][0, i])
        results.append(
            {
                "box": (out["boxes"][0, i] * back).tolist(),
                "score": float(out["scores"][0, i]),
                "class_id": cls,
                "class_name": names[cls],
            }
        )
    results.sort(key=lambda d: -d["score"])
    return results


def draw_detections(image_path: str, detections, out_path: str) -> None:
    """Render boxes + labels onto the image (PIL)."""
    from PIL import Image, ImageDraw

    from replication_faster_rcnn_tpu.utils.viz import draw_labeled_boxes

    with Image.open(image_path) as im:
        im = im.convert("RGB")
        draw = ImageDraw.Draw(im)
        draw_labeled_boxes(
            draw,
            (
                (d["box"], f"{d['class_name']} {d['score']:.2f}")
                for d in detections
            ),
            (255, 40, 40),
        )
        im.save(out_path)
