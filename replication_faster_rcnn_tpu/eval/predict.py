"""Image prediction — the user-facing inference path the reference
planned but never wrote (`test_eval.py` empty, `readme.md:7`).

Requests route through the serving engine (`serving/engine.py`): the
engine owns the compiled-program cache (one AOT program per resolution
bucket × batch size), keeps the inference params device-resident, and
coalesces multi-image calls into micro-batches. Box de-normalization
back to original image coordinates happens inside the engine; this
module just thresholds, attaches class names, and optionally draws.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from replication_faster_rcnn_tpu.config import FasterRCNNConfig, VOC_CLASSES

# re-export: the one-entry Evaluator cache moved into the serving engine
# (which owns every "keep the compiled inference program warm" concern),
# but callers historically import it from here
from replication_faster_rcnn_tpu.serving.engine import (  # noqa: F401
    get_engine,
    get_evaluator,
)


def _class_names(config: FasterRCNNConfig) -> List[str]:
    return list(
        VOC_CLASSES
        if config.model.num_classes == len(VOC_CLASSES)
        else [str(i) for i in range(config.model.num_classes)]
    )


def _to_detections(out: Dict[str, Any], thresh: float, names) -> List[Dict]:
    """Engine result (boxes already in original-image coords) ->
    thresholded, score-sorted list of detection dicts."""
    results = []
    for i in range(out["valid"].shape[0]):
        if not out["valid"][i] or out["scores"][i] < thresh:
            continue
        cls = int(out["classes"][i])
        results.append(
            {
                "box": out["boxes"][i].tolist(),
                "score": float(out["scores"][i]),
                "class_id": cls,
                "class_name": names[cls],
            }
        )
    results.sort(key=lambda d: -d["score"])
    return results


def predict_images(
    config: FasterRCNNConfig,
    model,
    variables: Any,
    image_paths: Sequence[str],
    score_thresh: Optional[float] = None,
    engine=None,
) -> List[List[Dict[str, Any]]]:
    """Run detection on many images as one micro-batched engine pass.

    All paths are submitted before any result is awaited, so same-bucket
    images coalesce into shared dispatches instead of paying per-image
    dispatch cost. Returns one detection list per input path, each a list
    of {'box' [4] in original image coords (row-major), 'score',
    'class_id', 'class_name'} sorted by score."""
    eng = engine if engine is not None else get_engine(config, model, variables)
    futures = [eng.submit_path(p) for p in image_paths]
    thresh = config.eval.score_thresh if score_thresh is None else score_thresh
    names = _class_names(config)
    return [_to_detections(f.result(), thresh, names) for f in futures]


def predict_image(
    config: FasterRCNNConfig,
    model,
    variables: Any,
    image_path: str,
    score_thresh: Optional[float] = None,
    engine=None,
) -> List[Dict[str, Any]]:
    """Single-image convenience wrapper over :func:`predict_images`.

    ``engine`` reuses a caller-owned InferenceEngine (its AOT-compiled
    programs stay warm); otherwise the module-level cache supplies one.
    """
    return predict_images(
        config, model, variables, [image_path], score_thresh, engine
    )[0]


def draw_detections(image_path: str, detections, out_path: str) -> None:
    """Render boxes + labels onto the image (PIL)."""
    from PIL import Image, ImageDraw

    from replication_faster_rcnn_tpu.utils.viz import draw_labeled_boxes

    with Image.open(image_path) as im:
        im = im.convert("RGB")
        draw = ImageDraw.Draw(im)
        draw_labeled_boxes(
            draw,
            (
                (d["box"], f"{d['class_name']} {d['score']:.2f}")
                for d in detections
            ),
            (255, 40, 40),
        )
        im.save(out_path)
