"""On-device image ops for the input pipeline.

Host-side scale jitter costs ~27 ms per 600x600 sample on one core (the
resample dominates; `PARITY.md` augmentation evidence), which makes the
measured +6.5-val-mAP augmentation ingest-bound exactly where the chip
is fastest. The TPU-native split: the HOST transforms only the boxes
and draws the jitter geometry (`data/augment.py` attaches an integer
``[ch, cw, shift_y, shift_x]`` row per sample), and the image resample
runs HERE, on device, as one vmapped bilinear gather that XLA fuses
into the input side of the step — per-batch cost is microseconds of
VPU time instead of tens of host milliseconds per image.

Geometry contract (must match ``data/augment.py::scale_jitter_sample``
exactly, which is why the host ships the rounded integers rather than
the raw scale): output pixel (y, x) reads content index
(y + shift_y, x + shift_x); a content index inside [0, ch) x [0, cw)
maps to the source image at half-pixel-center coordinates
((i + 0.5) * H / ch - 0.5), bilinear with edge-clamped taps; outside
it takes the per-image channel-mean fill. uint8 inputs round back to
uint8 (the host path's convention for device-normalize caches).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def scale_jitter_image(image: Array, params: Array) -> Array:
    """One image [H, W, C] + int32 params [4] = (ch, cw, sy, sx)."""
    h, w = image.shape[0], image.shape[1]
    ch = params[0].astype(jnp.float32)
    cw = params[1].astype(jnp.float32)
    sy = params[2]
    sx = params[3]
    im = image.astype(jnp.float32)

    iy = jnp.arange(h, dtype=jnp.int32) + sy  # content row index per out row
    ix = jnp.arange(w, dtype=jnp.int32) + sx
    valid_y = (iy >= 0) & (iy < params[0])
    valid_x = (ix >= 0) & (ix < params[1])

    ys = (iy.astype(jnp.float32) + 0.5) * (h / ch) - 0.5
    xs = (ix.astype(jnp.float32) + 0.5) * (w / cw) - 0.5
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    y0c, y1c = jnp.clip(y0, 0, h - 1), jnp.clip(y0 + 1, 0, h - 1)
    x0c, x1c = jnp.clip(x0, 0, w - 1), jnp.clip(x0 + 1, 0, w - 1)

    top = im[y0c][:, x0c] * (1 - wx) + im[y0c][:, x1c] * wx
    bot = im[y1c][:, x0c] * (1 - wx) + im[y1c][:, x1c] * wx
    out = top * (1 - wy) + bot * wy

    fill = im.mean(axis=(0, 1))
    if image.dtype == jnp.uint8:
        fill = jnp.clip(jnp.round(fill), 0, 255)
    valid = valid_y[:, None, None] & valid_x[None, :, None]
    out = jnp.where(valid, out, fill[None, None, :])
    if image.dtype == jnp.uint8:
        return jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    return out.astype(image.dtype)


def batched_scale_jitter(images: Array, params: Array) -> Array:
    """images [N, H, W, C], params int32 [N, 4] -> jittered images.

    Rows with (ch, cw, sy, sx) == (H, W, 0, 0) are identity resamples
    (the half-pixel map becomes exact passthrough up to float assoc.;
    uint8 rows round back to their original values)."""
    return jax.vmap(scale_jitter_image)(images, params)


# ---------------------------------------------------------------------------
# Fully on-device augmentation (data.augment_device)
#
# The host loader ships RAW samples plus one int32 ``aug = [idx, epoch]``
# row per sample (`data/augment.py::AugmentTagView`); every augmentation
# decision — the flip coin, the scale-jitter geometry, the translation
# offsets — is drawn INSIDE the compiled step from the same splitmix64
# counter-mix the host pipeline uses, keyed on (seed, epoch, dataset idx).
# A pure function of per-sample metadata needs no communication: every
# rank of an spmd/MP fleet and every checkpoint resume computes identical
# draws from the rows it holds, and elastic re-sharding just re-partitions
# the rows. jax default config has no uint64, so the 64-bit hash runs on
# two uint32 limbs (16-bit partial products for the multiplies); uniforms
# take the top 24 bits so the f32 math is exact and the numpy oracle
# (`data/augment.py::device_decisions`) can pin it bitwise.
# ---------------------------------------------------------------------------

_MASK32 = 0xFFFFFFFF
_GAMMA = 0x9E3779B97F4A7C15


def _const32(c: int) -> tuple:
    return jnp.uint32((c >> 32) & _MASK32), jnp.uint32(c & _MASK32)


def _mul32(a: Array, b: Array) -> tuple:
    """Full 32x32 -> 64-bit product as (hi, lo) uint32 limbs."""
    a0, a1 = a & 0xFFFF, a >> 16
    b0, b1 = b & 0xFFFF, b >> 16
    p00 = a0 * b0
    mid = a1 * b0 + (p00 >> 16)
    mid2 = a0 * b1 + (mid & 0xFFFF)
    lo = (p00 & 0xFFFF) | ((mid2 & 0xFFFF) << 16)
    hi = a1 * b1 + (mid >> 16) + (mid2 >> 16)
    return hi, lo


def _mul64(zh: Array, zl: Array, ch, cl) -> tuple:
    """Low 64 bits of z * c (c as uint32 halves); uint32 wrap IS mod 2^32."""
    hi, lo = _mul32(zl, cl)
    return hi + zl * ch + zh * cl, lo


def _add64(ah, al, bh, bl) -> tuple:
    lo = al + bl
    carry = (lo < al).astype(jnp.uint32)
    return ah + bh + carry, lo


def _shr_xor(zh: Array, zl: Array, n: int) -> tuple:
    """z ^ (z >> n) for 0 < n < 32."""
    return zh ^ (zh >> n), zl ^ ((zl >> n) | (zh << (32 - n)))


def _splitmix64(zh: Array, zl: Array) -> tuple:
    """data/augment.py::_splitmix on uint32 limbs, bit-for-bit."""
    zh, zl = _shr_xor(zh, zl, 30)
    zh, zl = _mul64(zh, zl, *_const32(0xBF58476D1CE4E5B9))
    zh, zl = _shr_xor(zh, zl, 27)
    zh, zl = _mul64(zh, zl, *_const32(0x94D049BB133111EB))
    return _shr_xor(zh, zl, 31)


def augment_draws(seed: int, epoch: Array, idx: Array) -> tuple:
    """Per-row draws: (flip bool, u_scale, u_off_y, u_off_x, u_ty, u_tx).

    Bitwise-identical to `data/augment.py::device_decisions` (the numpy
    oracle): same masked (seed, epoch, idx) counter-mix, same +GAMMA
    chaining, uniforms = top 24 bits of each output scaled by 2^-24 —
    exactly representable in f32 on both sides."""
    s = (int(seed) * _GAMMA) & 0xFFFFFFFFFFFFFFFF
    sh, sl = jnp.uint32(s >> 32), jnp.uint32(s & _MASK32)
    e = epoch.astype(jnp.uint32)
    i = idx.astype(jnp.uint32)
    zero = jnp.zeros_like(e)
    eh, el = _mul64(zero, e, *_const32(0xBF58476D1CE4E5B9))
    ih, il = _mul64(zero, i, *_const32(0x94D049BB133111EB))
    mh, ml = _add64(*_add64(sh, sl, eh, el), ih, il)
    gh, gl = _const32(_GAMMA)
    zh, zl = _splitmix64(mh, ml)
    flip = (zl & 1).astype(bool)

    def _next(z):
        return _splitmix64(*_add64(z[0], z[1], gh, gl))

    def _uniform(z):
        return (z[0] >> 8).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))

    z2 = _next((zh, zl))
    z3 = _next(z2)
    z4 = _next(z3)
    z5 = _next(z4)
    z6 = _next(z5)
    return (flip, _uniform(z2), _uniform(z3), _uniform(z4),
            _uniform(z5), _uniform(z6))


def hflip_batch_with_boxes(
    images: Array, boxes: Array, labels: Array, flip: Array
) -> tuple:
    """Mirror the rows of a batch where ``flip`` is set: image columns
    reversed, each real (labels >= 0) box's x-span reflected
    ((y1,x1,y2,x2) -> (y1, W-x2, y2, W-x1)); padded rows untouched.
    Bitwise parity with `data/augment.py::hflip_sample`."""
    w = images.shape[2]
    images = jnp.where(
        flip[:, None, None, None], images[:, :, ::-1, :], images
    )
    mirrored = jnp.stack(
        [boxes[..., 0], w - boxes[..., 3], boxes[..., 2], w - boxes[..., 1]],
        axis=-1,
    )
    take = flip[:, None] & (labels >= 0)
    return images, jnp.where(take[..., None], mirrored, boxes)


def _translate_image(image: Array, dy: Array, dx: Array) -> Array:
    """Integer content shift on a fixed canvas: output (y, x) reads input
    (y + dy, x + dx); out-of-range reads take the channel-mean fill (the
    same fill convention as `scale_jitter_image`). Pure gather — no
    interpolation, so in-range pixels are bitwise-exact."""
    h, w = image.shape[0], image.shape[1]
    iy = jnp.arange(h, dtype=jnp.int32) + dy
    ix = jnp.arange(w, dtype=jnp.int32) + dx
    out = image[jnp.clip(iy, 0, h - 1)][:, jnp.clip(ix, 0, w - 1)]
    fill = image.astype(jnp.float32).mean(axis=(0, 1))
    if image.dtype == jnp.uint8:
        fill = jnp.clip(jnp.round(fill), 0, 255)
    fill = fill.astype(image.dtype)
    valid = ((iy >= 0) & (iy < h))[:, None, None] & (
        (ix >= 0) & (ix < w)
    )[None, :, None]
    return jnp.where(valid, out, fill[None, None, :])


def translate_batch_with_boxes(
    images: Array,
    boxes: Array,
    labels: Array,
    mask: Array,
    shifts: Array,
) -> tuple:
    """Batch translation jitter: images gather-shifted by int32 ``shifts``
    [N, 2] = (dy, dx); real boxes move by (-dy, -dx) with canvas clip;
    rows collapsing below 1 px take the padded-row convention (label -1,
    mask False, -1 geometry). (dy, dx) == (0, 0) is an exact identity."""
    h, w = images.shape[1], images.shape[2]
    images = jax.vmap(_translate_image)(images, shifts[:, 0], shifts[:, 1])
    d = shifts.astype(boxes.dtype)
    d = jnp.concatenate([d, d], axis=-1)[:, None, :]  # (dy, dx, dy, dx)
    lim = jnp.asarray([h, w, h, w], jnp.float32).astype(boxes.dtype)
    b = jnp.clip(boxes - d, 0.0, lim)
    valid = labels >= 0
    collapsed = ((b[..., 2] - b[..., 0]) < 1.0) | (
        (b[..., 3] - b[..., 1]) < 1.0
    )
    kill = valid & collapsed
    boxes = jnp.where(valid[..., None], b, boxes)
    boxes = jnp.where(kill[..., None], -1.0, boxes)
    labels = jnp.where(kill, -1, labels)
    mask = jnp.where(kill, False, mask)
    return images, boxes, labels, mask


def jitter_boxes_batch(
    boxes: Array,
    labels: Array,
    mask: Array,
    geom: Array,
    h: int,
    w: int,
    apply: Array,
) -> tuple:
    """Batch half of `data/augment.py::jitter_boxes`: the affine
    b*s - shift with canvas clip; sub-1px rows collapse to the padded-row
    convention. ``apply`` [N] masks the rows whose geometry is not the
    identity (identity rows pass through untouched, like the host path's
    integer deadband)."""
    g = geom.astype(jnp.float32)
    sy, sx = g[:, 0] / h, g[:, 1] / w
    scale = jnp.stack([sy, sx, sy, sx], axis=-1)[:, None, :]
    shift = jnp.stack([g[:, 2], g[:, 3], g[:, 2], g[:, 3]], axis=-1)[
        :, None, :
    ]
    lim = jnp.asarray([h, w, h, w], jnp.float32)
    b = jnp.clip(boxes * scale - shift, 0.0, lim).astype(boxes.dtype)
    take = apply[:, None] & (labels >= 0)
    collapsed = ((b[..., 2] - b[..., 0]) < 1.0) | (
        (b[..., 3] - b[..., 1]) < 1.0
    )
    kill = take & collapsed
    boxes = jnp.where(take[..., None], b, boxes)
    boxes = jnp.where(kill[..., None], -1.0, boxes)
    labels = jnp.where(kill, -1, labels)
    mask = jnp.where(kill, False, mask)
    return boxes, labels, mask


def augment_batch(
    images: Array,
    boxes: Array,
    labels: Array,
    mask: Array,
    aug: Array,
    *,
    seed: int,
    hflip: bool = False,
    scale_range=None,
    translate: float = 0.0,
) -> tuple:
    """The whole train augmentation as ONE jitted batch transform.

    ``aug`` int32 [N, 2] = (dataset idx, epoch) per row; ``seed`` is
    static (baked into the trace from config). Order: flip, then
    translation jitter, then fixed-canvas scale jitter — each applied on
    the base canvas, ahead of any bucket resample
    (`resize_batch_with_boxes`). Rows whose draws are the identity pass
    through bitwise-untouched."""
    flip, u_s, u_oy, u_ox, u_ty, u_tx = augment_draws(
        seed, aug[:, 1], aug[:, 0]
    )
    h, w = images.shape[1], images.shape[2]
    if hflip:
        images, boxes = hflip_batch_with_boxes(images, boxes, labels, flip)
    if translate:
        amp_y = jnp.float32(translate * h)
        amp_x = jnp.float32(translate * w)
        dy = jnp.round((2.0 * u_ty - 1.0) * amp_y).astype(jnp.int32)
        dx = jnp.round((2.0 * u_tx - 1.0) * amp_x).astype(jnp.int32)
        images, boxes, labels, mask = translate_batch_with_boxes(
            images, boxes, labels, mask, jnp.stack([dy, dx], axis=-1)
        )
    if scale_range is not None:
        # scale_range is the static config tuple — plain Python floats
        lo, hi = scale_range
        scale = jnp.float32(lo) + jnp.float32(hi - lo) * u_s
        ch = jnp.maximum(1, jnp.round(jnp.float32(h) * scale)).astype(
            jnp.int32
        )
        cw = jnp.maximum(1, jnp.round(jnp.float32(w) * scale)).astype(
            jnp.int32
        )
        shy = jnp.round(
            (ch - h).astype(jnp.float32) * jnp.clip(u_oy, 0.0, 1.0)
        ).astype(jnp.int32)
        shx = jnp.round(
            (cw - w).astype(jnp.float32) * jnp.clip(u_ox, 0.0, 1.0)
        ).astype(jnp.int32)
        geom = jnp.stack([ch, cw, shy, shx], axis=-1)
        jittered = jnp.any(
            geom != jnp.asarray([h, w, 0, 0], jnp.int32), axis=-1
        )
        resampled = batched_scale_jitter(images, geom)
        images = jnp.where(jittered[:, None, None, None], resampled, images)
        boxes, labels, mask = jitter_boxes_batch(
            boxes, labels, mask, geom, h, w, jittered
        )
    return images, boxes, labels, mask


def resize_batch_with_boxes(
    images: Array, boxes: Array, out_hw: tuple
) -> tuple:
    """Bilinear batch resample to a STATIC output shape, boxes tracked.

    The multi-scale training buckets (data.train_resolutions) resample
    the base-resolution batch to each bucket's shape ON DEVICE, inside
    that bucket's compiled program — the feeds keep shipping one canvas
    shape, and the bucket is baked into the program like the serving
    buckets. Unlike :func:`scale_jitter_image` (fixed canvas, moving
    content window) this CHANGES the array shape, so it must run under
    a per-bucket trace, never under a shape-polymorphic one.

    images [N, H, W, C] (any float dtype or uint8), boxes [N, G, 4] in
    [r1, c1, r2, c2] pixel coords on the input canvas. Returns (resized
    [N, h, w, C] images in the input dtype, boxes scaled by (h/H, w/W)).
    Box padding rows (zeros or negatives) stay padding under the
    positive per-axis scaling. ``out_hw == (H, W)`` is the identity.
    """
    h, w = int(out_hw[0]), int(out_hw[1])
    n, ih, iw, c = images.shape
    if (ih, iw) == (h, w):
        return images, boxes
    out = jax.image.resize(
        images.astype(jnp.float32), (n, h, w, c), method="bilinear"
    )
    if images.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255)
    out = out.astype(images.dtype)
    sy = h / ih
    sx = w / iw
    scale = jnp.asarray([sy, sx, sy, sx], boxes.dtype)
    return out, boxes * scale
