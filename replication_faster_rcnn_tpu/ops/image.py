"""On-device image ops for the input pipeline.

Host-side scale jitter costs ~27 ms per 600x600 sample on one core (the
resample dominates; `PARITY.md` augmentation evidence), which makes the
measured +6.5-val-mAP augmentation ingest-bound exactly where the chip
is fastest. The TPU-native split: the HOST transforms only the boxes
and draws the jitter geometry (`data/augment.py` attaches an integer
``[ch, cw, shift_y, shift_x]`` row per sample), and the image resample
runs HERE, on device, as one vmapped bilinear gather that XLA fuses
into the input side of the step — per-batch cost is microseconds of
VPU time instead of tens of host milliseconds per image.

Geometry contract (must match ``data/augment.py::scale_jitter_sample``
exactly, which is why the host ships the rounded integers rather than
the raw scale): output pixel (y, x) reads content index
(y + shift_y, x + shift_x); a content index inside [0, ch) x [0, cw)
maps to the source image at half-pixel-center coordinates
((i + 0.5) * H / ch - 0.5), bilinear with edge-clamped taps; outside
it takes the per-image channel-mean fill. uint8 inputs round back to
uint8 (the host path's convention for device-normalize caches).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def scale_jitter_image(image: Array, params: Array) -> Array:
    """One image [H, W, C] + int32 params [4] = (ch, cw, sy, sx)."""
    h, w = image.shape[0], image.shape[1]
    ch = params[0].astype(jnp.float32)
    cw = params[1].astype(jnp.float32)
    sy = params[2]
    sx = params[3]
    im = image.astype(jnp.float32)

    iy = jnp.arange(h, dtype=jnp.int32) + sy  # content row index per out row
    ix = jnp.arange(w, dtype=jnp.int32) + sx
    valid_y = (iy >= 0) & (iy < params[0])
    valid_x = (ix >= 0) & (ix < params[1])

    ys = (iy.astype(jnp.float32) + 0.5) * (h / ch) - 0.5
    xs = (ix.astype(jnp.float32) + 0.5) * (w / cw) - 0.5
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    y0c, y1c = jnp.clip(y0, 0, h - 1), jnp.clip(y0 + 1, 0, h - 1)
    x0c, x1c = jnp.clip(x0, 0, w - 1), jnp.clip(x0 + 1, 0, w - 1)

    top = im[y0c][:, x0c] * (1 - wx) + im[y0c][:, x1c] * wx
    bot = im[y1c][:, x0c] * (1 - wx) + im[y1c][:, x1c] * wx
    out = top * (1 - wy) + bot * wy

    fill = im.mean(axis=(0, 1))
    if image.dtype == jnp.uint8:
        fill = jnp.clip(jnp.round(fill), 0, 255)
    valid = valid_y[:, None, None] & valid_x[None, :, None]
    out = jnp.where(valid, out, fill[None, None, :])
    if image.dtype == jnp.uint8:
        return jnp.clip(jnp.round(out), 0, 255).astype(jnp.uint8)
    return out.astype(image.dtype)


def batched_scale_jitter(images: Array, params: Array) -> Array:
    """images [N, H, W, C], params int32 [N, 4] -> jittered images.

    Rows with (ch, cw, sy, sx) == (H, W, 0, 0) are identity resamples
    (the half-pixel map becomes exact passthrough up to float assoc.;
    uint8 rows round back to their original values)."""
    return jax.vmap(scale_jitter_image)(images, params)


def resize_batch_with_boxes(
    images: Array, boxes: Array, out_hw: tuple
) -> tuple:
    """Bilinear batch resample to a STATIC output shape, boxes tracked.

    The multi-scale training buckets (data.train_resolutions) resample
    the base-resolution batch to each bucket's shape ON DEVICE, inside
    that bucket's compiled program — the feeds keep shipping one canvas
    shape, and the bucket is baked into the program like the serving
    buckets. Unlike :func:`scale_jitter_image` (fixed canvas, moving
    content window) this CHANGES the array shape, so it must run under
    a per-bucket trace, never under a shape-polymorphic one.

    images [N, H, W, C] (any float dtype or uint8), boxes [N, G, 4] in
    [r1, c1, r2, c2] pixel coords on the input canvas. Returns (resized
    [N, h, w, C] images in the input dtype, boxes scaled by (h/H, w/W)).
    Box padding rows (zeros or negatives) stay padding under the
    positive per-axis scaling. ``out_hw == (H, W)`` is the identity.
    """
    h, w = int(out_hw[0]), int(out_hw[1])
    n, ih, iw, c = images.shape
    if (ih, iw) == (h, w):
        return images, boxes
    out = jax.image.resize(
        images.astype(jnp.float32), (n, h, w, c), method="bilinear"
    )
    if images.dtype == jnp.uint8:
        out = jnp.clip(jnp.round(out), 0, 255)
    out = out.astype(images.dtype)
    sy = h / ih
    sx = w / iw
    scale = jnp.asarray([sy, sx, sy, sx], boxes.dtype)
    return out, boxes * scale
