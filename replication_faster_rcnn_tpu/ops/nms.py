"""Fixed-shape greedy NMS — the TPU-native replacement for
``torchvision.ops.nms`` (reference `nets/rpn.py:75`; SURVEY.md §2.3).

The reference's NMS returns a data-dependent number of boxes, which cannot
live inside a jit-compiled graph. Here NMS is a `lax.fori_loop` with exactly
``max_out`` iterations: each iteration selects the highest-scoring surviving
candidate and suppresses everything with IoU above the threshold against it.
The result is the same set, in the same score order, as sort-then-greedy NMS,
but as padded ``[max_out]`` indices plus a validity mask — a fixed shape XLA
can compile once and the batch dimension can vmap over.

Cost: ``max_out`` sequential steps of O(N) vector work. At the reference's
budgets (600 selections over <=12k candidates) this is latency- not
FLOP-bound — it measured ~35% of the v5e train step in round 1, which is
why the shipped default is the tiled exact algorithm (`ops/nms_tiled.py`,
bit-identical selections, ~25-75 sequential steps instead of 600; see
``nms_fixed_auto`` below). The loop stays as the oracle-simple fallback
(`FRCNN_NMS=loop`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.ops import boxes as box_ops

Array = jnp.ndarray

_NEG = -jnp.inf


@partial(jax.jit, static_argnames=("max_out",))
def nms_fixed(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
) -> tuple[Array, Array]:
    """Greedy NMS with a fixed output size.

    Args:
      boxes: [N, 4] candidate boxes ([r1, c1, r2, c2]).
      scores: [N] scores; higher is better.
      iou_thresh: suppress candidates with IoU strictly greater than this
        against a kept box (torchvision semantics).
      max_out: number of output slots (e.g. post_nms budget).
      mask: optional [N] bool; False entries are never selected.

    Returns:
      (idx, valid): [max_out] int32 indices into ``boxes`` in descending
      score order, and a [max_out] bool mask of which slots hold real
      selections. Invalid slots point at index 0.
    """
    n = boxes.shape[0]
    live_scores = scores.astype(jnp.float32)
    # Non-finite scores (NaN from a diverging score head) must never win
    # argmax — a NaN selection would mark the slot invalid without
    # suppressing anything, stalling every remaining iteration.
    live_scores = jnp.where(jnp.isfinite(live_scores), live_scores, _NEG)
    if mask is not None:
        live_scores = jnp.where(mask, live_scores, _NEG)

    def body(i, state):
        live, idx, valid = state
        best = jnp.argmax(live)
        best_score = live[best]
        is_valid = best_score > _NEG
        idx = idx.at[i].set(jnp.where(is_valid, best, 0).astype(jnp.int32))
        valid = valid.at[i].set(is_valid)
        ious = box_ops.iou(boxes[best][None, :], boxes)[0]  # [N]
        # The selected box suppresses itself (IoU 1) and all overlaps.
        suppress = (ious > iou_thresh) | (jnp.arange(n, dtype=jnp.int32) == best)
        live = jnp.where(is_valid & suppress, _NEG, live)
        return live, idx, valid

    idx0 = jnp.zeros((max_out,), jnp.int32)
    valid0 = jnp.zeros((max_out,), bool)
    _, idx, valid = jax.lax.fori_loop(0, max_out, body, (live_scores, idx0, valid0))
    return idx, valid


def nms_fixed_auto(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
    assume_sorted: bool = False,
) -> tuple[Array, Array]:
    """Backend dispatch for the proposal path.

    ``assume_sorted`` (candidates already in descending-score order) is a
    pure optimization hint: the tiled backend skips its internal sort;
    the loop backend ignores it (it is order-independent).

    Default on every backend (TPU included): the tiled exact algorithm
    (`ops/nms_tiled.py`; ~25-75 sequential matrix steps instead of one per
    selection). It is bit-identical to the selection loop (parity-tested in
    tests/test_nms_tiled.py), 10.8x the loop on CPU at the 12k->600 training
    budget (benchmarks/nms_backends.py), and plain XLA ops. The loop's ~600
    serial dispatches were measured at ~35% of the whole train step on v5e
    in round 1, which is why the loop is no longer any backend's default;
    validated in-step on v5e (round 2): the b8 600x600 train step went
    124 -> 180-186 images/sec across runs with this default (proposal NMS
    3.7 ms of a 42.9 ms step), and b16 went 96 -> 210
    (benchmarks/bench_v5e_round2.json).

    Overrides via FRCNN_NMS: ``loop`` (the selection loop above),
    ``tiled`` (explicit default), or ``pallas`` (the `ops/pallas/` kernel
    — same tile/fixpoint recurrence as tiled, bit-identical selections).
    ``FRCNN_NMS=pallas`` and the legacy ``FRCNN_PALLAS_NMS=1`` spelling
    were warn-and-fall-back tombstones between the round-5 removal of the
    old kernel (git 431e219: no CPU-testable parity path, and in-train-step
    compilation wedged the remote TPU service — see
    benchmarks/STAGE_BREAKDOWN.md) and the ISSUE-13 rebuild; they now
    resolve to the rebuilt backend. With no explicit FRCNN_NMS choice the
    `ops.backend` axis decides (`ops.want_pallas`): backend=pallas routes
    here too, backend=xla keeps the tiled default.
    """
    import os

    choice = os.environ.get("FRCNN_NMS", "").strip().lower()
    if not choice and os.environ.get("FRCNN_PALLAS_NMS") == "1":
        # the legacy opt-in spelling for the round-5 kernel — same signal
        # as FRCNN_NMS=pallas below, resolving to the rebuilt backend
        choice = "pallas"
    if choice and choice not in ("loop", "tiled", "pallas"):
        import warnings

        warnings.warn(
            f"unknown FRCNN_NMS={choice!r} (choices: loop, tiled, pallas); "
            "using the tiled default"
        )
        choice = ""
    if not choice:
        from replication_faster_rcnn_tpu import ops as ops_pkg

        choice = "pallas" if ops_pkg.want_pallas("nms") else "tiled"
    if choice == "pallas":
        from replication_faster_rcnn_tpu import ops as ops_pkg

        if ops_pkg.pallas_available("nms"):
            from replication_faster_rcnn_tpu.ops.pallas import nms_fixed_pallas

            return nms_fixed_pallas(
                boxes, scores, iou_thresh, max_out, mask=mask,
                tile=_tile_from_env(), assume_sorted=assume_sorted,
                interpret=ops_pkg.interpret_mode(),
            )
        choice = "tiled"  # pallas_available warned once already
    if choice == "tiled":
        from replication_faster_rcnn_tpu.ops.nms_tiled import nms_fixed_tiled

        return nms_fixed_tiled(
            boxes, scores, iou_thresh, max_out, mask=mask,
            tile=_tile_from_env(), assume_sorted=assume_sorted,
        )
    return nms_fixed(boxes, scores, iou_thresh, max_out, mask=mask)


def _tile_from_env() -> int:
    """FRCNN_NMS_TILE: candidates-per-sequential-step tile (default 512),
    honored by the tiled and pallas backends alike. Larger tiles mean
    fewer sequential steps but a bigger in-tile fixpoint matrix; the
    optimum is hardware- and budget-dependent (bench experiment:
    benchmarks/mfu_experiments.py). Bad values warn and fall back — a
    typo in a sweep must not crash a training run at trace time."""
    import os

    try:
        tile = int(os.environ.get("FRCNN_NMS_TILE", "512"))
        if tile < 1:
            raise ValueError(tile)
        return tile
    except ValueError:
        import warnings

        warnings.warn(
            f"invalid FRCNN_NMS_TILE={os.environ['FRCNN_NMS_TILE']!r} "
            "(want a positive int); using 512"
        )
        return 512


def batched_nms_fixed(
    boxes: Array,
    scores: Array,
    class_ids: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
) -> tuple[Array, Array]:
    """Per-class NMS in one pass (for inference postprocessing).

    Boxes of different classes never suppress each other: each class's boxes
    are shifted into a disjoint coordinate region (the standard trick), then
    a single fixed-shape NMS runs over all of them (backend chosen by
    `nms_fixed_auto` — same dispatch as the proposal path).
    """
    extent = jnp.max(boxes) + 1.0
    offsets = class_ids.astype(boxes.dtype)[:, None] * extent
    shifted = boxes + offsets
    return nms_fixed_auto(shifted, scores, iou_thresh, max_out, mask=mask)
