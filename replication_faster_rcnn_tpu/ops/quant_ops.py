"""Quantized matmul/conv op pair behind the ``ops.backend`` seam.

The int8 serve path (`serving.params_dtype = "int8"`) keeps planned
weights device-resident as int8 plus per-channel symmetric scales and
reconstitutes compute-dtype values on the way into each matmul/conv:

  * :func:`quant_dense` — true int8 GEMM: the activation is quantized
    against its calibrated range, the product runs int8 x int8 -> int32
    (MXU-native on TPU), and the result is rescaled by
    ``x_scale * w_scale``. This is the op the detection-head cls/reg
    layers take (`models/head.py::QuantDense`) and the one HX008 audits
    for int8 dot provenance.
  * :func:`quant_conv` — weight-only quantization: per-channel
    dequantize into the convolution. XLA:CPU has no usable int8
    convolution (measured ~75x slower than f32), and on TPU the MXU
    consumes the dequantized bf16/f32 operand directly, so the conv
    itself stays in compute dtype while residency stays int8.
  * :func:`dequantize` — the shared per-channel reconstruction.

Backend dispatch follows `ops/__init__.py::want_pallas`: the ``xla``
family is the correctness oracle (plain ``lax`` ops, the fingerprint
banks pin its HLO), ``pallas`` routes through
`ops/pallas/quant_kernel.py` (interpret-mode off-TPU). Integer
arithmetic has no rounding, so the two int8 GEMM families are bitwise
equal — tier-1 pins that (tests/test_quant.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu import ops as ops_dispatch

Array = jnp.ndarray

INT8_MAX = 127.0


def quantize_channelwise(w: Array, eps: float = 1e-12) -> tuple[Array, Array]:
    """Per-channel symmetric int8 quantization over the last axis.

    Returns ``(w_q int8, scale f32 [channels])`` with
    ``scale = max|w| / 127`` per output channel (all-but-last axes
    reduced) — the jnp twin of the numpy calibration implementation in
    `quant/calibrate.py` (which owns artifact determinism).
    """
    w = w.astype(jnp.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w), axis=reduce_axes)
    scale = jnp.maximum(amax, eps) / INT8_MAX
    w_q = jnp.clip(jnp.round(w / scale), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return w_q, scale


def quantize_activation(x: Array, x_scale: Array) -> Array:
    """Symmetric int8 activation quantization against a calibrated scale."""
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / x_scale), -INT8_MAX, INT8_MAX
    ).astype(jnp.int8)


def _int8_matmul_xla(x_q: Array, w_q: Array) -> Array:
    return jax.lax.dot_general(
        x_q,
        w_q,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def int8_matmul(x_q: Array, w_q: Array, config=None) -> Array:
    """int8 ``[M, K] @ [K, N] -> int32`` through the backend seam."""
    if ops_dispatch.want_pallas("quant_matmul", config):
        from replication_faster_rcnn_tpu.ops.pallas.quant_kernel import (
            quant_matmul_pallas,
        )

        return quant_matmul_pallas(x_q, w_q)
    return _int8_matmul_xla(x_q, w_q)


def dequantize(w_q: Array, scale: Array, config=None) -> Array:
    """Per-channel reconstruction ``w_q * scale`` (scale over last axis)."""
    if ops_dispatch.want_pallas("quant_dequant", config):
        from replication_faster_rcnn_tpu.ops.pallas.quant_kernel import (
            dequantize_pallas,
        )

        return dequantize_pallas(w_q, scale)
    return w_q.astype(jnp.float32) * scale.astype(jnp.float32)


def quant_dense(
    x: Array,
    w_q: Array,
    w_scale: Array,
    x_scale: Array,
    bias: Optional[Array] = None,
    config=None,
) -> Array:
    """int8 dense layer: quantize ``x``, int8 GEMM, rescale, add bias.

    ``x [..., K]`` (any float dtype), ``w_q [K, N] int8``,
    ``w_scale [N]``, ``x_scale`` scalar (calibrated activation range /
    127). Output is float32 ``[..., N]``.
    """
    lead = x.shape[:-1]
    x2 = x.reshape((-1, x.shape[-1]))
    x_q = quantize_activation(x2, x_scale)
    y = int8_matmul(x_q, w_q, config).astype(jnp.float32)
    y = y * (x_scale.astype(jnp.float32) * w_scale.astype(jnp.float32))[None, :]
    if bias is not None:
        y = y + bias.astype(jnp.float32)[None, :]
    return y.reshape(lead + (y.shape[-1],))


def quant_conv(
    x: Array,
    w_q: Array,
    w_scale: Array,
    *,
    window_strides=(1, 1),
    padding="SAME",
    dimension_numbers=("NHWC", "HWIO", "NHWC"),
    feature_group_count: int = 1,
    config=None,
) -> Array:
    """Weight-only quantized convolution: per-channel dequantize the
    ``HWIO`` int8 kernel into the conv operand dtype, then convolve."""
    w = dequantize(w_q, w_scale, config).astype(x.dtype)
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=window_strides,
        padding=padding,
        dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count,
    )
