"""Pallas TPU kernel for fixed-size greedy NMS.

Why a kernel: the XLA version (`ops/nms.py`) is a ``lax.fori_loop`` whose
``max_out`` iterations each dispatch a handful of small HBM-bound vector ops
— at the training budgets (600 selections over 12k candidates) that serial
overhead is ~35% of the whole train step (measured; see git history). This
kernel keeps scores and box planes resident in VMEM and runs the entire
greedy loop in-core on the VPU: per iteration it is ~6 vector passes over an
[R, 128] tile set with no HBM traffic and no dispatch.

Kernel-level design choices:
  * candidates are laid out as lane-major planes: scores [R, 128] and
    coordinates [4R, 128] (rows 0..R-1 = r1 plane, R..2R-1 = c1, ...), with
    flat candidate index = row * 128 + lane;
  * the argmax winner is extracted with a first-occurrence one-hot
    (min over index-where-max) and masked sums — no dynamic gathers;
  * the IoU-vs-threshold test is division-free:
    ``inter > t * union  <=>  iou > t`` since union > 0 wherever inter > 0;
  * selected indices/validity are scalar-stored into SMEM outputs.

Semantics are identical to ``nms.nms_fixed`` (same selection set, same
order, same tie-breaking on the lowest index) — parity-tested in
tests/test_nms_pallas.py, in interpret mode on CPU and compiled on TPU.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray

_LANES = 128
_NEG = -1e30  # well below any real score; avoids inf arithmetic in-kernel


def _nms_kernel(score_ref, coords_ref, sel_ref, live_ref, *, max_out, iou_thresh):
    """Writes sel_ref [R, 128] i32: the greedy selection round (0-based) of
    each candidate, or R*128 where never selected. The wrapper recovers the
    ordered index list with one argsort — SMEM scalar outputs would break
    vmap's batching rules, a VMEM plane doesn't."""
    r = score_ref.shape[0]
    live_ref[:] = score_ref[:]
    r1 = coords_ref[0:r, :]
    c1 = coords_ref[r : 2 * r, :]
    r2 = coords_ref[2 * r : 3 * r, :]
    c2 = coords_ref[3 * r : 4 * r, :]
    area = (r2 - r1) * (c2 - c1)
    flat = (
        jax.lax.broadcasted_iota(jnp.int32, (r, _LANES), 0) * _LANES
        + jax.lax.broadcasted_iota(jnp.int32, (r, _LANES), 1)
    )
    big = jnp.int32(r * _LANES)
    sel_ref[:] = jnp.full((r, _LANES), big, jnp.int32)

    def body(i, _):
        live = live_ref[:]
        m = jnp.max(live)
        is_valid = m > jnp.float32(_NEG / 2)
        # first occurrence of the max -> one-hot (ties: lowest flat index,
        # matching jnp.argmax in the XLA version)
        best_flat = jnp.min(jnp.where(live == m, flat, big))
        one_hot = flat == best_flat
        # winner's box via masked reductions (no dynamic indexing)
        br1 = jnp.sum(jnp.where(one_hot, r1, 0.0))
        bc1 = jnp.sum(jnp.where(one_hot, c1, 0.0))
        br2 = jnp.sum(jnp.where(one_hot, r2, 0.0))
        bc2 = jnp.sum(jnp.where(one_hot, c2, 0.0))
        barea = (br2 - br1) * (bc2 - bc1)
        # intersection with every candidate
        ih = jnp.minimum(br2, r2) - jnp.maximum(br1, r1)
        iw = jnp.minimum(bc2, c2) - jnp.maximum(bc1, c1)
        pos = (ih > 0.0) & (iw > 0.0)
        inter = jnp.where(pos, ih * iw, 0.0)
        union = barea + area - inter
        # iou > t  <=>  inter > t * union (union > 0 wherever inter > 0)
        suppress = (inter > iou_thresh * union) | one_hot
        keep = jnp.logical_and(is_valid, one_hot)
        sel_ref[:] = jnp.where(keep, i, sel_ref[:])
        live_ref[:] = jnp.where(jnp.logical_and(is_valid, suppress), _NEG, live)
        return 0

    jax.lax.fori_loop(0, max_out, body, 0)


@partial(jax.jit, static_argnames=("iou_thresh", "max_out", "interpret"))
def nms_fixed_pallas(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
    interpret: bool = False,
) -> tuple[Array, Array]:
    """Drop-in replacement for :func:`ops.nms.nms_fixed` backed by the
    Pallas kernel. Same contract: ([max_out] int32 indices in selection
    order, [max_out] bool validity)."""
    n = boxes.shape[0]
    r = max(-(-n // _LANES), 1)
    n_pad = r * _LANES

    s = scores.astype(jnp.float32)
    s = jnp.where(jnp.isfinite(s), s, _NEG)
    if mask is not None:
        s = jnp.where(mask, s, _NEG)
    s = jnp.pad(s, (0, n_pad - n), constant_values=_NEG)
    b = jnp.pad(boxes.astype(jnp.float32), ((0, n_pad - n), (0, 0)))

    score_planes = s.reshape(r, _LANES)
    # [4, n_pad] -> [4r, 128]: each coordinate's n_pad values reshape to an
    # [r, 128] plane, stacked coordinate-major
    coord_planes = b.T.reshape(4 * r, _LANES)

    sel = pl.pallas_call(
        partial(_nms_kernel, max_out=max_out, iou_thresh=float(iou_thresh)),
        out_shape=jax.ShapeDtypeStruct((r, _LANES), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((r, _LANES), jnp.float32)],
        interpret=interpret,
    )(score_planes, coord_planes)

    # selection rounds are unique, so ascending argsort puts round i at
    # position i; unselected candidates (sentinel n_pad) sort after them
    flat_sel = sel.reshape(-1)
    order = jnp.argsort(flat_sel)
    take = min(max_out, n_pad)
    idx = order[:take].astype(jnp.int32)
    valid = flat_sel[idx] < n_pad
    if take < max_out:  # fewer candidates than output slots: pad
        idx = jnp.pad(idx, (0, max_out - take))
        valid = jnp.pad(valid, (0, max_out - take))
    return jnp.where(valid, idx, 0), valid


def nms_fixed_auto(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
    assume_sorted: bool = False,
) -> tuple[Array, Array]:
    """Backend dispatch for the proposal path.

    ``assume_sorted`` (candidates already in descending-score order) is a
    pure optimization hint: the tiled backend skips its internal sort;
    the loop and Pallas backends ignore it (they are order-independent).

    Default on every backend (TPU included): the tiled exact algorithm
    (`ops/nms_tiled.py`; ~25-75 sequential matrix steps instead of one per
    selection). It is bit-identical to the selection loop (parity-tested in
    tests/test_nms_tiled.py), 10.8x the loop on CPU at the 12k->600 training
    budget (benchmarks/nms_backends.py), and — unlike the Pallas kernel —
    plain XLA ops, so it carries none of the remote-compile risk that keeps
    Pallas opt-in. The loop's ~600 serial dispatches were measured at ~35%
    of the whole train step on v5e in round 1, which is why the loop is no
    longer any backend's default; validated in-step on v5e (round 2): the
    b8 600x600 train step went 124 -> 180-186 images/sec across runs with
    this default (proposal NMS 3.7 ms of a 42.9 ms step), and b16 went
    96 -> 210 (benchmarks/bench_v5e_round2.json).

    Overrides via FRCNN_NMS (explicit choice always wins; the legacy
    FRCNN_PALLAS_NMS=1 is honored only when FRCNN_NMS is unset):

      * ``FRCNN_NMS=loop`` — the `ops/nms.py` selection loop, any backend.
      * ``FRCNN_NMS=tiled`` — the tiled algorithm, any backend.
      * ``FRCNN_NMS=pallas`` — the in-VMEM Pallas kernel, TPU only.
        Standalone it measures 3.2x the XLA loop (9.4ms vs 30.2ms for a
        batch-8 12k->600 NMS on v5e), but this image's remote-compile TPU
        service has been observed to wedge when the kernel is compiled
        INSIDE the full train-step module, taking the whole chip tunnel
        down with it — hence opt-in.
    """
    import os

    from replication_faster_rcnn_tpu.ops import nms as nms_xla

    choice = os.environ.get("FRCNN_NMS", "") or (
        "pallas" if os.environ.get("FRCNN_PALLAS_NMS") == "1" else ""
    )
    if choice == "pallas":
        if jax.default_backend() == "tpu":
            return nms_fixed_pallas(boxes, scores, iou_thresh, max_out, mask=mask)
        import warnings

        warnings.warn(
            "the Pallas NMS kernel needs a TPU backend; using the tiled default"
        )
        choice = "tiled"
    elif choice not in ("", "loop", "tiled"):
        import warnings

        warnings.warn(
            f"unknown FRCNN_NMS={choice!r} (choices: loop, tiled, pallas); "
            "using the backend default"
        )
        choice = ""
    if not choice:
        choice = "tiled"
    if choice == "tiled":
        from replication_faster_rcnn_tpu.ops.nms_tiled import nms_fixed_tiled

        # FRCNN_NMS_TILE tunes the candidates-per-sequential-step tile
        # (default 512). Larger tiles mean fewer sequential steps but a
        # bigger in-tile fixpoint matrix; the optimum is hardware- and
        # budget-dependent (bench experiment: benchmarks/mfu_experiments.py).
        # Bad values warn and fall back — a typo in a sweep must not
        # crash a training run at trace time
        try:
            tile = int(os.environ.get("FRCNN_NMS_TILE", "512"))
            if tile < 1:
                raise ValueError(tile)
        except ValueError:
            import warnings

            warnings.warn(
                f"invalid FRCNN_NMS_TILE={os.environ['FRCNN_NMS_TILE']!r} "
                "(want a positive int); using 512"
            )
            tile = 512
        return nms_fixed_tiled(
            boxes, scores, iou_thresh, max_out, mask=mask, tile=tile,
            assume_sorted=assume_sorted,
        )
    return nms_xla.nms_fixed(boxes, scores, iou_thresh, max_out, mask=mask)
