from replication_faster_rcnn_tpu.ops import anchors, boxes, nms, roi_ops  # noqa: F401
