"""Detection ops + the `ops.backend` dispatch seam.

Two implementation families live side by side:

* **xla** (default): the pure-XLA tilings (`nms_tiled.py`, `roi_ops.py`,
  `boxes.py`). The committed fingerprint banks (`frcnn audit`) pin these
  programs byte-for-byte, so the default backend must never change HLO.
* **pallas**: the Pallas kernels in `ops/pallas/` — interpret-mode off-TPU
  (pure JAX, parity-tested on CPU in tier-1), Mosaic-compiled on a TPU.

Resolution order, highest first:

1. :func:`backend_scope` — lexical override (tests, warmup twin programs)
2. ``FRCNN_OPS_BACKEND`` env var — read ONCE per process then cached, so a
   mid-run env flip can't split a program between backends (the trace-time
   ``FRCNN_NMS`` reads were a recurring source of that confusion)
3. the ``config.ops.backend`` value the caller passes down
4. ``"xla"``

`want_pallas(op)` is the single question dispatch sites ask; it folds in
availability (import failure of the kernel package warns once per op and
falls back to XLA rather than erroring — e.g. a jax build without pallas).
"""

import os
import threading
import warnings

from replication_faster_rcnn_tpu.ops import (  # noqa: F401
    anchors,
    boxes,
    nms,
    nms_tiled,
    roi_ops,
)

BACKENDS = ("xla", "pallas")

_ENV_VAR = "FRCNN_OPS_BACKEND"
_env_backend = None  # resolved-once cache: None = not read yet, "" = unset
_env_lock = threading.Lock()
_scope = threading.local()
_warned: set = set()


def _warn_once(key: str, msg: str) -> None:
    if key in _warned:
        return
    _warned.add(key)
    warnings.warn(msg, stacklevel=3)


def _env_override() -> str:
    """The FRCNN_OPS_BACKEND value, read once per process ("" = unset)."""
    global _env_backend
    if _env_backend is None:
        with _env_lock:
            if _env_backend is None:
                raw = os.environ.get(_ENV_VAR, "").strip().lower()
                if raw and raw not in BACKENDS:
                    _warn_once(
                        "env:invalid",
                        f"{_ENV_VAR}={raw!r} is not one of {BACKENDS}; "
                        "ignoring (using the config/default backend)",
                    )
                    raw = ""
                _env_backend = raw
    return _env_backend


class backend_scope:
    """Lexically pin the ops backend for the current thread.

    with ops.backend_scope("pallas"):
        ...   # every dispatch site in this block resolves to pallas

    Wins over the env var and config — this is how the warmup registry
    traces the ``__pallas`` twin programs and how tier-1 exercises both
    families in one process.
    """

    def __init__(self, backend: str):
        if backend not in BACKENDS:
            raise ValueError(
                f"backend must be one of {BACKENDS}, got {backend!r}"
            )
        self.backend = backend

    def __enter__(self):
        stack = getattr(_scope, "stack", None)
        if stack is None:
            stack = _scope.stack = []
        stack.append(self.backend)
        return self

    def __exit__(self, *exc):
        _scope.stack.pop()
        return False


def resolve_backend(config=None) -> str:
    """The effective ops backend: scope > env (read once) > config > xla."""
    stack = getattr(_scope, "stack", None)
    if stack:
        return stack[-1]
    env = _env_override()
    if env:
        return env
    if config is not None:
        ops_cfg = getattr(config, "ops", config)
        backend = getattr(ops_cfg, "backend", None)
        if backend is not None:
            if backend not in BACKENDS:
                raise ValueError(
                    f"config ops.backend must be one of {BACKENDS}, "
                    f"got {backend!r}"
                )
            return backend
    return "xla"


def pallas_available(op: str = "") -> bool:
    """Can the pallas kernels be used here? (warns once per op if not)"""
    try:
        from replication_faster_rcnn_tpu.ops import pallas  # noqa: F401

        return True
    except Exception as e:  # pragma: no cover - env without pallas support
        _warn_once(
            f"unavailable:{op}",
            f"ops.backend=pallas requested but the kernel package failed "
            f"to import ({type(e).__name__}: {e}); falling back to the XLA "
            + (f"implementation for {op!r}" if op else "implementations"),
        )
        return False


def want_pallas(op: str, config=None) -> bool:
    """True iff dispatch site ``op`` should take the pallas path."""
    return resolve_backend(config) == "pallas" and pallas_available(op)


def interpret_mode() -> bool:
    """Pallas interpret mode: everywhere except a real TPU backend."""
    import jax

    return jax.default_backend() != "tpu"
