"""Tiled exact greedy NMS — fewer sequential steps than the selection loop.

`ops/nms.py::nms_fixed` runs one sequential iteration per SELECTED box
(``max_out`` = 600 at the training budget), each doing a small vector pass —
on TPU that cost is dispatch/latency, not FLOPs. This module computes the
identical greedy result with one sequential step per TILE of candidates
plus a short in-tile fixpoint, the structure TPU NMS implementations use
(cf. TF's ``non_max_suppression_padded``): for 12k candidates at tile 512
that is ~25-75 sequential steps of dense [512, 512] / [max_out, 512] IoU
matrix work (VPU-friendly) instead of 600.

Exactness argument (parity-tested against ``nms_fixed``):
  * candidates are processed in descending-score order (stable sort — ties
    break on the lower original index, same as the loop's first-max argmax);
  * a box is greedy-kept iff it is valid and no earlier-ordered KEPT box
    overlaps it above threshold. Within a tile this recurrence
    ``g[b] = m0[b] & ~any_{a<b}(g[a] & S[a,b])`` is solved by fixpoint
    iteration of the whole vector: after k sweeps the first k entries are
    exact, and any fixpoint satisfies the (uniquely-determined) recurrence,
    so the early-exit-on-stable while_loop returns exactly greedy;
  * boxes selected in earlier tiles are the only cross-tile suppressors,
    and at most ``max_out`` selections are ever needed, so cross-tile
    suppression tests each tile against the compact selected-box buffer in
    ONE matrix op; the outer loop stops as soon as the buffer fills.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from replication_faster_rcnn_tpu.ops import boxes as box_ops

Array = jnp.ndarray

_NEG = -jnp.inf


@partial(jax.jit, static_argnames=("max_out", "tile", "assume_sorted"))
def nms_fixed_tiled(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
    tile: int = 512,
    assume_sorted: bool = False,
) -> tuple[Array, Array]:
    """Drop-in replacement for :func:`ops.nms.nms_fixed` (same contract:
    [max_out] int32 indices in selection order + [max_out] validity).

    ``assume_sorted``: the caller guarantees ``scores`` (after applying
    ``mask``) are already non-increasing, so the internal stable sort and
    its gathers are skipped. The proposal path uses this to sort ONCE:
    its top-pre_nms selection already produces descending candidates
    (`models/rpn.py::select_proposals`), and sorting 12k candidates twice
    per image was pure waste on the hot path.
    """
    n = boxes.shape[0]
    tile = min(tile, max(n, 1))
    s = scores.astype(jnp.float32)
    s = jnp.where(jnp.isfinite(s), s, _NEG)
    if mask is not None:
        s = jnp.where(mask, s, _NEG)

    n_tiles = -(-n // tile)
    n_pad = n_tiles * tile
    pad = n_pad - n
    if assume_sorted:
        order_p = jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, pad))
        s_sorted = jnp.pad(s, (0, pad), constant_values=_NEG)
        b_sorted = jnp.pad(boxes.astype(jnp.float32), ((0, pad), (0, 0)))
    else:
        # stable descending-score order; ties keep ascending original
        # index, matching nms_fixed's first-occurrence argmax
        order = jnp.argsort(-s)
        order_p = jnp.pad(order, (0, pad)).astype(jnp.int32)
        s_sorted = jnp.pad(s[order], (0, pad), constant_values=_NEG)
        b_sorted = jnp.pad(
            boxes.astype(jnp.float32)[order], ((0, pad), (0, 0))
        )
    valid_sorted = s_sorted > _NEG

    later = (
        jnp.arange(tile, dtype=jnp.int32)[:, None]
        < jnp.arange(tile, dtype=jnp.int32)[None, :]
    )  # a before b

    def outer_cond(st):
        i, count, _, _ = st
        return (i < n_tiles) & (count < max_out)

    def outer_body(st):
        i, count, sel_boxes, sel_idx = st
        tb = jax.lax.dynamic_slice_in_dim(b_sorted, i * tile, tile)
        tv = jax.lax.dynamic_slice_in_dim(valid_sorted, i * tile, tile)
        ti = jax.lax.dynamic_slice_in_dim(order_p, i * tile, tile)

        # cross-tile: suppressed by any already-selected box (one matrix op)
        kmask = jnp.arange(max_out, dtype=jnp.int32) < count
        cross = box_ops.iou(sel_boxes, tb) > iou_thresh  # [max_out, tile]
        m0 = tv & ~jnp.any(cross & kmask[:, None], axis=0)

        # in-tile greedy via fixpoint sweeps (exact; see module docstring)
        suppress = (box_ops.iou(tb, tb) > iou_thresh) & later

        def sweep_cond(gs):
            _, stable = gs
            return ~stable

        def sweep_body(gs):
            g, _ = gs
            g2 = m0 & ~jnp.any(suppress & g[:, None], axis=0)
            return g2, jnp.all(g2 == g)

        g, _ = jax.lax.while_loop(sweep_cond, sweep_body, (m0, jnp.array(False, dtype=bool)))

        # append this tile's selections to the compact buffers (in order)
        pos = count + jnp.cumsum(g) - 1
        slot = jnp.where(g & (pos < max_out), pos, max_out)  # overflow -> drop
        sel_boxes = sel_boxes.at[slot].set(tb, mode="drop")
        sel_idx = sel_idx.at[slot].set(ti, mode="drop")
        count = jnp.minimum(count + jnp.sum(g), max_out).astype(jnp.int32)
        return i + 1, count, sel_boxes, sel_idx

    init = (
        jnp.int32(0),
        jnp.int32(0),
        jnp.zeros((max_out, 4), jnp.float32),
        jnp.zeros((max_out,), jnp.int32),
    )
    _, count, _, sel_idx = jax.lax.while_loop(outer_cond, outer_body, init)
    valid = jnp.arange(max_out, dtype=jnp.int32) < count
    return jnp.where(valid, sel_idx, 0), valid
