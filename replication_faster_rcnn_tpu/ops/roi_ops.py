"""ROI feature extraction — TPU-native replacements for
``torchvision.ops.roi_pool`` / ``roi_align`` (reference `nets/heads.py:48`;
SURVEY.md §2.3).

Both ops are fixed-shape and differentiable w.r.t. the feature map, so the
detection-head gradient flows into the backbone exactly as it does through
torchvision's C++ kernels in the reference.

* :func:`roi_align` — bilinear sampling on a fixed ``sampling_ratio^2`` grid
  per output bin, averaged (torchvision ROIAlign, aligned=False semantics).
  Two implementations with identical numerics:
    - ``method="einsum"`` (default): bilinear interpolation is separable,
      so sampling IS a pair of batched matmuls — per-roi tent-weight
      matrices ``WR [R, P, H]`` / ``WC [R, Q, W]`` contract the feature map
      on the MXU. No gathers touch HBM: the TPU-native formulation.
    - ``method="gather"``: 4-corner gathers + weighted sum (the direct
      translation of the sampling definition); kept as the oracle and for
      very large feature maps where the dense weight matrices would not pay.
* :func:`roi_pool` — legacy quantized max pooling (round coords, +1 extents,
  floor/ceil bin edges, empty bins -> 0), matching the Caffe/torchvision
  ROIPool the reference uses. Implemented as masked maxes over the feature
  map with a static loop over the 7x7 output bins, so shapes stay fixed.

Features are NHWC ([H, W, C] per image here; callers vmap over the batch).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def _bilinear_gather(feat: Array, r: Array, c: Array) -> Array:
    """Bilinear-interpolate feat [H, W, C] at continuous (r, c) points.

    r, c: [...] coordinates in pixel units (centers at integers). Points
    outside [-1, H] x [-1, W] contribute zero (torchvision border rule);
    in-range points clamp to the valid gather window.
    """
    h, w = feat.shape[0], feat.shape[1]
    in_range = (r >= -1.0) & (r <= h) & (c >= -1.0) & (c <= w)
    r = jnp.clip(r, 0.0, h - 1.0)
    c = jnp.clip(c, 0.0, w - 1.0)
    r0 = jnp.floor(r)
    c0 = jnp.floor(c)
    r0i = r0.astype(jnp.int32)
    c0i = c0.astype(jnp.int32)
    r1i = jnp.minimum(r0i + 1, h - 1)
    c1i = jnp.minimum(c0i + 1, w - 1)
    ar = r - r0
    ac = c - c0
    w00 = (1 - ar) * (1 - ac)
    w01 = (1 - ar) * ac
    w10 = ar * (1 - ac)
    w11 = ar * ac
    gathered = (
        feat[r0i, c0i] * w00[..., None]
        + feat[r0i, c1i] * w01[..., None]
        + feat[r1i, c0i] * w10[..., None]
        + feat[r1i, c1i] * w11[..., None]
    )
    return gathered * in_range[..., None]


def _sample_grid(rois: Array, out_size: int, s: int, dtype) -> tuple:
    """Continuous sample coordinates per roi: (rr [R, out*s], cc [R, out*s])."""
    r1, c1, r2, c2 = rois[:, 0], rois[:, 1], rois[:, 2], rois[:, 3]
    # aligned=False semantics: roi extent clamps to a 1px minimum.
    roi_h = jnp.maximum(r2 - r1, 1.0)
    roi_w = jnp.maximum(c2 - c1, 1.0)
    bin_h = roi_h / out_size  # [R]
    bin_w = roi_w / out_size
    # Sample offsets within a roi, in bin units: (p + (i + .5)/s) for output
    # bin p and sample i — shape [out*s].
    pts = (jnp.arange(out_size * s, dtype=dtype) + 0.5) / s
    rr = r1[:, None] + pts[None, :] * bin_h[:, None]  # [R, out*s]
    cc = c1[:, None] + pts[None, :] * bin_w[:, None]
    return rr, cc


def _tent_weights(coords: Array, extent: int) -> Array:
    """Per-point bilinear weight rows: coords [R, P] -> [R, P, extent].

    Row p holds the two-tap interpolation weights of sample p against the
    integer grid 0..extent-1 (a tent max(0, 1-|x-i|) after the gather
    path's clamping), zeroed for points outside [-1, extent] (torchvision
    border rule). Matches `_bilinear_gather` exactly: clamping to
    [0, extent-1] collapses the tent to weight 1 at the border tap.
    """
    in_range = (coords >= -1.0) & (coords <= extent)
    x = jnp.clip(coords, 0.0, extent - 1.0)
    grid = jnp.arange(extent, dtype=coords.dtype)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(x[..., None] - grid))  # [R, P, extent]
    return w * in_range[..., None]


@partial(jax.jit, static_argnames=("out_size", "sampling_ratio", "method"))
def roi_align(
    feat: Array,
    rois: Array,
    out_size: int = 7,
    sampling_ratio: int = 2,
    spatial_scale: float = 1.0,
    method: str = "einsum",
) -> Array:
    """ROIAlign: feat [H, W, C], rois [R, 4] -> [R, out, out, C].

    Rois are in feature-map coordinates after multiplying by
    ``spatial_scale`` (the reference pre-scales rois itself and passes
    spatial_scale=1, `nets/heads.py:42-48`).

    ``method="einsum"``: bilinear sampling is separable, so the whole op is
    sampled[r,p,q,:] = WR[r,p,:] @ feat @ WC[r,q,:]^T — two batched
    matmuls on the MXU, no gathers (each weight row has <= 2 nonzeros, but
    dense-matmul beats random HBM access on TPU for detection-sized maps).
    ``method="gather"``: the direct 4-corner gather implementation.
    ``method="pallas"``: the fused `ops/pallas/roi_kernel.py` forward
    (same einsum formulation inside one kernel; tolerance-gated parity —
    see tests/test_pallas_roi.py), einsum VJP for the backward.
    """
    if method == "pallas":
        from replication_faster_rcnn_tpu import ops as ops_pkg
        from replication_faster_rcnn_tpu.ops.pallas import roi_align_pallas

        # the kernel wrapper applies spatial_scale itself — delegate before
        # the shared pre-scaling below
        return roi_align_pallas(
            feat, rois, out_size, sampling_ratio, spatial_scale,
            interpret=ops_pkg.interpret_mode(),
        )
    rois = rois * spatial_scale
    s = sampling_ratio
    rr, cc = _sample_grid(rois, out_size, s, feat.dtype)

    if method == "einsum":
        h, w = feat.shape[0], feat.shape[1]
        wr = _tent_weights(rr, h)  # [R, P, H]
        wc = _tent_weights(cc, w)  # [R, Q, W]
        # [R, P, H] x [H, W, C] -> [R, P, W, C]; then contract W with WC.
        rows = jnp.einsum("rph,hwc->rpwc", wr, feat)
        sampled = jnp.einsum("rpwc,rqw->rpqc", rows, wc)
    elif method == "gather":
        rg = rr[:, :, None] * jnp.ones_like(cc)[:, None, :]  # [R, out*s, out*s]
        cg = cc[:, None, :] * jnp.ones_like(rr)[:, :, None]
        sampled = _bilinear_gather(feat, rg, cg)  # [R, out*s, out*s, C]
    else:
        raise ValueError(f"unknown roi_align method {method!r}")

    r_, c_ = sampled.shape[0], sampled.shape[-1]
    sampled = sampled.reshape(r_, out_size, s, out_size, s, c_)
    return sampled.mean(axis=(2, 4))


@partial(jax.jit, static_argnames=("out_size",))
def roi_pool(
    feat: Array,
    rois: Array,
    out_size: int = 7,
    spatial_scale: float = 1.0,
) -> Array:
    """Legacy ROIPool: feat [H, W, C], rois [R, 4] -> [R, out, out, C].

    Quantization follows the Caffe/torchvision kernel: scaled coords are
    rounded; roi extent gets +1; bin edges are floor/ceil of the fractional
    bin size; bins clamp to the map; empty bins output 0.
    """
    h, w = feat.shape[0], feat.shape[1]
    r1 = jnp.round(rois[:, 0] * spatial_scale)
    c1 = jnp.round(rois[:, 1] * spatial_scale)
    r2 = jnp.round(rois[:, 2] * spatial_scale)
    c2 = jnp.round(rois[:, 3] * spatial_scale)
    roi_h = jnp.maximum(r2 - r1 + 1.0, 1.0)  # [R]
    roi_w = jnp.maximum(c2 - c1 + 1.0, 1.0)
    bin_h = roi_h / out_size
    bin_w = roi_w / out_size

    p = jnp.arange(out_size, dtype=feat.dtype)
    # Bin edges per roi/bin, clamped to the feature map: [R, out]
    hstart = jnp.clip(jnp.floor(p[None, :] * bin_h[:, None]) + r1[:, None], 0, h)
    hend = jnp.clip(jnp.ceil((p[None, :] + 1) * bin_h[:, None]) + r1[:, None], 0, h)
    wstart = jnp.clip(jnp.floor(p[None, :] * bin_w[:, None]) + c1[:, None], 0, w)
    wend = jnp.clip(jnp.ceil((p[None, :] + 1) * bin_w[:, None]) + c1[:, None], 0, w)

    rows = jnp.arange(h, dtype=feat.dtype)
    cols = jnp.arange(w, dtype=feat.dtype)
    # Membership masks: row_mask [R, out, H], col_mask [R, out, W]
    row_mask = (rows[None, None, :] >= hstart[:, :, None]) & (
        rows[None, None, :] < hend[:, :, None]
    )
    col_mask = (cols[None, None, :] >= wstart[:, :, None]) & (
        cols[None, None, :] < wend[:, :, None]
    )

    neg = jnp.asarray(-jnp.inf, feat.dtype)
    # Static loop over output bins keeps every intermediate at [R, H|W, C]
    # and lets XLA fuse each masked-select into its reduce.
    col_pooled = []  # per output col j: [R, H, C]
    for j in range(out_size):
        m = col_mask[:, j, None, :, None]  # [R, 1, W, 1]
        col_pooled.append(
            jnp.max(jnp.where(m, feat[None, :, :, :], neg), axis=2)
        )
    col_pooled = jnp.stack(col_pooled, axis=2)  # [R, H, out, C]

    out = []
    for i in range(out_size):
        m = row_mask[:, i, :, None, None]  # [R, H, 1, 1]
        out.append(jnp.max(jnp.where(m, col_pooled, neg), axis=1))  # [R, out, C]
    pooled = jnp.stack(out, axis=1)  # [R, out, out, C]
    return jnp.where(jnp.isfinite(pooled), pooled, 0.0)


def extract_roi_features(
    feat: Array,
    rois: Array,
    op: str = "align",
    out_size: int = 7,
    sampling_ratio: int = 2,
    spatial_scale: float = 1.0,
) -> Array:
    """Dispatch between ROIAlign and ROIPool by config string.

    ROIAlign additionally honors the `ops.backend` axis: backend=pallas
    routes to the fused kernel forward (XLA einsum VJP for the backward),
    backend=xla (default) keeps the einsum formulation byte-identical to
    the committed fingerprints.
    """
    if op == "align":
        from replication_faster_rcnn_tpu import ops as ops_pkg

        method = "pallas" if ops_pkg.want_pallas("roi_align") else "einsum"
        return roi_align(
            feat, rois, out_size, sampling_ratio, spatial_scale, method=method
        )
    if op == "pool":
        return roi_pool(feat, rois, out_size, spatial_scale)
    raise ValueError(f"unknown roi op {op!r}")
