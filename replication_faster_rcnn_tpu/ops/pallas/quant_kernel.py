"""Pallas int8 kernels for quantized serving (quant/, ISSUE 17).

Two kernels back the ``ops.backend = "pallas"`` half of the quantized
op pair in `ops/quant_ops.py`:

  * :func:`quant_matmul_pallas` — tiled int8 x int8 -> int32 matmul.
    Operands are blocked over (M, N) with the contraction axis resident
    per block, and the product accumulates in int32 on the MXU
    (``preferred_element_type=jnp.int32`` — int8 inputs otherwise
    accumulate in int8 and wrap). Integer arithmetic has no rounding,
    so the kernel is **bitwise** equal to the XLA reference
    (`quant_ops.py::_int8_matmul_xla`) in both interpret mode and on
    chip; tier-1 pins that equality.
  * :func:`dequantize_pallas` — per-channel symmetric dequantize
    ``w_q.astype(f32) * scale`` tiled over rows, the op the int8 serve
    programs apply to conv weights on their way into the convolution.

Both take ``interpret`` (default: interpret unless running on a real
TPU backend) so the kernel code is parity-tested on CPU in tier-1, and
both pad up to the int8 minimum tile (32, 128) — narrow head GEMMs
([N*R, 512] x [512, classes]) are the expected shape, far below one
natural MXU tile.

On-chip compilation must only happen through the warmup ProgramSpec
registry (`train/warmup.py::build_int8_program_specs`), never lazily.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jnp.ndarray

# int8 minimum TPU tile (sublane, lane); also a sane CPU interpret block
_MIN_TILE_M = 32
_MIN_TILE_N = 128


def _matmul_kernel(x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...],
        w_ref[...],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@partial(jax.jit, static_argnames=("tile_m", "tile_n", "interpret"))
def _quant_matmul(
    x_q: Array, w_q: Array, tile_m: int, tile_n: int, interpret: bool
) -> Array:
    m, k = x_q.shape
    k2, n = w_q.shape
    assert k == k2, (k, k2)
    mp = _round_up(max(m, 1), tile_m)
    np_ = _round_up(max(n, 1), tile_n)
    kp = _round_up(max(k, 1), _MIN_TILE_N)
    x_p = jnp.pad(x_q, ((0, mp - m), (0, kp - k)))
    w_p = jnp.pad(w_q, ((0, kp - k), (0, np_ - n)))
    # zero padding contributes zero products: the valid [m, n] block of
    # the padded product equals the unpadded product exactly
    out = pl.pallas_call(
        _matmul_kernel,
        grid=(mp // tile_m, np_ // tile_n),
        in_specs=[
            pl.BlockSpec((tile_m, kp), lambda i, j: (i, 0)),
            pl.BlockSpec((kp, tile_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((tile_m, tile_n), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.int32),
        interpret=interpret,
    )(x_p, w_p)
    return out[:m, :n]


def _dequant_kernel(w_ref, s_ref, o_ref):
    o_ref[...] = w_ref[...].astype(jnp.float32) * s_ref[...]


@partial(jax.jit, static_argnames=("tile_m", "interpret"))
def _dequantize(w_q: Array, scale: Array, tile_m: int, interpret: bool) -> Array:
    r, c = w_q.shape
    rp = _round_up(max(r, 1), tile_m)
    cp = _round_up(max(c, 1), _MIN_TILE_N)
    w_p = jnp.pad(w_q, ((0, rp - r), (0, cp - c)))
    s_p = jnp.pad(scale.astype(jnp.float32), (0, cp - c))[None, :]
    out = pl.pallas_call(
        _dequant_kernel,
        grid=(rp // tile_m,),
        in_specs=[
            pl.BlockSpec((tile_m, cp), lambda i: (i, 0)),
            pl.BlockSpec((1, cp), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_m, cp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rp, cp), jnp.float32),
        interpret=interpret,
    )(w_p, s_p)
    return out[:r, :c]


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def quant_matmul_pallas(
    x_q: Array,
    w_q: Array,
    tile_m: int = _MIN_TILE_M,
    tile_n: int = _MIN_TILE_N,
    interpret: bool | None = None,
) -> Array:
    """int8 ``x_q [M, K] @ w_q [K, N] -> int32 [M, N]``, int32-accumulated.

    Bitwise equal to ``jax.lax.dot_general`` over the same int8 operands
    with ``preferred_element_type=jnp.int32`` (integer arithmetic — no
    rounding anywhere to drift).
    """
    if x_q.dtype != jnp.int8 or w_q.dtype != jnp.int8:
        raise TypeError(
            f"quant_matmul_pallas wants int8 operands, got "
            f"{x_q.dtype}/{w_q.dtype}"
        )
    return _quant_matmul(x_q, w_q, tile_m, tile_n, _resolve_interpret(interpret))


def dequantize_pallas(
    w_q: Array,
    scale: Array,
    tile_m: int = _MIN_TILE_M,
    interpret: bool | None = None,
) -> Array:
    """Per-channel dequantize: ``w_q.astype(f32) * scale`` with ``scale``
    broadcast over the last axis. Arbitrary-rank weights are flattened to
    ``[prod(leading), channels]`` for the kernel and reshaped back."""
    shape = w_q.shape
    w2 = w_q.reshape((-1, shape[-1]))
    out = _dequantize(w2, scale, tile_m, _resolve_interpret(interpret))
    return out.reshape(shape)
