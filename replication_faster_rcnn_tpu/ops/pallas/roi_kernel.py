"""Pallas ROIAlign forward — bilinear sampling fused in VMEM.

One grid step per roi: the kernel builds the separable tent-weight matrices
(`ops/roi_ops.py::_tent_weights` semantics — torchvision aligned=False,
points outside [-1, extent] contribute zero, in-range points clamp to the
border tap) and contracts them against the VMEM-resident feature map on the
MXU, then bin-averages — the einsum formulation of `roi_ops.roi_align` with
the sampling, both contractions, and the pooling mean fused into one kernel
so no [R, P, W, C] intermediate ever touches HBM.

The forward is tolerance-gated against the gather oracle (not bit-identical:
contraction order differs from the XLA einsum schedule; tier-1 pins
atol=2e-5 / rtol=1e-5 in float32 — tests/test_pallas_roi.py). The backward
is a custom_vjp that replays the einsum formulation under `jax.vjp`, so
gradients are exactly the well-tested XLA path — Pallas only owns the
inference/forward hot loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jnp.ndarray


def _tent_rows(coords: Array, extent: int) -> Array:
    """coords [P] -> [P, extent] bilinear tent weights (border rule of
    `roi_ops._tent_weights`)."""
    p = coords.shape[0]
    in_range = (coords >= -1.0) & (coords <= extent)
    x = jnp.clip(coords, 0.0, extent - 1.0)
    grid = jax.lax.broadcasted_iota(jnp.float32, (p, extent), 1)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(x[:, None] - grid))
    return w * in_range[:, None]


def _roi_kernel(roi_ref, feat_ref, out_ref, *, out_size: int, s: int):
    h, w, c = feat_ref.shape
    p = out_size * s
    r1 = roi_ref[0, 0]
    c1 = roi_ref[0, 1]
    r2 = roi_ref[0, 2]
    c2 = roi_ref[0, 3]
    # aligned=False semantics: roi extent clamps to a 1px minimum
    bin_h = jnp.maximum(r2 - r1, 1.0) / out_size
    bin_w = jnp.maximum(c2 - c1, 1.0) / out_size
    pts = (jax.lax.broadcasted_iota(jnp.float32, (p, 1), 0)[:, 0] + 0.5) / s
    rr = r1 + pts * bin_h  # [P]
    cc = c1 + pts * bin_w

    wr = _tent_rows(rr, h)  # [P, H]
    wc = _tent_rows(cc, w)  # [P, W]
    feat = feat_ref[...].astype(jnp.float32)

    # sampled[p, q, ch] = sum_{i,j} wr[p, i] * feat[i, j, ch] * wc[q, j]
    rows = jnp.dot(
        wr, feat.reshape(h, w * c), preferred_element_type=jnp.float32
    ).reshape(p, w, c)
    sampled = jax.lax.dot_general(
        rows, wc, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [P, C, Q]
    sampled = sampled.transpose(0, 2, 1)  # [P, Q, C]
    pooled = sampled.reshape(out_size, s, out_size, s, c).mean(axis=(1, 3))
    out_ref[...] = pooled[None].astype(out_ref.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _roi_align_p(feat, rois, out_size, sampling_ratio, interpret):
    r = rois.shape[0]
    h, w, c = feat.shape
    return pl.pallas_call(
        partial(_roi_kernel, out_size=out_size, s=sampling_ratio),
        grid=(r,),
        in_specs=[
            pl.BlockSpec((1, 4), lambda i: (i, 0)),
            pl.BlockSpec((h, w, c), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, out_size, out_size, c), lambda i: (i, 0, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct(
            (r, out_size, out_size, c), feat.dtype
        ),
        interpret=interpret,
    )(rois.astype(jnp.float32), feat)


def _roi_align_p_fwd(feat, rois, out_size, sampling_ratio, interpret):
    return _roi_align_p(feat, rois, out_size, sampling_ratio, interpret), (
        feat,
        rois,
    )


def _roi_align_p_bwd(out_size, sampling_ratio, interpret, res, g):
    # backward = the einsum formulation's VJP: exactly the XLA path the
    # rest of training uses, so gradients carry no kernel-specific risk
    from replication_faster_rcnn_tpu.ops import roi_ops

    feat, rois = res
    _, vjp = jax.vjp(
        lambda f, r: roi_ops.roi_align(
            f, r, out_size, sampling_ratio, 1.0, method="einsum"
        ),
        feat,
        rois,
    )
    return vjp(g)


_roi_align_p.defvjp(_roi_align_p_fwd, _roi_align_p_bwd)


@partial(
    jax.jit, static_argnames=("out_size", "sampling_ratio", "interpret")
)
def _roi_align_pallas(feat, rois, out_size, sampling_ratio, spatial_scale, interpret):
    rois = rois * spatial_scale
    return _roi_align_p(feat, rois, out_size, sampling_ratio, interpret)


def roi_align_pallas(
    feat: Array,
    rois: Array,
    out_size: int = 7,
    sampling_ratio: int = 2,
    spatial_scale: float = 1.0,
    interpret: bool | None = None,
) -> Array:
    """Drop-in replacement for :func:`ops.roi_ops.roi_align`:
    feat [H, W, C], rois [R, 4] -> [R, out, out, C]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _roi_align_pallas(
        feat, rois, out_size, sampling_ratio, spatial_scale, bool(interpret)
    )
