"""Pallas tiled exact greedy NMS — bit-identical to `ops/nms_tiled.py`.

Same algorithm, same recurrence, same arithmetic: candidates are processed
in descending-score order one TILE per sequential grid step; within a tile
the greedy keep vector is solved by fixpoint sweeps of
``g = m0 & ~any(suppress & g[:, None], axis=0)``; selected boxes accumulate
into a compact ``[4, max_out]`` VMEM buffer that suppresses later tiles in
one matrix op. The in-kernel IoU replicates `ops/boxes.py::iou` op-for-op
(maximum/minimum/subtract/multiply/where/divide in the same order), so every
comparison against ``iou_thresh`` sees bitwise the same float as the XLA
tiling and the selections are exactly identical — tier-1 pins this
(tests/test_pallas_nms.py).

The grid is static (``n_tiles`` steps) where the XLA tiling uses a
while_loop that exits once the buffer fills; a ``count < max_out`` predicate
skips the per-tile work instead, which appends nothing either way, so
results match exactly.

Interpret mode (the default off-TPU) runs the kernel as a pure JAX
interpretation on any backend; on-chip lowering is reserved for the warmup
ProgramSpec registry (see package docstring).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray

_NEG = -jnp.inf


def _install_barrier_batching_rule() -> None:
    """Backport the (identity) vmap rule for ``optimization_barrier``.

    jax 0.4.37 has no batching rule for the primitive, so the producer
    barriers in these wrappers would break `jax.vmap` over the kernels —
    the batched `targets/anchor_targets.py` path. The barrier is
    elementwise identity, so the rule is trivial: bind on the batched
    operands, keep the dims. Newer jax registers exactly this upstream;
    installing is a no-op there.
    """
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # pragma: no cover - future jax moves the internals
        return
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _rule(args, dims):
        return optimization_barrier_p.bind(*args), list(dims)

    batching.primitive_batchers[optimization_barrier_p] = _rule


_install_barrier_batching_rule()


def _iou_cols(a: Array, b: Array, zero: Array) -> Array:
    """`ops/boxes.py::iou` on column-major boxes: a [4, Na], b [4, Nb] ->
    [Na, Nb]. The elementwise op sequence is identical to the row-major
    original, so results are bitwise equal — with one subtlety: ``zero``
    is a RUNTIME +0.0 scalar added to each product. The interpreter
    inlines the kernel jaxpr into the caller's XLA module, where LLVM
    codegen FMA-contracts a product into a following add/subtract in some
    fusion contexts (a 1-ulp drift off strict IEEE; HLO-level bitcast
    roundtrips are optimized away before codegen, so they can't pin it).
    Routing each product through ``+ zero`` is bit-exact on every codegen
    path: left alone it adds +0.0 (identity on the areas/intersection,
    which are never -0.0 here), and if contracted it becomes
    ``fma(x, y, 0)`` = ``round(x*y)`` — the strict product — while the
    remaining add/subtract chain has no multiply left to contract.

    Together with the producer `optimization_barrier` in the wrappers
    (which keeps pad/transpose producers from fusing into the kernel loop
    and re-triggering the contraction on the division), this makes the
    kernels strict-IEEE in every context tested — including ones where
    XLA:CPU's own compilation of `ops/boxes.py::iou` drifts 1 ulp from
    strict under heavy producer fusion (tests pin the kernels against a
    strict numpy oracle as well as the XLA reference)."""
    tl_r = jnp.maximum(a[0][:, None], b[0][None, :])
    tl_c = jnp.maximum(a[1][:, None], b[1][None, :])
    br_r = jnp.minimum(a[2][:, None], b[2][None, :])
    br_c = jnp.minimum(a[3][:, None], b[3][None, :])
    wh_r = br_r - tl_r
    wh_c = br_c - tl_c
    valid = (wh_r > 0) & (wh_c > 0)
    inter = jnp.where(valid, wh_r * wh_c, 0.0) + zero
    area_a = (a[2] - a[0]) * (a[3] - a[1]) + zero
    area_b = (b[2] - b[0]) * (b[3] - b[1]) + zero
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def _nms_kernel(
    thresh_ref,
    zero_ref,
    coords_ref,
    scores_ref,
    order_ref,
    idx_ref,
    valid_ref,
    selbox_ref,
    count_ref,
    *,
    tile: int,
    max_out: int,
):
    i = pl.program_id(0)
    n_tiles = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        count_ref[0] = 0
        idx_ref[...] = jnp.zeros_like(idx_ref)
        valid_ref[...] = jnp.zeros_like(valid_ref)
        selbox_ref[...] = jnp.zeros_like(selbox_ref)

    count = count_ref[0]

    @pl.when(count < max_out)
    def _tile_step():
        thresh = thresh_ref[0, 0]
        zero = zero_ref[0, 0]
        tb = coords_ref[...]  # [4, tile] column-major boxes
        ts = scores_ref[0, :]  # [tile]
        ti = order_ref[0, :]  # [tile] original indices
        tv = ts > _NEG
        sel = selbox_ref[...]  # [4, max_out]

        # cross-tile: suppressed by any already-selected box (one matrix op)
        cross = _iou_cols(sel, tb, zero) > thresh  # [max_out, tile]
        kmask = jax.lax.broadcasted_iota(jnp.int32, (max_out, tile), 0) < count
        m0 = tv & ~jnp.any(cross & kmask, axis=0)

        # in-tile greedy via fixpoint sweeps (exact; see nms_tiled docstring)
        later = (
            jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 0)
            < jax.lax.broadcasted_iota(jnp.int32, (tile, tile), 1)
        )  # a before b
        suppress = (_iou_cols(tb, tb, zero) > thresh) & later

        def sweep_cond(gs):
            _, stable = gs
            return ~stable

        def sweep_body(gs):
            g, _ = gs
            g2 = m0 & ~jnp.any(suppress & g[:, None], axis=0)
            return g2, jnp.all(g2 == g)

        g, _ = jax.lax.while_loop(
            sweep_cond, sweep_body, (m0, jnp.array(False, dtype=bool))
        )

        # append this tile's selections in order; the scatter of the XLA
        # tiling (`at[slot].set(mode="drop")`) becomes a one-hot
        # gather-free write: each output slot takes at most one candidate
        pos = count + jnp.cumsum(g) - 1  # [tile] target slot per kept box
        slots = jax.lax.broadcasted_iota(jnp.int32, (max_out, tile), 0)
        onehot = g[None, :] & (slots == pos[None, :]) & (pos[None, :] < max_out)
        taken = jnp.any(onehot, axis=1)  # [max_out]
        new_box = jnp.sum(jnp.where(onehot[None, :, :], tb[:, None, :], 0.0), axis=2)
        new_idx = jnp.sum(jnp.where(onehot, ti[None, :], 0), axis=1)
        selbox_ref[...] = jnp.where(taken[None, :], new_box, sel)
        idx_ref[0, :] = jnp.where(taken, new_idx, idx_ref[0, :]).astype(jnp.int32)
        count_ref[0] = jnp.minimum(count + jnp.sum(g), max_out).astype(jnp.int32)

    @pl.when(i == n_tiles - 1)
    def _finalize():
        final = count_ref[0]
        valid_ref[...] = (
            jax.lax.broadcasted_iota(jnp.int32, (1, max_out), 1) < final
        ).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=("max_out", "tile", "assume_sorted", "interpret"),
)
def _nms_fixed_pallas(
    boxes: Array,
    scores: Array,
    iou_thresh: Array,
    max_out: int,
    mask: Array | None,
    tile: int,
    assume_sorted: bool,
    interpret: bool,
) -> tuple[Array, Array]:
    # ---- prep: identical to nms_fixed_tiled ----
    n = boxes.shape[0]
    tile = min(tile, max(n, 1))
    s = scores.astype(jnp.float32)
    s = jnp.where(jnp.isfinite(s), s, _NEG)
    if mask is not None:
        s = jnp.where(mask, s, _NEG)

    n_tiles = -(-n // tile)
    n_pad = n_tiles * tile
    pad = n_pad - n
    if assume_sorted:
        order_p = jnp.pad(jnp.arange(n, dtype=jnp.int32), (0, pad))
        s_sorted = jnp.pad(s, (0, pad), constant_values=_NEG)
        b_sorted = jnp.pad(boxes.astype(jnp.float32), ((0, pad), (0, 0)))
    else:
        order = jnp.argsort(-s)
        order_p = jnp.pad(order, (0, pad)).astype(jnp.int32)
        s_sorted = jnp.pad(s[order], (0, pad), constant_values=_NEG)
        b_sorted = jnp.pad(boxes.astype(jnp.float32)[order], ((0, pad), (0, 0)))

    thresh = jnp.full((1, 1), iou_thresh, jnp.float32)
    zero = jnp.zeros((1, 1), jnp.float32)  # runtime +0.0, see _iou_cols
    coords = b_sorted.T  # [4, n_pad] — lane-major for the kernel
    s_row = s_sorted[None, :]
    o_row = order_p[None, :]
    # producer barrier: keep the sort/pad/transpose prep from fusing into
    # the inlined kernel body on CPU, where it perturbs LLVM vectorization
    # of the IoU arithmetic (see _iou_cols docstring)
    thresh, zero, coords, s_row, o_row = jax.lax.optimization_barrier(
        (thresh, zero, coords, s_row, o_row)
    )

    idx_row, valid_row = pl.pallas_call(
        partial(_nms_kernel, tile=tile, max_out=max_out),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((4, tile), lambda i: (0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, max_out), lambda i: (0, 0)),
            pl.BlockSpec((1, max_out), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, max_out), jnp.int32),
            jax.ShapeDtypeStruct((1, max_out), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((4, max_out), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
        ],
        interpret=interpret,
    )(thresh, zero, coords, s_row, o_row)

    valid = valid_row[0].astype(bool)
    return jnp.where(valid, idx_row[0], 0), valid


def nms_fixed_pallas(
    boxes: Array,
    scores: Array,
    iou_thresh: float,
    max_out: int,
    mask: Array | None = None,
    tile: int = 512,
    assume_sorted: bool = False,
    interpret: bool | None = None,
) -> tuple[Array, Array]:
    """Drop-in replacement for :func:`ops.nms_tiled.nms_fixed_tiled`
    (same contract, bit-identical selections).

    ``interpret=None`` resolves to interpret mode unless the default JAX
    backend is a real TPU — the CPU tier-1 path always interprets.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _nms_fixed_pallas(
        boxes,
        scores,
        jnp.asarray(iou_thresh, jnp.float32),
        max_out,
        mask,
        tile,
        assume_sorted,
        bool(interpret),
    )
