"""Pallas dense IoU matrix + anchor matching — exact vs the jnp pass.

Target assignment (`targets/anchor_targets.py`, `targets/proposal_targets.py`)
opens with the same shape of work: a dense ``[N, G]`` IoU matrix against the
(padded) gt boxes, masked to -1 on padded gt columns, then row argmax/max and
— for the RPN pass — the per-gt best anchor (column argmax). For 16k+ anchors
that matrix is the dominant cost of the pass and XLA materializes it through
HBM; here it is tiled over the anchor axis with the matching reductions fused
in VMEM, one grid step per anchor tile.

Exactness: the in-kernel IoU replicates `ops/boxes.py::iou` op-for-op
(elementwise IEEE arithmetic — bitwise equal), row argmax/max use the same
``jnp.argmax`` / ``jnp.max(jnp.maximum(x, 0.0))`` ops on the same values, and
the column argmax streams across tiles with a strictly-greater update, which
reproduces ``jnp.argmax(axis=0)`` first-occurrence tie-breaking exactly
(padded anchor rows are forced to -1 and sit after all real rows, so they can
tie but never win). Tier-1 pins all four outputs bitwise
(tests/test_pallas_iou.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from replication_faster_rcnn_tpu.ops.pallas.nms_kernel import _iou_cols

Array = jnp.ndarray


def _match_kernel(
    z_ref,
    a_ref,
    g_ref,
    m_ref,
    iou_ref,
    am_ref,
    mx_ref,
    best_ref,
    bval_ref,
    *,
    tile: int,
    n_rows: int,
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        best_ref[...] = jnp.zeros_like(best_ref)
        bval_ref[...] = jnp.full_like(bval_ref, -jnp.inf)

    g_count = g_ref.shape[1]
    ious = _iou_cols(a_ref[...], g_ref[...], z_ref[0, 0])  # [tile, G]
    ious = jnp.where(m_ref[0, :][None, :] != 0, ious, -1.0)  # padded gt cols
    # padded anchor rows (beyond n_rows) must never win the column argmax;
    # they sit after every real row, so forcing -1 lets them tie but not beat
    row_ok = (
        jax.lax.broadcasted_iota(jnp.int32, (tile, g_count), 0) + i * tile
    ) < n_rows
    ious = jnp.where(row_ok, ious, -1.0)

    iou_ref[...] = ious
    am_ref[0, :] = jnp.argmax(ious, axis=1).astype(jnp.int32)
    mx_ref[0, :] = jnp.max(jnp.maximum(ious, 0.0), axis=1)

    # streaming column argmax: strictly-greater keeps the earliest row on
    # ties, matching jnp.argmax(axis=0) first-occurrence semantics
    col_max = jnp.max(ious, axis=0)  # [G]
    col_arg = jnp.argmax(ious, axis=0).astype(jnp.int32) + i * tile
    prev = bval_ref[0, :]
    beat = col_max > prev
    bval_ref[0, :] = jnp.where(beat, col_max, prev)
    best_ref[0, :] = jnp.where(beat, col_arg, best_ref[0, :])


@partial(jax.jit, static_argnames=("tile", "interpret", "want_col"))
def _match_boxes_pallas(
    boxes: Array,
    gt_boxes: Array,
    gt_mask: Array,
    tile: int,
    interpret: bool,
    want_col: bool,
):
    n = boxes.shape[0]
    g = gt_boxes.shape[0]
    tile = min(tile, max(n, 1))
    n_tiles = -(-n // tile)
    pad = n_tiles * tile - n

    coords = jnp.pad(boxes.astype(jnp.float32), ((0, pad), (0, 0))).T  # [4, n_pad]
    gt_cols = gt_boxes.astype(jnp.float32).T  # [4, G]
    mask_row = gt_mask.astype(jnp.int32)[None, :]  # [1, G]

    zero = jnp.zeros((1, 1), jnp.float32)  # runtime +0.0, see _iou_cols
    # keep the pad/transpose producers out of the kernel body's fusion: on
    # XLA:CPU, fusing them in changes LLVM vectorization of the inlined
    # (interpret-mode) kernel and can drift the final division by 1 ulp
    zero, coords, gt_cols, mask_row = jax.lax.optimization_barrier(
        (zero, coords, gt_cols, mask_row)
    )
    ious_p, am_p, mx_p, best_p = pl.pallas_call(
        partial(_match_kernel, tile=tile, n_rows=n),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((4, tile), lambda i: (0, i)),
            pl.BlockSpec((4, g), lambda i: (0, 0)),
            pl.BlockSpec((1, g), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tile, g), lambda i: (i, 0)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((1, tile), lambda i: (0, i)),
            pl.BlockSpec((1, g), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles * tile, g), jnp.float32),
            jax.ShapeDtypeStruct((1, n_tiles * tile), jnp.int32),
            jax.ShapeDtypeStruct((1, n_tiles * tile), jnp.float32),
            jax.ShapeDtypeStruct((1, g), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((1, g), jnp.float32)],
        interpret=interpret,
    )(zero, coords, gt_cols, mask_row)

    out = (ious_p[:n], am_p[0, :n], mx_p[0, :n])
    if want_col:
        return out + (best_p[0],)
    return out


def _resolve_interpret(interpret: bool | None) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def match_boxes_pallas(
    boxes: Array,
    gt_boxes: Array,
    gt_mask: Array,
    tile: int = 512,
    interpret: bool | None = None,
) -> tuple[Array, Array, Array, Array]:
    """The RPN matching pass: boxes [N, 4], gt [G, 4], gt_mask [G] ->
    (ious [N, G] masked to -1 on padded gt, argmax [N] int32,
    max_iou [N] f32, gt_best [G] int32) — all bitwise equal to the jnp
    formulation in `targets/anchor_targets.py`."""
    return _match_boxes_pallas(
        boxes, gt_boxes, gt_mask, tile, _resolve_interpret(interpret), True
    )


def iou_matrix_pallas(
    boxes: Array,
    gt_boxes: Array,
    gt_mask: Array,
    tile: int = 512,
    interpret: bool | None = None,
) -> tuple[Array, Array, Array]:
    """The head-assignment variant (no column argmax): returns
    (ious [N, G], argmax [N], max_iou [N]) as in
    `targets/proposal_targets.py`."""
    return _match_boxes_pallas(
        boxes, gt_boxes, gt_mask, tile, _resolve_interpret(interpret), False
    )
