"""Pallas TPU kernels for the detection hot ops (ROADMAP open item 1).

This package is the `ops.backend = "pallas"` half of the dispatch seam in
`ops/__init__.py`. Three kernels cover the ops the reference delegated to
torchvision C++ and that pure-XLA tilings fuse worst:

  * :func:`nms_fixed_pallas` — tiled exact greedy NMS, same tile/fixpoint
    recurrence as `ops/nms_tiled.py::nms_fixed_tiled`; selections are
    bit-identical (the in-kernel IoU replicates `ops/boxes.py::iou`
    op-for-op, all elementwise IEEE arithmetic).
  * :func:`roi_align_pallas` — multilevel ROIAlign forward with the
    bilinear tent-weight sampling fused in VMEM (the separable-matmul
    formulation of `ops/roi_ops.py` method="einsum" on the MXU), wrapped
    in a custom_vjp whose backward falls back to the einsum formulation.
  * :func:`match_boxes_pallas` / :func:`iou_matrix_pallas` — the dense
    IoU matrix + row/column argmax matching pass used by RPN and head
    target assignment, tiled over the anchor axis.

Every kernel takes ``interpret`` (default: interpret unless running on a
real TPU backend) so the exact same kernel code is parity-tested on CPU
in tier-1 — the round-5 Pallas NMS removal (git 431e219) was driven by
the old kernel having no CPU validation path. On-chip (non-interpret)
compilation must only happen through the warmup ProgramSpec registry
(`train/warmup.py::build_pallas_program_specs`), never lazily inside a
train step — the other half of the 431e219 failure mode.
"""

from replication_faster_rcnn_tpu.ops.pallas.iou_kernel import (  # noqa: F401
    iou_matrix_pallas,
    match_boxes_pallas,
)
from replication_faster_rcnn_tpu.ops.pallas.nms_kernel import (  # noqa: F401
    nms_fixed_pallas,
)
from replication_faster_rcnn_tpu.ops.pallas.quant_kernel import (  # noqa: F401
    dequantize_pallas,
    quant_matmul_pallas,
)
from replication_faster_rcnn_tpu.ops.pallas.roi_kernel import (  # noqa: F401
    roi_align_pallas,
)
