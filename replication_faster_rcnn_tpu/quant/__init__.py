"""Post-training int8 quantization (PTQ) for serving — ISSUE 17.

The subsystem has four pieces, mirroring the calibrate -> sweep -> serve
workflow (`frcnn quantize`, then `frcnn serve --params-dtype int8`):

* `calibrate.py` — per-channel symmetric int8 weight scales (numpy, so
  the artifact is bit-identical across runs and thread counts) plus
  activation ranges captured from a small calibration sweep through the
  model's inference forward.
* `sensitivity.py` — the arXiv:1806.00370 per-layer sweep: quantize one
  layer group at a time (fake-quant), measure response-reconstruction
  error and optionally the mAP delta on a mini eval set, and emit a
  per-group dtype plan (int8 vs bf16 fallback).
* `artifact.py` — the sidecar serialization next to the checkpoint:
  JSON with per-entry CRC32s and an atomic tmp+rename write, the PR 3
  checkpoint-manifest discipline applied to quantization state.
* `apply.py` — turning (f32 variables + artifact) into the quantized
  resident tree the serving engine uploads, and the in-program
  reconstruction the `serve_*__int8` programs run through the
  `ops/quant_ops.py` backend seam.
"""

from replication_faster_rcnn_tpu.quant.artifact import (  # noqa: F401
    ARTIFACT_SCHEMA,
    QuantArtifactError,
    default_artifact_path,
    load_artifact,
    save_artifact,
)
from replication_faster_rcnn_tpu.quant.calibrate import (  # noqa: F401
    EMBED_RANGE_KEY,
    QUANT_DENSE_PATHS,
    calibrate,
    dataset_calibration_batches,
    layer_group_of,
    quantizable,
    synthetic_calibration_batches,
    weight_scales,
)
from replication_faster_rcnn_tpu.quant.apply import (  # noqa: F401
    abstract_quantize_variables,
    build_infer_variables,
    fake_quant_variables,
    quantize_variables,
    quantized_params_bytes,
    round_trip_errors,
    synthetic_artifact,
)
from replication_faster_rcnn_tpu.quant.sensitivity import (  # noqa: F401
    response_reconstruction_error,
    sweep,
)
