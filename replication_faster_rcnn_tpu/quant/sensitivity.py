"""Per-layer-group sensitivity sweep -> int8/bf16 dtype plan.

The arXiv:1806.00370 observation: per-layer response-reconstruction
error under compression predicts which layers a network tolerates
compressing, so bits should be allocated per layer instead of
uniformly. Applied to PTQ: quantize ONE layer group at a time
(fake-quant round trip, `apply.py::fake_quant_variables`), run the
inference forward over the calibration batches, and measure the
relative L2 error of the detection responses (cls logits + box deltas)
against the f32 forward. Optionally an ``eval_fn`` measures the mAP
delta on a mini eval set per group.

A group falls back to bf16 when either signal crosses its configured
threshold (`quant.sensitivity_recon_rel_err`,
`quant.sensitivity_map_drop_pt`) — the "demonstrably falls back on
quality grounds" contract pinned by the injected-hostile-layer test in
tier-1.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np

Array = Any


def _responses(model, variables, images) -> np.ndarray:
    """The detection responses (cls logits ++ reg deltas), flattened f32."""
    import jax.numpy as jnp

    outputs = model.apply(variables, jnp.asarray(images), train=False)
    _, _, _, _, cls, reg, _ = outputs
    return np.concatenate(
        [
            np.asarray(cls, dtype=np.float32).ravel(),
            np.asarray(reg, dtype=np.float32).ravel(),
        ]
    )


def response_reconstruction_error(
    model, variables, fq_variables, batches: Sequence[Any]
) -> float:
    """Relative L2 of quantized vs f32 detection responses over batches."""
    num = 0.0
    den = 0.0
    for images in batches:
        ref = _responses(model, variables, images)
        got = _responses(model, fq_variables, images)
        num += float(np.sum((got - ref) ** 2))
        den += float(np.sum(ref**2))
    return float(np.sqrt(num) / (np.sqrt(den) + 1e-12))


def sweep(
    model,
    variables,
    artifact: Dict[str, Any],
    batches: Sequence[Any],
    config,
    eval_fn: Optional[Callable[[Any], float]] = None,
) -> Dict[str, Any]:
    """Quantize one group at a time; emit the per-group dtype plan.

    ``eval_fn(variables) -> mAP`` runs the mini eval set (None skips the
    mAP signal — recon error alone then drives the plan). Mutates and
    returns the artifact with ``plan`` and ``sensitivity`` filled in.
    """
    from replication_faster_rcnn_tpu.quant.apply import fake_quant_variables

    recon_budget = config.quant.sensitivity_recon_rel_err
    map_budget = config.quant.sensitivity_map_drop_pt
    base_map = eval_fn(variables) if eval_fn is not None else None

    plan: Dict[str, str] = {}
    sensitivity: Dict[str, Dict[str, Any]] = {}
    for group, paths in sorted(artifact["groups"].items()):
        fq = fake_quant_variables(variables, artifact["weight_scales"], paths)
        recon = response_reconstruction_error(model, variables, fq, batches)
        record: Dict[str, Any] = {"recon_rel_err": recon}
        drop_pt = None
        if base_map is not None:
            group_map = eval_fn(fq)
            drop_pt = (base_map - group_map) * 100.0
            record["map_drop_pt"] = drop_pt
            record["map"] = group_map
        hostile = recon > recon_budget or (
            drop_pt is not None and drop_pt > map_budget
        )
        plan[group] = "bfloat16" if hostile else "int8"
        record["dtype"] = plan[group]
        sensitivity[group] = record

    artifact["plan"] = plan
    artifact["sensitivity"] = sensitivity
    if base_map is not None:
        artifact["sensitivity"]["__baseline__"] = {"map": base_map}
    return artifact
