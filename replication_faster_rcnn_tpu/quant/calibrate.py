"""PTQ calibration: weight scales + activation ranges.

Weight scales are per-channel symmetric over the last (output-channel)
axis — ``scale = max|w| / 127`` — computed in **numpy** over the
checkpoint leaves. Every reduction in this file is an abs-max, which is
exactly associative and commutative in IEEE arithmetic, so the scales
(and therefore the artifact bytes) are bit-identical no matter how the
work is chunked or threaded; tests/test_quant.py pins that across runs
and across a thread-pool split.

Activation ranges come from a small calibration sweep: the full
inference forward over a handful of batches with
``capture_intermediates`` filtered to the module whose output feeds the
detection-head cls/reg GEMMs (the ResNet/VGG ``tail``, or ``fc6``/
``fc7`` for the FPN two-fc head). Those ranges become the static
``x_scale`` of `ops/quant_ops.py::quant_dense` — the true-int8 GEMMs in
the serve program.

Layer groups follow the ISSUE 17 / arXiv:1806.00370 granularity:
backbone conv blocks (``trunk.stem``, ``trunk.layer1`` ...), FPN
laterals (``neck``), RPN head (``rpn``), detection head (``head``) —
each independently quantizable so the sensitivity sweep can fall a
single group back to bf16.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

INT8_MAX = 127.0
SCALE_EPS = 1e-12

# param paths (under "params") routed through QuantDense / quant_dense —
# true int8 GEMMs with activation quantization, not weight-only dequant
QUANT_DENSE_PATHS = (
    ("head", "cls", "kernel"),
    ("head", "reg", "kernel"),
)

# activation_ranges key for the cls/reg input (the head embedding)
EMBED_RANGE_KEY = "head.embed"


def layer_group_of(path: Tuple[str, ...]) -> str:
    """Map a param path (under the "params" collection) to its layer group.

    ("trunk", "layer2.1", "conv1", "kernel") -> "trunk.layer2"
    ("trunk", "conv1", "kernel")             -> "trunk.stem"
    ("neck", ...) / ("rpn", ...) / ("head", ...) -> that subsystem.
    """
    top = path[0]
    if top == "trunk":
        if len(path) < 3:
            return "trunk.stem"
        block = path[1].split(".")[0]
        return f"trunk.{block}" if block.startswith("layer") else "trunk.stem"
    return top


def quantizable(path: Tuple[str, ...], leaf: Any) -> bool:
    """int8-eligible leaves: float weight tensors of rank >= 2 (conv and
    dense kernels). Biases and norm scales/offsets stay in bf16."""
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return getattr(leaf, "ndim", 0) >= 2 and dtype.kind == "f"


def flatten_params(params: Dict[str, Any]) -> List[Tuple[Tuple[str, ...], Any]]:
    """Deterministic (sorted) flattening of a nested params dict."""
    out: List[Tuple[Tuple[str, ...], Any]] = []

    def walk(prefix: Tuple[str, ...], node: Any) -> None:
        if isinstance(node, dict):
            for k in sorted(node):
                walk(prefix + (str(k),), node[k])
        else:
            out.append((prefix, node))

    walk((), params)
    return out


def path_key(path: Sequence[str]) -> str:
    return "/".join(path)


def channel_scale(w: np.ndarray) -> np.ndarray:
    """Per-output-channel symmetric scale: ``max|w| / 127`` over all but
    the last axis (order-invariant — abs-max is exactly associative)."""
    w = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)))
    return (np.maximum(amax, SCALE_EPS) / INT8_MAX).astype(np.float32)


def weight_scales(params: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """All quantizable leaves' per-channel scales, keyed by param path."""
    scales: Dict[str, np.ndarray] = {}
    for path, leaf in flatten_params(params):
        if quantizable(path, leaf):
            scales[path_key(path)] = channel_scale(np.asarray(leaf))
    return scales


def quantize_weight(w: np.ndarray, scale: np.ndarray) -> np.ndarray:
    """Symmetric round-to-nearest int8 against a per-channel scale."""
    w = np.asarray(w, dtype=np.float32)
    q = np.rint(w / scale.astype(np.float32))
    return np.clip(q, -INT8_MAX, INT8_MAX).astype(np.int8)


def _embed_capture_filter(mdl, method_name: str) -> bool:
    return method_name == "__call__" and mdl.name in ("tail", "fc6", "fc7")


def _leaf_arrays(tree: Any) -> List[np.ndarray]:
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def activation_ranges(model, variables, batches: Sequence[Any]) -> Dict[str, float]:
    """Run the calibration sweep, returning abs-max activation ranges.

    ``batches`` is a sequence of image arrays exactly as the engine feeds
    them (NHWC, preprocessed upstream of the model's own normalize).
    Captures the output of the module feeding the cls/reg GEMMs: the
    ``tail`` for single-scale heads, ``fc7`` for the FPN two-fc head
    (whose relu the head applies before cls/reg — folded in here).
    """
    import jax.numpy as jnp

    amax = 0.0
    for images in batches:
        _, inter = model.apply(
            variables,
            jnp.asarray(images),
            train=False,
            capture_intermediates=_embed_capture_filter,
        )
        tree = inter.get("intermediates", inter).get("head", {})
        # prefer fc7 (FPN) over tail: fc7's relu-ed output is the GEMM input
        feeder = tree.get("fc7") or tree.get("tail")
        if feeder is None:
            raise ValueError(
                "calibration captured no head tail/fc7 intermediates; "
                f"got keys {sorted(tree)}"
            )
        for arr in _leaf_arrays(feeder):
            a = np.asarray(arr, dtype=np.float32)
            if "fc7" in tree and tree.get("fc7") is feeder:
                a = np.maximum(a, 0.0)  # head applies relu before cls/reg
            batch_max = float(np.max(np.abs(a)))
            amax = max(amax, batch_max)
    return {EMBED_RANGE_KEY: amax}


def embed_scale(ranges: Dict[str, float]) -> float:
    """The quant_dense x_scale derived from the calibrated embed range."""
    return max(ranges[EMBED_RANGE_KEY], SCALE_EPS) / INT8_MAX


def group_paths(params: Dict[str, Any]) -> Dict[str, List[str]]:
    """group name -> sorted quantizable param paths in that group."""
    groups: Dict[str, List[str]] = {}
    for path, leaf in flatten_params(params):
        if quantizable(path, leaf):
            groups.setdefault(layer_group_of(path), []).append(path_key(path))
    return {g: sorted(ps) for g, ps in sorted(groups.items())}


def synthetic_calibration_batches(
    config, batches: int, batch_size: int, seed: int = 0
) -> List[np.ndarray]:
    """Deterministic synthetic calibration images (uniform [0, 255) f32,
    the scale the data pipeline's normalize expects) for environments
    without a dataset on disk — tests and the CPU bench host."""
    h, w = config.data.image_size
    rng = np.random.RandomState(seed)
    return [
        rng.uniform(0.0, 255.0, size=(batch_size, h, w, 3)).astype(np.float32)
        for _ in range(batches)
    ]


def dataset_calibration_batches(
    dataset, batches: int, batch_size: int
) -> List[np.ndarray]:
    """Calibration batches drawn from a map-style dataset's normalized
    ``"image"`` samples, in index order (deterministic — wrap-around when
    the dataset is smaller than the sweep)."""
    n = len(dataset)
    out = []
    idx = 0
    for _ in range(batches):
        imgs = [
            np.asarray(dataset[(idx + j) % n]["image"], dtype=np.float32)
            for j in range(batch_size)
        ]
        idx += batch_size
        out.append(np.stack(imgs))
    return out


def calibrate(
    model,
    variables,
    batches: Sequence[Any],
    config=None,
) -> Dict[str, Any]:
    """The full calibration pass -> an (unplanned) artifact dict.

    Weight scales for every quantizable leaf, activation ranges from the
    sweep, layer-group membership, and an all-int8 default plan the
    sensitivity sweep may later demote per group.
    """
    params = variables["params"]
    scales = weight_scales(params)
    groups = group_paths(params)
    ranges = activation_ranges(model, variables, batches)
    plan = {g: "int8" for g in groups}
    return {
        "weight_scales": scales,
        "activation_ranges": ranges,
        "groups": groups,
        "plan": plan,
        "calib": {
            "batches": len(batches),
            "batch_size": int(np.asarray(batches[0]).shape[0]) if batches else 0,
        },
    }
