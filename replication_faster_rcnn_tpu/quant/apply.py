"""Quantized resident tree <-> in-program reconstruction.

`quantize_variables` is the engine-side (host, once at startup) half:
it turns f32 checkpoint variables + a calibration artifact into the
tree the ServingEngine uploads — planned weights as int8, their
per-channel scales alongside (device-resident, per the ISSUE 17
contract), everything else cast to the compute dtype. The detection
head's cls/reg kernels stay int8 *inside* the params tree and carry a
``"quant"`` collection entry (w_scale + calibrated x_scale) so
`models/head.py::QuantDense` runs them as true int8 GEMMs.

`build_infer_variables` is the in-program (traced, per dispatch) half:
every other int8 leaf is reconstructed on its way into the matmul/conv
through `ops/quant_ops.py::dequantize` — the op behind the
``ops.backend = xla|pallas`` seam, so the ``serve_*__int8`` and
``serve_*__int8__pallas`` twin programs differ exactly in that kernel.

`fake_quant_variables` is the sensitivity-sweep simulator: float
variables with one layer group's weights replaced by their
quantize->dequantize round trip, no serving machinery involved.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from replication_faster_rcnn_tpu.quant.calibrate import (
    EMBED_RANGE_KEY,
    QUANT_DENSE_PATHS,
    embed_scale,
    flatten_params,
    group_paths,
    layer_group_of,
    path_key,
    quantizable,
    quantize_weight,
)

_DENSE_KEYS = {path_key(p) for p in QUANT_DENSE_PATHS}


def _planned_int8(artifact: Dict[str, Any], path: Tuple[str, ...], leaf) -> bool:
    if not quantizable(path, leaf):
        return False
    if path_key(path) not in artifact["weight_scales"]:
        return False
    return artifact["plan"].get(layer_group_of(path), "bfloat16") == "int8"


def quantize_variables(
    variables: Dict[str, Any],
    artifact: Dict[str, Any],
    compute_dtype: Any = None,
) -> Dict[str, Any]:
    """Build the quantized resident tree from f32 variables + artifact.

    Returns ``{"params": ..., "qscales": {path: scale}, "quant": {...},
    <other collections cast to compute_dtype>}``. ``compute_dtype``
    defaults to bfloat16 — the fallback dtype of everything the plan
    does not keep int8.
    """
    import jax.numpy as jnp

    compute_dtype = compute_dtype or jnp.bfloat16
    qscales: Dict[str, Any] = {}
    dense_quant: Dict[str, Any] = {}
    x_scale = np.float32(embed_scale(artifact["activation_ranges"]))

    def walk(prefix: Tuple[str, ...], node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(prefix + (str(k),), v) for k, v in node.items()}
        key = path_key(prefix)
        if _planned_int8(artifact, prefix, node):
            scale = artifact["weight_scales"][key]
            w_q = quantize_weight(np.asarray(node), scale)
            if key in _DENSE_KEYS:
                # head/cls/kernel -> quant collection entry at scope
                # head/{cls,reg} consumed by QuantDense
                name = prefix[-2]
                dense_quant[name] = {
                    "w_scale": jnp.asarray(scale),
                    "x_scale": jnp.asarray(x_scale),
                }
            else:
                qscales[key] = jnp.asarray(scale)
            return jnp.asarray(w_q)
        if np.dtype(getattr(node, "dtype", np.float32)).kind == "f":
            return jnp.asarray(node, dtype=compute_dtype)
        return jnp.asarray(node)

    out: Dict[str, Any] = {}
    for collection, tree in variables.items():
        if collection == "params":
            out["params"] = walk((), tree)
        else:
            out[collection] = walk((collection, "!"), tree)
    out["qscales"] = qscales
    if dense_quant:
        out["quant"] = {"head": dense_quant}
    return out


def build_infer_variables(
    qvars: Dict[str, Any], config=None, compute_dtype: Any = None
) -> Dict[str, Any]:
    """In-program reconstruction: dequantize every int8 leaf except the
    QuantDense kernels, yielding the variables dict ``model.apply``
    consumes (including the pass-through ``"quant"`` collection).

    ``compute_dtype`` is the dtype the forward actually runs in —
    ``config.model.compute_dtype`` when a config is given (bfloat16
    otherwise). Residency and compute are deliberately decoupled: the
    resident tree stays int8 + bf16 (that's the memory claim), while
    the traced reconstruction both dequantizes the int8 leaves and
    upcasts the bf16 fallback leaves into the compute dtype. On
    XLA:CPU, whose bf16 conv/dot lowerings are several times slower
    than f32, serving a compute_dtype=float32 model any other way
    would burn the entire quantization win on slow bf16 math."""
    import jax.numpy as jnp

    from replication_faster_rcnn_tpu.ops import quant_ops

    if compute_dtype is None:
        compute_dtype = (
            jnp.dtype(config.model.compute_dtype)
            if config is not None
            else jnp.bfloat16
        )
    qscales = qvars.get("qscales", {})

    def walk(prefix: Tuple[str, ...], node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(prefix + (str(k),), v) for k, v in node.items()}
        key = path_key(prefix)
        if node.dtype == jnp.int8 and key not in _DENSE_KEYS:
            return quant_ops.dequantize(node, qscales[key], config).astype(
                compute_dtype
            )
        if jnp.issubdtype(node.dtype, jnp.floating):
            return node.astype(compute_dtype)
        return node

    out = {"params": walk((), qvars["params"])}
    for collection, tree in qvars.items():
        if collection in ("params", "qscales", "quant"):
            continue
        out[collection] = walk((collection, "!"), tree)
    out["quant"] = qvars.get("quant")
    if out["quant"] is None:
        del out["quant"]
    return out


def fake_quant_variables(
    variables: Dict[str, Any],
    scales: Dict[str, np.ndarray],
    paths: List[str],
) -> Dict[str, Any]:
    """Float variables with the given param paths' weights replaced by
    their int8 quantize->dequantize round trip (sensitivity sweep)."""
    import jax.numpy as jnp

    wanted = set(paths)

    def walk(prefix: Tuple[str, ...], node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(prefix + (str(k),), v) for k, v in node.items()}
        key = path_key(prefix)
        if key in wanted:
            scale = scales[key].astype(np.float32)
            w_q = quantize_weight(np.asarray(node), scale)
            return jnp.asarray(w_q.astype(np.float32) * scale)
        return node

    out = dict(variables)
    out["params"] = walk((), variables["params"])
    return out


def round_trip_errors(
    params: Dict[str, Any], scales: Dict[str, np.ndarray]
) -> Dict[str, float]:
    """Per-path max-abs quantize->dequantize error relative to the
    channel scale (<= 0.5 by construction of round-to-nearest; pinned
    in tier-1)."""
    errors: Dict[str, float] = {}
    for path, leaf in flatten_params(params):
        key = path_key(path)
        if key not in scales:
            continue
        w = np.asarray(leaf, dtype=np.float32)
        scale = scales[key].astype(np.float32)
        w_rt = quantize_weight(w, scale).astype(np.float32) * scale
        errors[key] = float(np.max(np.abs(w - w_rt) / scale))
    return errors


def synthetic_artifact(variables_abs: Dict[str, Any]) -> Dict[str, Any]:
    """A structure-only artifact (unit scales, all-int8 plan) for AOT
    lowering when no calibration ran — the audit/warmup registry builds
    the ``serve_*__int8`` programs' abstract inputs from it. Never used
    to serve real traffic (the engine demands a real sidecar)."""
    params = variables_abs["params"]
    scales = {
        path_key(path): np.full(
            (leaf.shape[-1],), 1.0 / 127.0, dtype=np.float32
        )
        for path, leaf in flatten_params(params)
        if quantizable(path, leaf)
    }
    groups = group_paths(params)
    return {
        "weight_scales": scales,
        "activation_ranges": {EMBED_RANGE_KEY: 127.0},
        "groups": groups,
        "plan": {g: "int8" for g in groups},
        "calib": {"batches": 0, "batch_size": 0, "synthetic": True},
    }


def abstract_quantize_variables(
    variables_abs: Dict[str, Any],
    artifact: Dict[str, Any],
    compute_dtype: Any = None,
) -> Dict[str, Any]:
    """`quantize_variables` over ``jax.ShapeDtypeStruct`` leaves: the
    abstract qvars tree the warmup registry lowers the int8 serving
    programs against (same structure, no values)."""
    import jax
    import jax.numpy as jnp

    compute_dtype = np.dtype(compute_dtype or jnp.bfloat16)
    qscales: Dict[str, Any] = {}
    dense_quant: Dict[str, Any] = {}

    def walk(prefix: Tuple[str, ...], node: Any) -> Any:
        if isinstance(node, dict):
            return {k: walk(prefix + (str(k),), v) for k, v in node.items()}
        key = path_key(prefix)
        if _planned_int8(artifact, prefix, node):
            out_ch = node.shape[-1]
            if key in _DENSE_KEYS:
                dense_quant[prefix[-2]] = {
                    "w_scale": jax.ShapeDtypeStruct((out_ch,), np.float32),
                    "x_scale": jax.ShapeDtypeStruct((), np.float32),
                }
            else:
                qscales[key] = jax.ShapeDtypeStruct((out_ch,), np.float32)
            return jax.ShapeDtypeStruct(node.shape, np.int8)
        if np.issubdtype(node.dtype, np.floating):
            return jax.ShapeDtypeStruct(node.shape, compute_dtype)
        return node

    out: Dict[str, Any] = {}
    for collection, tree in variables_abs.items():
        if collection == "params":
            out["params"] = walk((), tree)
        else:
            out[collection] = walk((collection, "!"), tree)
    out["qscales"] = qscales
    if dense_quant:
        out["quant"] = {"head": dense_quant}
    return out


def quantized_params_bytes(qvars: Dict[str, Any]) -> int:
    """Total bytes of the resident quantized tree (weights + scales)."""
    import jax

    return int(
        sum(x.nbytes for x in jax.tree_util.tree_leaves(qvars))
    )
