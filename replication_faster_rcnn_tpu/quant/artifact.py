"""Quantization sidecar artifact — CRC-manifested JSON next to the ckpt.

Same discipline as the PR 3 checkpoint manifest (`train/fault.py`):
every scale tensor is recorded with its crc32/shape/dtype, the file
carries a schema tag, and the write is atomic (tmp + ``os.replace``) so
a crash mid-write can never leave a half-artifact that `frcnn serve
--params-dtype int8` would trust.

The payload is pure JSON with scale bytes base64-encoded from their
float32 little-endian buffer: byte-exact round-trips, and — because
calibration itself is order-invariant (see `calibrate.py`) — the file
is bit-identical across runs and thread counts for the same checkpoint
and calibration batch order (``sort_keys`` + fixed separators).
"""

from __future__ import annotations

import base64
import json
import os
import tempfile
import zlib
from typing import Any, Dict, Optional

import numpy as np

ARTIFACT_SCHEMA = "quant_artifact/v1"
ARTIFACT_BASENAME = "quant_artifact.json"


class QuantArtifactError(RuntimeError):
    """Missing, corrupt, or schema-incompatible quantization sidecar."""


def default_artifact_path(config, checkpoint_dir: Optional[str] = None) -> str:
    """``quant.artifact`` if set, else ``<checkpoint_dir>/quant_artifact.json``."""
    quant_cfg = getattr(config, "quant", None)
    configured = getattr(quant_cfg, "artifact", "") if quant_cfg else ""
    if configured:
        return configured
    base = checkpoint_dir or getattr(
        getattr(config, "train", None), "checkpoint_dir", ""
    ) or "."
    return os.path.join(base, ARTIFACT_BASENAME)


def _encode_scale(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.ascontiguousarray(np.asarray(arr, dtype="<f4"))
    raw = arr.tobytes()
    return {
        "b64": base64.b64encode(raw).decode("ascii"),
        "shape": list(arr.shape),
        "dtype": "float32",
        "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
    }


def _decode_scale(path: str, rec: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(rec["b64"])
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    if crc != rec["crc32"]:
        raise QuantArtifactError(
            f"quant artifact CRC mismatch for scale {path!r}: "
            f"recorded {rec['crc32']}, computed {crc}"
        )
    return np.frombuffer(raw, dtype="<f4").reshape(rec["shape"]).copy()


def save_artifact(
    path: str, artifact: Dict[str, Any], config_hash: Optional[str] = None
) -> str:
    """Serialize a `calibrate.py`/`sensitivity.py` artifact dict atomically."""
    doc = {
        "schema": ARTIFACT_SCHEMA,
        "config_hash": config_hash,
        "calib": artifact.get("calib", {}),
        "activation_ranges": {
            k: float(v) for k, v in sorted(artifact["activation_ranges"].items())
        },
        "groups": {g: list(ps) for g, ps in sorted(artifact["groups"].items())},
        "plan": {g: artifact["plan"][g] for g in sorted(artifact["plan"])},
        "sensitivity": artifact.get("sensitivity", {}),
        "weight_scales": {
            k: _encode_scale(v)
            for k, v in sorted(artifact["weight_scales"].items())
        },
    }
    payload = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path) or ".", prefix=".quant_artifact."
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_artifact(path: str) -> Dict[str, Any]:
    """Load + CRC-verify a sidecar; raises :class:`QuantArtifactError`."""
    if not os.path.exists(path):
        raise QuantArtifactError(
            f"no quantization sidecar at {path!r} — run `frcnn quantize` "
            "against this checkpoint first (it writes the calibration "
            "artifact serving.params_dtype=int8 requires)"
        )
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise QuantArtifactError(f"unreadable quant artifact {path!r}: {e}")
    if doc.get("schema") != ARTIFACT_SCHEMA:
        raise QuantArtifactError(
            f"quant artifact {path!r} has schema {doc.get('schema')!r}, "
            f"expected {ARTIFACT_SCHEMA!r} — re-run `frcnn quantize`"
        )
    scales = {
        k: _decode_scale(k, rec) for k, rec in doc["weight_scales"].items()
    }
    return {
        "schema": doc["schema"],
        "config_hash": doc.get("config_hash"),
        "calib": doc.get("calib", {}),
        "activation_ranges": dict(doc["activation_ranges"]),
        "groups": {g: list(ps) for g, ps in doc["groups"].items()},
        "plan": dict(doc["plan"]),
        "sensitivity": doc.get("sensitivity", {}),
        "weight_scales": scales,
    }
