"""Static analysis + runtime strictness for JAX jit hygiene.

Two halves, one contract:

* :mod:`analysis.jaxlint` — an AST analyzer with project-specific rules
  (JX001-JX006) that walks the call graph from the package's jit/shard_map
  entry points and flags host-sync hazards, tracer branching, donated-buffer
  reuse, bad static args, RNG key reuse, and un-spanned device syncs.
  Findings resolve against the committed suppression file
  ``analysis/baseline.toml``; ``frcnn check`` runs it standalone.
* :mod:`analysis.strict` — a runtime harness (``--strict`` /
  ``debug.strict``) that proves at runtime what jaxlint claims statically:
  post-warmup trainer steps perform zero implicit host<->device transfers
  (``jax.transfer_guard``) and zero recompiles (XLA compile-event counter +
  per-program jit cache size).
"""

from replication_faster_rcnn_tpu.analysis.jaxlint import (  # noqa: F401
    Finding,
    LintResult,
    RULES,
    lint_package,
    lint_paths,
)
from replication_faster_rcnn_tpu.analysis.strict import (  # noqa: F401
    StrictHarness,
    StrictViolation,
)
