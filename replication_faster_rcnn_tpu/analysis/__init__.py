"""Static analysis + runtime strictness for JAX jit hygiene.

Three gates, one contract:

* :mod:`analysis.jaxlint` — an AST analyzer with project-specific rules
  (JX001-JX007) that walks the call graph from the package's jit/shard_map
  entry points and flags host-sync hazards, tracer branching, donated-buffer
  reuse, bad static args, RNG key reuse, un-spanned device syncs, and
  implicit-dtype array creation. Findings resolve against the committed
  suppression file ``analysis/baseline.toml``; ``frcnn check`` runs it
  standalone.
* :mod:`analysis.hlolint` + :mod:`analysis.fingerprint` — the HLO program
  auditor (``frcnn audit``, rules HX001-HX006): AOT-lowers every registered
  (feed × K) train program + eval and asserts what the COMPILER emitted —
  donation survives as input/output aliasing (and the device cache never
  aliases), no silent dtype upcasts, the collective inventory matches the
  backend, peak memory fits the HBM budget — against committed fingerprints
  under ``analysis/fingerprints/``.
* :mod:`analysis.strict` — a runtime harness (``--strict`` /
  ``debug.strict``) that proves at runtime what the static gates claim:
  post-warmup trainer steps perform zero implicit host<->device transfers
  (``jax.transfer_guard``) and zero recompiles (XLA compile-event counter +
  per-program jit cache size).
"""

from replication_faster_rcnn_tpu.analysis.jaxlint import (  # noqa: F401
    Finding,
    LintResult,
    RULES,
    lint_package,
    lint_paths,
)
from replication_faster_rcnn_tpu.analysis.strict import (  # noqa: F401
    StrictHarness,
    StrictViolation,
)

__all__ = [
    "Finding",
    "LintResult",
    "RULES",
    "lint_package",
    "lint_paths",
    "StrictHarness",
    "StrictViolation",
]

# analysis.hlolint / analysis.fingerprint import jax and the model stack;
# they are imported lazily by their consumers (`frcnn audit`, tests) so
# that the AST-only `frcnn check` path keeps its no-jax startup.
