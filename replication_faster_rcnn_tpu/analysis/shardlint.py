"""shardlint — static sharding & collective-cost analyzer (fifth gate).

jaxlint reads Python source, threadlint the host concurrency, obslint the
metrics surfaces, hlolint the live AOT artifacts. This gate reads the
COMMITTED fingerprint bank (``analysis/fingerprints/*.json``, written by
`frcnn audit --update`): every banked program carries its abstract arg
shardings, input/output aliasing, collective inventories and the
commcost wire-byte estimate, which is exactly the placement story the
Plan layer promised — so placement regressions are lintable from JSON,
with no jax lowering, on every ``frcnn check``.

Rules (findings name rule + program; `func` IS the program name, so the
shared ``baseline.toml`` waivers address programs, with fnmatch globs —
``func = "train_mp_k*"`` waives a family):

  SL001  a large arg buffer (>= analysis.replicated_bytes_threshold)
         replicated over a >1 MODEL axis although `zero.shard_dim` finds
         a divisible dim — HBM burned on copies the mp layout already
         knows how to split. (The data axis is exempt: replicating
         params over dp IS data parallelism.)
  SL002  sharding disagreement for the same logical state tree — across
         programs of one feed (k1 vs k2, resolution buckets), or between
         a program's own state in_specs and its compiled out_shardings:
         either way a hidden reshard on the train->checkpoint->serve
         chain.
  SL003  mesh-axis misuse: collectives in a program whose mesh has no >1
         axis, a partitioned collective classified onto a mesh axis of
         size <= 1, or a declared >1 axis that no in_spec shards and no
         collective spans (the mesh is a lie — shrink it or use it).
  SL004  a donated (aliased) input whose sharding differs from its
         aliased output's — XLA inserts a copy instead of aliasing, so
         the donation (HX001 checks its *existence*) buys nothing.
  SL005  collective wire bytes per device per step, statically priced by
         analysis/commcost.py over the banked inventory, exceed
         analysis.comm_budget_bytes — or the banked total no longer
         matches its own per-kind tallies (hand-edited bank). The live
         drift arm of this rule runs in `frcnn audit` (hlolint).
  SL006  ZeRO layout fallback: on a shard_opt_state feed an optimizer
         leaf deviates from `zero.compose_spec` — most importantly a
         leaf silently left replicated although `shard_dim` finds a
         divisible dim.

The ZeRO layout rule is recomputed here from a pure reimplementation of
`parallel/zero.py::shard_dim` / `compose_spec` (tested for parity) so
linting stays import-light; feed intent comes from
`parallel/plan.py::FEED_STATE_INTENT` / `ZERO_INTENT_FEEDS` — the same
declarative table the Plan decision cells document.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import glob
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from replication_faster_rcnn_tpu.analysis import commcost
from replication_faster_rcnn_tpu.analysis import fingerprint as _fp
from replication_faster_rcnn_tpu.analysis.jaxlint import (
    Baseline,
    Finding,
    Waiver,
    default_baseline_path,
    load_baseline,
    package_root,
)
from replication_faster_rcnn_tpu.config import AnalysisConfig
from replication_faster_rcnn_tpu.parallel.plan import ZERO_INTENT_FEEDS

RULES: Dict[str, str] = {
    "SL001": (
        "large buffer replicated over a >1 model axis despite a "
        "shardable dim (route it through zero.param_shardings)"
    ),
    "SL002": (
        "sharding mismatch for the same logical state tree across "
        "programs or between in_specs and out_shardings (hidden reshard)"
    ),
    "SL003": (
        "mesh-axis misuse: collective over a degenerate axis, or a "
        "declared >1 axis nothing shards over"
    ),
    "SL004": (
        "donated arg sharding differs from its aliased output's "
        "(XLA copies instead of aliasing)"
    ),
    "SL005": (
        "static collective wire bytes exceed analysis.comm_budget_bytes "
        "(or banked comm record is self-inconsistent)"
    ),
    "SL006": (
        "optimizer leaf deviates from the zero.compose_spec layout on a "
        "shard_opt_state feed (silent replicated fallback)"
    ),
}

MODEL_AXIS = "model"
DATA_AXIS = "data"

# replica-group buckets that span (or may span) every mesh axis — they
# count as "using" any axis for SL003's dead-axis check. On a (2,1) mesh
# the data-axis groups ARE all devices, so 'all' is the common bucket.
_WHOLE_MESH_AXES = ("all", "world", "other")

# relative slack for SL005's banked-total-vs-tallies self-consistency
_COMM_CONSISTENCY_TOL = 0.01


# --------------------------------------------------- pure zero.py layout

def shard_dim(shape: Sequence[int], n: int) -> int:
    """Pure reimplementation of `parallel.zero.shard_dim` (parity-tested
    in tests/test_shardlint.py): the largest dim divisible by ``n``, or
    -1 when the leaf must stay replicated."""
    if n <= 1 or not shape:
        return -1
    divisible = [d for d, s in enumerate(shape) if s % n == 0 and s >= n]
    if not divisible:
        return -1
    return max(divisible, key=lambda d: shape[d])


def compose_spec_dims(
    shape: Sequence[int],
    n_data: int,
    n_model: int,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Tuple[Optional[str], ...]:
    """Pure `parallel.zero.compose_spec`, as a per-dim tuple with
    trailing Nones trimmed (the normalized form specs compare in)."""
    mp_d = shard_dim(shape, n_model)
    spec: List[Optional[str]] = [None] * len(shape)
    if mp_d >= 0:
        spec[mp_d] = model_axis
    if n_data > 1:
        cands = [
            d
            for d, s in enumerate(shape)
            if d != mp_d and s % n_data == 0 and s >= n_data
        ]
        if cands:
            spec[max(cands, key=lambda d: shape[d])] = data_axis
    while spec and spec[-1] is None:
        spec.pop()
    return tuple(spec)


# ------------------------------------------------- sharding repr parsing

# `NamedSharding(mesh=Mesh('data': 2, 'model': 1),
#  spec=PartitionSpec(None, 'data'), memory_kind=unpinned_host)` — the
# repr summarize_abstract banks. PartitionSpec entries may be None, a
# quoted axis name, or a tuple of names (one nesting level).
_MESH_RE = re.compile(r"mesh=Mesh\(([^)]*)\)")
_MESH_AXIS_RE = re.compile(r"'(\w+)':\s*(\d+)")
_SPEC_RE = re.compile(r"spec=PartitionSpec\(((?:[^()]|\([^()]*\))*)\)")


@dataclasses.dataclass(frozen=True)
class ShardingView:
    """A parsed NamedSharding repr: mesh axis sizes + normalized per-dim
    spec (each entry None or a tuple of axis names, trailing Nones
    trimmed)."""

    mesh: Tuple[Tuple[str, int], ...]
    spec: Tuple[Optional[Tuple[str, ...]], ...]

    @property
    def axes_used(self) -> frozenset:
        names: set = set()
        for entry in self.spec:
            if entry:
                names.update(entry)
        return frozenset(names)

    def spec_str(self) -> str:
        if not self.spec:
            return "P()"
        toks = []
        for entry in self.spec:
            if entry is None:
                toks.append("None")
            elif len(entry) == 1:
                toks.append(f"'{entry[0]}'")
            else:
                toks.append("(" + ", ".join(f"'{a}'" for a in entry) + ")")
        return f"P({', '.join(toks)})"


def _parse_spec_body(body: str) -> Tuple[Optional[Tuple[str, ...]], ...]:
    # split on top-level commas only: tuple entries `('a', 'b')` nest one
    # paren level
    parts: List[str] = []
    depth = 0
    token = ""
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(token)
            token = ""
        else:
            token += ch
    parts.append(token)
    entries: List[Optional[Tuple[str, ...]]] = []
    for part in parts:
        part = part.strip()
        if not part:
            continue
        if part == "None":
            entries.append(None)
            continue
        names = re.findall(r"'(\w+)'", part)
        if names:
            entries.append(tuple(names))
    while entries and entries[-1] is None:
        entries.pop()
    return tuple(entries)


def parse_sharding(repr_str: Optional[str]) -> Optional[ShardingView]:
    """ShardingView for a banked NamedSharding repr; None for anything
    else (null, SingleDeviceSharding, unparseable) — callers skip those
    leaves rather than guess."""
    if not repr_str or "NamedSharding" not in repr_str:
        return None
    mm = _MESH_RE.search(repr_str)
    sm = _SPEC_RE.search(repr_str)
    if not mm or not sm:
        return None
    mesh = tuple(
        (name, int(size)) for name, size in _MESH_AXIS_RE.findall(mm.group(1))
    )
    return ShardingView(mesh=mesh, spec=_parse_spec_body(sm.group(1)))


# --------------------------------------------------------- program views

_NP_DTYPE_BYTES = {"bool": 1, "bool_": 1}


def _dtype_nbytes(name: str) -> int:
    if name in _NP_DTYPE_BYTES:
        return _NP_DTYPE_BYTES[name]
    m = re.search(r"(\d+)$", name)
    if not m:
        return 4  # unknown dtype: assume word-sized rather than skip
    return max(1, int(m.group(1)) // 8)


def _leaf_nbytes(leaf: Dict[str, Any]) -> int:
    elems = 1
    for s in leaf.get("shape", ()):
        elems *= int(s)
    return elems * _dtype_nbytes(str(leaf.get("dtype", "")))


@dataclasses.dataclass
class ProgramView:
    """One banked program, parsed once for all rules."""

    name: str
    feed: str
    mesh: Dict[str, int]
    args: Dict[str, List[Dict[str, Any]]]
    params: Dict[str, List[int]]
    record: Dict[str, Any]

    @classmethod
    def from_record(cls, name: str, rec: Dict[str, Any]) -> "ProgramView":
        return cls(
            name=name,
            feed=str(rec.get("feed", "")),
            mesh=dict((rec.get("meta") or {}).get("mesh_shape") or {}),
            args=rec.get("args") or {},
            params=rec.get("params") or {},
            record=rec,
        )

    def leaves(self, role: str):
        for leaf in self.args.get(role, []):
            yield leaf, parse_sharding(leaf.get("sharding"))

    def flat_leaf(self, index: int) -> Optional[Dict[str, Any]]:
        """The arg leaf at one flat (XLA parameter-order) index, via the
        banked role ranges."""
        for role, (start, end) in self.params.items():
            if start <= index < end:
                leaves = self.args.get(role, [])
                if index - start < len(leaves):
                    return leaves[index - start]
        return None

    def state_role(self) -> Optional[str]:
        for role in ("state", "variables", "qvariables"):
            if role in self.args:
                return role
        return None


# --------------------------------------------------------------- the rules


def _fmt_bytes(n: float) -> str:
    return f"{n / (1 << 20):.1f} MiB"


def _check_sl001(
    pv: ProgramView, path: str, threshold: int
) -> List[Finding]:
    n_model = int(pv.mesh.get(MODEL_AXIS, 1) or 1)
    if n_model <= 1:
        return []
    out: List[Finding] = []
    for role in pv.args:
        hits: List[Tuple[str, int]] = []
        total = 0
        for leaf, sh in pv.leaves(role):
            if sh is None or MODEL_AXIS in sh.axes_used:
                continue
            nbytes = _leaf_nbytes(leaf)
            if nbytes < threshold:
                continue
            if shard_dim(leaf.get("shape", ()), n_model) < 0:
                continue
            hits.append((leaf["path"], nbytes))
            total += nbytes
        if hits:
            out.append(
                Finding(
                    rule="SL001",
                    path=path,
                    line=0,
                    col=0,
                    func=pv.name,
                    message=(
                        f"{len(hits)} {role} leaf(s) totaling "
                        f"{_fmt_bytes(total)} replicated over the "
                        f"{n_model}-way model axis despite shardable dims "
                        f"(first: {hits[0][0]}, {_fmt_bytes(hits[0][1])})"
                    ),
                )
            )
    return out


def _state_spec_map(pv: ProgramView) -> Dict[str, str]:
    role = pv.state_role()
    if role is None:
        return {}
    out = {}
    for leaf, sh in pv.leaves(role):
        if sh is not None:
            out[leaf["path"]] = sh.spec_str()
    return out


def _check_sl002_cross(
    views: List[ProgramView], path: str
) -> List[Finding]:
    """Same-feed programs must agree on the state tree's in_specs."""
    by_feed: Dict[Tuple[str, Tuple], List[ProgramView]] = {}
    for pv in views:
        if pv.state_role() is None or not pv.mesh:
            continue
        key = (pv.feed, tuple(sorted(pv.mesh.items())))
        by_feed.setdefault(key, []).append(pv)
    out: List[Finding] = []
    for (_feed, _mesh), group in sorted(by_feed.items()):
        if len(group) < 2:
            continue
        group = sorted(group, key=lambda pv: pv.name)
        ref = group[0]
        ref_specs = _state_spec_map(ref)
        for pv in group[1:]:
            diffs = []
            for p, spec in _state_spec_map(pv).items():
                if p in ref_specs and ref_specs[p] != spec:
                    diffs.append((p, ref_specs[p], spec))
            if diffs:
                p0, a, b = diffs[0]
                out.append(
                    Finding(
                        rule="SL002",
                        path=path,
                        line=0,
                        col=0,
                        func=pv.name,
                        message=(
                            f"{len(diffs)} state leaf spec(s) differ from "
                            f"{ref.name}'s for the same tree (first: {p0} "
                            f"is {b} here, {a} there) — a checkpoint moving "
                            "between them reshards"
                        ),
                    )
                )
    return out


def _check_sl002_inout(pv: ProgramView, path: str) -> List[Finding]:
    """A train program's state out_shardings must match its in_specs —
    under donation anything else reshards the state every step."""
    out_sh = pv.record.get("out_shardings")
    role = pv.state_role()
    if not out_sh or role != "state" or role not in pv.params:
        return []
    leaves = pv.args.get(role, [])
    if len(out_sh) < len(leaves):
        return []
    diffs = []
    for i, leaf in enumerate(leaves):
        in_v = parse_sharding(leaf.get("sharding"))
        out_v = parse_sharding(out_sh[i])
        if in_v is None or out_v is None:
            continue
        if in_v.spec != out_v.spec:
            diffs.append((leaf["path"], in_v.spec_str(), out_v.spec_str()))
    if not diffs:
        return []
    p0, a, b = diffs[0]
    return [
        Finding(
            rule="SL002",
            path=path,
            line=0,
            col=0,
            func=pv.name,
            message=(
                f"{len(diffs)} state leaf(s) change sharding across the "
                f"step (first: {p0} enters as {a}, leaves as {b}) — "
                "hidden per-step reshard under donation"
            ),
        )
    ]


def _check_sl003(pv: ProgramView, path: str) -> List[Finding]:
    if not pv.mesh:
        return []
    out: List[Finding] = []
    sizes = {a: int(s or 1) for a, s in pv.mesh.items()}
    collectives = pv.record.get("collectives") or {}
    partitioned = pv.record.get("partitioned_collectives")
    # (a) collectives over axes the mesh does not have
    if collectives and all(s <= 1 for s in sizes.values()):
        out.append(
            Finding(
                rule="SL003",
                path=path,
                line=0,
                col=0,
                func=pv.name,
                message=(
                    f"lowered collectives {sorted(collectives)} in a "
                    f"program whose mesh {sizes} has no >1 axis"
                ),
            )
        )
    for kind, entry in (partitioned or {}).items():
        for axis, n_ops in (entry.get("axes") or {}).items():
            if axis in sizes and sizes[axis] <= 1 and n_ops:
                out.append(
                    Finding(
                        rule="SL003",
                        path=path,
                        line=0,
                        col=0,
                        func=pv.name,
                        message=(
                            f"{n_ops} {kind} op(s) classified on mesh "
                            f"axis '{axis}' of size {sizes[axis]}"
                        ),
                    )
                )
    # (b) a declared >1 axis nothing uses. `partitioned_collectives` may
    # legitimately be absent on legacy records — unknown is not unused.
    for axis, size in sorted(sizes.items()):
        if size <= 1:
            continue
        used = False
        for role in pv.args:
            for _leaf, sh in pv.leaves(role):
                if sh is not None and axis in sh.axes_used:
                    used = True
                    break
            if used:
                break
        if not used and collectives and axis == DATA_AXIS:
            # hand-written shard_map collectives run over the data axis
            used = True
        if not used and partitioned is None:
            used = True
        if not used:
            for entry in (partitioned or {}).values():
                axes = entry.get("axes") or {}
                if axes.get(axis) or any(
                    axes.get(b) for b in _WHOLE_MESH_AXES
                ):
                    used = True
                    break
        if not used:
            out.append(
                Finding(
                    rule="SL003",
                    path=path,
                    line=0,
                    col=0,
                    func=pv.name,
                    message=(
                        f"mesh declares '{axis}': {size} but no in_spec "
                        "shards over it and no collective spans it — "
                        "dead mesh axis"
                    ),
                )
            )
    return out


def _check_sl004(pv: ProgramView, path: str) -> List[Finding]:
    out_sh = pv.record.get("out_shardings")
    if not out_sh:
        return []
    diffs = []
    for entry in pv.record.get("aliasing") or []:
        oidx = str(entry.get("output", ""))
        if not oidx.isdigit() or int(oidx) >= len(out_sh):
            continue
        leaf = pv.flat_leaf(int(entry.get("parameter", -1)))
        if leaf is None:
            continue
        in_v = parse_sharding(leaf.get("sharding"))
        out_v = parse_sharding(out_sh[int(oidx)])
        if in_v is None or out_v is None:
            continue
        if in_v.spec != out_v.spec:
            diffs.append(
                (leaf["path"], in_v.spec_str(), out_v.spec_str())
            )
    if not diffs:
        return []
    p0, a, b = diffs[0]
    return [
        Finding(
            rule="SL004",
            path=path,
            line=0,
            col=0,
            func=pv.name,
            message=(
                f"{len(diffs)} donated input(s) alias outputs with a "
                f"different sharding (first: {p0} donated as {a}, output "
                f"is {b}) — XLA copies instead of aliasing"
            ),
        )
    ]


def _check_sl005(
    pv: ProgramView, path: str, budget: int
) -> List[Finding]:
    comm = pv.record.get("comm")
    if not comm:
        return []
    out: List[Finding] = []
    try:
        wire = int(comm.get("wire_bytes_per_device", 0))
    except (TypeError, ValueError):
        wire = 0
    if wire > budget:
        out.append(
            Finding(
                rule="SL005",
                path=path,
                line=0,
                col=0,
                func=pv.name,
                message=(
                    f"static collective cost {_fmt_bytes(wire)}/device/"
                    f"step exceeds analysis.comm_budget_bytes "
                    f"({_fmt_bytes(budget)})"
                ),
            )
        )
    resum = commcost.recompute_wire_total(comm)
    if resum is not None and wire and (
        abs(resum - wire) > _COMM_CONSISTENCY_TOL * max(wire, 1)
    ):
        out.append(
            Finding(
                rule="SL005",
                path=path,
                line=0,
                col=0,
                func=pv.name,
                message=(
                    f"banked wire_bytes_per_device ({wire}) disagrees "
                    f"with its own per-kind tallies ({resum}) — "
                    "hand-edited comm record"
                ),
            )
        )
    return out


def _check_sl006(pv: ProgramView, path: str) -> List[Finding]:
    if pv.feed not in ZERO_INTENT_FEEDS:
        return []
    role = pv.state_role()
    if role is None or not pv.mesh:
        return []
    n_data = int(pv.mesh.get(DATA_AXIS, 1) or 1)
    n_model = (
        int(pv.mesh.get(MODEL_AXIS, 1) or 1)
        if pv.feed == "mp_zero"
        else 1
    )
    diffs = []
    fallbacks = 0
    for leaf, sh in pv.leaves(role):
        if ".opt_state" not in leaf["path"] or sh is None:
            continue
        expected = compose_spec_dims(leaf.get("shape", ()), n_data, n_model)
        actual = sh.spec
        exp_norm = tuple(
            None if e is None else (e,) for e in expected
        )
        if actual != exp_norm:
            diffs.append((leaf["path"], exp_norm, actual))
            if exp_norm and not actual:
                fallbacks += 1
    if not diffs:
        return []
    p0, exp, act = diffs[0]
    return [
        Finding(
            rule="SL006",
            path=path,
            line=0,
            col=0,
            func=pv.name,
            message=(
                f"{len(diffs)} opt_state leaf(s) deviate from the "
                f"zero.compose_spec layout ({fallbacks} silently "
                f"replicated despite a divisible dim; first: {p0} "
                f"expected {exp}, got {act})"
            ),
        )
    ]


# ------------------------------------------------------------ lint driver


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, str]]
    excluded: List[Finding]
    stale_waivers: List[Waiver]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": RULES,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "reason": r} for f, r in self.suppressed
            ],
            "excluded_count": len(self.excluded),
            "stale_waivers": [dataclasses.asdict(w) for w in self.stale_waivers],
            "ok": not self.findings and not self.stale_waivers,
        }


def _rel(path: str, pkg_root: str) -> str:
    repo_root = os.path.dirname(os.path.abspath(pkg_root))
    ap = os.path.abspath(path)
    if ap.startswith(repo_root + os.sep):
        return os.path.relpath(ap, repo_root).replace(os.sep, "/")
    return os.path.basename(ap)


def lint_bank(
    bank: Dict[str, Any],
    rel_path: str,
    replicated_bytes_threshold: int,
    comm_budget_bytes: int,
) -> List[Finding]:
    """All raw SL findings for one loaded fingerprint bank."""
    views = [
        ProgramView.from_record(name, rec)
        for name, rec in sorted((bank.get("programs") or {}).items())
    ]
    raw: List[Finding] = []
    for pv in views:
        raw.extend(_check_sl001(pv, rel_path, replicated_bytes_threshold))
        raw.extend(_check_sl002_inout(pv, rel_path))
        raw.extend(_check_sl003(pv, rel_path))
        raw.extend(_check_sl004(pv, rel_path))
        raw.extend(_check_sl005(pv, rel_path, comm_budget_bytes))
        raw.extend(_check_sl006(pv, rel_path))
    raw.extend(_check_sl002_cross(views, rel_path))
    return sorted(raw, key=lambda f: (f.func, f.rule, f.message))


def _waive(base: Baseline, f: Finding) -> Optional[Waiver]:
    """Waiver resolution with fnmatch on func (the program name) —
    `func = "train_mp_k*"` addresses a program family. Exact-func and
    "*" waivers behave identically to jaxlint's matcher."""
    for w in base.waivers:
        if (
            w.rule == f.rule
            and w.path == f.path
            and fnmatch.fnmatchcase(f.func, w.func)
        ):
            w.used = True
            return w
    return None


def lint_paths(
    paths: Sequence[str],
    baseline: Optional[str] = None,
    pkg_root: Optional[str] = None,
    replicated_bytes_threshold: Optional[int] = None,
    comm_budget_bytes: Optional[int] = None,
) -> LintResult:
    """Lint explicit fingerprint-bank JSON paths. Non-bank files (other
    suffixes, wrong schema) are skipped — when `frcnn check` fans a mixed
    path list over all analyzers, banks are this one's share."""
    defaults = AnalysisConfig()
    threshold = (
        replicated_bytes_threshold
        if replicated_bytes_threshold is not None
        else defaults.replicated_bytes_threshold
    )
    budget = (
        comm_budget_bytes
        if comm_budget_bytes is not None
        else defaults.comm_budget_bytes
    )
    root = pkg_root or package_root()
    raw: List[Finding] = []
    for path in paths:
        if not str(path).endswith(".json"):
            continue
        bank = _fp.load_bank(str(path))
        if bank is None:
            continue
        raw.extend(lint_bank(bank, _rel(str(path), root), threshold, budget))
    base = (
        load_baseline(baseline).restricted(RULES) if baseline else Baseline()
    )
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    excluded: List[Finding] = []
    for f in raw:
        if base.excluded(f):
            excluded.append(f)
            continue
        w = _waive(base, f)
        if w is not None:
            suppressed.append((f, w.reason))
        else:
            findings.append(f)
    stale = [w for w in base.waivers if not w.used]
    return LintResult(findings, suppressed, excluded, stale)


def lint_package(baseline: Optional[str] = "default") -> LintResult:
    """Lint every committed bank under analysis/fingerprints/."""
    if baseline == "default":
        baseline = default_baseline_path()
        if not os.path.exists(baseline):
            baseline = None
    banks = sorted(
        glob.glob(os.path.join(_fp.default_fingerprint_dir(), "*.json"))
    )
    return lint_paths(banks, baseline=baseline)
