"""threadlint — AST lint for host-thread concurrency contracts.

The package runs five thread-based host subsystems on the critical path
(DevicePrefetcher, AsyncCheckpointWriter, MicroBatcher, the HTTP serving
tier, loader workers + the telemetry watchdog). `frcnn check` (jaxlint),
`frcnn audit` (hlolint) and ``--strict`` cover jitted code and compiled
programs but say nothing about host concurrency — an unlocked shared
attribute or a lock-order inversion is invisible to tier-1 until it
deadlocks under load. This analyzer walks the call graph from every
*thread entry point* (the same :mod:`analysis.callgraph` machinery
jaxlint walks from jit roots) and enforces:

  TL001  instance attribute written from >= 2 thread roots without a
         common lock held at every write (``with self._lock:`` context
         tracking; ``__init__`` writes are pre-publication and exempt).
  TL002  unbounded ``queue.Queue`` shared by a producer/consumer pair,
         or a blocking ``get``/``put`` without a timeout inside a
         shutdown-path method (``close``/``stop``/...): a dead peer
         deadlocks teardown.
  TL003  a blocking consumer loop (``q.get()`` with no timeout inside a
         loop) must have a close-sentinel ``put`` on the same queue
         reachable from a shutdown-path method — otherwise shutdown can
         leave the consumer blocked forever.
  TL004  cycle in the static lock-order graph (lock B acquired while
         holding A in one function, A while holding B elsewhere —
         including one level of interprocedural acquisition through
         resolvable calls made under a lock). A plain ``Lock`` re-
         acquired while already held is a self-cycle.
  TL005  ``time.sleep`` while holding a lock: the sleeper serializes
         every other thread contending for that lock.
  TL006  a daemon thread performing durable file writes (``open`` for
         write/append, ``os.replace``/``os.rename``, ``shutil.move``):
         daemon threads are killed mid-write at interpreter exit.

Thread roots: ``threading.Thread(target=...)`` / ``threading.Timer``
spawns (resolving ``self._method``, nested defs and bare names),
``Thread``-subclass ``run`` methods, ``BaseHTTPRequestHandler`` subclass
``do_*`` methods (one thread per connection — concurrent with
*themselves*, so a single handler root counts as two writers for TL001),
and callables submitted to a ``ThreadPoolExecutor``. Everything not
reachable exclusively through a spawn is attributed to the synthetic
``main`` root; a function reachable both ways gets both attributions
(e.g. ``StallWatchdog.snapshot`` from the watchdog thread and ``beat``).

Findings resolve against the same ``analysis/baseline.toml`` as jaxlint
(each analyzer restricts the shared file to its own rule set, so
waivers never cross-report as stale) and ship through ``frcnn check``
(``--rules TL001,...`` filters).

Known limits (deliberate — the runtime half is :mod:`analysis.threadsan`):
callables passed as constructor parameters (``MicroBatcher(process=...)``)
and attr-of-attr dispatch (``self.watchdog.beat(...)``) are not followed,
so cross-object thread reachability is under-approximated; lock tracking
sees ``with`` statements only (bare ``acquire()`` calls are invisible);
``lambda`` spawn targets are not resolved.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from replication_faster_rcnn_tpu.analysis.callgraph import (
    FunctionInfo,
    Index,
    ModuleInfo,
    _dotted,
    _local_aliases,
    _resolve_dotted_prefix,
    _resolve_name,
    build_edges,
    parse_modules,
    reachable_from,
)
from replication_faster_rcnn_tpu.analysis.jaxlint import (
    Baseline,
    Finding,
    Waiver,
    default_baseline_path,
    iter_package_files,
    load_baseline,
    package_root,
)

RULES: Dict[str, str] = {
    "TL001": "attribute written from >=2 thread roots without a common lock",
    "TL002": "unbounded shared queue, or blocking queue op without timeout in a shutdown path",
    "TL003": "blocking consumer loop with no close-sentinel put from a shutdown method",
    "TL004": "lock-order cycle in the static lock acquisition graph",
    "TL005": "time.sleep while holding a lock",
    "TL006": "daemon thread performs durable file writes",
}

_THREAD_CTORS = {"threading.Thread", "threading.Timer"}
_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}
_RLOCK_CTORS = {"threading.RLock", "threading.Condition"}
_QUEUE_CTORS = {
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "queue.SimpleQueue",
}
# method names whose call on an attribute mutates the underlying object
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popitem", "popleft", "remove", "discard", "clear",
}
_SHUTDOWN_NAMES = {
    "close", "stop", "shutdown", "join", "drain", "finish", "terminate",
    "__exit__", "__del__",
}
_INIT_NAMES = {"__init__", "__post_init__", "__new__"}
# durable-write calls for TL006 (reads are fine; a daemon thread that
# only consumes data dies harmlessly)
_WRITE_MODE_CHARS = ("w", "a", "x", "+")
_RENAME_CALLS = {"os.replace", "os.rename", "shutil.move"}


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    label: str  # e.g. "thread:device-prefetch", "http:do_GET"
    fn: FunctionInfo
    daemon: bool = False
    multi: bool = False  # many instances run concurrently (HTTP/pool)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, str]]
    excluded: List[Finding]
    stale_waivers: List[Waiver]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": RULES,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "reason": r} for f, r in self.suppressed
            ],
            "excluded_count": len(self.excluded),
            "stale_waivers": [dataclasses.asdict(w) for w in self.stale_waivers],
            "ok": not self.findings and not self.stale_waivers,
        }


# ------------------------------------------------------------------ discovery


def _dotted_names(
    idx: Index, fi: Optional[FunctionInfo], mi: ModuleInfo, expr: ast.AST,
    aliases: Optional[Dict[str, List[Any]]] = None,
) -> List[str]:
    """Every dotted spelling an expression's callee may denote (both the
    raw text and the import-resolved form)."""
    out: List[str] = []
    d = _dotted(expr)
    if d is not None:
        out.append(d)
        out.append(_resolve_dotted_prefix(mi, d))
    if isinstance(expr, ast.Name):
        for t in _resolve_name(idx, fi, mi, expr.id, aliases):
            if isinstance(t, str):
                out.append(t)
    return out


def _owner_prefix(fi: FunctionInfo) -> Optional[str]:
    """Qualname prefix of the class owning ``fi`` (walks out of nested
    defs), or None for free functions."""
    return fi.owner_class()


def _resolve_callable_ref(
    idx: Index, fi: FunctionInfo, mi: ModuleInfo, expr: ast.AST,
    aliases: Dict[str, List[Any]],
) -> List[FunctionInfo]:
    """A function reference used as a spawn target: bare name, nested
    def, or ``self.method``."""
    if isinstance(expr, ast.Name):
        return [
            t
            for t in _resolve_name(idx, fi, mi, expr.id, aliases)
            if isinstance(t, FunctionInfo)
        ]
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        cls = _owner_prefix(fi)
        if cls is not None:
            m = mi.functions.get(f"{cls}.{expr.attr}")
            if m is not None:
                return [m]
    return []


def _const_kw(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def discover_thread_roots(
    idx: Index,
) -> Tuple[List[ThreadRoot], Set[int]]:
    """All thread entry points, plus the AST node ids of the spawn-target
    expressions (so edge augmentation does not turn ``target=self._run``
    into a caller→callee edge: a spawn is not a call)."""
    roots: List[ThreadRoot] = []
    spawn_ref_ids: Set[int] = set()
    for mi in idx.modules.values():
        # Thread subclasses and HTTP handler classes
        for cls, bases in mi.class_bases.items():
            for b in bases:
                if b == "Thread" or b.endswith(".Thread"):
                    run = mi.functions.get(f"{cls}.run")
                    if run is not None:
                        roots.append(
                            ThreadRoot(f"thread:{cls}.run", run, daemon=False)
                        )
                if b.endswith("BaseHTTPRequestHandler"):
                    for qual, f in mi.functions.items():
                        if (
                            qual.startswith(f"{cls}.do_")
                            and f.cls == cls
                        ):
                            roots.append(
                                ThreadRoot(
                                    f"http:{f.name}", f, daemon=True, multi=True
                                )
                            )
        # spawn call sites
        for fi in mi.functions.values():
            aliases = _local_aliases(idx, fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted_names(idx, fi, mi, node.func, aliases)
                if any(d in _THREAD_CTORS for d in dotted):
                    is_timer = any(d == "threading.Timer" for d in dotted)
                    target = None
                    for kw in node.keywords:
                        if kw.arg in ("target", "function"):
                            target = kw.value
                    if target is None and is_timer and len(node.args) >= 2:
                        target = node.args[1]
                    elif target is None and not is_timer and node.args:
                        # Thread(group, target) positional — rare
                        if len(node.args) >= 2:
                            target = node.args[1]
                    if target is None:
                        continue
                    spawn_ref_ids.add(id(target))
                    daemon = bool(_const_kw(node, "daemon"))
                    tname = _const_kw(node, "name")
                    for f in _resolve_callable_ref(idx, fi, mi, target, aliases):
                        label = f"thread:{tname or f.name}"
                        roots.append(ThreadRoot(label, f, daemon=daemon))
                # pool.submit(fn, ...) / pool.map(fn, ...): fn runs on pool
                # threads, concurrently with itself
                if (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("submit", "map")
                    and node.args
                ):
                    recv = _dotted(node.func.value) or ""
                    low = recv.lower()
                    if "pool" in low or "executor" in low:
                        spawn_ref_ids.add(id(node.args[0]))
                        for f in _resolve_callable_ref(
                            idx, fi, mi, node.args[0], aliases
                        ):
                            roots.append(
                                ThreadRoot(f"pool:{f.name}", f, multi=True)
                            )
    # dedupe (a call site walked from both a method and its nested defs)
    seen: Set[Tuple[str, FunctionInfo]] = set()
    out = []
    for r in roots:
        key = (r.label, r.fn)
        if key not in seen:
            seen.add(key)
            out.append(r)
    return out, spawn_ref_ids


def _augment_self_method_edges(idx: Index, spawn_ref_ids: Set[int]) -> None:
    """jaxlint's edge builder does not follow ``self.method`` (jitted code
    is free functions); thread code is all methods, so add those edges.
    Any ``self.m`` *reference* counts (``on_skip = self._on_sample_skip``
    then calling ``on_skip`` later is still a potential call) — except
    spawn targets, which become roots, not edges."""
    for mi in idx.modules.values():
        for fi in mi.functions.values():
            cls = _owner_prefix(fi)
            if cls is None:
                continue
            edges = idx.edges.setdefault(fi, set())
            for node in ast.walk(fi.node):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and id(node) not in spawn_ref_ids
                ):
                    m = mi.functions.get(f"{cls}.{node.attr}")
                    if m is not None and m is not fi:
                        edges.add(m)


def build_thread_index(
    paths: Sequence[str], pkg_root: str
) -> Tuple[Index, List[ThreadRoot], Dict[FunctionInfo, Set[str]]]:
    """Index + thread roots + per-function root attribution.

    Attribution: each worker root's BFS closure gets that root's label; a
    synthetic ``main`` label goes to everything reachable from functions
    that no worker reaches (the code the controlling thread can run).
    Worker entries are only ever *spawned*, so they are removed from all
    call-edge sets first — otherwise the parent→nested-def containment
    edge would smear ``main`` over every worker body.
    """
    idx = parse_modules(list(paths), pkg_root)
    build_edges(idx)
    roots, spawn_ref_ids = discover_thread_roots(idx)
    _augment_self_method_edges(idx, spawn_ref_ids)
    entry_fns = {r.fn for r in roots}
    for edges in idx.edges.values():
        edges -= entry_fns
    attribution: Dict[FunctionInfo, Set[str]] = {}
    worker_union: Set[FunctionInfo] = set()
    for r in roots:
        for f in reachable_from(idx, {r.fn}):
            attribution.setdefault(f, set()).add(r.label)
            worker_union.add(f)
    main_entries = [
        f
        for mi in idx.modules.values()
        for f in mi.functions.values()
        if f not in worker_union
    ]
    for f in reachable_from(idx, main_entries):
        attribution.setdefault(f, set()).add("main")
    return idx, roots, attribution


# ----------------------------------------------------------- contract walker


@dataclasses.dataclass
class _WriteSite:
    fn: FunctionInfo
    attr: str
    lockset: frozenset
    node: ast.AST


@dataclasses.dataclass
class _QueueOp:
    qkey: Tuple  # queue identity
    op: str  # put | get | put_nowait | get_nowait
    blocking: bool  # would wait forever (no timeout, block not False)
    in_loop: bool
    fn: FunctionInfo
    node: ast.AST


@dataclasses.dataclass
class _LockEdge:
    src: str
    dst: str
    fn: FunctionInfo
    node: ast.AST


class _Collector:
    """One pass over every function: attribute writes with held-lock
    context, queue registry + ops, lock acquisition order, sleeps under
    locks, daemon-reachable file writes."""

    def __init__(self, idx: Index, roots: List[ThreadRoot],
                 attribution: Dict[FunctionInfo, Set[str]]):
        self.idx = idx
        self.roots = roots
        self.attribution = attribution
        self.writes: Dict[Tuple[str, str, str], List[_WriteSite]] = {}
        self.queues: Dict[Tuple, bool] = {}  # qkey -> bounded
        self.queue_ctor: Dict[Tuple, Tuple[FunctionInfo, ast.AST]] = {}
        self.queue_ops: List[_QueueOp] = []
        self.class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        self.module_locks: Dict[Tuple[str, str], str] = {}
        self.rlocks: Set[str] = set()  # lock ids that are re-entrant
        self.lock_edges: List[_LockEdge] = []
        self.direct_acquires: Dict[FunctionInfo, Set[str]] = {}
        # (held lockset, resolved callee, caller, call node)
        self.calls_under_lock: List[
            Tuple[frozenset, FunctionInfo, FunctionInfo, ast.AST]
        ] = []
        self.sleeps: List[Tuple[FunctionInfo, ast.AST, str]] = []
        self.file_writes: Dict[FunctionInfo, List[Tuple[ast.AST, str]]] = {}
        self.findings: List[Finding] = []

    # -------------------------------------------------------------- prepass

    @staticmethod
    def _name_call_assign(stmt: ast.stmt) -> Optional[Tuple[ast.Name, ast.Call]]:
        """(Name target, Call value) for ``x = Ctor(...)`` — plain or
        annotated assignment."""
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
        ):
            return stmt.targets[0], stmt.value
        if (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Name)
            and isinstance(stmt.value, ast.Call)
        ):
            return stmt.target, stmt.value
        return None

    @staticmethod
    def _self_call_assign(
        stmt: ast.stmt,
    ) -> Optional[Tuple[ast.Attribute, ast.Call]]:
        """(self.X target, Call value) for ``self.x = Ctor(...)``."""
        if (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Attribute)
            and isinstance(stmt.targets[0].value, ast.Name)
            and stmt.targets[0].value.id == "self"
            and isinstance(stmt.value, ast.Call)
        ):
            return stmt.targets[0], stmt.value
        if (
            isinstance(stmt, ast.AnnAssign)
            and isinstance(stmt.target, ast.Attribute)
            and isinstance(stmt.target.value, ast.Name)
            and stmt.target.value.id == "self"
            and isinstance(stmt.value, ast.Call)
        ):
            return stmt.target, stmt.value
        return None

    def prepass(self) -> None:
        """Register locks and queues (class attrs + module level) before
        the main walk needs them."""
        for mi in self.idx.modules.values():
            for stmt in mi.tree.body:
                hit = self._name_call_assign(stmt)
                if hit is None:
                    continue
                target, call = hit
                name = target.id
                dotted = _dotted_names(self.idx, None, mi, call.func)
                if any(d in _LOCK_CTORS for d in dotted):
                    lock_id = f"{mi.modname}.{name}"
                    self.module_locks[(mi.modname, name)] = lock_id
                    if any(d in _RLOCK_CTORS for d in dotted):
                        self.rlocks.add(lock_id)
                if any(d in _QUEUE_CTORS for d in dotted):
                    qkey = (mi.modname, name)
                    self.queues[qkey] = self._bounded(call)
                    self.queue_ctor.setdefault(qkey, (None, call))
            for fi in mi.functions.values():
                cls = _owner_prefix(fi)
                if cls is None:
                    continue
                for node in ast.walk(fi.node):
                    if not isinstance(node, ast.stmt):
                        continue
                    hit = self._self_call_assign(node)
                    if hit is None:
                        continue
                    target, call = hit
                    attr = target.attr
                    dotted = _dotted_names(self.idx, fi, mi, call.func)
                    if any(d in _LOCK_CTORS for d in dotted):
                        lock_id = f"{mi.modname}.{cls}.{attr}"
                        self.class_locks.setdefault(
                            (mi.modname, cls), {}
                        )[attr] = lock_id
                        if any(d in _RLOCK_CTORS for d in dotted):
                            self.rlocks.add(lock_id)
                    if any(d in _QUEUE_CTORS for d in dotted):
                        qkey = (mi.modname, cls, attr)
                        self.queues[qkey] = self._bounded(call)
                        self.queue_ctor.setdefault(qkey, (fi, call))

    @staticmethod
    def _bounded(call: ast.Call) -> bool:
        if call.args:
            return True  # positional maxsize
        return any(kw.arg == "maxsize" for kw in call.keywords)

    # ------------------------------------------------------------ main walk

    def collect(self) -> None:
        self.prepass()
        for mi in self.idx.modules.values():
            for fi in mi.functions.values():
                self._walk_function(fi)

    def _family_root(self, fi: FunctionInfo) -> FunctionInfo:
        while fi.parent is not None:
            fi = fi.parent
        return fi

    def _local_queues(self, fi: FunctionInfo) -> Dict[str, Tuple]:
        """name -> qkey for queues assigned to local names anywhere in this
        function's top-level family (closures share the enclosing scope)."""
        fam = self._family_root(fi)
        out: Dict[str, Tuple] = {}
        mi = fi.module
        for node in ast.walk(fam.node):
            hit = self._name_call_assign(node) if isinstance(node, ast.stmt) else None
            if hit is not None:
                target, call = hit
                dotted = _dotted_names(self.idx, fam, mi, call.func)
                if any(d in _QUEUE_CTORS for d in dotted):
                    qkey = (mi.modname, fam.qualname, target.id)
                    out[target.id] = qkey
                    self.queues.setdefault(qkey, self._bounded(call))
                    self.queue_ctor.setdefault(qkey, (fam, call))
        return out

    def _local_locks(self, fi: FunctionInfo) -> Dict[str, str]:
        fam = self._family_root(fi)
        out: Dict[str, str] = {}
        mi = fi.module
        for node in ast.walk(fam.node):
            hit = self._name_call_assign(node) if isinstance(node, ast.stmt) else None
            if hit is not None:
                target, call = hit
                dotted = _dotted_names(self.idx, fam, mi, call.func)
                if any(d in _LOCK_CTORS for d in dotted):
                    lock_id = f"{mi.modname}.{fam.qualname}.{target.id}"
                    out[target.id] = lock_id
                    if any(d in _RLOCK_CTORS for d in dotted):
                        self.rlocks.add(lock_id)
        return out

    def _walk_function(self, fi: FunctionInfo) -> None:
        mi = fi.module
        cls = _owner_prefix(fi)
        ctx = {
            "fi": fi,
            "mi": mi,
            "cls": cls,
            "aliases": _local_aliases(self.idx, fi),
            "locals_q": self._local_queues(fi),
            "locals_l": self._local_locks(fi),
        }
        self.direct_acquires.setdefault(fi, set())
        self._walk_stmts(getattr(fi.node, "body", []), frozenset(), 0, ctx)

    def _lock_of_expr(self, expr: ast.AST, ctx) -> Optional[str]:
        """Lock id of a with-item expression, if it names a known lock."""
        if isinstance(expr, ast.Call):
            # `with lock.acquire_timeout(...)`-style helpers: not tracked
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and ctx["cls"] is not None
        ):
            table = self.class_locks.get((ctx["mi"].modname, ctx["cls"]), {})
            return table.get(expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in ctx["locals_l"]:
                return ctx["locals_l"][expr.id]
            return self.module_locks.get((ctx["mi"].modname, expr.id))
        return None

    def _queue_of_expr(self, expr: ast.AST, ctx) -> Optional[Tuple]:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and ctx["cls"] is not None
        ):
            qkey = (ctx["mi"].modname, ctx["cls"], expr.attr)
            return qkey if qkey in self.queues else None
        if isinstance(expr, ast.Name):
            if expr.id in ctx["locals_q"]:
                return ctx["locals_q"][expr.id]
            qkey = (ctx["mi"].modname, expr.id)
            return qkey if qkey in self.queues else None
        return None

    def _walk_stmts(
        self, stmts, lockset: frozenset, loop_depth: int, ctx
    ) -> None:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # nested defs walked as their own functions
            if isinstance(s, ast.With):
                acquired = []
                for item in s.items:
                    self._scan_expr(item.context_expr, lockset, loop_depth, ctx)
                    lock = self._lock_of_expr(item.context_expr, ctx)
                    if lock is not None:
                        acquired.append((lock, item.context_expr))
                for lock, node in acquired:
                    self.direct_acquires[ctx["fi"]].add(lock)
                    for held in lockset:
                        self.lock_edges.append(
                            _LockEdge(held, lock, ctx["fi"], node)
                        )
                    if lock in lockset and lock not in self.rlocks:
                        # immediate self-deadlock: plain Lock re-acquired
                        self.lock_edges.append(
                            _LockEdge(lock, lock, ctx["fi"], node)
                        )
                inner = lockset | {lk for lk, _ in acquired}
                self._walk_stmts(s.body, frozenset(inner), loop_depth, ctx)
                continue
            if isinstance(s, (ast.For, ast.While)):
                if isinstance(s, ast.While):
                    self._scan_expr(s.test, lockset, loop_depth, ctx)
                else:
                    self._scan_expr(s.iter, lockset, loop_depth, ctx)
                self._walk_stmts(s.body, lockset, loop_depth + 1, ctx)
                self._walk_stmts(s.orelse, lockset, loop_depth, ctx)
                continue
            if isinstance(s, ast.If):
                self._scan_expr(s.test, lockset, loop_depth, ctx)
                self._walk_stmts(s.body, lockset, loop_depth, ctx)
                self._walk_stmts(s.orelse, lockset, loop_depth, ctx)
                continue
            if isinstance(s, ast.Try):
                self._walk_stmts(s.body, lockset, loop_depth, ctx)
                for h in s.handlers:
                    self._walk_stmts(h.body, lockset, loop_depth, ctx)
                self._walk_stmts(s.orelse, lockset, loop_depth, ctx)
                self._walk_stmts(s.finalbody, lockset, loop_depth, ctx)
                continue
            # leaf statements: record writes, then scan expressions
            if isinstance(s, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                self._record_writes(s, lockset, ctx)
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, lockset, loop_depth, ctx)

    # ------------------------------------------------------------- recorders

    def _attr_write_targets(self, s: ast.stmt) -> List[Tuple[str, ast.AST]]:
        """self-attribute names stored to by this statement (direct
        assigns, tuple elements, subscript/attribute stores through a
        self attr)."""
        targets: List[ast.expr] = []
        if isinstance(s, ast.Assign):
            targets = list(s.targets)
        elif isinstance(s, (ast.AugAssign, ast.AnnAssign)):
            targets = [s.target]
        out: List[Tuple[str, ast.AST]] = []

        def base_self_attr(node: ast.AST) -> Optional[str]:
            # innermost self.X of an attribute/subscript chain
            while isinstance(node, (ast.Attribute, ast.Subscript)):
                if (
                    isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    return node.attr
                node = node.value
            return None

        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                attr = base_self_attr(e)
                if attr is not None:
                    out.append((attr, e))
        return out

    def _record_writes(self, s: ast.stmt, lockset: frozenset, ctx) -> None:
        fi, cls = ctx["fi"], ctx["cls"]
        if cls is None or fi.name in _INIT_NAMES:
            return
        for attr, node in self._attr_write_targets(s):
            key = (ctx["mi"].modname, cls, attr)
            self.writes.setdefault(key, []).append(
                _WriteSite(fi, attr, lockset, node)
            )

    def _scan_expr(
        self, expr: ast.AST, lockset: frozenset, loop_depth: int, ctx
    ) -> None:
        fi, mi = ctx["fi"], ctx["mi"]
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda,)):
                continue  # executes later, in an unknown lock context
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted_names(self.idx, fi, mi, node.func, ctx["aliases"])
            # -- TL005: sleeping with a lock held
            if any(d == "time.sleep" for d in dotted) and lockset:
                self.sleeps.append((fi, node, ", ".join(sorted(lockset))))
            # -- mutator calls count as writes (self.xs.append(...))
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
                and isinstance(node.func.value, ast.Attribute)
                and isinstance(node.func.value.value, ast.Name)
                and node.func.value.value.id == "self"
                and ctx["cls"] is not None
                and fi.name not in _INIT_NAMES
            ):
                attr = node.func.value.attr
                key = (mi.modname, ctx["cls"], attr)
                self.writes.setdefault(key, []).append(
                    _WriteSite(fi, attr, lockset, node)
                )
            # -- queue ops
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("put", "get", "put_nowait", "get_nowait")
            ):
                qkey = self._queue_of_expr(node.func.value, ctx)
                if qkey is not None:
                    op = node.func.attr
                    blocking = self._op_blocking(node, op)
                    self.queue_ops.append(
                        _QueueOp(qkey, op, blocking, loop_depth > 0, fi, node)
                    )
            # -- TL004: resolvable call made while holding locks acquires
            #    the callee's (transitive) locks — expanded in finish()
            if lockset:
                for g in self._resolved_callees(node, ctx):
                    self.calls_under_lock.append((lockset, g, fi, node))
            # -- TL006 raw material: durable file writes
            w = self._file_write_kind(node, dotted)
            if w is not None:
                self.file_writes.setdefault(fi, []).append((node, w))

    @staticmethod
    def _op_blocking(call: ast.Call, op: str) -> bool:
        if op.endswith("_nowait"):
            return False
        # put(item, block=?, timeout=?) / get(block=?, timeout=?)
        pos_offset = 1 if op == "put" else 0
        args = call.args
        if len(args) > pos_offset:  # block positional
            b = args[pos_offset]
            if isinstance(b, ast.Constant) and b.value is False:
                return False
        if len(args) > pos_offset + 1:  # timeout positional
            return False
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) and kw.value.value is False:
                return False
            if kw.arg == "timeout":
                if isinstance(kw.value, ast.Constant) and kw.value.value is None:
                    continue  # timeout=None is still forever
                return False
        return True

    @staticmethod
    def _file_write_kind(call: ast.Call, dotted: List[str]) -> Optional[str]:
        if any(d in _RENAME_CALLS for d in dotted):
            return next(d for d in dotted if d in _RENAME_CALLS)
        is_open = (
            isinstance(call.func, ast.Name) and call.func.id == "open"
        ) or any(d == "open" for d in dotted)
        if is_open:
            mode = None
            if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
                mode = call.args[1].value
            for kw in call.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and any(c in mode for c in _WRITE_MODE_CHARS):
                return f"open(..., {mode!r})"
        return None

    # --------------------------------------------------------------- verdicts

    def _emit(self, rule: str, fi: FunctionInfo, node: ast.AST, msg: str) -> None:
        self.findings.append(
            Finding(
                rule=rule,
                path=fi.module.relpath,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                func=fi.qualname,
                message=msg,
            )
        )

    def _root_labels(self, fi: FunctionInfo) -> Set[str]:
        return self.attribution.get(fi, set())

    def _effective_writers(self, sites: List[_WriteSite]) -> Tuple[Set[str], bool]:
        """(labels, concurrent): labels writing the attr, and True when a
        single multi-instance root (HTTP handler, pool task) writes — it
        races with itself."""
        multi_labels = {r.label for r in self.roots if r.multi}
        labels: Set[str] = set()
        for s in sites:
            labels |= self._root_labels(s.fn)
        concurrent = len(labels) >= 2 or bool(labels & multi_labels)
        return labels, concurrent

    def finish(self) -> List[Finding]:
        self._tl001()
        self._tl002()
        self._tl003()
        self._tl004()
        self._tl005()
        self._tl006()
        self.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
        return self.findings

    def _tl001(self) -> None:
        for (modname, cls, attr), sites in sorted(
            self.writes.items(), key=lambda kv: str(kv[0])
        ):
            labels, concurrent = self._effective_writers(sites)
            if not concurrent:
                continue
            common = None
            for s in sites:
                common = s.lockset if common is None else (common & s.lockset)
            if common:
                continue  # every write holds a shared lock
            anchor = min(
                (s for s in sites if not s.lockset),
                default=sites[0],
                key=lambda s: (getattr(s.node, "lineno", 0)),
            )
            shown = ", ".join(sorted(labels)) or "one multi-instance root"
            self._emit(
                "TL001",
                anchor.fn,
                anchor.node,
                f"`self.{attr}` of {cls} is written from {shown} without a "
                "common lock — wrap every write (and the paired reads) in "
                "one `with self._lock:`",
            )

    def _tl002(self) -> None:
        # (a) unbounded queue bridging two roots
        for qkey, bounded in sorted(self.queues.items(), key=str):
            if bounded:
                continue
            ops = [o for o in self.queue_ops if o.qkey == qkey]
            put_labels: Set[str] = set()
            get_labels: Set[str] = set()
            for o in ops:
                labels = self._root_labels(o.fn)
                if o.op.startswith("put"):
                    put_labels |= labels
                else:
                    get_labels |= labels
            if put_labels and get_labels and len(put_labels | get_labels) >= 2:
                ctor_fn, ctor_node = self.queue_ctor[qkey]
                fn = ctor_fn or next(o.fn for o in ops)
                self._emit(
                    "TL002",
                    fn,
                    ctor_node,
                    f"unbounded queue {qkey[-1]!r} bridges producer "
                    f"({', '.join(sorted(put_labels))}) and consumer "
                    f"({', '.join(sorted(get_labels))}) — give it a maxsize "
                    "so a stalled consumer applies backpressure instead of "
                    "filling RAM",
                )
        # (b) blocking op without timeout in a shutdown path
        for o in self.queue_ops:
            if not o.blocking:
                continue
            if o.fn.name in _SHUTDOWN_NAMES:
                self._emit(
                    "TL002",
                    o.fn,
                    o.node,
                    f"blocking `{o.op}` on {o.qkey[-1]!r} inside shutdown "
                    f"path `{o.fn.name}` with no timeout — a dead peer "
                    "thread deadlocks teardown; use a timeout loop that "
                    "checks thread liveness, or the _nowait variant",
                )

    def _tl003(self) -> None:
        shutdown_put_queues: Set[Tuple] = {
            o.qkey
            for o in self.queue_ops
            if o.op.startswith("put") and o.fn.name in _SHUTDOWN_NAMES
        }
        seen: Set[Tuple] = set()
        for o in self.queue_ops:
            if o.op != "get" or not o.blocking or not o.in_loop:
                continue
            if o.qkey in shutdown_put_queues or o.qkey in seen:
                continue
            seen.add(o.qkey)
            self._emit(
                "TL003",
                o.fn,
                o.node,
                f"blocking consumer loop on {o.qkey[-1]!r} has no close-"
                "sentinel `put` reachable from a close()/stop()/shutdown() "
                "method — shutdown can leave this loop blocked forever; "
                "put a sentinel in close() or give the get a timeout",
            )

    def _tl004(self) -> None:
        # interprocedural one-hop: transitive acquires per function
        trans: Dict[FunctionInfo, Set[str]] = {
            f: set(a) for f, a in self.direct_acquires.items()
        }
        changed = True
        while changed:
            changed = False
            for f, edges in self.idx.edges.items():
                cur = trans.setdefault(f, set())
                for g in edges:
                    extra = trans.get(g, set()) - cur
                    if extra:
                        cur |= extra
                        changed = True
        # graph + cycle detection
        graph: Dict[str, Set[str]] = {}
        site: Dict[Tuple[str, str], _LockEdge] = {}
        for e in self.lock_edges:
            graph.setdefault(e.src, set()).add(e.dst)
            site.setdefault((e.src, e.dst), e)
        # augment with call-under-lock edges recorded during the walk
        for e in self._call_under_lock_edges(trans):
            graph.setdefault(e.src, set()).add(e.dst)
            site.setdefault((e.src, e.dst), e)
        reported: Set[frozenset] = set()
        for a in sorted(graph):
            for b in sorted(graph[a]):
                if a == b:
                    cyc = frozenset((a,))
                    if cyc in reported:
                        continue
                    reported.add(cyc)
                    e = site[(a, b)]
                    self._emit(
                        "TL004", e.fn, e.node,
                        f"lock `{a}` re-acquired while already held — a "
                        "non-reentrant Lock self-deadlocks; use RLock or "
                        "restructure",
                    )
                    continue
                if self._reaches(graph, b, a):
                    cyc = frozenset((a, b))
                    if cyc in reported:
                        continue
                    reported.add(cyc)
                    e = site[(a, b)]
                    self._emit(
                        "TL004", e.fn, e.node,
                        f"lock-order cycle: `{a}` -> `{b}` here, but `{b}` "
                        f"-> `{a}` elsewhere — two threads taking the two "
                        "orders deadlock; pick one global order",
                    )

    def _call_under_lock_edges(self, trans) -> List[_LockEdge]:
        """A resolvable call inside `with lock:` pulls in the callee's
        transitive acquisitions as ordered edges."""
        out: List[_LockEdge] = []
        for lockset, callee, caller, node in self.calls_under_lock:
            for dst in trans.get(callee, ()):
                for src in lockset:
                    if src != dst:
                        out.append(_LockEdge(src, dst, caller, node))
                    elif src == dst and dst not in self.rlocks:
                        # call re-acquires a plain Lock the caller holds
                        out.append(_LockEdge(src, dst, caller, node))
        return out

    def _resolved_callees(self, call: ast.Call, ctx) -> List[FunctionInfo]:
        fi, mi = ctx["fi"], ctx["mi"]
        out: List[FunctionInfo] = []
        if isinstance(call.func, ast.Name):
            for t in _resolve_name(self.idx, fi, mi, call.func.id, ctx["aliases"]):
                if isinstance(t, FunctionInfo):
                    out.append(t)
        elif (
            isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"
            and ctx["cls"] is not None
        ):
            m = mi.functions.get(f"{ctx['cls']}.{call.func.attr}")
            if m is not None:
                out.append(m)
        return out

    def _reaches(self, graph: Dict[str, Set[str]], a: str, b: str) -> bool:
        seen: Set[str] = set()
        frontier = [a]
        while frontier:
            x = frontier.pop()
            if x == b:
                return True
            if x in seen:
                continue
            seen.add(x)
            frontier.extend(graph.get(x, ()))
        return False

    def _tl005(self) -> None:
        for fi, node, held in self.sleeps:
            self._emit(
                "TL005",
                fi,
                node,
                f"time.sleep while holding {held} — every thread contending "
                "for the lock serializes behind the sleeper; sleep outside "
                "the critical section or use Condition.wait with a timeout",
            )

    def _tl006(self) -> None:
        daemon_roots = [r for r in self.roots if r.daemon]
        emitted: Set[int] = set()
        for r in daemon_roots:
            for f in reachable_from(self.idx, {r.fn}):
                for node, kind in self.file_writes.get(f, ()):  # noqa: B020
                    if id(node) in emitted:
                        continue
                    emitted.add(id(node))
                    self._emit(
                        "TL006",
                        f,
                        node,
                        f"durable write ({kind}) reachable from daemon "
                        f"thread root {r.label} — daemon threads are killed "
                        "mid-write at interpreter exit; make the thread "
                        "non-daemon or move the write to the controlling "
                        "thread",
                    )


# ----------------------------------------------------------------- drivers


def lint_paths(
    paths: Sequence[str],
    baseline: Optional[str] = None,
    pkg_root: Optional[str] = None,
) -> LintResult:
    idx, roots, attribution = build_thread_index(
        list(paths), pkg_root or package_root()
    )
    col = _Collector(idx, roots, attribution)
    col.collect()
    raw = col.finish()
    base = (
        load_baseline(baseline).restricted(RULES) if baseline else Baseline()
    )
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    excluded: List[Finding] = []
    for f in raw:
        if base.excluded(f):
            excluded.append(f)
            continue
        w = base.waive(f)
        if w is not None:
            suppressed.append((f, w.reason))
        else:
            findings.append(f)
    stale = [w for w in base.waivers if not w.used]
    return LintResult(findings, suppressed, excluded, stale)


def lint_package(baseline: Optional[str] = "default") -> LintResult:
    if baseline == "default":
        import os

        baseline = default_baseline_path()
        if not os.path.exists(baseline):
            baseline = None
    return lint_paths(iter_package_files(), baseline=baseline)
