"""Strict mode: runtime proof of jit hygiene.

jaxlint (analysis/jaxlint.py) reasons about the source; this harness
checks the same contract at runtime, where dynamic feeds and real
shardings live. Under ``debug.strict`` / ``--strict`` the trainer (and
the CLI bounded-step loop) run with:

* ``jax.transfer_guard("disallow")`` engaged globally for the whole
  session — any *implicit* host<->device transfer raises immediately with
  a traceback at the offending line. Explicit ``jax.device_put`` /
  ``jax.device_get`` are exempt by JAX itself, which is exactly the
  contract jaxlint's JX001/JX006 push toward: transfers happen only where
  the code says so.
* a recompile detector around every dispatch site — the first
  ``warmup_dispatches`` calls of each named program are expected to
  compile (and run under a thread-local ``transfer_guard("allow")``,
  since trace-time constant staging is legitimately implicit); after
  that, any growth in the program's jit cache (``fn._cache_size()``) or
  any XLA backend-compile event observed during a warm dispatch raises
  :class:`StrictViolation` naming the program.

The acceptance contract this enforces: post-warmup, N trainer steps
perform **zero** implicit transfers and **zero** recompiles on every
feed (loader, --cache-device, spmd, fused K>1).

Typical wiring (see train/trainer.py)::

    strict = StrictHarness()
    with strict.session():
        for batch in feed:
            with strict.dispatch("train_step", jitted_step):
                state, metrics = jitted_step(state, batch)
    report = strict.report()   # dispatch/compile counts per program
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, Iterator, Optional

import jax

__all__ = ["StrictHarness", "StrictViolation"]


class StrictViolation(RuntimeError):
    """A strict-mode contract was broken (recompile after warmup).

    Implicit-transfer violations surface as JAX's own transfer-guard
    errors, which carry the exact offending line; this exception covers
    the recompile half, naming the program and the evidence.
    """


# One process-wide compile-event counter. jax.monitoring has no
# unregister API, so the listener must be installed once and count into
# module state that outlives any particular harness. The counter is
# guarded by _listener_lock (the XLA client may fire events from a
# compilation thread); harnesses never read it directly — they take
# start/end deltas via compile_event_count() so two sequential (or
# threaded) sessions can't attribute each other's compiles.
_compile_events = 0
_listener_installed = False
_listener_lock = threading.Lock()


def _on_event_duration(event: str, duration: float, **kwargs: Any) -> None:
    global _compile_events
    if "backend_compile" in event:
        with _listener_lock:
            _compile_events += 1


def _install_compile_listener() -> None:
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
        _listener_installed = True


def compile_event_count() -> int:
    """Process-wide XLA backend-compile events seen since the listener
    was installed (0 until a StrictHarness session has run). Read under
    the lock; compare two calls for a session-relative delta."""
    with _listener_lock:
        return _compile_events


class _ProgramState:
    __slots__ = ("dispatches", "warm_dispatches", "cache_size", "compiles_during_warm")

    def __init__(self) -> None:
        self.dispatches = 0
        self.warm_dispatches = 0
        self.cache_size: Optional[int] = None
        self.compiles_during_warm = 0


class StrictHarness:
    """Transfer-guard + recompile gate around dispatch sites.

    ``warmup_dispatches`` — dispatches per program name that are allowed
    to compile (and to transfer implicitly, for trace-time staging)
    before the gate arms. Distinctly-shaped programs (e.g. a fused tail
    chunk with a smaller K) must be given distinct names so each gets
    its own warmup.
    """

    def __init__(self, warmup_dispatches: int = 1) -> None:
        if warmup_dispatches < 1:
            raise ValueError("warmup_dispatches must be >= 1")
        self.warmup_dispatches = warmup_dispatches
        self.programs: Dict[str, _ProgramState] = {}
        self.violations: list[str] = []
        self._active = False
        # per-session compile accounting: events observed during THIS
        # harness's sessions only (start/end deltas of the process-wide
        # counter), so concurrent or back-to-back harnesses don't claim
        # each other's compiles
        self._session_base: Optional[int] = None
        self._session_events = 0

    # ------------------------------------------------------------- session

    @contextlib.contextmanager
    def session(self) -> Iterator["StrictHarness"]:
        """Engage ``transfer_guard("disallow")`` globally and the compile
        listener for the duration of the block."""
        _install_compile_listener()
        prev = getattr(jax.config, "jax_transfer_guard", None)
        jax.config.update("jax_transfer_guard", "disallow")
        self._active = True
        self._session_base = compile_event_count()
        try:
            yield self
        finally:
            self._active = False
            self._session_events += compile_event_count() - self._session_base
            self._session_base = None
            jax.config.update("jax_transfer_guard", prev or "allow")

    # ------------------------------------------------------------ dispatch

    @contextlib.contextmanager
    def dispatch(
        self, program: str, fn: Optional[Callable[..., Any]] = None
    ) -> Iterator[None]:
        """Wrap one dispatch of ``program``.

        ``fn`` is the jitted callable, used for its per-program cache
        size (``_cache_size``); pass the same object every time. During
        warmup the body runs under a thread-local
        ``transfer_guard("allow")``; once warm, the global "disallow"
        stays in force and cache growth / compile events raise.
        """
        st = self.programs.setdefault(program, _ProgramState())
        warm = st.dispatches >= self.warmup_dispatches
        st.dispatches += 1
        compiles_before = compile_event_count()
        cache_before = self._cache_size(fn)
        if warm:
            yield
            st.warm_dispatches += 1
            cache_after = self._cache_size(fn)
            compiled = compile_event_count() - compiles_before
            st.compiles_during_warm += compiled
            evidence = []
            if (
                cache_before is not None
                and cache_after is not None
                and cache_after > cache_before
            ):
                evidence.append(
                    f"jit cache grew {cache_before}->{cache_after}"
                )
            if compiled:
                evidence.append(f"{compiled} backend_compile event(s)")
            if evidence:
                msg = (
                    f"strict mode: program '{program}' recompiled after "
                    f"warmup (dispatch #{st.dispatches}): "
                    + "; ".join(evidence)
                    + " — a shape, dtype, or static-arg value changed "
                    "between steps"
                )
                self.violations.append(msg)
                raise StrictViolation(msg)
        else:
            # Warmup: tracing legitimately stages host constants to
            # device; thread-local guard overrides the global disallow.
            with jax.transfer_guard("allow"):
                yield
            st.cache_size = self._cache_size(fn)

    @staticmethod
    def _cache_size(fn: Optional[Callable[..., Any]]) -> Optional[int]:
        if fn is None:
            return None
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            return None
        try:
            return int(probe())
        except Exception:
            return None

    # -------------------------------------------------------------- report

    def session_compile_events(self) -> int:
        """Compile events attributed to THIS harness's sessions (closed
        sessions' deltas plus the live session's so far) — NOT the
        process-wide total another harness may have grown."""
        live = 0
        if self._active and self._session_base is not None:
            live = compile_event_count() - self._session_base
        return self._session_events + live

    def report(self) -> Dict[str, Any]:
        """Machine-readable summary: per-program dispatch/compile counts
        plus this harness's session-scoped compile-event total."""
        return {
            "active": self._active,
            "warmup_dispatches": self.warmup_dispatches,
            "compile_events_total": self.session_compile_events(),
            "violations": list(self.violations),
            "programs": {
                name: {
                    "dispatches": st.dispatches,
                    "warm_dispatches": st.warm_dispatches,
                    "recompiles_after_warmup": st.compiles_during_warm,
                    "cache_size": st.cache_size,
                }
                for name, st in self.programs.items()
            },
        }

    def check(self) -> None:
        """Raise if any violation was recorded (belt-and-braces for
        callers that swallow exceptions at dispatch sites)."""
        if self.violations:
            raise StrictViolation("; ".join(self.violations))
