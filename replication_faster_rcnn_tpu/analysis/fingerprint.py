"""Compiled-program fingerprints: what the compiler actually emitted.

jaxlint (analysis/jaxlint.py) reasons about Python source; strict mode
(analysis/strict.py) observes the live process. This module captures the
layer between them — the AOT artifacts: for each registered program
(train/warmup.py::build_program_specs) it extracts, from the LOWERED
StableHLO and the COMPILED executable,

* the abstract arg/output shapes, dtypes and shardings,
* the input/output aliasing map (did ``donate_argnums`` survive?),
* the collective inventory (which psums, at which element types — read
  from the lowered IR, because XLA:CPU legalizes bf16 all-reduces to f32
  in the compiled module and would mask the contract),
* HloCostAnalysis flops/bytes (via `benchmark.lowered_cost_analysis`,
  the same pricing the step-profile harness banks), and
* the executable's memory analysis with a peak-HBM estimate
  (arguments + outputs − aliased + temporaries).

Fingerprints serialize to committed JSON banks under
``analysis/fingerprints/`` (`save_bank` / `load_bank`, atomic replace);
`diff_programs` reports field-level drift between a live fingerprint and
a banked one. The contract rules over these records live in
analysis/hlolint.py (HLO contracts + drift) and analysis/shardlint.py
(sharding & collective-cost, over the committed bank only).

jax is imported lazily: everything except `summarize_abstract` /
`fingerprint_program` is pure text/JSON work, and the static consumers
(shardlint, commcost) reuse the parsers here without touching a backend.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional

SCHEMA = "hlo_fingerprint/v1"

# kinds of StableHLO collective ops inventoried from the lowered IR
COLLECTIVE_KINDS = (
    "all_reduce",
    "all_gather",
    "all_to_all",
    "reduce_scatter",
    "collective_permute",
    "collective_broadcast",
)

# `"stablehlo.all_reduce"(%x) <{...}> ({ region }) : (tensor<10x20xbf16>)
# -> ...` — the result element type follows the region close; DOTALL
# because the reduction region spans lines. reduce_scatter carries the
# same reduction-region syntax; all_gather is region-free, so its operand
# type follows the attribute dict directly.
_ALL_REDUCE_RE = re.compile(
    r'"stablehlo\.all_reduce"\(.*?\}\) : \(tensor<([^>]*)>', re.S
)
_ELEMENT_TYPE_RES = {
    "all_reduce": _ALL_REDUCE_RE,
    "reduce_scatter": re.compile(
        r'"stablehlo\.reduce_scatter"\(.*?\}\) : \(tensor<([^>]*)>', re.S
    ),
    "all_gather": re.compile(
        r'"stablehlo\.all_gather"\([^)]*\)\s*<\{.*?\}>\s*:\s*\(tensor<([^>]*)>',
        re.S,
    ),
}
# compiled-module header: `input_output_alias={ {0}: (0, {}, may-alias),
# {1,2}: (3, {}, must-alias), ... }`
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{[^}]*\},\s*(may-alias|must-alias)\)"
)
# element types are the last 'x'-separated token of a tensor type
# (`tensor<4xf64>`) or the whole body for scalars (`tensor<f64>`)
_F64_RE = re.compile(r"[<x]f64>")
# custom calls print either as the pretty form `stablehlo.custom_call
# @target(...)` or the generic form with an explicit attribute
# `call_target_name = "target"`; the same module never mixes both for
# one op, so counting both patterns cannot double-count
_CUSTOM_CALL_RES = (
    re.compile(r"stablehlo\.custom_call\s+@([\w.$-]+)"),
    re.compile(r'call_target_name\s*=\s*"([^"]+)"'),
)


def parse_custom_calls(stablehlo_text: str) -> Dict[str, int]:
    """{call_target_name: count} over a lowered module's custom calls.

    The ops-backend provenance signal for hlolint's HX007: on TPU the
    pallas kernels lower to ``tpu_custom_call`` (Mosaic) targets, while a
    backend=xla program must contain none of them. Empty dict == no
    custom calls at all."""
    counts: Dict[str, int] = {}
    for pattern in _CUSTOM_CALL_RES:
        for target in pattern.findall(stablehlo_text):
            counts[target] = counts.get(target, 0) + 1
    return dict(sorted(counts.items()))


def parse_int8_ops(stablehlo_text: str) -> Dict[str, int]:
    """{op_kind: count} of dot_general/convolution ops with an int8
    operand in a lowered module.

    The quantization provenance signal for hlolint's HX008: a
    ``serve_*__int8`` program with true-int8 GEMMs must show i8 dots,
    and NO other program may contain any — an i8 contraction outside the
    quantized twins means quantized weights leaked into a program whose
    numerics were never calibrated for them."""
    counts: Dict[str, int] = {}
    for line in stablehlo_text.splitlines():
        if "xi8>" not in line:
            continue
        for kind in ("dot_general", "convolution"):
            if f"stablehlo.{kind}" in line:
                counts[kind] = counts.get(kind, 0) + 1
    return dict(sorted(counts.items()))


def module_hash(stablehlo_text: str) -> str:
    """sha256[:16] of the lowered module text — a whole-program identity
    cheap enough to bank. Interpret-mode pallas twins contain no custom
    call on CPU, so this is the only artifact-level evidence that the
    backend scope actually changed the lowered program (HX007 compares a
    twin's hash against its base's)."""
    return hashlib.sha256(stablehlo_text.encode()).hexdigest()[:16]


def parse_alias_map(compiled_text: str) -> List[Dict[str, Any]]:
    """The input/output aliasing entries of a compiled module's text:
    [{"output": "0", "parameter": 0, "kind": "may-alias"}, ...]. Empty
    when the header is absent (nothing donated, or a backend that prints
    no alias table — absence is indistinguishable from no aliasing, which
    is the conservative reading for the donation contract)."""
    if "input_output_alias" not in compiled_text:
        return []
    # the `{out}: (param, {}, kind)` entry shape (with the literal alias
    # kind) only occurs in the module header's alias table; scanning the
    # pre-ENTRY header avoids bracket-matching the nested braces
    header = compiled_text.split("ENTRY", 1)[0]
    out = []
    for om, pm, kind in _ALIAS_ENTRY_RE.findall(header):
        out.append(
            {
                "output": om.replace(" ", ""),
                "parameter": int(pm),
                "kind": kind,
            }
        )
    return out


def parse_collectives(stablehlo_text: str) -> Dict[str, Any]:
    """Inventory of collective ops in a lowered module's StableHLO text.

    {"all_reduce": {"count": N, "element_types": {"bf16": i, "f32": j}},
     "<other kind>": {"count": M}, ...} — kinds with zero occurrences are
    omitted, so an empty dict means a collective-free program."""
    inv: Dict[str, Any] = {}
    for kind in COLLECTIVE_KINDS:
        n = len(re.findall(rf'"?stablehlo\.{kind}"?\(', stablehlo_text))
        if n:
            inv[kind] = {"count": n}
    for kind, pattern in _ELEMENT_TYPE_RES.items():
        if kind not in inv:
            continue
        types: Dict[str, int] = {}
        for tensor in pattern.findall(stablehlo_text):
            elem = tensor.split("x")[-1]
            types[elem] = types.get(elem, 0) + 1
        inv[kind]["element_types"] = dict(sorted(types.items()))
    return inv


# ------------------------------------------------- partitioned collectives
#
# The lowered StableHLO only shows collectives the *program* wrote
# (shard_map bodies). Auto-partitioned programs (pjit with shardings)
# get theirs inserted by GSPMD/ShardingPropagation *after* lowering, so
# the model-parallel weight all-gathers are only visible in the COMPILED
# module's HLO text. Inventory those separately and classify each op's
# replica groups against the (data, model) mesh axes: with the row-major
# device grid `make_mesh` builds, model-axis groups are consecutive runs
# ({{0,1,2,3},{4,5,6,7}} on a (2,4) mesh) and data-axis groups are
# strided ({{0,4},{1,5},{2,6},{3,7}}).

# `%all-reduce.1 = f32[8]{0} all-reduce(%x), channel_id=1,
#  replica_groups={{0,1},{2,3}}, ...` — opcode after `= <shape>`, so the
# instruction *name* (%all-reduce.1) is not double-counted
_PARTITIONED_OP_RE = re.compile(
    r"=\s+\S+\s+"
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start)?\("
)
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{}\s]*\}\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)"
)


def _parse_replica_groups(text: str) -> Optional[List[List[int]]]:
    """Decode one ``replica_groups=`` value into a list of device-id
    groups. Handles the explicit ``{{0,1},{2,3}}`` form and the iota
    form ``[G,S]<=[d0,d1,...]T(perm)`` (reshape iota(prod d) to ``d``,
    transpose by ``perm``, regroup as G rows of S)."""
    text = text.strip()
    if text.startswith("{{"):
        groups = []
        for grp in re.findall(r"\{([\d,\s]*)\}", text[1:-1]):
            ids = [int(t) for t in grp.replace(" ", "").split(",") if t]
            if ids:
                groups.append(ids)
        return groups or None
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?$", text)
    if not m:
        return None
    gshape = [int(t) for t in m.group(1).split(",")]
    dshape = [int(t) for t in m.group(2).split(",")]
    n = 1
    for d in dshape:
        n *= d
    flat = list(range(n))
    # reshape to dshape, apply transpose, flatten (row-major throughout)
    if m.group(3):
        perm = [int(t) for t in m.group(3).split(",")]
        strides = [0] * len(dshape)
        acc = 1
        for i in range(len(dshape) - 1, -1, -1):
            strides[i] = acc
            acc *= dshape[i]
        tshape = [dshape[p] for p in perm]
        tstrides = [strides[p] for p in perm]
        out = []

        def _walk(dim: int, off: int) -> None:
            if dim == len(tshape):
                out.append(off)
                return
            for i in range(tshape[dim]):
                _walk(dim + 1, off + i * tstrides[dim])

        _walk(0, 0)
        flat = out
    if len(gshape) != 2 or gshape[0] * gshape[1] != len(flat):
        return None
    size = gshape[1]
    return [flat[i * size : (i + 1) * size] for i in range(gshape[0])]


def _classify_groups(
    groups: List[List[int]], mesh_shape: Dict[str, int]
) -> str:
    """Which mesh axis a replica-group set spans: 'model' (consecutive
    runs of the minor axis), 'data' (strided over the major axis), 'all'
    (one group of every device), else 'other'. 'world' when the mesh
    shape is unknown/degenerate."""
    n_data = int(mesh_shape.get("data", 0) or 0)
    n_model = int(mesh_shape.get("model", 0) or 0)
    got = {frozenset(g) for g in groups}
    if n_data <= 0 or n_model <= 0:
        return "world"
    n = n_data * n_model
    if got == {frozenset(range(n))}:
        return "all"
    model_axis = {
        frozenset(r * n_model + c for c in range(n_model))
        for r in range(n_data)
    }
    if got == model_axis:
        return "model"
    data_axis = {
        frozenset(r * n_model + c for r in range(n_data))
        for c in range(n_model)
    }
    if got == data_axis:
        return "data"
    return "other"


def parse_partitioned_collectives(
    compiled_text: str, mesh_shape: Optional[Dict[str, int]] = None
) -> Dict[str, Any]:
    """Inventory of collective ops in a COMPILED module's HLO text, with
    per-mesh-axis classification of each op's replica groups:

    {"all-gather": {"count": N, "axes": {"model": i, "data": j}}, ...}

    Kinds with zero occurrences are omitted. ``axes`` buckets: 'model' /
    'data' (one mesh axis each), 'all' (every device in one group),
    'world' (mesh shape unknown), 'other' (anything else)."""
    inv: Dict[str, Any] = {}
    mesh_shape = mesh_shape or {}
    for line in compiled_text.splitlines():
        m = _PARTITIONED_OP_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        entry = inv.setdefault(kind, {"count": 0, "axes": {}})
        entry["count"] += 1
        gm = _REPLICA_GROUPS_RE.search(line)
        groups = _parse_replica_groups(gm.group(1)) if gm else None
        axis = _classify_groups(groups, mesh_shape) if groups else "world"
        entry["axes"][axis] = entry["axes"].get(axis, 0) + 1
    for entry in inv.values():
        entry["axes"] = dict(sorted(entry["axes"].items()))
    return dict(sorted(inv.items()))


def contains_f64(stablehlo_text: str) -> bool:
    """True when any tensor in the lowered IR has element type f64 — the
    silent x64-promotion the dtype contract (HX002) forbids."""
    return _F64_RE.search(stablehlo_text) is not None


def memory_stats(compiled) -> Optional[Dict[str, float]]:
    """The executable's memory analysis as plain floats, plus
    ``peak_bytes_estimate`` = arguments + outputs − aliased + temporaries
    (donated buffers are counted once). None when the backend exposes no
    memory analysis — callers must treat that as "unknown", not "fits"."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "alias_size_in_bytes",
        "temp_size_in_bytes",
        "generated_code_size_in_bytes",
    )
    out = {}
    for f in fields:
        v = getattr(ma, f, None)
        if v is None:
            return None
        out[f] = float(v)
    out["peak_bytes_estimate"] = (
        out["argument_size_in_bytes"]
        + out["output_size_in_bytes"]
        - out["alias_size_in_bytes"]
        + out["temp_size_in_bytes"]
    )
    return out


def summarize_abstract(tree) -> List[Dict[str, Any]]:
    """Flattened [{path, shape, dtype, sharding}] for one abstract
    argument (or output) pytree, in XLA's flat-parameter order."""
    import jax

    leaves = jax.tree_util.tree_leaves_with_path(tree)
    out = []
    for path, leaf in leaves:
        sharding = getattr(leaf, "sharding", None)
        out.append(
            {
                "path": jax.tree_util.keystr(path),
                "shape": list(getattr(leaf, "shape", ())),
                "dtype": str(jax.numpy.dtype(leaf.dtype)),
                "sharding": repr(sharding) if sharding is not None else None,
            }
        )
    return out


def fingerprint_program(spec) -> Dict[str, Any]:
    """AOT-lower and compile one ProgramSpec; return its fingerprint.

    The dtype/collective facts come from the LOWERED StableHLO (the
    program as written — CPU legalization would otherwise rewrite bf16
    collectives out of sight); aliasing and memory from the COMPILED
    executable (the program as it will run); costs from the shared
    HloCostAnalysis helper."""
    import jax

    from replication_faster_rcnn_tpu.analysis import commcost
    from replication_faster_rcnn_tpu.benchmark import lowered_cost_analysis

    jitted, args = spec.build()
    lowered = jitted.lower(*args)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    try:
        compiled_text = compiled.as_text()
    except Exception:  # pragma: no cover - some backends hide HLO text
        compiled_text = ""

    sizes = [len(jax.tree_util.tree_leaves(a)) for a in args]
    params: Dict[str, List[int]] = {}
    start = 0
    for role, n in zip(spec.arg_roles, sizes):
        params[role] = [start, start + n]
        start += n

    try:
        out_tree = jax.eval_shape(jitted, *args)
    except Exception:  # pragma: no cover - defensive; specs are jittable
        out_tree = ()

    # the compiled executable's flat output shardings (repr strings), the
    # ground truth shardlint's SL002/SL004 read; None when the backend
    # doesn't expose them
    try:
        out_shardings = [
            repr(s)
            for s in jax.tree_util.tree_leaves(compiled.output_shardings)
        ]
    except Exception:
        out_shardings = None

    return {
        "program": spec.name,
        "feed": spec.feed,
        "k": spec.k,
        "args": {role: summarize_abstract(a) for role, a in zip(spec.arg_roles, args)},
        "params": params,
        "outputs": summarize_abstract(out_tree),
        "aliasing": parse_alias_map(compiled_text),
        "collectives": parse_collectives(stablehlo),
        "partitioned_collectives": parse_partitioned_collectives(
            compiled_text, spec.meta.get("mesh_shape")
        ),
        "comm": commcost.collect_comm(
            stablehlo, compiled_text, spec.meta.get("mesh_shape")
        ),
        "out_shardings": out_shardings,
        "has_f64": contains_f64(stablehlo),
        "custom_calls": parse_custom_calls(stablehlo),
        "int8_ops": parse_int8_ops(stablehlo),
        "module_hash": module_hash(stablehlo),
        "cost": lowered_cost_analysis(lowered),
        "memory": memory_stats(compiled),
        "meta": dict(spec.meta),
    }


# ------------------------------------------------------------------- bank IO


def default_fingerprint_dir() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "fingerprints")


def bank_path(directory: str, name: str, platform: str) -> str:
    return os.path.join(directory, f"{name}_{platform}.json")


def load_bank(path: str) -> Optional[Dict[str, Any]]:
    """The banked fingerprint record, or None when absent/unreadable
    (callers surface that as the HX006 missing-bank violation)."""
    try:
        with open(path) as f:
            bank = json.load(f)
    except (OSError, ValueError):
        return None
    if bank.get("schema") != SCHEMA:
        return None
    return bank


def save_bank(path: str, bank: Dict[str, Any]) -> None:
    """Atomic write (tmp + os.replace) so a killed re-bank can't leave a
    half-written record for the next audit to choke on."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(bank, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def make_bank(
    programs: Dict[str, Dict[str, Any]],
    platform: str,
    n_devices: int,
    config_summary: Dict[str, Any],
) -> Dict[str, Any]:
    return {
        "schema": SCHEMA,
        "platform": platform,
        "n_devices": n_devices,
        "config": config_summary,
        "programs": programs,
    }


# --------------------------------------------------------------------- drift

# relative tolerances per numeric field: costs are deterministic for an
# unchanged program (any real change moves them), memory estimates wobble
# with XLA's buffer assignment across versions
COST_REL_TOL = 0.02
MEMORY_REL_TOL = 0.25

# structural fields compared exactly. `partitioned_collectives` is
# deliberately absent: pre-existing banks predate the field, and the
# post-partitioning inventory wobbles with XLA's SPMD pass pipeline —
# the hlolint HX003 mp cells assert on the live value instead.
# `custom_calls` / `module_hash` are likewise excluded: banks recorded
# before those fields stay valid, and module text wobbles with the jax
# version — the HX007 ops-backend rule asserts on the live values.
# `int8_ops` follows the same pattern: the HX008 quantization-provenance
# rule asserts on the live inventory, so pre-ISSUE-17 bank entries stay
# bitwise valid. `comm` / `out_shardings` (ISSUE 20) are excluded too:
# the SL005 comm-budget arm compares live-vs-banked wire bytes with its
# own tolerance (the partitioned half wobbles with the SPMD pipeline),
# and out_shardings reprs wobble with the jax version — shardlint parses
# the banked values structurally instead of comparing text.
_EXACT_FIELDS = ("args", "params", "outputs", "aliasing", "collectives", "has_f64")


def _rel_delta(cur: float, banked: float) -> float:
    if banked == 0.0:
        return 0.0 if cur == 0.0 else float("inf")
    return abs(cur - banked) / abs(banked)


def diff_programs(
    current: Dict[str, Any],
    banked: Dict[str, Any],
    cost_tol: float = COST_REL_TOL,
    memory_tol: float = MEMORY_REL_TOL,
) -> List[str]:
    """Field-level drift between one program's live fingerprint and its
    banked record: [] when they agree, else human-readable mismatches."""
    out: List[str] = []
    for field in _EXACT_FIELDS:
        if current.get(field) != banked.get(field):
            out.append(f"{field} changed vs bank")
    for key in ("flops", "bytes_accessed"):
        cur = float(current.get("cost", {}).get(key, 0.0))
        bank = float(banked.get("cost", {}).get(key, 0.0))
        d = _rel_delta(cur, bank)
        if d > cost_tol:
            out.append(
                f"cost.{key} drifted {d:+.1%} (now {cur:.4g}, banked "
                f"{bank:.4g}, tol {cost_tol:.0%})"
            )
    cur_mem, bank_mem = current.get("memory"), banked.get("memory")
    if (cur_mem is None) != (bank_mem is None):
        out.append("memory analysis availability changed vs bank")
    elif cur_mem is not None:
        d = _rel_delta(
            float(cur_mem.get("peak_bytes_estimate", 0.0)),
            float(bank_mem.get("peak_bytes_estimate", 0.0)),
        )
        if d > memory_tol:
            out.append(
                f"memory.peak_bytes_estimate drifted {d:+.1%} "
                f"(tol {memory_tol:.0%})"
            )
    return out
