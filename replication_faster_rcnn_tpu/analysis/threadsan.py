"""threadsan — opt-in runtime lock/queue sanitizer (lightweight lockdep).

The static half (:mod:`analysis.threadlint`) proves contracts about code
it can resolve; this harness watches the contracts it cannot — callables
passed through constructors, attr-of-attr dispatch, locks taken in any
order the scheduler happens to produce. ``--threadsan`` installs it for
the whole run:

* ``threading.Lock`` / ``threading.RLock`` / ``queue.Queue`` factories
  are patched so objects **created by package code** (decided by the
  caller's filename — stdlib and third-party callers get the real thing)
  come back instrumented.
* Every acquisition is recorded against the thread's currently-held
  stack, building a global lock-order graph at runtime. Acquiring B
  while holding A when some thread previously acquired A while holding B
  is a lock-order inversion: the classic AB/BA deadlock, observable even
  when the interleaving that would actually deadlock never happens in
  this run. Default policy raises :class:`LockOrderInversion` (after
  releasing the just-taken lock, so the raise itself cannot wedge).
* Held-duration per lock and live/peak queue depth are exported as
  gauges; :meth:`ThreadSanitizer.register_gauges` plugs them into the
  telemetry watchdog's provider map so every stall snapshot and incident
  carries them. (The watchdog itself attaches all-thread faulthandler
  tracebacks to stall incidents — between the two, a hung run records
  who held what, for how long, and where every thread was.)

Scope and cost: only locks/queues created *after* :meth:`install` and
*by package files* are wrapped — module-level locks created at import
time stay real (they are single-purpose leaf locks; threadlint covers
them statically). Acquisition adds one thread-local list append and,
for first-time edges, one dict insert under a meta-lock — microseconds,
fine for CI tiers and bringup, not meant for production serving.
"""

from __future__ import annotations

import os
import queue as queue_module
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "LockOrderInversion",
    "ThreadSanitizer",
    "current",
]

_CURRENT: Optional["ThreadSanitizer"] = None


def current() -> Optional["ThreadSanitizer"]:
    """The installed sanitizer, if any (None outside --threadsan runs)."""
    return _CURRENT


class LockOrderInversion(RuntimeError):
    """Two locks were acquired in opposite orders by different code paths."""


class _LockProxy:
    """Wraps a real lock; reports acquire/release to the sanitizer.

    Supports the full Lock/RLock surface the package uses: context
    manager, explicit acquire/release, locked().
    """

    __slots__ = ("_lock", "_san", "name", "reentrant")

    def __init__(self, lock, san: "ThreadSanitizer", name: str, reentrant: bool):
        self._lock = lock
        self._san = san
        self.name = name
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            try:
                self._san._note_acquire(self)
            except LockOrderInversion:
                self._lock.release()
                raise
        return ok

    def release(self) -> None:
        self._san._note_release(self)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "_LockProxy":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        kind = "RLock" if self.reentrant else "Lock"
        return f"<threadsan {kind} {self.name}>"


class _SanQueue(queue_module.Queue):
    """queue.Queue that tracks peak depth (updated under the queue's own
    mutex, where qsize is consistent)."""

    def __init__(self, maxsize: int = 0, *, san: "ThreadSanitizer", name: str):
        super().__init__(maxsize)
        self._san = san
        self.tsname = name
        self.peak_depth = 0

    def _put(self, item) -> None:
        super()._put(item)
        depth = len(self.queue)
        if depth > self.peak_depth:
            self.peak_depth = depth


class ThreadSanitizer:
    """Install/uninstall pair (also a context manager) around a run.

    Args:
        raise_on_inversion: raise :class:`LockOrderInversion` in the
            acquiring thread (default). False records only — the run
            finishes and :meth:`report` carries the evidence.
    """

    def __init__(self, raise_on_inversion: bool = True):
        self.raise_on_inversion = raise_on_inversion
        self._meta = threading.Lock()  # real lock: created pre-install
        self._tls = threading.local()
        # (held.name, acquired.name) -> "thread-name @ site" of first sighting
        self._edges: Dict[Tuple[str, str], str] = {}
        self.inversions: List[Dict[str, Any]] = []
        self._held_total_s: Dict[str, float] = {}
        self._held_max_s: Dict[str, float] = {}
        self._acquire_count: Dict[str, int] = {}
        self._queues: List[_SanQueue] = []
        self._lock_count = 0
        self._installed = False
        self._orig: Dict[str, Any] = {}
        here = os.path.abspath(__file__)
        self._pkg_dir = os.path.dirname(os.path.dirname(here)) + os.sep

    # -- installation ------------------------------------------------------

    def install(self) -> "ThreadSanitizer":
        global _CURRENT
        if self._installed:
            return self
        self._orig = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "Queue": queue_module.Queue,
        }
        san = self
        real_lock, real_rlock = self._orig["Lock"], self._orig["RLock"]
        real_queue = self._orig["Queue"]

        def Lock():  # noqa: N802 - must shadow threading.Lock
            if san._caller_in_pkg():
                return san._new_lock(real_lock(), reentrant=False, depth=2)
            return real_lock()

        def RLock():  # noqa: N802
            if san._caller_in_pkg():
                return san._new_lock(real_rlock(), reentrant=True, depth=2)
            return real_rlock()

        def Queue(maxsize: int = 0):  # noqa: N802
            if san._caller_in_pkg():
                q = _SanQueue(maxsize, san=san, name=san._site(depth=2))
                with san._meta:
                    san._queues.append(q)
                return q
            return real_queue(maxsize)

        threading.Lock = Lock
        threading.RLock = RLock
        queue_module.Queue = Queue
        self._installed = True
        _CURRENT = self
        return self

    def uninstall(self) -> None:
        global _CURRENT
        if not self._installed:
            return
        threading.Lock = self._orig["Lock"]
        threading.RLock = self._orig["RLock"]
        queue_module.Queue = self._orig["Queue"]
        self._installed = False
        if _CURRENT is self:
            _CURRENT = None

    def __enter__(self) -> "ThreadSanitizer":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False

    def _caller_in_pkg(self) -> bool:
        # frames: 0=_caller_in_pkg, 1=factory, 2=creating code
        frame = sys._getframe(2)
        return frame.f_code.co_filename.startswith(self._pkg_dir)

    def _site(self, depth: int) -> str:
        frame = sys._getframe(depth + 1)
        fname = frame.f_code.co_filename
        if fname.startswith(self._pkg_dir):
            fname = fname[len(self._pkg_dir):]
        return f"{fname}:{frame.f_lineno}"

    def _new_lock(self, lock, reentrant: bool, depth: int) -> _LockProxy:
        proxy = _LockProxy(lock, self, self._site(depth + 1), reentrant)
        with self._meta:
            self._lock_count += 1
        return proxy

    def wrap_lock(self, name: str, reentrant: bool = False) -> _LockProxy:
        """Explicitly instrumented lock (tests, code outside the package)."""
        ctor = self._orig.get("RLock" if reentrant else "Lock") or (
            threading.RLock if reentrant else threading.Lock
        )
        proxy = _LockProxy(ctor(), self, name, reentrant)
        with self._meta:
            self._lock_count += 1
        return proxy

    # -- event recording ---------------------------------------------------

    def _stack(self) -> List[Tuple[_LockProxy, float]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, proxy: _LockProxy) -> None:
        stack = self._stack()
        now = time.monotonic()
        if any(p is proxy for p, _ in stack):
            # re-entrant re-acquire: no new ordering information
            stack.append((proxy, now))
            return
        if stack:
            tname = threading.current_thread().name
            with self._meta:
                inversion = None
                for held, _ in stack:
                    if held is proxy:
                        continue
                    edge = (held.name, proxy.name)
                    reverse = (proxy.name, held.name)
                    if reverse in self._edges and edge not in self._edges:
                        inversion = {
                            "first": reverse,
                            "second": edge,
                            "thread": tname,
                            "prior": self._edges[reverse],
                        }
                        self.inversions.append(inversion)
                    self._edges.setdefault(edge, f"{tname}")
                if inversion is not None and self.raise_on_inversion:
                    raise LockOrderInversion(
                        f"lock-order inversion in thread {tname!r}: acquired "
                        f"{inversion['second'][1]} while holding "
                        f"{inversion['second'][0]}, but thread "
                        f"{inversion['prior']!r} previously acquired them in "
                        "the opposite order — two such threads interleaved "
                        "deadlock"
                    )
        stack.append((proxy, now))

    def _note_release(self, proxy: _LockProxy) -> None:
        stack = getattr(self._tls, "stack", None)
        if not stack:
            return  # released on a thread that never acquired (Lock-as-event)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is proxy:
                _, t0 = stack.pop(i)
                held = time.monotonic() - t0
                with self._meta:
                    self._held_total_s[proxy.name] = (
                        self._held_total_s.get(proxy.name, 0.0) + held
                    )
                    if held > self._held_max_s.get(proxy.name, 0.0):
                        self._held_max_s[proxy.name] = held
                    self._acquire_count[proxy.name] = (
                        self._acquire_count.get(proxy.name, 0) + 1
                    )
                return

    # -- reporting ---------------------------------------------------------

    def gauges(self) -> Dict[str, Any]:
        """Live sanitizer state, shaped for a watchdog provider: small,
        JSON-safe, never raises."""
        with self._meta:
            max_held = max(self._held_max_s.values(), default=0.0)
            queues = list(self._queues)
            inversions = len(self.inversions)
            locks = self._lock_count
        return {
            "inversions": inversions,
            "locks_tracked": locks,
            "queues_tracked": len(queues),
            "max_lock_held_ms": round(max_held * 1e3, 3),
            "queue_depth": max((q.qsize() for q in queues), default=0),
            "queue_peak_depth": max(
                (q.peak_depth for q in queues), default=0
            ),
        }

    def register_gauges(self, watchdog) -> None:
        """Export gauges into a StallWatchdog's provider map — every stall
        snapshot / incident then carries the sanitizer's view."""
        watchdog.providers["threadsan"] = self.gauges

    def report(self) -> Dict[str, Any]:
        """Full end-of-run summary (also what the CLI prints)."""
        with self._meta:
            held = {
                name: {
                    "acquires": self._acquire_count.get(name, 0),
                    "total_ms": round(self._held_total_s[name] * 1e3, 3),
                    "max_ms": round(self._held_max_s.get(name, 0.0) * 1e3, 3),
                }
                for name in sorted(self._held_total_s)
            }
            queues = {
                q.tsname: {
                    "depth": q.qsize(),
                    "peak_depth": q.peak_depth,
                    "maxsize": q.maxsize,
                }
                for q in self._queues
            }
            inversions = list(self.inversions)
        return {
            "inversions": inversions,
            "locks": held,
            "queues": queues,
            **{
                k: v
                for k, v in self.gauges().items()
                if k in ("locks_tracked", "queues_tracked")
            },
        }
