"""Static collective-communication cost model (pure text work, no jax).

For each collective op in a program's artifacts this module estimates the
WIRE BYTES PER DEVICE a ring implementation moves, from nothing but the
op's tensor type and the participating axis size `n`:

    wire_bytes = tensor_bytes × factor(kind, n)

with the standard ring factors (Rabenseifner-style trees change constants,
not asymptotics, so the ring numbers are the stable thing to bank):

    all_reduce        2(n−1)/n × full          (reduce-scatter + all-gather)
    reduce_scatter     (n−1)/n × full
    all_gather         (n−1)   × shard   ==    (n−1)/n × full
    all_to_all         (n−1)/n × full
    collective_permute       1 × tensor        (one send per device)

Two inventories, two bases — matching how analysis/fingerprint.py splits
the collective story:

* LOWERED (StableHLO): collectives the program *wrote* (shard_map
  bodies). Operand types are read from the lowered text, where
  all_reduce/reduce_scatter operands are the FULL per-device tensor and
  all_gather operands are the SHARD. The participating axis is the mesh's
  data axis (the only axis shard_map programs collect over here).
* PARTITIONED (compiled HLO): collectives GSPMD inserted after lowering.
  Result shapes are read from the compiled text — all-reduce/all-gather
  results are the FULL (per-device) tensor, reduce-scatter results the
  SHARD — and each op's replica groups are classified against the mesh
  axes by fingerprint's parser to pick `n`.

A program's headline `wire_bytes_per_device` uses the lowered inventory
when one exists (shard_map feeds: the compiled text re-shows the same
ops, but XLA:CPU legalizes bf16 collectives to f32 there, inflating the
estimate) and falls back to the partitioned inventory for pjit/GSPMD
programs, whose lowered text has no collectives at all. The `basis` field
records which. shardlint's SL005 gates this number against
`analysis.comm_budget_bytes`; `frcnn audit` re-derives it live and fails
on drift from the bank.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from replication_faster_rcnn_tpu.analysis import fingerprint as _fp

# lowered operand-type regexes: reuse fingerprint's ar/rs/ag patterns and
# extend with the region-free kinds it has no size patterns for
_LOWERED_OPERAND_RES = dict(_fp._ELEMENT_TYPE_RES)
_LOWERED_OPERAND_RES["all_to_all"] = re.compile(
    r'"stablehlo\.all_to_all"\([^)]*\)\s*<\{.*?\}>\s*:\s*\(tensor<([^>]*)>',
    re.S,
)
_LOWERED_OPERAND_RES["collective_permute"] = re.compile(
    r'"stablehlo\.collective_permute"\([^)]*\)\s*<\{.*?\}>\s*:\s*'
    r"\(tensor<([^>]*)>",
    re.S,
)

# compiled-HLO instruction line: `%name = <result types> <opcode>(...)`
# where the result is either one `f32[2,64]{1,0}` or a tuple of them
_HLO_LINE_RE = re.compile(
    r"=\s+(?P<res>\(?[a-z]\w*\[[^=]*?)\s+"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)"
    r"(?:-start)?\("
)
_HLO_SHAPE_RE = re.compile(r"([a-z]\w*)\[([\d,]*)\]")

# wire-byte factor per unit of the FULL per-device tensor
_FULL_FACTORS = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "all-reduce": lambda n: 2.0 * (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "reduce-scatter": lambda n: (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "all-gather": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "all-to-all": lambda n: (n - 1) / n,
    "collective_permute": lambda n: 1.0,
    "collective-permute": lambda n: 1.0,
    "collective_broadcast": lambda n: 1.0,
}


def dtype_bytes(name: str) -> int:
    """Bytes per element for a StableHLO/HLO element-type name ('bf16',
    'f32', 's32', 'i1', 'pred', 'u8', ...). Sub-byte types round up."""
    if name == "pred":
        return 1
    m = re.search(r"(\d+)$", name)
    if not m:
        raise ValueError(f"unrecognized element type {name!r}")
    return max(1, int(m.group(1)) // 8)


def tensor_type_bytes(tensor: str) -> int:
    """Bytes of one StableHLO tensor-type body, e.g. '512x21xbf16' ->
    21504, 'f32' (scalar) -> 4."""
    parts = tensor.strip().split("x")
    elems = 1
    for p in parts[:-1]:
        elems *= int(p)
    return elems * dtype_bytes(parts[-1])


def _hlo_result_bytes(res: str) -> int:
    """Bytes of a compiled-HLO result chunk — one shape or a tuple of
    shapes, e.g. '(f32[4]{0}, f32[8]{0})'."""
    total = 0
    for elem, dims in _HLO_SHAPE_RE.findall(res):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        total += elems * dtype_bytes(elem)
    return total


def lowered_comm(
    stablehlo_text: str, mesh_shape: Optional[Dict[str, int]] = None
) -> Dict[str, Any]:
    """Per-kind {ops, operand_bytes, wire_bytes} over a lowered module's
    hand-written collectives, pricing each op on the mesh's data axis
    (n=1 -> zero wire bytes: nothing crosses a device boundary)."""
    n = int((mesh_shape or {}).get("data", 1) or 1)
    inv: Dict[str, Any] = {}
    for kind, pattern in _LOWERED_OPERAND_RES.items():
        sizes = [tensor_type_bytes(t) for t in pattern.findall(stablehlo_text)]
        if not sizes:
            continue
        operand = sum(sizes)
        if kind == "all_gather":
            # the lowered operand is the shard; (n−1) × shard on the wire
            wire = (n - 1) * operand
        else:
            wire = _FULL_FACTORS[kind](n) * operand if n > 1 else 0.0
        inv[kind] = {
            "ops": len(sizes),
            "operand_bytes": int(operand),
            "wire_bytes": int(round(wire)),
        }
    return dict(sorted(inv.items()))


def _axis_size(axis: str, mesh_shape: Dict[str, int]) -> int:
    """Participant count for one classified replica-group bucket: a named
    mesh axis uses its declared size; 'all'/'world'/'other' conservatively
    use the whole device grid."""
    if axis in mesh_shape:
        return max(1, int(mesh_shape[axis] or 1))
    total = 1
    for s in mesh_shape.values():
        total *= max(1, int(s or 1))
    return max(2, total)


def partitioned_comm(
    compiled_text: str, mesh_shape: Optional[Dict[str, int]] = None
) -> Dict[str, Any]:
    """Per-kind {ops, result_bytes, wire_bytes, axes:{axis: {...}}} over a
    COMPILED module's collectives, result shapes priced per classified
    replica-group axis. reduce-scatter results are shards, so their wire
    factor is (n−1) × result; all-reduce/all-gather results are full."""
    mesh_shape = dict(mesh_shape or {})
    inv: Dict[str, Any] = {}
    for line in compiled_text.splitlines():
        m = _HLO_LINE_RE.search(line)
        if not m:
            continue
        kind = m.group("kind")
        size = _hlo_result_bytes(m.group("res"))
        gm = _fp._REPLICA_GROUPS_RE.search(line)
        groups = _fp._parse_replica_groups(gm.group(1)) if gm else None
        axis = (
            _fp._classify_groups(groups, mesh_shape) if groups else "world"
        )
        n = _axis_size(axis, mesh_shape)
        if kind == "reduce-scatter":
            wire = (n - 1) * size
        else:
            wire = _FULL_FACTORS[kind](n) * size if n > 1 else 0.0
        entry = inv.setdefault(
            kind, {"ops": 0, "result_bytes": 0, "wire_bytes": 0, "axes": {}}
        )
        entry["ops"] += 1
        entry["result_bytes"] += size
        entry["wire_bytes"] += int(round(wire))
        a = entry["axes"].setdefault(
            axis, {"ops": 0, "result_bytes": 0, "wire_bytes": 0}
        )
        a["ops"] += 1
        a["result_bytes"] += size
        a["wire_bytes"] += int(round(wire))
    for entry in inv.values():
        entry["axes"] = dict(sorted(entry["axes"].items()))
    return dict(sorted(inv.items()))


def collect_comm(
    stablehlo_text: str,
    compiled_text: str,
    mesh_shape: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """The full comm record fingerprint_program banks: both inventories,
    the chosen basis, and the headline wire_bytes_per_device."""
    lowered = lowered_comm(stablehlo_text, mesh_shape)
    partitioned = partitioned_comm(compiled_text, mesh_shape)
    if lowered:
        basis = "lowered"
        total = sum(e["wire_bytes"] for e in lowered.values())
    elif partitioned:
        basis = "partitioned"
        total = sum(e["wire_bytes"] for e in partitioned.values())
    else:
        basis = "none"
        total = 0
    return {
        "lowered": lowered,
        "partitioned": partitioned,
        "basis": basis,
        "wire_bytes_per_device": int(total),
    }


def recompute_wire_total(comm: Dict[str, Any]) -> Optional[int]:
    """Re-derive wire_bytes_per_device from a banked comm record's own
    per-kind tallies — shardlint's SL005 self-consistency check against a
    hand-edited bank. None when the record is too malformed to re-sum."""
    try:
        basis = comm["basis"]
        if basis == "none":
            return 0
        inv = comm[basis]
        return int(sum(int(e["wire_bytes"]) for e in inv.values()))
    except (KeyError, TypeError, ValueError):
        return None
