"""obslint — AST lint for the unified-metrics contract.

PR 16 replaced every hand-rolled stats dict (engine, batcher, router,
breakers) with instruments owned by
:class:`~replication_faster_rcnn_tpu.telemetry.metrics.MetricsRegistry`:
counters/gauges/histograms carry their own locks, and the ``/stats`` /
``/metrics`` render paths read them back out of the registry.  The
contract only holds if nobody quietly grows a new mutable stats dict on
the side — the exact drift this analyzer gates:

  OB001  mutation of a shared stats mapping (an attribute named
         ``stats``/``*_stats``/``_counters``) outside ``__init__``:
         subscript assignment/augmented assignment or a mutating method
         call (``update``/``setdefault``/``pop``/``clear``/...).
         Construction in ``__init__`` is pre-publication and exempt;
         reads are always fine; ``telemetry/metrics.py`` itself (the
         registry the rule points at) is exempt.

Pure AST, no call graph: the naming convention IS the contract (a
shared stats surface not named like one is invisible here — threadlint's
TL001 still covers it as a plain unlocked shared write).  Findings
resolve against the same ``analysis/baseline.toml`` as jaxlint and
threadlint and ship through ``frcnn check`` (``--rules OB001``).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

from replication_faster_rcnn_tpu.analysis.jaxlint import (
    Baseline,
    Finding,
    Waiver,
    default_baseline_path,
    iter_package_files,
    load_baseline,
    package_root,
)

RULES: Dict[str, str] = {
    "OB001": (
        "shared stats mapping mutated outside MetricsRegistry "
        "(use registry counters/gauges/histograms)"
    ),
}

# attribute names that declare "I am a stats surface"
_STATS_ATTR_RE = re.compile(r"^_?(stats|counters)$|_stats$")

# method calls that mutate a dict in place
_DICT_MUTATORS = {
    "update",
    "setdefault",
    "pop",
    "popitem",
    "clear",
    "__setitem__",
}

_INIT_NAMES = {"__init__", "__post_init__", "__new__"}

# the registry module itself owns its tables
_EXEMPT_SUFFIXES = (os.path.join("telemetry", "metrics.py"),)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: List[Tuple[Finding, str]]
    excluded: List[Finding]
    stale_waivers: List[Waiver]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": RULES,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "reason": r} for f, r in self.suppressed
            ],
            "excluded_count": len(self.excluded),
            "stale_waivers": [dataclasses.asdict(w) for w in self.stale_waivers],
            "ok": not self.findings and not self.stale_waivers,
        }


def _stats_attr(node: ast.AST) -> Optional[str]:
    """``<expr>.<attr>`` where attr names a stats surface -> dotted-ish
    label for the message (``self.stats``, ``router.stats``)."""
    if not isinstance(node, ast.Attribute):
        return None
    if not _STATS_ATTR_RE.search(node.attr):
        return None
    base = node.value
    if isinstance(base, ast.Name):
        return f"{base.id}.{node.attr}"
    if isinstance(base, ast.Attribute):
        return f"<expr>.{base.attr}.{node.attr}"
    return f"<expr>.{node.attr}"


class _Visitor(ast.NodeVisitor):
    def __init__(self, rel_path: str) -> None:
        self.rel_path = rel_path
        self.findings: List[Finding] = []
        self._func_stack: List[str] = []

    # ------------------------------------------------------- scope tracking

    def _qualname(self) -> str:
        return ".".join(self._func_stack) if self._func_stack else "<module>"

    def _in_init(self) -> bool:
        return bool(self._func_stack) and (
            self._func_stack[-1] in _INIT_NAMES
        )

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # ------------------------------------------------------------ the rule

    def _emit(self, node: ast.AST, label: str, how: str) -> None:
        self.findings.append(
            Finding(
                rule="OB001",
                path=self.rel_path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                func=self._qualname(),
                message=(
                    f"{how} on shared stats mapping {label!r} outside "
                    "MetricsRegistry — register a counter/gauge/histogram "
                    "instead of mutating a dict"
                ),
            )
        )

    def _check_store_target(self, target: ast.AST, node: ast.AST) -> None:
        # self.stats["k"] = v  /  self.stats["k"] += 1
        if isinstance(target, ast.Subscript):
            label = _stats_attr(target.value)
            if label is not None and not self._in_init():
                self._emit(node, label, "subscript write")

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_store_target(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_target(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_store_target(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._check_store_target(t, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # self.stats.update(...) and friends
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in _DICT_MUTATORS
        ):
            label = _stats_attr(fn.value)
            if label is not None and not self._in_init():
                self._emit(node, label, f".{fn.attr}() call")
        self.generic_visit(node)


def _rel(path: str, pkg_root: str) -> str:
    # repo-relative posix path, matching callgraph.parse_modules so the
    # shared baseline's waiver paths resolve identically across analyzers
    repo_root = os.path.dirname(os.path.abspath(pkg_root))
    ap = os.path.abspath(path)
    if ap.startswith(repo_root + os.sep):
        return os.path.relpath(ap, repo_root).replace(os.sep, "/")
    return os.path.basename(ap)


def lint_paths(
    paths: Sequence[str],
    baseline: Optional[str] = None,
    pkg_root: Optional[str] = None,
) -> LintResult:
    root = pkg_root or package_root()
    raw: List[Finding] = []
    for path in paths:
        if any(str(path).endswith(sfx) for sfx in _EXEMPT_SUFFIXES):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=str(path))
        except (OSError, SyntaxError):
            continue  # unreadable/unparseable files are other gates' problem
        visitor = _Visitor(_rel(str(path), root))
        visitor.visit(tree)
        raw.extend(visitor.findings)
    base = (
        load_baseline(baseline).restricted(RULES) if baseline else Baseline()
    )
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    excluded: List[Finding] = []
    for f in raw:
        if base.excluded(f):
            excluded.append(f)
            continue
        w = base.waive(f)
        if w is not None:
            suppressed.append((f, w.reason))
        else:
            findings.append(f)
    stale = [w for w in base.waivers if not w.used]
    return LintResult(findings, suppressed, excluded, stale)


def lint_package(baseline: Optional[str] = "default") -> LintResult:
    if baseline == "default":
        baseline = default_baseline_path()
        if not os.path.exists(baseline):
            baseline = None
    return lint_paths(iter_package_files(), baseline=baseline)
