"""Shared AST call-graph machinery for the analysis/ analyzers.

Extracted from `analysis/jaxlint.py` (PR 5) so that analyzers with
different *roots* can share one resolution engine: jaxlint walks the
graph from every ``jax.jit``/``shard_map`` entry point, threadlint from
every thread entry point (``threading.Thread(target=...)``, ``Thread``
subclass ``run``, HTTP handler methods, pool-submitted callables). The
machinery here is root-agnostic:

* **Module index** — per-module import tables (absolute, relative and
  aliased imports; module-level simple aliases like
  ``_shard_map = jax.shard_map``), every function/method/nested def as a
  :class:`FunctionInfo` with qualname, scope chain and parameter list.
* **Resolution** — a name or attribute expression to the
  :class:`FunctionInfo`\\ (s) it can denote: local scope, module top
  level, imports (including package ``__init__`` re-exports),
  ``self.attr`` bindings recorded in ``ModuleInfo.class_attrs``, factory
  returns (``jax.jit(make_step(...))``), tuple-assignment aliasing and
  ``functools.partial`` wrappers.
* **Edges + reachability** — a call-graph edge set per function that
  also follows function-reference arguments (``lax.scan(body, ...)``,
  ``value_and_grad(loss_fn)``, ``tree_map(keep, ...)``) and flax
  ``.apply(..., method="name")`` dynamic dispatch, plus a BFS helper.

Analyzer-specific discovery (which functions are roots, what donation or
static-arg metadata means) stays in the analyzers; they populate
``Index.roots`` / ``Index.donating`` / ``Index.static_args`` themselves.

The jit/shard_map wrapper names live here (not in jaxlint) because
:func:`_callable_from_expr` must see through ``jax.jit(fn)`` to resolve
the underlying callable — that is a resolution concern, independent of
which rules run over the result.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

# parameters that are static by convention even without an annotation
# (cfg/config are the repo's frozen host dataclasses)
_STATIC_PARAM_NAMES = {"self", "cls", "train", "training", "deterministic", "cfg", "config"}
# annotation heads that mark a parameter host-static
_STATIC_ANNOTATION_HEADS = {"bool", "int", "str", "float", "Sequence", "Tuple", "tuple", "List", "list", "Dict", "dict"}

_JIT_NAMES = {"jax.jit"}
_SHARD_MAP_NAMES = {
    "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
}
_REMAT_NAMES = {"flax.linen.remat", "nn.remat", "jax.checkpoint", "jax.remat"}


def _annotation_static(ann: Optional[str]) -> bool:
    """True when the annotation names a host-side (non-array) type:
    scalars, host containers, Optional/| None of those, and the repo's
    frozen ``*Config`` dataclasses."""
    if ann is None:
        return False
    ann = ann.strip()
    if ann.startswith("Optional[") and ann.endswith("]"):
        ann = ann[len("Optional["):-1].strip()
    if ann.endswith("| None"):
        ann = ann[: -len("| None")].strip()
    head = ann.split("[", 1)[0].split(".")[-1]
    return head in _STATIC_ANNOTATION_HEADS or head.endswith("Config")


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain; 'self.x' for self attributes."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        # e.g. tspans.current_tracer().span — dotted of the outer attrs only
        inner = _dotted(node.func)
        if inner is not None and parts:
            return inner + "()." + ".".join(reversed(parts))
    return None


def _ann_str(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - defensive
        return None


class FunctionInfo:
    def __init__(self, module: "ModuleInfo", qualname: str, node: ast.AST,
                 parent: Optional["FunctionInfo"], cls: Optional[str]):
        self.module = module
        self.qualname = qualname
        self.node = node
        self.parent = parent
        self.cls = cls  # enclosing class name, if a method
        self.nested: Dict[str, FunctionInfo] = {}
        self.jit_reachable = False
        self._returns_tracer: Optional[bool] = None
        self._return_elts: Optional[List[List[Optional[ast.AST]]]] = None
        # static params: annotated host types, conventional names, and any
        # marked by a static_argnums/argnames jit/remat wrapper
        self.params: List[str] = []
        self.static_params: Set[str] = set()
        args = getattr(node, "args", None)
        if args is not None:
            allargs = (
                list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
            )
            for a in allargs:
                self.params.append(a.arg)
                if a.arg in _STATIC_PARAM_NAMES or _annotation_static(
                    _ann_str(a.annotation)
                ):
                    self.static_params.add(a.arg)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    def owner_class(self) -> Optional[str]:
        """The class this function belongs to, walking out of nested defs
        (a closure inside a method belongs to the method's class)."""
        fi: Optional[FunctionInfo] = self
        while fi is not None:
            if fi.cls is not None:
                return fi.cls
            fi = fi.parent
        return None

    def returns(self) -> List[List[Optional[ast.AST]]]:
        """Per-return list of element exprs ([expr] or tuple elements)."""
        if self._return_elts is None:
            elts: List[List[Optional[ast.AST]]] = []
            body = getattr(self.node, "body", [])
            for stmt in ast.walk(ast.Module(body=list(body), type_ignores=[])):
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # walk() still descends; nested returns filtered below
            for stmt in _returns_of(self.node):
                v = stmt.value
                if isinstance(v, ast.Tuple):
                    elts.append(list(v.elts))
                else:
                    elts.append([v])
            self._return_elts = elts
        return self._return_elts


def _returns_of(fn_node: ast.AST) -> List[ast.Return]:
    """Return statements belonging to fn_node itself (not nested defs)."""
    out: List[ast.Return] = []

    def visit(stmts):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                out.append(s)
            for attr in ("body", "orelse", "finalbody"):
                visit(getattr(s, attr, []))
            for h in getattr(s, "handlers", []):
                visit(h.body)

    visit(getattr(fn_node, "body", []))
    return out


class ModuleInfo:
    def __init__(self, path: str, relpath: str, modname: str, tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.modname = modname  # dotted, e.g. pkg.train.trainer
        self.tree = tree
        self.imports: Dict[str, str] = {}  # local name -> dotted target
        self.functions: Dict[str, FunctionInfo] = {}  # qualname -> info
        self.toplevel: Dict[str, FunctionInfo] = {}
        # class name -> attr name -> list of resolution dicts
        self.class_attrs: Dict[str, Dict[str, List[Dict[str, Any]]]] = {}
        # class name -> list of base-class dotted names (import-resolved)
        self.class_bases: Dict[str, List[str]] = {}


class Index:
    """Cross-module symbol index + call graph + root reachability."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleInfo] = {}  # modname -> info
        self.by_dotted: Dict[str, FunctionInfo] = {}  # pkg.mod.qualname -> fn
        self.methods_by_name: Dict[str, List[FunctionInfo]] = {}
        self.edges: Dict[FunctionInfo, Set[FunctionInfo]] = {}
        self.roots: Set[FunctionInfo] = set()
        # donating callables: identifier -> donated positional indices.
        # identifiers: "Class.attr" for self-attrs, "mod.qual" for locals
        self.donating: Dict[str, Tuple[int, ...]] = {}
        # static-arg callables: dotted fn -> static param names
        self.static_args: Dict[str, Set[str]] = {}
        # memo caches (also cycle-breakers for mutually-recursive factories)
        self._returned_memo: Dict[Any, Tuple[List[FunctionInfo], Optional[Tuple[int, ...]]]] = {}
        self._aliases_memo: Dict["FunctionInfo", Dict[str, List[Any]]] = {}


def _module_name(path: str, package_root: str) -> str:
    rel = os.path.relpath(path, os.path.dirname(package_root))
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace(os.sep, ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _collect_imports(mi: ModuleInfo) -> None:
    pkg_parts = mi.modname.split(".")
    for node in ast.walk(mi.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mi.imports[alias.asname or alias.name.split(".")[0]] = (
                    alias.name if alias.asname else alias.name.split(".")[0]
                )
                if alias.asname:
                    mi.imports[alias.asname] = alias.name
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import
                base = pkg_parts[: -(node.level)]
                mod = ".".join(base + ([node.module] if node.module else []))
            else:
                mod = node.module or ""
            for alias in node.names:
                mi.imports[alias.asname or alias.name] = f"{mod}.{alias.name}"
    # module-level simple aliases (e.g. `_shard_map = jax.shard_map`)
    for stmt in mi.tree.body:
        if isinstance(stmt, (ast.If, ast.Try)):
            bodies = [stmt.body] + [getattr(stmt, "orelse", [])]
            for b in bodies:
                for s in b:
                    _maybe_module_alias(mi, s)
        else:
            _maybe_module_alias(mi, stmt)


def _maybe_module_alias(mi: ModuleInfo, stmt: ast.stmt) -> None:
    if (
        isinstance(stmt, ast.Assign)
        and len(stmt.targets) == 1
        and isinstance(stmt.targets[0], ast.Name)
    ):
        d = _dotted(stmt.value)
        if d is not None:
            root = d.split(".")[0]
            resolved = mi.imports.get(root)
            if resolved is not None:
                d = resolved + d[len(root):]
            mi.imports.setdefault(stmt.targets[0].id, d)


def _collect_functions(mi: ModuleInfo) -> None:
    def visit(stmts, prefix: str, parent: Optional[FunctionInfo], cls: Optional[str]):
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{s.name}" if prefix else s.name
                fi = FunctionInfo(mi, qual, s, parent, cls)
                mi.functions[qual] = fi
                if parent is None and cls is None:
                    mi.toplevel[s.name] = fi
                elif parent is not None:
                    parent.nested[s.name] = fi
                visit(s.body, qual + ".", fi, None)
            elif isinstance(s, ast.ClassDef):
                mi.class_bases[s.name] = [
                    _resolve_dotted_prefix(mi, d)
                    for d in (_dotted(b) for b in s.bases)
                    if d is not None
                ]
                visit(s.body, f"{prefix}{s.name}.", None, s.name)
            elif isinstance(s, (ast.If, ast.Try, ast.With, ast.For, ast.While)):
                for attr in ("body", "orelse", "finalbody"):
                    visit(getattr(s, attr, []), prefix, parent, cls)
                for h in getattr(s, "handlers", []):
                    visit(h.body, prefix, parent, cls)

    visit(mi.tree.body, "", None, None)


def parse_modules(paths: Sequence[str], package_root: str) -> Index:
    """Parse ``paths`` into an :class:`Index` with modules, the dotted
    symbol table, the method-name table and resolved ``self.attr``
    bindings — everything except roots/edges, which are the analyzer's
    job (call :func:`build_edges` after populating ``idx.roots``)."""
    idx = Index()
    repo_root = os.path.dirname(os.path.abspath(package_root))
    for path in paths:
        with open(path) as f:
            src = f.read()
        tree = ast.parse(src, filename=path)
        ap = os.path.abspath(path)
        if ap.startswith(repo_root + os.sep):
            rel = os.path.relpath(ap, repo_root)
        else:
            rel = os.path.basename(ap)
        mi = ModuleInfo(ap, rel.replace(os.sep, "/"), _module_name(ap, package_root), tree)
        _collect_imports(mi)
        _collect_functions(mi)
        idx.modules[mi.modname] = mi
        for qual, fi in mi.functions.items():
            idx.by_dotted[f"{mi.modname}.{qual}"] = fi
            idx.methods_by_name.setdefault(fi.name, []).append(fi)
    _resolve_class_attrs(idx)
    return idx


# ------------------------------------------------------------- resolution


def _resolve_dotted_prefix(mi: ModuleInfo, dotted: str) -> str:
    """Substitute the leading import alias in a dotted chain."""
    root, _, rest = dotted.partition(".")
    target = mi.imports.get(root)
    if target is None:
        return dotted
    return f"{target}.{rest}" if rest else target


def _resolve_name(
    idx: Index, fn: Optional[FunctionInfo], mi: ModuleInfo, name: str,
    aliases: Optional[Dict[str, List[Any]]] = None, _depth: int = 0,
) -> List[Any]:
    """Resolve a bare name to FunctionInfo(s) or a dotted external string."""
    if _depth > 6:
        return []
    if aliases and name in aliases:
        out: List[Any] = []
        for tgt in aliases[name]:
            if isinstance(tgt, str):
                out.extend(
                    _resolve_name(idx, fn, mi, tgt, aliases=None, _depth=_depth + 1)
                )
            else:
                out.append(tgt)
        if out:
            return out
    scope = fn
    while scope is not None:
        if name in scope.nested:
            return [scope.nested[name]]
        if scope.cls is None and scope.parent is None and name == scope.name:
            break
        scope = scope.parent
    if name in mi.toplevel:
        return [mi.toplevel[name]]
    if name in mi.imports:
        dotted = mi.imports[name]
        target = idx.by_dotted.get(dotted)
        if target is not None:
            return [target]
        # maybe a re-export through an __init__: try "<mod>.<name>" tails
        for modname, m in idx.modules.items():
            if dotted == f"{modname}.{name}" and name in m.toplevel:
                return [m.toplevel[name]]
        # package __init__ re-export: resolve one indirection
        mod_part = dotted.rsplit(".", 1)[0]
        m = idx.modules.get(mod_part)
        if m is not None and name in m.imports:
            return _resolve_name(idx, None, m, name, _depth=_depth + 1)
        return [dotted]
    return []


def _resolve_callee(
    idx: Index, fn: Optional[FunctionInfo], mi: ModuleInfo, node: ast.AST,
    aliases: Optional[Dict[str, List[Any]]] = None,
) -> List[Any]:
    """Resolve a call target expr to FunctionInfo(s) and/or dotted strings."""
    if isinstance(node, ast.Name):
        return _resolve_name(idx, fn, mi, node.id, aliases)
    if isinstance(node, ast.Attribute):
        d = _dotted(node)
        if d is None:
            return []
        if d.startswith("self.") and fn is not None and fn.cls is not None:
            entries = mi.class_attrs.get(fn.cls, {}).get(d[len("self."):], [])
            out = []
            for e in entries:
                if e.get("func") is not None:
                    out.append(e["func"])
            return out or [d]
        resolved = _resolve_dotted_prefix(mi, d)
        target = idx.by_dotted.get(resolved)
        if target is not None:
            return [target]
        # a method path like pkg.mod.Class.method
        return [resolved]
    return []


def _callable_from_expr(
    idx: Index, fn: Optional[FunctionInfo], mi: ModuleInfo, expr: ast.AST,
    aliases: Optional[Dict[str, List[Any]]] = None, _depth: int = 0,
) -> Tuple[List[FunctionInfo], Optional[Tuple[int, ...]]]:
    """(functions, donate) for an expr that evaluates to a callable.

    Handles: a bare function reference, ``jax.jit(fn, ...)``,
    ``shard_map(fn, ...)``, ``partial(jax.jit, ...)`` decorators, a
    factory call whose return is a nested def, and aliases of any of
    those. ``donate`` is the donate_argnums tuple if a jit wrapper in the
    chain donates.
    """
    if _depth > 6:
        return [], None
    donate: Optional[Tuple[int, ...]] = None
    if isinstance(expr, (ast.Name, ast.Attribute)):
        targets = _resolve_callee(idx, fn, mi, expr, aliases)
        return [t for t in targets if isinstance(t, FunctionInfo)], None
    if isinstance(expr, ast.Call):
        callee = _resolve_callee(idx, fn, mi, expr.func, aliases)
        dotted = [t for t in callee if isinstance(t, str)]
        fis = [t for t in callee if isinstance(t, FunctionInfo)]
        if any(d in _JIT_NAMES for d in dotted):
            for kw in expr.keywords:
                if kw.arg == "donate_argnums":
                    donate = _int_tuple(kw.value)
            if expr.args:
                inner, inner_donate = _callable_from_expr(
                    idx, fn, mi, expr.args[0], aliases, _depth + 1
                )
                return inner, donate if donate is not None else inner_donate
            return [], donate
        if any(d in _SHARD_MAP_NAMES for d in dotted):
            if expr.args:
                return (
                    _callable_from_expr(idx, fn, mi, expr.args[0], aliases, _depth + 1)[0],
                    None,
                )
            return [], None
        if any(d.endswith("functools.partial") or d == "partial" for d in dotted):
            if expr.args:
                return _callable_from_expr(
                    idx, fn, mi, expr.args[0], aliases, _depth + 1
                )
            return [], None
        # factory call: follow the factory's returned function(s)
        out: List[FunctionInfo] = []
        for factory in fis:
            rf, rd = _returned_functions(idx, factory, index=None)
            out.extend(rf)
            donate = donate if donate is not None else rd
        return out, donate
    return [], None


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                vals.append(e.value)
        return tuple(vals)
    return None


def _str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    return ()


def _returned_functions(
    idx: Index, factory: FunctionInfo, index: Optional[int]
) -> Tuple[List[FunctionInfo], Optional[Tuple[int, ...]]]:
    """Functions a factory returns (element ``index`` of tuple returns,
    or any element when None); plus donate info from a jit wrapper."""
    memo_key = (factory, index)
    if memo_key in idx._returned_memo:
        return idx._returned_memo[memo_key]
    # seed with the empty answer to cut cycles (mutually-recursive
    # factories resolve to nothing rather than recursing forever)
    idx._returned_memo[memo_key] = ([], None)
    out: List[FunctionInfo] = []
    donate: Optional[Tuple[int, ...]] = None
    aliases = _local_aliases(idx, factory)
    for elts in factory.returns():
        chosen = elts if index is None else (
            [elts[index]] if index < len(elts) else []
        )
        for e in chosen:
            if e is None:
                continue
            fis, d = _callable_from_expr(
                idx, factory, factory.module, e, aliases, _depth=1
            )
            out.extend(fis)
            if d is not None:
                donate = d
    idx._returned_memo[memo_key] = (out, donate)
    return out, donate


def _local_aliases(idx: Index, fn: FunctionInfo) -> Dict[str, List[Any]]:
    """name -> [FunctionInfo|name] for simple aliasing assignments inside
    ``fn`` (incl. tuple-assign pairs like ``body, spec = f, P(...)``)."""
    if fn in idx._aliases_memo:
        return idx._aliases_memo[fn]
    aliases: Dict[str, List[Any]] = {}
    idx._aliases_memo[fn] = aliases  # pre-register to cut cycles

    def add(name: str, value: ast.AST) -> None:
        if isinstance(value, ast.Name):
            aliases.setdefault(name, []).append(value.id)
        elif isinstance(value, (ast.Attribute, ast.Call)):
            fis, _ = _callable_from_expr(idx, fn, fn.module, value, None)
            for f in fis:
                aliases.setdefault(name, []).append(f)

    for stmt in ast.walk(fn.node):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt, val = stmt.targets[0], stmt.value
            if isinstance(tgt, ast.Name):
                add(tgt.id, val)
            elif (
                isinstance(tgt, ast.Tuple)
                and isinstance(val, ast.Tuple)
                and len(tgt.elts) == len(val.elts)
            ):
                for t, v in zip(tgt.elts, val.elts):
                    if isinstance(t, ast.Name):
                        add(t.id, v)
    return aliases


def _resolve_class_attrs(idx: Index) -> None:
    """Fill ModuleInfo.class_attrs: ``self.x = ...`` bindings resolved to
    functions where possible (jit wrappers recording donate_argnums)."""
    for mi in idx.modules.values():
        for qual, fi in mi.functions.items():
            if fi.cls is None:
                continue
            table = mi.class_attrs.setdefault(fi.cls, {})
            for stmt in ast.walk(fi.node):
                if not isinstance(stmt, ast.Assign):
                    continue
                targets = stmt.targets
                if len(targets) != 1:
                    continue
                tgt = targets[0]
                if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
                    fis, donate = _callable_from_expr(idx, fi, mi, stmt.value)
                    entry: Dict[str, Any] = {
                        "func": fis[0] if fis else None,
                        "funcs": fis,
                        "donate": donate,
                    }
                    # value may instead be a tracer-returning call result
                    table.setdefault(tgt.attr, []).append(entry)
                    if donate:
                        idx.donating[f"{fi.cls}.{tgt.attr}"] = donate
                elif isinstance(tgt, ast.Tuple) and isinstance(stmt.value, ast.Call):
                    # self.a, self.b = factory(...)
                    callee = _resolve_callee(idx, fi, mi, stmt.value.func)
                    factories = [t for t in callee if isinstance(t, FunctionInfo)]
                    for i, t in enumerate(tgt.elts):
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        fis: List[FunctionInfo] = []
                        donate = None
                        for fac in factories:
                            rf, rd = _returned_functions(idx, fac, index=i)
                            fis.extend(rf)
                            donate = donate if donate is not None else rd
                        table.setdefault(t.attr, []).append(
                            {"func": fis[0] if fis else None, "funcs": fis, "donate": donate}
                        )
                        if donate:
                            idx.donating[f"{fi.cls}.{t.attr}"] = donate


# ------------------------------------------------------- edges + reachability


def build_edges(idx: Index) -> None:
    """Populate ``idx.edges``: direct calls, function-reference arguments
    (``lax.scan(body, ...)``, ``value_and_grad(loss_fn)``), flax
    ``X.apply(..., method="name")`` dynamic dispatch, and nested defs."""
    for mi in idx.modules.values():
        for fi in mi.functions.values():
            aliases = _local_aliases(idx, fi)
            edges = idx.edges.setdefault(fi, set())
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                for t in _resolve_callee(idx, fi, mi, node.func, aliases):
                    if isinstance(t, FunctionInfo):
                        edges.add(t)
                # function-reference arguments: lax.scan(body, ...),
                # value_and_grad(loss_fn), tree_map(keep, ...)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    if isinstance(arg, ast.Name):
                        for t in _resolve_name(idx, fi, mi, arg.id, aliases):
                            if isinstance(t, FunctionInfo):
                                edges.add(t)
                # flax dynamic dispatch: X.apply(..., method="name")
                fd = _dotted(node.func)
                if fd is not None and fd.endswith(".apply"):
                    method = None
                    for kw in node.keywords:
                        if kw.arg == "method" and isinstance(kw.value, ast.Constant):
                            method = kw.value.value
                    for m in idx.methods_by_name.get(method or "__call__", []):
                        if m.cls is not None:
                            edges.add(m)
            # nested defs are reachable from their parent by construction
            edges.update(fi.nested.values())


def reachable_from(idx: Index, roots) -> Set[FunctionInfo]:
    """BFS the (pre-built) call graph from ``roots``; returns the closure
    including the roots themselves."""
    seen: Set[FunctionInfo] = set()
    frontier = list(roots)
    while frontier:
        f = frontier.pop()
        if f in seen:
            continue
        seen.add(f)
        frontier.extend(idx.edges.get(f, ()))
    return seen
