"""HLO program auditor: contract rules over compiled-program fingerprints.

The third static gate. jaxlint (`frcnn check`) proves jit hygiene at the
Python-AST level and strict mode (`--strict`) polices the live process;
this auditor asserts what the COMPILER emitted for every registered
(feed × K) program of the step (train/warmup.py::build_program_specs)
before anything runs:

HX001  donation survives lowering as input/output aliasing for the state
       arg — and NEVER for the device cache / batch / eval inputs
       (train/train_step.py::make_cached_train_step's "cache must NOT be
       donated" contract, checked in the artifact).
HX002  dtype contracts: no silent f32→f64 promotion anywhere; the
       gradient all-reduce element type matches
       ``train.grad_allreduce_dtype`` (bf16 config ⇒ one bf16
       all_reduce per float grad leaf; f32 config ⇒ zero bf16).
HX003  collective inventory matches the backend: the shard_map feed
       carries hand-placed psums (all_reduce only); loader/cached/eval
       and the model-parallel (mp/mp_zero) programs lower collective-free
       IR (GSPMD inserts collectives after partitioning, never in the
       lowered module) — and on the COMPILED side, mp programs must show
       model-axis collectives (the GSPMD weight exchange) while every
       other feed must show none on the model axis.
HX004  compiled peak-memory estimate within ``analysis.hbm_budget_bytes``.
HX005  per-program drift vs the banked fingerprint: structural fields
       (shapes, shardings, aliasing, collectives) exactly, flops/bytes
       and memory within tolerance.
HX006  program set = expected bucket count: the bank covers exactly the
       registry's programs on this platform (recompile/bucket drift
       caught before runtime, complementing analysis/strict.py).
HX007  ops-backend provenance: a backend=xla program must contain NO
       pallas custom-call targets (tpu_custom_call / mosaic / triton);
       a backend=pallas program on a real TPU must contain at least one;
       off-TPU (interpret mode lowers pallas to plain StableHLO, so no
       custom call exists to witness) the twin's ``module_hash`` must
       differ from its base's — the backend scope demonstrably changed
       the lowered program.
HX008  quantization provenance: a ``serve_*__int8`` program whose plan
       keeps the head dense layers int8 must lower true-int8
       contractions (``stablehlo.dot_general`` over i8 operands), and NO
       other program may contain an i8 dot/conv — quantized weights in
       an uncalibrated program would be a silent numerics break.

SL005  (shardlint's comm-budget rule, live arm) the static collective
       wire-byte estimate (analysis/commcost.py) of a live program must
       stay within ``analysis.comm_budget_bytes`` AND within
       ``COMM_REL_TOL`` of its banked value — accidental collective
       growth fails the audit naming rule + program. The bank-only arm
       (and SL001-SL004/SL006) runs in `frcnn check` via
       analysis/shardlint.py.

`frcnn audit` drives this (``--json``, ``--update`` to re-bank, nonzero
exit on any violation); tests/test_hlolint.py gates a CPU subset in
tier 1 against the committed bank under ``analysis/fingerprints/``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from replication_faster_rcnn_tpu.analysis import fingerprint as fp_mod
from replication_faster_rcnn_tpu.config import FasterRCNNConfig

HLO_RULES: Dict[str, str] = {
    "HX001": "donation lost or leaked: state arg must alias, cache/batch/eval must not",
    "HX002": "dtype contract: f64 in lowered IR, or all-reduce type != grad_allreduce_dtype",
    "HX003": "collective inventory does not match the backend's expectation",
    "HX004": "compiled peak-memory estimate exceeds the HBM budget",
    "HX005": "fingerprint drift vs the banked record",
    "HX006": "program set does not match the expected bucket count / bank missing",
    "HX007": "ops-backend provenance: pallas custom-calls in an xla program, or a pallas twin indistinguishable from its base",
    "HX008": "quantization provenance: int8 dot/conv missing from a quantized program, or present anywhere else",
}

# shardlint rules the audit enforces live (the rest are bank-static and
# run under `frcnn check`); merged into the audit's JSON rules payload
AUDIT_SHARD_RULES: Dict[str, str] = {
    "SL005": (
        "static collective wire bytes exceed analysis.comm_budget_bytes "
        "or drifted beyond tolerance vs the banked record"
    ),
}

# relative tolerance for live-vs-banked comm wire bytes: the partitioned
# half of the estimate wobbles with XLA's SPMD pass pipeline across
# versions, but a real collective regression moves the total far more
COMM_REL_TOL = 0.10

# custom-call targets that witness a pallas lowering (Mosaic on TPU,
# Triton on GPU) — matched as substrings of the call_target_name
PALLAS_CALL_MARKERS = ("tpu_custom_call", "mosaic", "triton")

# the audited program matrix: every feed the Trainer can run, single-step
# and fused — including the ZeRO-1 variant of the shard_map backend and
# its LAMB chain (sharded trust ratio), and the model-parallel auto-
# partitioned feeds on the audit (dp, mp) mesh — plus eval (15 programs)
# and the serving engine's bucket matrix (audit_config's 2 resolutions ×
# 2 batch sizes = 4 more) — plus the three ops.backend=pallas twins
# (train/warmup.py::pallas_twin_base_names: loader k=1, eval, one
# serving bucket), plus the multi-scale TRAIN bucket programs —
# EVERY train feed buckets (the shard_map/mp in/out specs shard batch
# dims only, so they are resolution-independent): audit_config's 2
# train_resolutions × all 7 feeds × both Ks = 28 more — plus the
# quantized serving twins (4 ``serve_*__int8`` bucket programs + 1 int8
# pallas twin), 55 programs total
AUDIT_FEEDS = ("loader", "cached", "spmd", "zero", "zero_lamb", "mp", "mp_zero")
AUDIT_KS = (1, 2)
AUDIT_BANK_NAME = "ci"
AUDIT_CACHE_N = 4


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    program: str
    message: str

    def to_dict(self) -> Dict[str, str]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.rule} [{self.program}] {self.message}"


@dataclasses.dataclass
class AuditResult:
    violations: List[Violation]
    programs: Dict[str, Dict[str, Any]]
    bank_file: str
    updated: bool = False
    # per-program comm-byte section: {program: {wire_bytes_per_device,
    # basis, banked_wire_bytes_per_device}} — the SL005 evidence
    comm: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": {**HLO_RULES, **AUDIT_SHARD_RULES},
            "violations": [v.to_dict() for v in self.violations],
            "programs": self.programs,
            "bank_file": self.bank_file,
            "updated": self.updated,
            "comm": self.comm,
            "ok": self.ok,
        }


def audit_config() -> FasterRCNNConfig:
    """The audited config: the fast-tier 64×64 synthetic shape family
    (same trims as benchmarks/step_profile.py::tiny_config) on a 2-way
    data mesh with the bf16 gradient all-reduce ON — small enough to
    compile everywhere, wide enough that every contract (psums, bf16
    collectives, donation under out_shardings) is exercised for real."""
    from replication_faster_rcnn_tpu.config import (
        DataConfig,
        FasterRCNNConfig,
        MeshConfig,
        ModelConfig,
        ProposalConfig,
        ROITargetConfig,
        ServingConfig,
        TrainConfig,
    )

    return FasterRCNNConfig(
        model=ModelConfig(
            backbone="resnet18", roi_op="align", compute_dtype="float32"
        ),
        data=DataConfig(
            dataset="synthetic",
            image_size=(64, 64),
            max_boxes=8,
            # multi-scale train buckets: a downsample bucket plus the
            # identity bucket, so both the resample path and the no-op
            # path are audited and banked per (feed x K)
            train_resolutions=((32, 32), (64, 64)),
        ),
        train=TrainConfig(
            batch_size=2,
            n_epoch=4,
            grad_allreduce_dtype="bfloat16",
        ),
        mesh=MeshConfig(num_data=2),
        proposals=ProposalConfig(pre_nms_train=128, post_nms_train=32),
        roi_targets=ROITargetConfig(n_sample=8),
        # pinned (not derived) buckets so the audited serving matrix can't
        # shift under an image_size change without an explicit re-bank;
        # bf16 resident params = the serving default, exercised for real
        serving=ServingConfig(
            resolutions=((32, 32), (64, 64)),
            batch_sizes=(1, 2),
            params_dtype="bfloat16",
        ),
    )


def expected_program_names(
    feeds: Sequence[str] = AUDIT_FEEDS,
    ks: Sequence[int] = AUDIT_KS,
    include_eval: bool = True,
    config: Optional[FasterRCNNConfig] = None,
) -> List[str]:
    """The audited program set; with ``config`` the serving engine's
    bucket programs (serving.resolutions × batch_sizes), the multi-scale
    TRAIN bucket programs (data.train_resolutions × every feed × ks)
    and the ops.backend=pallas twin programs are included."""
    from replication_faster_rcnn_tpu.train.warmup import (
        bucket_train_program_names,
        int8_program_names,
        pallas_program_name,
        pallas_twin_base_names,
        program_name,
        serving_program_names,
    )

    names = [program_name(f, k) for f in feeds for k in ks]
    if include_eval:
        names.append("eval_infer")
    if config is not None:
        names.extend(serving_program_names(config))
        names.extend(bucket_train_program_names(config, feeds=feeds, ks=ks))
        names.extend(
            pallas_program_name(b) for b in pallas_twin_base_names(config)
        )
        names.extend(int8_program_names(config))
    return names


def collect_fingerprints(
    config: FasterRCNNConfig,
    programs: Optional[Sequence[str]] = None,
    cache_n: int = AUDIT_CACHE_N,
) -> Dict[str, Dict[str, Any]]:
    """Lower + compile the requested programs (default: the full matrix)
    and fingerprint each. This is the expensive arm — tens of seconds per
    program on CPU; the contract/drift rules below are pure functions
    over the returned dicts."""
    from replication_faster_rcnn_tpu.train.warmup import (
        build_int8_program_specs,
        build_pallas_program_specs,
        build_program_specs,
        build_serving_specs,
    )

    specs = build_program_specs(
        config, feeds=AUDIT_FEEDS, ks=AUDIT_KS, include_eval=True, cache_n=cache_n
    )
    specs = {
        **specs,
        **build_serving_specs(config),
        **build_pallas_program_specs(config),
        **build_int8_program_specs(config),
    }
    if programs is None:
        wanted = list(specs)
    else:
        unknown = set(programs) - set(specs)
        if unknown:
            raise ValueError(
                f"unknown programs {sorted(unknown)}; registry has {sorted(specs)}"
            )
        wanted = list(programs)
    return {name: fp_mod.fingerprint_program(specs[name]) for name in wanted}


# ------------------------------------------------------------ contract rules


def check_contracts(
    fingerprints: Dict[str, Dict[str, Any]],
    config: FasterRCNNConfig,
    hbm_budget_bytes: int,
) -> List[Violation]:
    """HX001–HX004 over live fingerprints (pure; no lowering here)."""
    out: List[Violation] = []
    want_dt = config.train.grad_allreduce_dtype
    for name, fp in sorted(fingerprints.items()):
        params: Dict[str, List[int]] = fp.get("params", {})
        aliased = {a["parameter"] for a in fp.get("aliasing", [])}

        # HX001 — donation as aliasing (serving programs share eval's
        # contract: pure inference, nothing may be donated/clobbered —
        # the engine's resident params survive every dispatch)
        if fp.get("feed") in ("eval", "serve"):
            if aliased:
                out.append(
                    Violation(
                        "HX001",
                        name,
                        f"{fp.get('feed')} program aliases params "
                        f"{sorted(aliased)[:8]} but nothing is donated to it",
                    )
                )
        elif "state" in params:
            s0, s1 = params["state"]
            missing = sorted(set(range(s0, s1)) - aliased)
            if missing:
                out.append(
                    Violation(
                        "HX001",
                        name,
                        f"donated state arg lost input/output aliasing for "
                        f"{len(missing)}/{s1 - s0} leaves (first params "
                        f"{missing[:8]}) — donation did not survive lowering",
                    )
                )
            for role, (r0, r1) in sorted(params.items()):
                if role == "state":
                    continue
                leaked = sorted(aliased & set(range(r0, r1)))
                if leaked:
                    out.append(
                        Violation(
                            "HX001",
                            name,
                            f"non-donated arg `{role}` is aliased (params "
                            f"{leaked[:8]}) — its buffer would be clobbered "
                            "by the dispatch",
                        )
                    )

        # HX002 — dtype contracts
        if fp.get("has_f64"):
            out.append(
                Violation(
                    "HX002",
                    name,
                    "f64 tensors in the lowered IR — silent x64 promotion "
                    "on a program that must stay f32/bf16",
                )
            )
        collectives = fp.get("collectives", {})
        ar = collectives.get("all_reduce")
        if fp.get("feed") in ("spmd", "zero", "zero_lamb"):
            # the gradient exchange: plain psum all_reduces on the
            # replicated backend, psum_scatter reduce_scatters under
            # ZeRO-1 — either way one bf16 collective per float grad leaf
            types: Dict[str, int] = {}
            for kind in ("all_reduce", "reduce_scatter"):
                for elem, n in (
                    collectives.get(kind, {}).get("element_types", {}).items()
                ):
                    types[elem] = types.get(elem, 0) + n
            n_bf16 = types.get("bf16", 0)
            n_grad = int(fp.get("meta", {}).get("n_float_grad_leaves", 1))
            if want_dt == "bfloat16" and n_bf16 < n_grad:
                out.append(
                    Violation(
                        "HX002",
                        name,
                        "grad-exchange element type: expected >= "
                        f"{n_grad} bf16 all_reduce/reduce_scatter ops (one "
                        f"per float grad leaf) under "
                        f"grad_allreduce_dtype=bfloat16, found "
                        f"{n_bf16} (types: {types or 'none'})",
                    )
                )
            elif want_dt == "float32" and n_bf16:
                out.append(
                    Violation(
                        "HX002",
                        name,
                        f"{n_bf16} bf16 grad-exchange collectives under "
                        "grad_allreduce_dtype=float32 — the gradient "
                        "exchange silently lost precision",
                    )
                )

        # HX003 — collective inventory per backend
        if fp.get("feed") == "spmd":
            if not ar or not ar.get("count"):
                out.append(
                    Violation(
                        "HX003",
                        name,
                        "no all_reduce in the lowered IR — the hand-placed "
                        "psums of parallel/spmd.py are gone",
                    )
                )
            other = sorted(set(collectives) - {"all_reduce"})
            if other:
                out.append(
                    Violation(
                        "HX003",
                        name,
                        f"unexpected collective kinds {other} — the "
                        "replicated shard_map backend emits psum "
                        "all_reduces only",
                    )
                )
        elif fp.get("feed") in ("zero", "zero_lamb"):
            # zero_lamb shares the inventory: LAMB's sharded trust-ratio
            # norm psums lower as additional all_reduce ops, a kind
            # already required here (their count is pinned by HX005)
            required = {"all_reduce", "reduce_scatter", "all_gather"}
            missing = sorted(required - set(collectives))
            if missing:
                out.append(
                    Violation(
                        "HX003",
                        name,
                        f"missing collective kinds {missing} — ZeRO-1 "
                        "needs reduce_scatter (grad exchange), all_gather "
                        "(param reassembly) and all_reduce (metrics/health "
                        "psums); the hand-placed collectives of "
                        "parallel/spmd.py are gone",
                    )
                )
            other = sorted(set(collectives) - required)
            if other:
                out.append(
                    Violation(
                        "HX003",
                        name,
                        f"unexpected collective kinds {other} — the ZeRO-1 "
                        "shard_map backend emits all_reduce, "
                        "reduce_scatter and all_gather only",
                    )
                )
        elif collectives:
            out.append(
                Violation(
                    "HX003",
                    name,
                    f"collectives {sorted(collectives)} in a "
                    f"{fp.get('feed')} program — the jit backend lowers "
                    "collective-free IR (GSPMD inserts collectives after "
                    "partitioning, not here)",
                )
            )

        # HX003 — model-axis partitioned collectives: the mp feeds' weight
        # exchange is GSPMD-inserted, so it only shows in the COMPILED
        # module's inventory (`partitioned_collectives`, classified per
        # mesh axis). mp programs must carry it; every other feed must
        # lower ZERO model-axis collectives. `.get` throughout: records
        # banked before the field existed simply skip this rule.
        pcoll = fp.get("partitioned_collectives")
        if pcoll is not None:
            model_ops = {
                kind: entry.get("axes", {}).get("model", 0)
                for kind, entry in pcoll.items()
                if entry.get("axes", {}).get("model", 0)
            }
            if fp.get("feed") in ("mp", "mp_zero"):
                if not model_ops:
                    out.append(
                        Violation(
                            "HX003",
                            name,
                            "no model-axis collectives in the compiled "
                            "module — GSPMD emitted no weight exchange, so "
                            "the 1/mp parameter sharding was optimized away "
                            f"(partitioned inventory: {sorted(pcoll) or 'empty'})",
                        )
                    )
            elif model_ops:
                out.append(
                    Violation(
                        "HX003",
                        name,
                        f"model-axis collectives {model_ops} in a "
                        f"{fp.get('feed')} program — only the mp feeds "
                        "shard over the model axis",
                    )
                )

        # HX007 — ops-backend provenance. Applied only to records that
        # carry the `custom_calls` field (live fingerprints and post-
        # ISSUE-13 banks; older banked records simply skip the rule).
        cc = fp.get("custom_calls")
        if cc is not None:
            pallas_cc = {
                t: n
                for t, n in cc.items()
                if any(m in t.lower() for m in PALLAS_CALL_MARKERS)
            }
            meta = fp.get("meta", {})
            if meta.get("ops_backend", "xla") != "pallas":
                if pallas_cc:
                    out.append(
                        Violation(
                            "HX007",
                            name,
                            f"pallas custom-calls {pallas_cc} in a "
                            "backend=xla program — the ops dispatch leaked "
                            "a pallas kernel into the default lowering",
                        )
                    )
            elif not meta.get("pallas_interpret"):
                if not pallas_cc:
                    out.append(
                        Violation(
                            "HX007",
                            name,
                            "no pallas custom-call in a backend=pallas "
                            "program compiled for a real accelerator — the "
                            "backend scope did not reach the lowering "
                            f"(custom calls: {sorted(cc) or 'none'})",
                        )
                    )
            else:
                # interpret mode: no custom call exists to witness the
                # backend, so require the twin's module to differ from
                # its base's (skipped when the base wasn't collected in
                # this audit — e.g. an explicit --programs subset)
                base = fingerprints.get(meta.get("twin", ""))
                if (
                    base is not None
                    and fp.get("module_hash")
                    and fp.get("module_hash") == base.get("module_hash")
                ):
                    out.append(
                        Violation(
                            "HX007",
                            name,
                            "interpret-mode pallas twin lowered a module "
                            f"byte-identical to its base {meta.get('twin')!r} "
                            "— the backend scope changed nothing",
                        )
                    )

        # HX008 — quantization provenance. Like HX007, applied only to
        # records carrying the `int8_ops` field (live fingerprints and
        # post-ISSUE-17 banks; older banked records skip the rule).
        int8_ops = fp.get("int8_ops")
        if int8_ops is not None:
            meta = fp.get("meta", {})
            n_int8 = sum(int8_ops.values())
            if meta.get("params_dtype") == "int8" and meta.get("int8_dense"):
                if not n_int8:
                    out.append(
                        Violation(
                            "HX008",
                            name,
                            "no int8 dot_general/convolution in a quantized "
                            "program whose plan keeps the head dense layers "
                            "int8 — the QuantDense GEMMs were dequantized "
                            "away before the contraction",
                        )
                    )
            elif n_int8:
                out.append(
                    Violation(
                        "HX008",
                        name,
                        f"int8 contraction ops {int8_ops} in a "
                        f"params_dtype={meta.get('params_dtype', 'float32')!r} "
                        "program — quantized weights leaked outside the "
                        "serve_*__int8 twins",
                    )
                )

        # HX004 — memory budget
        mem = fp.get("memory")
        if mem is not None:
            peak = float(mem.get("peak_bytes_estimate", 0.0))
            if peak > hbm_budget_bytes:
                out.append(
                    Violation(
                        "HX004",
                        name,
                        f"peak-memory estimate {peak / 2**30:.2f} GiB "
                        f"exceeds analysis.hbm_budget_bytes "
                        f"({hbm_budget_bytes / 2**30:.2f} GiB)",
                    )
                )
    return out


def check_drift(
    fingerprints: Dict[str, Dict[str, Any]],
    bank: Optional[Dict[str, Any]],
    bank_file: str,
    expected: Sequence[str],
    platform: str,
    n_devices: int,
) -> List[Violation]:
    """HX005 (per-program drift) + HX006 (bank presence / program set)."""
    out: List[Violation] = []
    if bank is None:
        out.append(
            Violation(
                "HX006",
                "<bank>",
                f"no banked fingerprints at {bank_file} — run "
                "`frcnn audit --update` to bank the current programs",
            )
        )
        return out
    if bank.get("platform") != platform or bank.get("n_devices") != n_devices:
        out.append(
            Violation(
                "HX006",
                "<bank>",
                f"bank was recorded on {bank.get('platform')}/"
                f"{bank.get('n_devices')} devices but this audit runs on "
                f"{platform}/{n_devices} — fingerprints do not transfer "
                "across topologies; re-bank per platform",
            )
        )
        return out
    banked = bank.get("programs", {})
    missing = sorted(set(expected) - set(banked))
    extra = sorted(set(banked) - set(expected))
    if missing:
        out.append(
            Violation(
                "HX006",
                "<bank>",
                f"bank is missing programs {missing} of the expected "
                f"{len(expected)}-program matrix — run `frcnn audit --update`",
            )
        )
    if extra:
        out.append(
            Violation(
                "HX006",
                "<bank>",
                f"bank has unexpected programs {extra} — stale bucket "
                "(recompile drift) or a renamed program; re-bank",
            )
        )
    for name, fp in sorted(fingerprints.items()):
        if name not in banked:
            continue  # HX006 above already owns set mismatches
        for msg in fp_mod.diff_programs(fp, banked[name]):
            out.append(Violation("HX005", name, msg))
    return out


def check_comm(
    fingerprints: Dict[str, Dict[str, Any]],
    bank: Optional[Dict[str, Any]],
    comm_budget_bytes: int,
    comm_tol: float = COMM_REL_TOL,
):
    """SL005's live arm: every program's statically-priced collective
    wire bytes must fit the absolute budget, and (when a banked comm
    record exists — pass bank=None while re-banking) stay within
    ``comm_tol`` of the bank. Returns (violations, per-program comm
    summary). Records without a `comm` field (legacy banks passed in as
    pre-collected fingerprints) skip the rule, mirroring HX007/HX008."""
    banked_programs = (bank or {}).get("programs", {})
    out: List[Violation] = []
    summary: Dict[str, Dict[str, Any]] = {}
    for name, fp in sorted(fingerprints.items()):
        comm = fp.get("comm")
        if comm is None:
            continue
        wire = int(comm.get("wire_bytes_per_device", 0) or 0)
        bcomm = (banked_programs.get(name) or {}).get("comm") or {}
        banked_wire = bcomm.get("wire_bytes_per_device")
        summary[name] = {
            "wire_bytes_per_device": wire,
            "basis": comm.get("basis", "none"),
            "banked_wire_bytes_per_device": banked_wire,
        }
        if wire > comm_budget_bytes:
            out.append(
                Violation(
                    "SL005",
                    name,
                    f"static collective cost {wire / 2**20:.1f} MiB/device/"
                    "step exceeds analysis.comm_budget_bytes "
                    f"({comm_budget_bytes / 2**20:.1f} MiB)",
                )
            )
        if banked_wire is not None:
            d = fp_mod._rel_delta(float(wire), float(banked_wire))
            if d > comm_tol:
                out.append(
                    Violation(
                        "SL005",
                        name,
                        f"collective wire bytes drifted {d:+.1%} vs bank "
                        f"(now {wire}, banked {int(banked_wire)}, tol "
                        f"{comm_tol:.0%}) — the collective volume per "
                        "step changed; re-bank if intended",
                    )
                )
    return out, summary


# -------------------------------------------------------------------- driver


def resolve_bank_file(
    config: FasterRCNNConfig,
    fingerprint_dir: Optional[str] = None,
    bank_name: str = AUDIT_BANK_NAME,
) -> str:
    import jax

    directory = (
        fingerprint_dir
        or config.analysis.fingerprint_dir
        or fp_mod.default_fingerprint_dir()
    )
    return fp_mod.bank_path(directory, bank_name, jax.default_backend())


def run_audit(
    config: Optional[FasterRCNNConfig] = None,
    programs: Optional[Sequence[str]] = None,
    update: bool = False,
    fingerprint_dir: Optional[str] = None,
    hbm_budget_bytes: Optional[int] = None,
    fingerprints: Optional[Dict[str, Dict[str, Any]]] = None,
    bank_name: str = AUDIT_BANK_NAME,
    cache_n: int = AUDIT_CACHE_N,
) -> AuditResult:
    """The audit gate: collect (or accept pre-collected) fingerprints,
    enforce HX001–HX004 contracts, then either re-bank (``update``) or
    check HX005/HX006 drift against the committed bank. Violations in the
    result ⇒ the CLI exits nonzero."""
    import jax

    if config is None:
        config = audit_config()
    expected = expected_program_names(config=config)
    if fingerprints is None:
        fingerprints = collect_fingerprints(config, programs, cache_n=cache_n)
    budget = (
        hbm_budget_bytes
        if hbm_budget_bytes is not None
        else config.analysis.hbm_budget_bytes
    )
    violations = check_contracts(fingerprints, config, budget)
    bank_file = resolve_bank_file(config, fingerprint_dir, bank_name)
    platform = jax.default_backend()
    n_devices = len(jax.devices())
    bank = fp_mod.load_bank(bank_file)
    bank_matches = (
        bank is not None
        and bank.get("platform") == platform
        and bank.get("n_devices") == n_devices
    )
    # SL005 live arm: absolute budget always; drift vs bank only when a
    # matching bank exists and we are not about to overwrite it
    comm_violations, comm_summary = check_comm(
        fingerprints,
        bank if (bank_matches and not update) else None,
        config.analysis.comm_budget_bytes,
    )
    violations.extend(comm_violations)
    updated = False
    if update:
        banked_programs: Dict[str, Any] = {}
        if bank_matches:
            banked_programs = dict(bank.get("programs", {}))
        banked_programs.update(fingerprints)
        fp_mod.save_bank(
            bank_file,
            fp_mod.make_bank(
                banked_programs,
                platform,
                n_devices,
                config_summary={
                    "image_size": list(config.data.image_size),
                    "batch_size": config.train.batch_size,
                    "grad_allreduce_dtype": config.train.grad_allreduce_dtype,
                    "backbone": config.model.backbone,
                    "num_data": config.mesh.num_data,
                    "cache_n": cache_n,
                },
            ),
        )
        updated = True
        missing = sorted(set(expected) - set(banked_programs))
        if missing:
            violations.append(
                Violation(
                    "HX006",
                    "<bank>",
                    f"re-banked {len(fingerprints)} programs but the bank "
                    f"still misses {missing} — run `frcnn audit --update` "
                    "without --programs to bank the full matrix",
                )
            )
    else:
        violations.extend(
            check_drift(
                fingerprints, bank, bank_file, expected, platform, n_devices
            )
        )
    return AuditResult(
        violations=violations,
        programs=fingerprints,
        bank_file=bank_file,
        updated=updated,
        comm=comm_summary,
    )
