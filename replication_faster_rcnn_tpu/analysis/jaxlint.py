"""jaxlint — AST lint for JAX jit hygiene, tuned to this codebase.

The perf work (fused dispatch, critical-path overlap) is silently undone
whenever a stray host sync, tracer branch, or avoidable recompile creeps
back into a jitted path; benchmarks catch that only after the fact. This
module catches it at review time, with project-specific rules:

  JX001  host-sync hazard: ``float()`` / ``int()`` / ``.item()`` /
         ``np.asarray()`` applied to a tracer-typed (jnp) value — inside a
         jit-reachable function that forces a device sync per call, and in
         host code it forces a sync of un-jitted device math (the classic
         per-step ``float(schedule(step))`` pull).
  JX002  Python ``if``/``while`` branching on a tracer value inside a
         jit-reachable function (a trace-time crash or, worse, a silent
         constant-fold on the tracing value).
  JX003  donated-buffer reuse: reading an argument again after passing it
         to a dispatch that donates it (``donate_argnums``).
  JX004  mutable/non-hashable value (list/dict/set) passed — or defaulted —
         for a parameter marked static (``static_argnums``/``argnames``):
         every call re-hashes, a changed value silently recompiles, an
         unhashable one throws at dispatch.
  JX005  ``jax.random`` key reused by two sampling calls without an
         intervening ``split`` (identical randomness; ``fold_in`` derives
         fresh keys and is exempt).
  JX006  ``block_until_ready`` / ``jax.device_get`` outside a telemetry
         span: unattributed sync time that telemetry reports then book to
         the wrong phase (the spans contract from PR 1).

Jit-reachability is computed by walking the call graph from every
``jax.jit`` / ``shard_map`` entry point in the package (the known roots
live in train/train_step.py, parallel/spmd.py, eval/evaluator.py; the
discovery scans every module so new roots are picked up automatically).
The call-graph machinery itself — module indexing, name/callee
resolution, factory-return and alias following, edge building — lives in
:mod:`analysis.callgraph`, shared with :mod:`analysis.threadlint` (which
walks the same graph from *thread* entry points instead of jit roots).
The walker follows factory returns (``jax.jit(make_train_step(...))``),
tuple-assignment aliasing (``body, spec = per_shard_multi, P(...)``),
``self.attr`` bindings (``self.jitted_step = jax.jit(...)``) and
function-reference arguments (``lax.scan(body, ...)``,
``value_and_grad(loss_fn)``). ``flax`` module dispatch is resolved by
method name for ``.apply(..., method="name")`` call sites.

Findings resolve against a committed suppression file
(``analysis/baseline.toml``): every pre-existing violation is either fixed
or explicitly waived with a reason. The baseline file is shared with
threadlint; each analyzer only matches (and stale-checks) waivers for its
own rule set. ``frcnn check`` runs this standalone (``--json`` for
machine-readable output, nonzero exit on unsuppressed findings) and
tests/test_jaxlint.py asserts the package lints clean.

Known limits (deliberate — this is a reviewer, not a verifier): taint is
per-function and flow-insensitive across branches; dynamic dispatch other
than the patterns above is not followed; runtime truth is the job of
analysis/strict.py.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from replication_faster_rcnn_tpu.analysis.callgraph import (  # noqa: F401
    _JIT_NAMES,
    _REMAT_NAMES,
    _SHARD_MAP_NAMES,
    _STATIC_ANNOTATION_HEADS,
    _STATIC_PARAM_NAMES,
    FunctionInfo,
    Index,
    ModuleInfo,
    _ann_str,
    _annotation_static,
    _callable_from_expr,
    _dotted,
    _int_tuple,
    _local_aliases,
    _resolve_callee,
    _resolve_dotted_prefix,
    _resolve_name,
    _str_tuple,
    build_edges,
    parse_modules,
    reachable_from,
)

RULES: Dict[str, str] = {
    "JX001": "host-sync hazard: float()/int()/.item()/np.asarray on a jnp value",
    "JX002": "Python if/while branches on a tracer value in jit-reachable code",
    "JX003": "donated buffer read again after a donating dispatch",
    "JX004": "mutable/non-hashable value for a static jit argument",
    "JX005": "jax.random key reused without split",
    "JX006": "block_until_ready/device_get outside a telemetry span",
    "JX007": "implicit-dtype array creation in jit-reachable code",
}

PACKAGE = "replication_faster_rcnn_tpu"

# attribute reads that are static under tracing (no device value involved)
_SHAPE_ATTRS = {"shape", "dtype", "ndim", "size", "aval", "sharding", "weak_type"}
# dotted-call prefixes whose results are tracer-typed
_TRACER_CALL_PREFIXES = (
    "jax.numpy.",
    "jax.lax.",
    "jax.random.",
    "jax.nn.",
    "jax.scipy.",
)
# external callables that just map over their arguments (taint passes through)
_PASSTHROUGH_CALLS = {
    "jax.tree_util.tree_map",
    "jax.tree_map",
    "jax.tree.map",
    "optax.apply_updates",
    "jax.checkpoint",
    "jax.remat",
}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
# jnp creation calls whose result dtype follows weak-type/x64 promotion
# unless pinned; value = index of the positional dtype parameter (the
# package idiom `jnp.zeros((), jnp.int32)` counts as explicit)
_IMPLICIT_DTYPE_CALLS = {
    "jax.numpy.array": 1,
    "jax.numpy.asarray": 1,
    "jax.numpy.zeros": 1,
    "jax.numpy.ones": 1,
    "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
    "jax.numpy.arange": 3,
}


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    col: int
    func: str  # function qualname within the module ("<module>" at top level)
    message: str

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.func)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.func}] {self.message}"


@dataclasses.dataclass
class Waiver:
    rule: str
    path: str
    func: str  # "*" matches any function in the file
    reason: str
    used: bool = False
    line: int = 0  # 1-based line of this [[waiver]] header in the TOML

    def matches(self, f: Finding) -> bool:
        return (
            self.rule == f.rule
            and self.path == f.path
            and (self.func == "*" or self.func == f.func)
        )


@dataclasses.dataclass
class Baseline:
    waivers: List[Waiver] = dataclasses.field(default_factory=list)
    # rule -> excluded path prefixes (measurement/tooling modules where the
    # rule's premise does not apply)
    excludes: Dict[str, List[str]] = dataclasses.field(default_factory=dict)

    def excluded(self, f: Finding) -> bool:
        return any(f.path.startswith(p) for p in self.excludes.get(f.rule, ()))

    def waive(self, f: Finding) -> Optional[Waiver]:
        for w in self.waivers:
            if w.matches(f):
                w.used = True
                return w
        return None

    def restricted(self, rules: "Set[str] | Dict[str, str]") -> "Baseline":
        """A view keeping only waivers/excludes for ``rules`` — the shared
        baseline.toml carries entries for several analyzers; each must
        stale-check only its own."""
        return Baseline(
            waivers=[w for w in self.waivers if w.rule in rules],
            excludes={r: p for r, p in self.excludes.items() if r in rules},
        )


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]  # unsuppressed
    suppressed: List[Tuple[Finding, str]]  # (finding, waiver reason)
    excluded: List[Finding]
    stale_waivers: List[Waiver]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rules": RULES,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [
                {**f.to_dict(), "reason": r} for f, r in self.suppressed
            ],
            "excluded_count": len(self.excluded),
            "stale_waivers": [dataclasses.asdict(w) for w in self.stale_waivers],
            "ok": not self.findings and not self.stale_waivers,
        }


def load_baseline(path: str) -> Baseline:
    try:
        import tomllib  # py >= 3.11
    except ModuleNotFoundError:  # pragma: no cover - py 3.10 image
        import tomli as tomllib
    with open(path, "rb") as f:
        raw = f.read().decode("utf-8")
    data = tomllib.loads(raw)
    # tomllib keeps array-of-tables in document order, so the Nth parsed
    # waiver belongs to the Nth `[[waiver]]` header — that line number
    # makes stale-waiver reports point at the exact entry to delete
    header_lines = [
        i + 1
        for i, ln in enumerate(raw.splitlines())
        if ln.strip().startswith("[[waiver]]")
    ]
    waivers = []
    for n, w in enumerate(data.get("waiver", [])):
        if not w.get("reason"):
            raise ValueError(
                f"baseline waiver {w.get('rule')}:{w.get('path')} has no "
                "reason — every suppression must say why"
            )
        waivers.append(
            Waiver(
                rule=w["rule"],
                path=w["path"],
                func=w.get("func", "*"),
                reason=w["reason"],
                line=header_lines[n] if n < len(header_lines) else 0,
            )
        )
    excludes = {
        rule: list(paths) for rule, paths in data.get("excludes", {}).items()
    }
    return Baseline(waivers=waivers, excludes=excludes)


# ----------------------------------------------------------- index + roots


def build_index(paths: Sequence[str], package_root: str) -> Index:
    """Parse, discover jit/shard_map roots, build edges, mark
    jit-reachability. The parsing/resolution half lives in callgraph."""
    idx = parse_modules(list(paths), package_root)
    _discover(idx)
    build_edges(idx)
    for f in reachable_from(idx, idx.roots):
        f.jit_reachable = True
    return idx


def _discover(idx: Index) -> None:
    """Find jit/shard_map roots, donating callables, and static-arg specs."""
    for mi in idx.modules.values():
        # decorators
        for fi in mi.functions.values():
            for dec in getattr(fi.node, "decorator_list", []):
                d = _dotted(dec) if not isinstance(dec, ast.Call) else _dotted(dec.func)
                if d is None:
                    continue
                rd = _resolve_dotted_prefix(mi, d)
                if rd in _JIT_NAMES:
                    idx.roots.add(fi)
                    if isinstance(dec, ast.Call):
                        _record_static(idx, mi, fi, dec.keywords)
                elif rd.endswith("functools.partial") and isinstance(dec, ast.Call):
                    inner = dec.args[0] if dec.args else None
                    di = _dotted(inner) if inner is not None else None
                    if di is not None and _resolve_dotted_prefix(mi, di) in _JIT_NAMES:
                        idx.roots.add(fi)
                        _record_static(idx, mi, fi, dec.keywords)
        # call sites
        for qual, fi in list(mi.functions.items()):
            aliases = _local_aliases(idx, fi)
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = _resolve_callee(idx, fi, mi, node.func, aliases)
                dotted = [t for t in callee if isinstance(t, str)]
                if any(d in _JIT_NAMES or d in _SHARD_MAP_NAMES for d in dotted):
                    if node.args:
                        fis, donate = _callable_from_expr(
                            idx, fi, mi, node.args[0], aliases
                        )
                        idx.roots.update(fis)
                        for kw in node.keywords:
                            if kw.arg == "donate_argnums":
                                donate = _int_tuple(kw.value) or donate
                        if donate:
                            for f in fis:
                                idx.donating[
                                    f"{f.module.modname}.{f.qualname}"
                                ] = donate
                if any(d in _REMAT_NAMES for d in dotted) and node.args:
                    fis, _ = _callable_from_expr(idx, fi, mi, node.args[0], aliases)
                    for kw in node.keywords:
                        if kw.arg in ("static_argnums", "static_argnames"):
                            for f in fis:
                                _record_static_for(idx, f, kw)
        # module-level jit sites (`jitted = jax.jit(step, ...)` at top
        # level): not inside any function, so the walk above misses them
        for stmt in mi.tree.body:
            for node in ast.walk(stmt):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # function bodies were handled with local scope
                if not isinstance(node, ast.Call):
                    continue
                callee = _resolve_callee(idx, None, mi, node.func)
                dotted = [t for t in callee if isinstance(t, str)]
                if any(d in _JIT_NAMES or d in _SHARD_MAP_NAMES for d in dotted) and node.args:
                    fis, donate = _callable_from_expr(idx, None, mi, node.args[0])
                    idx.roots.update(fis)
                    for kw in node.keywords:
                        if kw.arg == "donate_argnums":
                            donate = _int_tuple(kw.value) or donate
                    if donate:
                        for f in fis:
                            idx.donating[f"{f.module.modname}.{f.qualname}"] = donate
                        if (
                            isinstance(stmt, ast.Assign)
                            and len(stmt.targets) == 1
                            and isinstance(stmt.targets[0], ast.Name)
                        ):
                            # calls through the module-level binding donate too
                            idx.donating[
                                f"{mi.modname}.{stmt.targets[0].id}"
                            ] = donate


def _record_static(idx: Index, mi: ModuleInfo, fi: FunctionInfo, keywords) -> None:
    for kw in keywords:
        if kw.arg in ("static_argnums", "static_argnames"):
            _record_static_for(idx, fi, kw)


def _record_static_for(idx: Index, fi: FunctionInfo, kw: ast.keyword) -> None:
    key = f"{fi.module.modname}.{fi.qualname}"
    names = idx.static_args.setdefault(key, set())
    if kw.arg == "static_argnames":
        names.update(_str_tuple(kw.value))
    else:
        nums = _int_tuple(kw.value) or ()
        for n in nums:
            if 0 <= n < len(fi.params):
                names.add(fi.params[n])


# ----------------------------------------------------------- taint + rules


class _Env:
    __slots__ = ("tainted", "containers", "keys", "key_uses", "dead", "in_span")

    def __init__(self) -> None:
        self.tainted: Set[str] = set()
        # names bound to Python containers (list/tuple/dict literals or
        # comprehensions): their *truthiness* is a host length check even
        # when the elements are tracers
        self.containers: Set[str] = set()
        self.keys: Set[str] = set()
        self.key_uses: Dict[str, int] = {}
        self.dead: Dict[str, int] = {}  # donated name -> line of donation
        self.in_span = 0


class _RuleWalker:
    """Single in-order pass over one function's statements."""

    def __init__(self, idx: Index, fi: FunctionInfo, findings: List[Finding]):
        self.idx = idx
        self.fi = fi
        self.mi = fi.module
        self.findings = findings
        self.aliases = _local_aliases(idx, fi)
        self.env = _Env()
        if fi.jit_reachable:
            for p in fi.params:
                if p not in fi.static_params:
                    self.env.tainted.add(p)

    # ---------------- helpers

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(
            Finding(
                rule=rule,
                path=self.mi.relpath,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                func=self.fi.qualname,
                message=message,
            )
        )

    def _callee_dotted(self, call: ast.Call) -> List[str]:
        out = []
        for t in _resolve_callee(self.idx, self.fi, self.mi, call.func, self.aliases):
            if isinstance(t, str):
                out.append(t)
        d = _dotted(call.func)
        if d is not None:
            out.append(_resolve_dotted_prefix(self.mi, d))
            out.append(d)
        return out

    def _callee_fns(self, call: ast.Call) -> List[FunctionInfo]:
        return [
            t
            for t in _resolve_callee(self.idx, self.fi, self.mi, call.func, self.aliases)
            if isinstance(t, FunctionInfo)
        ]

    def _returns_tracer(self, fn: FunctionInfo, _depth: int = 0) -> bool:
        if fn._returns_tracer is not None:
            return fn._returns_tracer
        if _depth > 4:
            return False
        fn._returns_tracer = False  # cut recursion cycles
        w = _RuleWalker(self.idx, fn, [])  # throwaway: taint only
        result = False
        for elts in fn.returns():
            for e in elts:
                if e is not None and w.tainted(e):
                    result = True
        fn._returns_tracer = result
        return result

    # ---------------- taint

    def tainted(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _SHAPE_ATTRS:
                return False
            return self.tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.tainted(node.value)
        if isinstance(node, ast.Call):
            return self.call_tainted(node)
        if isinstance(node, (ast.BinOp,)):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            ops = node.ops
            if all(isinstance(o, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for o in ops):
                return False
            if any(
                isinstance(c, ast.Constant) and c.value is None
                for c in [node.left] + node.comparators
            ):
                return False
            return self.tainted(node.left) or any(
                self.tainted(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self.tainted(v) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self.tainted(node.value)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self.tainted(node.elt) or any(
                self.tainted(g.iter) for g in node.generators
            )
        if isinstance(node, ast.DictComp):
            return self.tainted(node.value) or any(
                self.tainted(g.iter) for g in node.generators
            )
        if isinstance(node, ast.JoinedStr):
            return False
        return False

    def call_tainted(self, call: ast.Call) -> bool:
        dotted = self._callee_dotted(call)
        # host conversions return host values (JX001 flags them separately)
        if isinstance(call.func, ast.Name) and call.func.id in (
            "float", "int", "bool", "str", "len", "repr",
        ):
            return False
        if isinstance(call.func, ast.Attribute) and call.func.attr == "item":
            return False
        if any(d in _SYNC_CALLS for d in dotted):
            return False
        for d in dotted:
            if d.startswith(_TRACER_CALL_PREFIXES) and not d.startswith(
                ("jax.random.PRNGKey",)
            ):
                return True
            if d in _PASSTHROUGH_CALLS:
                return any(self.tainted(a) for a in call.args)
        if any(d.startswith("jax.random.") for d in dotted):
            return True
        for fn in self._callee_fns(call):
            if self._returns_tracer(fn):
                return True
        # method call on a tainted object (x.sum(), x.astype(...))
        if isinstance(call.func, ast.Attribute) and self.tainted(call.func.value):
            return True
        return False

    # ---------------- statement walk

    def walk(self) -> None:
        self._walk_stmts(getattr(self.fi.node, "body", []))

    def _walk_stmts(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are walked as their own functions
        if isinstance(s, ast.Assign):
            self._expr(s.value)
            self._assign(s.targets, s.value, s)
        elif isinstance(s, ast.AugAssign):
            self._expr(s.value)
            if isinstance(s.target, ast.Name):
                if self.tainted(s.value):
                    self.env.tainted.add(s.target.id)
                self._revive(s.target.id)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self._expr(s.value)
                self._assign([s.target], s.value, s)
        elif isinstance(s, (ast.If, ast.While)):
            # `not isinstance(x, ...Tracer) and <rest>` is the idiomatic
            # "host value only" guard: x is proven concrete for the rest
            # of the test and the body — narrow its taint there.
            guarded = self._tracer_guarded_names(s.test)
            re_taint = guarded & self.env.tainted
            self.env.tainted -= guarded
            self._expr(s.test)
            if self.fi.jit_reachable and self._truth_tainted(s.test):
                kind = "if" if isinstance(s, ast.If) else "while"
                self._emit(
                    "JX002",
                    s,
                    f"`{kind}` branches on a tracer value inside jit-reachable "
                    f"`{self.fi.qualname}` — use jnp.where/lax.cond, or mark "
                    "the argument static",
                )
            self._walk_stmts(s.body)
            self.env.tainted |= re_taint
            self._walk_stmts(s.orelse)
        elif isinstance(s, ast.For):
            self._expr(s.iter)
            if isinstance(s.target, ast.Name) and self.tainted(s.iter):
                self.env.tainted.add(s.target.id)
            self._walk_stmts(s.body)
            self._walk_stmts(s.orelse)
        elif isinstance(s, ast.With):
            spanned = any(self._is_span(item.context_expr) for item in s.items)
            for item in s.items:
                self._expr(item.context_expr)
            if spanned:
                self.env.in_span += 1
            self._walk_stmts(s.body)
            if spanned:
                self.env.in_span -= 1
        elif isinstance(s, ast.Try):
            self._walk_stmts(s.body)
            for h in s.handlers:
                self._walk_stmts(h.body)
            self._walk_stmts(s.orelse)
            self._walk_stmts(s.finalbody)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self._expr(s.value)
        elif isinstance(s, ast.Expr):
            self._expr(s.value)
            if isinstance(s.value, ast.Call):
                self._donating_call(s.value, targets=[])
        elif isinstance(s, (ast.Raise, ast.Assert)):
            for sub in ast.walk(s):
                if isinstance(sub, ast.Call):
                    self._expr(sub)
                    break
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    self.env.tainted.discard(t.id)
                    self.env.dead.pop(t.id, None)

    def _tracer_guarded_names(self, test: ast.AST) -> Set[str]:
        """Names proven non-tracer by a ``not isinstance(x, ...Tracer)``
        conjunct in ``test``."""
        out: Set[str] = set()
        conjuncts = test.values if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And) else [test]
        for c in conjuncts:
            if not (isinstance(c, ast.UnaryOp) and isinstance(c.op, ast.Not)):
                continue
            call = c.operand
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == "isinstance"
                and len(call.args) == 2
                and isinstance(call.args[0], ast.Name)
            ):
                continue
            cls = _dotted(call.args[1])
            if cls is not None and cls.endswith("Tracer"):
                out.add(call.args[0].id)
        return out

    def _truth_tainted(self, test: ast.AST) -> bool:
        """Like ``tainted`` but for truthiness: ``if xs`` / ``if not xs``
        on a Python container is a host length check even when the
        elements are tracers."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._truth_tainted(test.operand)
        if isinstance(test, ast.Name) and test.id in self.env.containers:
            return False
        if isinstance(test, ast.BoolOp):
            return any(self._truth_tainted(v) for v in test.values)
        return self.tainted(test)

    def _is_span(self, expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        f = expr.func
        if isinstance(f, ast.Attribute) and f.attr == "span":
            return True
        if isinstance(f, ast.Name) and "span" in f.id.lower():
            return True
        return False

    def _assign(self, targets, value: ast.AST, stmt: ast.stmt) -> None:
        names = [t.id for t in ast.walk(ast.Tuple(elts=list(targets), ctx=ast.Store())) if isinstance(t, ast.Name)]
        tgt_dotted = set()
        for t in targets:
            for sub in ast.walk(t):
                d = _dotted(sub)
                if d is not None:
                    tgt_dotted.add(d)
        if isinstance(value, ast.Call):
            self._donating_call(value, targets=sorted(tgt_dotted))
        value_tainted = self.tainted(value)
        # pairwise tuple-to-tuple assignment keeps taint per element
        if (
            len(targets) == 1
            and isinstance(targets[0], ast.Tuple)
            and isinstance(value, ast.Tuple)
            and len(targets[0].elts) == len(value.elts)
        ):
            for t, v in zip(targets[0].elts, value.elts):
                if isinstance(t, ast.Name):
                    self._set_taint(t.id, self.tainted(v))
                    self._track_key(t.id, v)
            return
        container = isinstance(
            value,
            (ast.List, ast.Tuple, ast.Set, ast.Dict, ast.ListComp, ast.SetComp, ast.DictComp),
        )
        for name in names:
            self._set_taint(name, value_tainted)
            if container:
                self.env.containers.add(name)
            else:
                self.env.containers.discard(name)
            self._track_key(name, value)

    def _set_taint(self, name: str, tainted: bool) -> None:
        if tainted:
            self.env.tainted.add(name)
        else:
            self.env.tainted.discard(name)
        self._revive(name)

    def _revive(self, name: str) -> None:
        self.env.dead.pop(name, None)
        # a rebind of a key name resets its use count
        if name in self.env.keys:
            self.env.key_uses[name] = 0

    def _track_key(self, name: str, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        dotted = self._callee_dotted(value)
        if any(
            d in ("jax.random.PRNGKey", "jax.random.split", "jax.random.fold_in", "jax.random.key")
            for d in dotted
        ):
            self.env.keys.add(name)
            self.env.key_uses[name] = 0

    def _donating_call(self, call: ast.Call, targets: List[str]) -> None:
        """JX003 bookkeeping: mark donated args dead unless reassigned."""
        donate: Optional[Tuple[int, ...]] = None
        f = call.func
        d = _dotted(f)
        if d is not None and d.startswith("self.") and self.fi.cls is not None:
            donate = self.idx.donating.get(f"{self.fi.cls}.{d[len('self.'):]}")
        if donate is None and isinstance(f, ast.Name):
            # a module-level jitted binding (`jitted = jax.jit(fn, ...)`)
            donate = self.idx.donating.get(f"{self.mi.modname}.{f.id}")
        if donate is None and isinstance(f, ast.Name):
            for t in _resolve_name(self.idx, self.fi, self.mi, f.id, self.aliases):
                if isinstance(t, FunctionInfo):
                    donate = self.idx.donating.get(
                        f"{t.module.modname}.{t.qualname}"
                    )
                    if donate:
                        break
                elif isinstance(t, str):
                    donate = self.idx.donating.get(t)
                    if donate:
                        break
            # locally-jitted donating callable: `step = jax.jit(f, donate_...)`
            if donate is None and f.id in self.aliases:
                pass
        if not donate:
            return
        for i in donate:
            if i >= len(call.args):
                continue
            arg = call.args[i]
            ad = _dotted(arg)
            if ad is None:
                continue
            if ad in targets:
                continue  # donated buffer is rebound by this statement: safe
            self.env.dead[ad] = getattr(call, "lineno", 0)

    # ---------------- expression rules

    def _expr(self, node: Optional[ast.AST]) -> None:
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub)
            elif isinstance(sub, (ast.Name, ast.Attribute)) and isinstance(
                getattr(sub, "ctx", None), ast.Load
            ):
                d = _dotted(sub)
                if d is not None and d in self.env.dead:
                    self._emit(
                        "JX003",
                        sub,
                        f"`{d}` was donated to a dispatch at line "
                        f"{self.env.dead[d]} and read again — its buffer may "
                        "already be reused; rebind the result "
                        "(`x, out = jitted(x, ...)`) or pass a copy",
                    )
                    self.env.dead.pop(d, None)  # one report per donation

    def _check_call(self, call: ast.Call) -> None:
        dotted = self._callee_dotted(call)
        # ---- JX001: host conversion of a tracer value
        conv = None
        if isinstance(call.func, ast.Name) and call.func.id in ("float", "int"):
            conv = call.func.id
            arg = call.args[0] if call.args else None
        elif isinstance(call.func, ast.Attribute) and call.func.attr == "item" and not call.args:
            conv = ".item()"
            arg = call.func.value
        elif any(d in ("numpy.asarray", "numpy.array", "np.asarray", "np.array") for d in dotted):
            conv = "np.asarray"
            arg = call.args[0] if call.args else None
        else:
            arg = None
        if conv is not None and arg is not None and self.tainted(arg):
            where = (
                "inside jit-reachable code (device sync per call)"
                if self.fi.jit_reachable
                else "in host code (forces a device sync of un-jitted jnp math)"
            )
            self._emit(
                "JX001",
                call,
                f"`{conv}` applied to a jnp value {where} — keep the math in "
                "jnp, or fetch once at a sync boundary via jax.device_get",
            )
        # ---- JX005: key reuse
        if any(d.startswith("jax.random.") for d in dotted) and not any(
            d in ("jax.random.PRNGKey", "jax.random.key", "jax.random.fold_in")
            for d in dotted
        ):
            if call.args and isinstance(call.args[0], ast.Name):
                name = call.args[0].id
                if name in self.env.keys:
                    self.env.key_uses[name] = self.env.key_uses.get(name, 0) + 1
                    if self.env.key_uses[name] >= 2:
                        self._emit(
                            "JX005",
                            call,
                            f"key `{name}` consumed by a second jax.random "
                            "call without an intervening split — identical "
                            "randomness; split (or fold_in) first",
                        )
        # ---- JX006: un-spanned sync
        sync = None
        if isinstance(call.func, ast.Attribute) and call.func.attr == "block_until_ready":
            sync = "block_until_ready"
        elif any(d in _SYNC_CALLS for d in dotted):
            sync = next(d for d in dotted if d in _SYNC_CALLS).split(".")[-1]
        if sync is not None and not self.env.in_span:
            self._emit(
                "JX006",
                call,
                f"`{sync}` outside a telemetry span — sync time is "
                "unattributed; wrap in `tracer.span(...)` (telemetry/spans.py) "
                "or waive with a reason if a caller holds the span",
            )
        # ---- JX007: implicit-dtype creation in jit-reachable code
        if self.fi.jit_reachable:
            self._check_implicit_dtype(call, dotted)
        # ---- JX004: mutable static args
        self._check_static_args(call, dotted)

    def _check_implicit_dtype(self, call: ast.Call, dotted: List[str]) -> None:
        hit = next((d for d in dotted if d in _IMPLICIT_DTYPE_CALLS), None)
        if hit is None:
            return
        if any(kw.arg == "dtype" for kw in call.keywords):
            return
        if len(call.args) > _IMPLICIT_DTYPE_CALLS[hit]:
            return  # positional dtype argument present
        short = hit.replace("jax.numpy.", "jnp.")
        if short in ("jnp.array", "jnp.asarray"):
            # converting a tracer keeps its dtype; only host values
            # (Python scalars/lists) take the weak-type promotion path
            if call.args and self.tainted(call.args[0]):
                return
        self._emit(
            "JX007",
            call,
            f"`{short}` with no explicit dtype in jit-reachable code — the "
            "result dtype follows weak-type/x64 promotion (f32 today, f64 "
            "under jax_enable_x64) and can silently drift a compiled "
            "program's dtypes; pass dtype= explicitly",
        )

    def _check_static_args(self, call: ast.Call, dotted: List[str]) -> None:
        static: Set[str] = set()
        target: Optional[FunctionInfo] = None
        for t in self._callee_fns(call):
            key = f"{t.module.modname}.{t.qualname}"
            if key in self.idx.static_args:
                static = self.idx.static_args[key]
                target = t
                break
        if not static or target is None:
            return

        def mutable(expr: ast.AST) -> bool:
            if isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)):
                return True
            return False

        for kw in call.keywords:
            if kw.arg in static and mutable(kw.value):
                self._emit(
                    "JX004",
                    call,
                    f"static arg `{kw.arg}` of `{target.name}` gets a "
                    "mutable (unhashable) value — jit static args must be "
                    "hashable; pass a tuple",
                )
        for i, arg in enumerate(call.args):
            if i < len(target.params) and target.params[i] in static and mutable(arg):
                self._emit(
                    "JX004",
                    call,
                    f"static arg `{target.params[i]}` of `{target.name}` gets "
                    "a mutable (unhashable) value — jit static args must be "
                    "hashable; pass a tuple",
                )


def _static_defaults(idx: Index, findings: List[Finding]) -> None:
    """JX004 at the definition: a static param defaulting to a mutable."""
    for key, static in idx.static_args.items():
        fi = idx.by_dotted.get(key)
        if fi is None:
            continue
        args = getattr(fi.node, "args", None)
        if args is None:
            continue
        pos = list(args.posonlyargs) + list(args.args)
        defaults = list(args.defaults)
        for a, d in zip(pos[len(pos) - len(defaults):], defaults):
            if a.arg in static and isinstance(d, (ast.List, ast.Dict, ast.Set)):
                findings.append(
                    Finding(
                        rule="JX004",
                        path=fi.module.relpath,
                        line=d.lineno,
                        col=d.col_offset,
                        func=fi.qualname,
                        message=(
                            f"static param `{a.arg}` defaults to a mutable "
                            "(unhashable) literal — use a tuple"
                        ),
                    )
                )
        for a, d in zip(args.kwonlyargs, args.kw_defaults):
            if d is not None and a.arg in static and isinstance(d, (ast.List, ast.Dict, ast.Set)):
                findings.append(
                    Finding(
                        rule="JX004",
                        path=fi.module.relpath,
                        line=d.lineno,
                        col=d.col_offset,
                        func=fi.qualname,
                        message=(
                            f"static param `{a.arg}` defaults to a mutable "
                            "(unhashable) literal — use a tuple"
                        ),
                    )
                )


# ----------------------------------------------------------------- drivers


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.toml")


def iter_package_files(root: Optional[str] = None) -> List[str]:
    root = root or package_root()
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if name.endswith(".py"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def lint_paths(
    paths: Sequence[str],
    baseline: Optional[str] = None,
    pkg_root: Optional[str] = None,
) -> LintResult:
    """Lint explicit files. ``baseline`` is a path to a suppression TOML
    (None = no suppressions)."""
    idx = build_index(list(paths), pkg_root or package_root())
    raw: List[Finding] = []
    for mi in idx.modules.values():
        for fi in mi.functions.values():
            _RuleWalker(idx, fi, raw).walk()
    _static_defaults(idx, raw)
    raw.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    base = load_baseline(baseline).restricted(RULES) if baseline else Baseline()
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    excluded: List[Finding] = []
    for f in raw:
        if base.excluded(f):
            excluded.append(f)
            continue
        w = base.waive(f)
        if w is not None:
            suppressed.append((f, w.reason))
        else:
            findings.append(f)
    stale = [w for w in base.waivers if not w.used]
    return LintResult(findings, suppressed, excluded, stale)


def lint_package(baseline: Optional[str] = "default") -> LintResult:
    """Lint every module of the installed package against the committed
    baseline (pass ``baseline=None`` for raw findings)."""
    if baseline == "default":
        baseline = default_baseline_path()
        if not os.path.exists(baseline):
            baseline = None
    return lint_paths(iter_package_files(), baseline=baseline)
