"""Utility subpackage. Deliberately lazy: no eager submodule imports.

``debug`` and ``profiling`` import jax at module level; eagerly pulling
them in here would make every stdlib-only utility (``xplane``,
``logging``) drag the full jax import — and, under this image's
remote-TPU plugin env, a possibly-wedged tunnel — into host-side tools
like ``cli trace-summary``. ``from ...utils import debug`` still works:
the import system falls back to importing the submodule when the
attribute is absent.
"""

from replication_faster_rcnn_tpu.utils.logging import MetricLogger  # noqa: F401
