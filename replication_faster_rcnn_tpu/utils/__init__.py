from replication_faster_rcnn_tpu.utils.logging import MetricLogger  # noqa: F401
