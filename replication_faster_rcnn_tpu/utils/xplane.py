"""Minimal XSpace/XPlane trace reader — op-level time attribution from
``jax.profiler.trace`` output with zero external tooling.

SURVEY.md §5 "tracing/profiling": the bench already records per-stage
wall times (`benchmark.py::_stage_breakdown`); this module turns a
captured trace (``<dir>/plugins/profile/*/\\*.xplane.pb``) into a per-op
table so the backward/update stages can be attributed at the XLA-op
level (VERDICT r3 #2). The image's tensorboard profile plugin cannot do
this (its generated protos predate the installed protobuf and fail to
import), so the stable xplane wire format is decoded directly: a
~60-line protobuf wire reader plus a walker for the four message types
the table needs. Schema (field numbers are stable across TF/TSL/JAX):

    XSpace   { repeated XPlane planes = 1; }
    XPlane   { int64 id=1; string name=2; repeated XLine lines=3;
               map<int64,XEventMetadata> event_metadata=4; }
    XLine    { string name=2; repeated XEvent events=4; }
    XEvent   { int64 metadata_id=1; int64 duration_ps=3; }
    XEventMetadata { int64 id=1; string name=2; string display_name=4; }

The reference has no profiling of any kind (SURVEY.md §5); torch users
reach for the TensorBoard plugin this replaces.
"""

from __future__ import annotations

import glob
import os
from typing import Dict, Iterator, List, Optional, Tuple

# ----------------------------------------------------------------- wire

_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        if i >= len(buf):
            raise ValueError(f"truncated varint at byte {i}")
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.

    LEN fields yield their raw bytes (caller decides: submessage vs
    string); unknown wire types raise — better loud than silently
    misaligned."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == _VARINT:
            v, i = _read_varint(buf, i)
        elif wt == _I64:
            if i + 8 > n:
                raise ValueError(f"truncated fixed64 at byte {i}")
            v, i = int.from_bytes(buf[i:i + 8], "little"), i + 8
        elif wt == _LEN:
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise ValueError(f"truncated length-delimited at byte {i}")
            v, i = buf[i:i + ln], i + ln
        elif wt == _I32:
            if i + 4 > n:
                raise ValueError(f"truncated fixed32 at byte {i}")
            v, i = int.from_bytes(buf[i:i + 4], "little"), i + 4
        else:
            raise ValueError(f"unsupported wire type {wt} at byte {i}")
        yield field, wt, v


# --------------------------------------------------------------- schema


def _parse_event(buf: bytes) -> Tuple[int, int]:
    """(metadata_id, duration_ps)"""
    mid = dur = 0
    for f, _, v in _fields(buf):
        if f == 1:
            mid = v
        elif f == 3:
            dur = v
    return mid, dur


def _parse_line(buf: bytes) -> Tuple[str, List[Tuple[int, int]]]:
    name, events = "", []
    for f, wt, v in _fields(buf):
        if f == 2 and wt == _LEN:
            name = v.decode("utf-8", "replace")
        elif f == 4 and wt == _LEN:
            events.append(_parse_event(v))
    return name, events


def _parse_metadata_entry(buf: bytes) -> Tuple[int, str]:
    """map<int64, XEventMetadata> entry -> (id, best name)."""
    key, name, display = 0, "", ""
    for f, wt, v in _fields(buf):
        if f == 1:
            key = v
        elif f == 2 and wt == _LEN:
            for mf, mwt, mv in _fields(v):
                if mf == 2 and mwt == _LEN:
                    name = mv.decode("utf-8", "replace")
                elif mf == 4 and mwt == _LEN:
                    display = mv.decode("utf-8", "replace")
    return key, display or name


class Plane:
    def __init__(self, name: str):
        self.name = name
        self.lines: List[Tuple[str, List[Tuple[int, int]]]] = []
        self.event_names: Dict[int, str] = {}


def parse_xspace(path: str) -> List[Plane]:
    with open(path, "rb") as f:
        space = f.read()
    planes: List[Plane] = []
    for f_no, wt, v in _fields(space):
        if f_no != 1 or wt != _LEN:
            continue
        plane = Plane("")
        for pf, pwt, pv in _fields(v):
            if pf == 2 and pwt == _LEN:
                plane.name = pv.decode("utf-8", "replace")
            elif pf == 3 and pwt == _LEN:
                plane.lines.append(_parse_line(pv))
            elif pf == 4 and pwt == _LEN:
                k, name = _parse_metadata_entry(pv)
                plane.event_names[k] = name
        planes.append(plane)
    return planes


# ---------------------------------------------------------------- table


def find_xplane_files(trace_dir: str) -> List[str]:
    """All *.xplane.pb under a ``jax.profiler.trace`` output dir."""
    return sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )


def has_device_trace(trace_dir: str) -> bool:
    """True when ``trace_dir`` holds a device profiler capture. Used by
    `telemetry.report` to point a run summary at ``trace-summary`` when a
    --profile capture sits next to the host-side span trace."""
    return bool(find_xplane_files(trace_dir))


def op_table(
    trace_dir: str,
    plane_filter: Optional[str] = None,
    top: int = 25,
) -> List[Dict[str, object]]:
    """Aggregate event durations by op name across matching planes.

    ``plane_filter`` substring-matches the plane name (e.g. "TPU" to
    exclude host threads; default: device planes preferred — any plane
    whose name contains 'TPU' or 'GPU' or starts with '/device', else
    all planes). Returns rows sorted by total time, each
    {op, total_ms, count, pct} with pct of the table's total.
    """
    totals: Dict[str, Tuple[float, int]] = {}
    for path in find_xplane_files(trace_dir):
        for plane in parse_xspace(path):
            if plane_filter is not None:
                if plane_filter.lower() not in plane.name.lower():
                    continue
            elif not _is_device_plane(plane.name):
                continue
            # device planes carry several overlapping timelines ("XLA
            # Modules" spans whole programs, "Steps" spans steps); the
            # "XLA Ops" line is the non-overlapping leaf-op timeline —
            # restrict to it when present so totals don't double-count
            lines = [
                (n, ev) for n, ev in plane.lines if n == "XLA Ops"
            ] or plane.lines
            for _, events in lines:
                for mid, dur_ps in events:
                    name = plane.event_names.get(mid, f"op#{mid}")
                    ms, cnt = totals.get(name, (0.0, 0))
                    totals[name] = (ms + dur_ps / 1e9, cnt + 1)
    if not totals and plane_filter is None:
        # host-only trace (CPU backend): fall back to every plane
        return op_table(trace_dir, plane_filter="", top=top)
    grand = sum(ms for ms, _ in totals.values()) or 1.0
    rows = [
        {
            "op": op,
            "total_ms": round(ms, 3),
            "count": cnt,
            "pct": round(100.0 * ms / grand, 2),
        }
        for op, (ms, cnt) in totals.items()
    ]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:top]


def _is_device_plane(name: str) -> bool:
    low = name.lower()
    return "tpu" in low or "gpu" in low or name.startswith("/device")


def format_table(rows: List[Dict[str, object]]) -> str:
    if not rows:
        return "(no events)"
    w = max(len(str(r["op"])) for r in rows)
    out = [f"{'op':<{w}}  total_ms   count    pct"]
    for r in rows:
        out.append(
            f"{r['op']:<{w}}  {r['total_ms']:>8.3f}  {r['count']:>6}  "
            f"{r['pct']:>5.2f}%"
        )
    return "\n".join(out)
