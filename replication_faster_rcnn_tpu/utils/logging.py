"""Structured scalar logging — replaces the reference's bare prints
(`train.py:124,143`; SURVEY.md §5 metrics/observability).

Plain-text structured lines by default; optional JSONL sink for machine
consumption. Keeps zero third-party deps (no tensorboard in this image).
"""

from __future__ import annotations

import json
import sys
import time
from typing import Any, Dict, Optional, TextIO


def _fmt(v: Any) -> str:
    try:
        return f"{float(v):.4g}"
    except (TypeError, ValueError):
        return str(v)


def _jsonable(v: Any) -> Any:
    """json.dumps ``default``: numpy/jax scalars → Python numbers, anything
    else → repr, so one odd metric value cannot kill the logging path."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


class MetricLogger:
    def __init__(
        self,
        stream: Optional[TextIO] = None,
        jsonl_path: Optional[str] = None,
        rank: Optional[int] = None,
    ):
        # None = resolve sys.stdout at write time: a default bound at import
        # time pins whatever stdout was then (stale under redirection)
        self._stream = stream
        self.jsonl_path = jsonl_path
        # process_index of a multi-process run: stamped on every JSONL row
        # so merged per-rank logs stay attributable (single-process runs
        # pass None and the rows are byte-identical to before)
        self.rank = rank
        self._t0 = time.time()

    @property
    def stream(self) -> TextIO:
        return self._stream if self._stream is not None else sys.stdout

    def _write_jsonl(self, record: Dict) -> None:
        if self.jsonl_path:
            if self.rank is not None:
                record = {"process_index": self.rank, **record}
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(record, default=_jsonable) + "\n")

    def log(self, step: int, metrics: Dict[str, float]) -> None:
        parts = " ".join(f"{k}={_fmt(v)}" for k, v in sorted(metrics.items()))
        self.stream.write(f"[step {step:>6}] {parts}\n")
        self.stream.flush()
        self._write_jsonl({"step": step, "t": time.time() - self._t0, **metrics})

    def event(self, kind: str, **fields) -> None:
        """Out-of-band run event (stall, recovery, ...) — one stream line
        plus a ``{"event": kind, ...}`` JSONL row, distinguishable from
        step rows by the absence of a ``step`` key."""
        parts = " ".join(f"{k}={_fmt(v)}" for k, v in sorted(fields.items()))
        self.stream.write(f"[event {kind}] {parts}\n")
        self.stream.flush()
        self._write_jsonl({"event": kind, "t": time.time() - self._t0, **fields})

    def log_epoch(self, epoch: int, images_per_sec: float) -> None:
        self.stream.write(
            f"[epoch {epoch:>3}] throughput={images_per_sec:.2f} images/sec\n"
        )
        self.stream.flush()
        self._write_jsonl({"epoch": epoch, "images_per_sec": images_per_sec})
