"""Visual sanity artifacts — the reference's two matplotlib ``__main__``
checks, as a real API (PIL, no matplotlib in this image):

  * anchor-center scatter (reference `utils/anchors.py:64-77`, which saves
    ``anchor_points.png``): one dot per anchor grid center over the image
    extent — a transposed-center bug (the reference had one, fixed in
    `ops/anchors.py`) shows up instantly as a rotated/clipped lattice.
  * ground-truth box overlay (reference `utils/data_loader.py:119-134`):
    draws a dataset sample's un-normalized image with its gt boxes +
    class names — the first thing to look at when labels seem wrong.

Both return the PIL image and optionally save it; `cli viz` is the
command-line surface.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def draw_labeled_boxes(draw, items, color: Tuple[int, int, int]) -> None:
    """Shared box-annotation loop (used by this module's gt overlay and
    `eval/predict.py::draw_detections`): ``items`` is an iterable of
    (row-major box [r1, c1, r2, c2], label text)."""
    for (r1, c1, r2, c2), text in items:
        draw.rectangle([c1, r1, c2, r2], outline=color, width=2)
        draw.text((c1 + 2, max(r1 - 12, 0)), text, fill=color)


def draw_anchor_centers(config, out_path: Optional[str] = None):
    """Anchor grid centers as a scatter over the configured image extent.

    Derived from the REAL anchor pipeline (``ops/anchors.make_anchors``)
    rather than stride arithmetic, so a center bug upstream shows here;
    the K same-cell anchors share a midpoint, so centers are deduplicated
    before drawing."""
    from PIL import Image, ImageDraw

    from replication_faster_rcnn_tpu.ops import anchors as anchor_ops

    h, w = config.data.image_size
    fh, fw = config.feature_size()
    all_anchors = anchor_ops.make_anchors(config.anchors, (fh, fw))
    centers = np.unique(
        np.stack(
            [
                (all_anchors[:, 0] + all_anchors[:, 2]) / 2.0,
                (all_anchors[:, 1] + all_anchors[:, 3]) / 2.0,
            ],
            axis=1,
        ),
        axis=0,
    )

    im = Image.new("RGB", (w, h), (255, 255, 255))
    draw = ImageDraw.Draw(im)
    for r, c in centers:
        if 0 <= r < h and 0 <= c < w:
            draw.ellipse([c - 1, r - 1, c + 1, r + 1], fill=(200, 30, 30))
    if out_path:
        im.save(out_path)
    return im


def _unnormalize(image: np.ndarray, mean, std) -> np.ndarray:
    """normalized float32 HWC -> uint8 RGB (uint8 passes through:
    device_normalize samples are already raw pixels)."""
    if image.dtype == np.uint8:
        return image
    arr = (image * np.asarray(std, np.float32) + np.asarray(mean, np.float32))
    return (np.clip(arr, 0.0, 1.0) * 255.0).astype(np.uint8)


def draw_gt_overlay(
    sample,
    config,
    out_path: Optional[str] = None,
    class_names: Optional[Sequence[str]] = None,
):
    """Dataset sample dict ({'image','boxes','labels','mask'}) -> PIL image
    with its ground-truth boxes drawn (row-major [r1, c1, r2, c2])."""
    from PIL import Image, ImageDraw

    from replication_faster_rcnn_tpu.config import VOC_CLASSES

    if class_names is None:
        class_names = (
            VOC_CLASSES
            if config.model.num_classes == len(VOC_CLASSES)
            else [str(i) for i in range(config.model.num_classes)]
        )
    rgb = _unnormalize(
        np.asarray(sample["image"]), config.data.pixel_mean, config.data.pixel_std
    )
    im = Image.fromarray(rgb)
    draw = ImageDraw.Draw(im)
    boxes = np.asarray(sample["boxes"])
    labels = np.asarray(sample["labels"])
    mask = np.asarray(sample["mask"])

    def _name(cls: int) -> str:
        return class_names[cls] if 0 <= cls < len(class_names) else str(cls)

    draw_labeled_boxes(
        draw,
        (
            (boxes[i], _name(int(labels[i])))
            for i in range(len(boxes))
            if bool(mask[i])
        ),
        (40, 220, 40),
    )
    if out_path:
        im.save(out_path)
    return im
