"""Profiling & timing — SURVEY.md §5 "tracing/profiling" (the reference has
none; its only signal is a per-step loss print at `train.py:124`).

Two tools:
  * :func:`trace` — context manager around `jax.profiler` producing a
    TensorBoard/Perfetto trace directory for device timeline inspection.
  * :class:`StepTimer` / :func:`measure_throughput` — wall-clock throughput
    with correct device synchronization. Synchronization is done by a
    host transfer of a scalar rather than ``block_until_ready`` because the
    remote-TPU plugin in this image returns from the latter before
    execution completes (measured ~100x inflation; see benchmark.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax


def sync(tree: Any) -> None:
    """Force completion of everything `tree` depends on (host transfer)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        jax.device_get(leaves[0])


@contextlib.contextmanager
def trace(logdir: Optional[str]) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard / Perfetto.

    ``logdir=None`` is a no-op, so callers with an optional --profile flag
    can unconditionally write ``with trace(flag):``."""
    if logdir is None:
        yield
        return
    from replication_faster_rcnn_tpu.telemetry import spans as tspans

    # mirrored as a telemetry span so the host-side trace.json shows when
    # (and for how long) the device profiler was recording
    with tspans.current_tracer().span("profiler/trace", cat="profile",
                                      logdir=logdir):
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


class StepTimer:
    """Running images/sec over a training loop (per-window, synced)."""

    def __init__(self, window: int = 50):
        self.window = window
        self._count = 0
        self._images = 0
        self._t0: Optional[float] = None
        self.images_per_sec = 0.0

    def update(self, batch_size: int, sync_tree: Any = None) -> Optional[float]:
        """Call once per step; returns images/sec at window boundaries."""
        if self._t0 is None:
            self._t0 = time.time()
        self._count += 1
        self._images += batch_size
        if self._count % self.window == 0:
            if sync_tree is not None:
                sync(sync_tree)
            dt = time.time() - self._t0
            self.images_per_sec = self._images / dt if dt > 0 else 0.0
            self._t0 = time.time()
            self._images = 0
            return self.images_per_sec
        return None


def measure_throughput(
    fn: Callable[..., Any],
    args: tuple,
    batch_size: int,
    n_steps: int = 10,
    warmup: int = 3,
    carry_state: bool = True,
) -> Dict[str, float]:
    """Benchmark a (state, batch) -> (state, aux) step function.

    With ``carry_state`` the state threads through iterations (real training
    dependency chain); sync is a host transfer of the final aux.
    """
    state, batch = args
    aux = None
    for _ in range(warmup):
        out = fn(state, batch)
        state = out[0] if carry_state else state
        aux = out[1] if isinstance(out, tuple) and len(out) > 1 else out
    sync(aux)
    t0 = time.time()
    for _ in range(n_steps):
        out = fn(state, batch)
        state = out[0] if carry_state else state
        aux = out[1] if isinstance(out, tuple) and len(out) > 1 else out
    sync(aux)
    dt = time.time() - t0
    return {
        "sec_per_step": dt / n_steps,
        "images_per_sec": n_steps * batch_size / dt,
    }
