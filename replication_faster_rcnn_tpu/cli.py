"""Command-line interface — the config/flag layer the reference never had
(SURVEY.md §5: hyperparameters live in scattered constants and a flagless
``__main__`` at reference `train.py:153-161`; BASELINE.json requires a
``--device=tpu`` path).

Subcommands:
  train      — run the jitted SPMD trainer (--telemetry enables the
               span-trace/health/watchdog observability layer)
  eval       — run inference + VOC mAP over a dataset split
  bench      — train-step throughput (same measurement as bench.py)
  telemetry  — summarize a --telemetry run dir (phase times + health)

``--config`` selects one of the five BASELINE presets (config.CONFIGS);
individual flags override preset fields.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
from typing import List, Optional


def _apply_device(device: str) -> None:
    """--device=tpu|cpu: pick the JAX backend before any computation."""
    import jax

    if device != "auto":
        jax.config.update("jax_platforms", device)


def _apply_distributed(args) -> None:
    """--num-processes/--coordinator/--process-id: bring up the multi-host
    runtime BEFORE anything queries the device topology (jax.distributed
    must initialize before the backend does). No-op single-process."""
    n = getattr(args, "num_processes", None)
    if not n or n <= 1:
        return
    from replication_faster_rcnn_tpu.parallel import initialize_distributed

    initialize_distributed(
        coordinator_address=getattr(args, "coordinator", None),
        num_processes=n,
        process_id=getattr(args, "process_id", None),
    )


def _parse_mesh_shape(text):
    """`--mesh-shape DP,MP` -> MeshConfig overrides. MP > 1 turns on
    model-axis parameter sharding (the whole point of naming a 2D mesh);
    `--mesh-shape 8,1` is an explicit dp-only pin."""
    parts = text.split(",")
    try:
        dp, mp = (int(p.strip()) for p in parts)
        if dp < 1 or mp < 1:
            raise ValueError
    except ValueError:
        raise SystemExit(
            f"--mesh-shape expects 'DP,MP' with two positive integers "
            f"(e.g. 2,4), got {text!r}"
        )
    return {"num_data": dp, "num_model": mp, "param_sharding": mp > 1}


def _build_config(args):
    from replication_faster_rcnn_tpu.config import get_config

    cfg = get_config(args.config)
    if args.dataset:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, dataset=args.dataset))
    if args.data_root:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, root_dir=args.data_root))
    if args.image_size:
        cfg = cfg.replace(
            data=dataclasses.replace(
                cfg.data, image_size=(args.image_size, args.image_size)
            )
        )
    data_kw = {}
    if getattr(args, "loader_workers", None) is not None:
        data_kw["loader_workers"] = args.loader_workers
    if getattr(args, "loader_mode", None):
        data_kw["loader_mode"] = args.loader_mode
    if getattr(args, "augment_hflip", False):
        data_kw["augment_hflip"] = True
    elif getattr(args, "no_augment_hflip", False):
        data_kw["augment_hflip"] = False
    if getattr(args, "augment_scale", None):
        data_kw["augment_scale"] = tuple(args.augment_scale)
    if getattr(args, "augment_scale_device", False):
        data_kw["augment_scale_device"] = True
    if getattr(args, "augment_device", False):
        data_kw["augment_device"] = True
    if getattr(args, "augment_translate", None) is not None:
        data_kw["augment_translate"] = args.augment_translate
    if getattr(args, "cache_ram", False):
        data_kw["loader_cache_ram"] = True
    if getattr(args, "cache_device", False):
        data_kw["cache_device"] = True
    if getattr(args, "device_normalize", False):
        data_kw["device_normalize"] = True
    if getattr(args, "prefetch_device", None) is not None:
        data_kw["prefetch_device"] = args.prefetch_device
    if getattr(args, "train_resolutions", None):
        try:
            data_kw["train_resolutions"] = tuple(
                tuple(int(x) for x in r.split("x"))
                for r in args.train_resolutions.split(",")
            )
        except ValueError:
            raise SystemExit(
                "--train-resolutions expects 'HxW,HxW' with positive "
                f"integers (e.g. 300x300,600x600), got "
                f"{args.train_resolutions!r}"
            )
    if data_kw:
        cfg = cfg.replace(data=dataclasses.replace(cfg.data, **data_kw))
    train_kw = {}
    if args.lr is not None:
        train_kw["lr"] = args.lr
    if args.batch_size is not None:
        train_kw["batch_size"] = args.batch_size
    if args.epochs is not None:
        train_kw["n_epoch"] = args.epochs
    if args.seed is not None:
        train_kw["seed"] = args.seed
    if getattr(args, "backend", None):
        train_kw["backend"] = args.backend
    if getattr(args, "shard_opt", False):
        train_kw["shard_opt_state"] = True
    if getattr(args, "eval_every", None) is not None:
        train_kw["eval_every_epochs"] = args.eval_every
    if getattr(args, "mu_dtype", None):
        train_kw["adam_mu_dtype"] = args.mu_dtype
    if getattr(args, "steps_per_dispatch", None) is not None:
        train_kw["steps_per_dispatch"] = args.steps_per_dispatch
    if getattr(args, "grad_allreduce_dtype", None):
        train_kw["grad_allreduce_dtype"] = args.grad_allreduce_dtype
    if getattr(args, "nonfinite_policy", None):
        train_kw["nonfinite_policy"] = args.nonfinite_policy
    if getattr(args, "max_consecutive_skips", None) is not None:
        train_kw["max_consecutive_skips"] = args.max_consecutive_skips
    if getattr(args, "async_checkpoint", False):
        train_kw["async_checkpoint"] = True
    if getattr(args, "lr_scaling", None):
        train_kw["lr_scaling"] = args.lr_scaling
    if getattr(args, "base_batch_size", None) is not None:
        train_kw["base_batch_size"] = args.base_batch_size
    if getattr(args, "warmup_epochs", None) is not None:
        train_kw["warmup_epochs"] = args.warmup_epochs
    if getattr(args, "lars", False):
        train_kw["lars"] = True
    if getattr(args, "optimizer", None):
        train_kw["optimizer"] = args.optimizer
    if getattr(args, "checkpoint_every_steps", None) is not None:
        train_kw["checkpoint_every_steps"] = args.checkpoint_every_steps
    if getattr(args, "sampling_strategy", None):
        train_kw["sampling_strategy"] = args.sampling_strategy
    if train_kw:
        cfg = cfg.replace(train=dataclasses.replace(cfg.train, **train_kw))
    if getattr(args, "compile_cache", None):
        cfg = cfg.replace(
            compile=dataclasses.replace(
                cfg.compile, cache_dir=args.compile_cache
            )
        )
    if getattr(args, "strict", False):
        cfg = cfg.replace(
            debug=dataclasses.replace(cfg.debug, strict=True)
        )
    if getattr(args, "threadsan", False):
        cfg = cfg.replace(
            debug=dataclasses.replace(cfg.debug, threadsan=True)
        )
    if getattr(args, "chaos_spec", None):
        cfg = cfg.replace(
            debug=dataclasses.replace(cfg.debug, chaos_spec=args.chaos_spec)
        )
    if (args.backbone or args.roi_op or getattr(args, "remat", False)
            or getattr(args, "frozen_bn", False)
            or getattr(args, "norm", None)):
        model_kw = {}
        if args.backbone:
            model_kw["backbone"] = args.backbone
        if args.roi_op:
            model_kw["roi_op"] = args.roi_op
        if getattr(args, "remat", False):
            model_kw["remat"] = True
        if getattr(args, "frozen_bn", False):
            model_kw["frozen_bn"] = True
        if getattr(args, "norm", None):
            model_kw["norm"] = args.norm
        cfg = cfg.replace(model=dataclasses.replace(cfg.model, **model_kw))
    mesh_kw = {}
    if getattr(args, "mesh_shape", None):
        mesh_kw.update(_parse_mesh_shape(args.mesh_shape))
    if getattr(args, "num_model", None) is not None:
        mesh_kw["num_model"] = args.num_model
    if getattr(args, "spatial", False):
        mesh_kw["spatial"] = True
    if mesh_kw:
        cfg = cfg.replace(mesh=dataclasses.replace(cfg.mesh, **mesh_kw))
    eval_kw = {}
    if getattr(args, "iou_thresh", None) is not None:
        eval_kw["iou_thresh"] = args.iou_thresh
    if getattr(args, "use_07_metric", False):
        eval_kw["use_07_metric"] = True
    if getattr(args, "metric", None):
        eval_kw["metric"] = args.metric
    if getattr(args, "tta_hflip", False):
        eval_kw["tta_hflip"] = True
    if eval_kw:
        cfg = cfg.replace(eval=dataclasses.replace(cfg.eval, **eval_kw))
    return cfg


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", default="voc_resnet18",
                   help="preset name (see replication_faster_rcnn_tpu.config.CONFIGS)")
    p.add_argument("--device", default="auto", choices=["auto", "tpu", "cpu"],
                   help="JAX backend (BASELINE --device flag)")
    p.add_argument("--strict", action="store_true",
                   help="runtime jit-hygiene gate (debug.strict): "
                        "jax.transfer_guard('disallow') for the whole "
                        "session + a per-program recompile check after "
                        "warmup — implicit transfers and silent recompiles "
                        "raise instead of eating throughput")
    p.add_argument("--threadsan", action="store_true",
                   help="runtime lock sanitizer (debug.threadsan): "
                        "package-created locks/queues are instrumented, "
                        "lock-order inversions raise (lightweight lockdep), "
                        "and held-duration + queue-depth gauges feed the "
                        "telemetry watchdog; runtime half of the TL rules "
                        "in 'frcnn check'")
    p.add_argument("--chaos-spec", default=None, metavar="SPEC",
                   help="deterministic fault injection (faultlib): "
                        "'site:kind:prob:seed[:arg[:max_fires[:after]]]' comma "
                        "list, or a JSON schedule file (path or @path); "
                        "sites/kinds in faultlib.failpoints.SITES/KINDS. "
                        "Same spec + seed => identical fault sequence")
    p.add_argument("--dataset", default=None, choices=[None, "voc", "coco", "synthetic"])
    p.add_argument("--data-root", default=None)
    p.add_argument("--image-size", type=int, default=None)
    p.add_argument("--backbone", default=None,
                   choices=[None, "resnet18", "resnet34", "resnet50", "resnet101",
                            "resnet152", "resnext50_32x4d", "resnext101_32x8d",
                            "wide_resnet50_2", "wide_resnet101_2", "vgg16"])
    p.add_argument("--roi-op", default=None, choices=[None, "align", "pool"])
    p.add_argument("--batch-size", type=int, default=None)
    p.add_argument("--lr", type=float, default=None)
    p.add_argument("--epochs", type=int, default=None)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--backend", default=None, choices=[None, "auto", "spmd"],
                   help="SPMD backend: jit auto-partitioning or explicit "
                        "shard_map collectives (parallel/spmd.py)")
    p.add_argument("--shard-opt", action="store_true",
                   help="ZeRO-1 weight-update sharding: Adam moments shard "
                        "over the data axis (arXiv:2004.13336). Works on "
                        "both backends: jit lets GSPMD place the "
                        "collectives, spmd hand-places reduce-scatter + "
                        "all-gather around a sharded update")
    p.add_argument("--num-processes", type=int, default=None, metavar="N",
                   help="multi-host data parallelism: total process count "
                        "of this run (each process sees only its local "
                        "devices; batch-size stays GLOBAL and must divide "
                        "by N). Pair with --coordinator/--process-id")
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="coordinator address for --num-processes > 1 "
                        "(jax.distributed.initialize)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's rank in [0, --num-processes) "
                        "(rank 0 is the coordinator: it owns checkpoints, "
                        "manifests and the canonical telemetry files)")
    p.add_argument("--lr-scaling", default=None, choices=[None, "none", "linear"],
                   help="large-batch LR recipe: 'linear' scales the peak "
                        "LR by batch_size / base-batch-size "
                        "(arXiv:1706.02677 via arXiv:1711.04325)")
    p.add_argument("--base-batch-size", type=int, default=None,
                   help="reference batch size the preset LR was tuned at "
                        "(denominator of --lr-scaling linear; default 8)")
    p.add_argument("--warmup-epochs", type=float, default=None,
                   help="linear LR warmup from ~0 to the (scaled) peak "
                        "over this many epochs before the cosine decay "
                        "(large-batch stability; fractions allowed)")
    p.add_argument("--lars", action="store_true",
                   help="layer-wise trust-ratio scaling (LARS, "
                        "arXiv:1708.03888) between Adam and the LR — the "
                        "large-batch optimizer recipe. Incompatible with "
                        "--shard-opt on the spmd backend (per-leaf norms)")
    p.add_argument("--optimizer", default=None, choices=[None, "adam", "lamb"],
                   help="optimizer chain (train.optimizer): 'adam' "
                        "(default) or 'lamb' — Adam plus a per-layer "
                        "trust ratio (arXiv:1904.00962). LAMB composes "
                        "with --shard-opt on BOTH backends: the spmd+ZeRO "
                        "path computes each layer's norms from its local "
                        "shard and completes them with a psum, so the "
                        "trust ratio is exact at 1/N moment memory")
    p.add_argument("--checkpoint-every-steps", type=int, default=None,
                   metavar="N",
                   help="scheduled checkpoint every N optimizer steps, in "
                        "addition to the per-epoch cadence (0 = off). "
                        "Bounds the rollback of an elastic re-formation, "
                        "which resumes from the last verified step "
                        "(train.checkpoint_every_steps)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each trunk block (recompute "
                        "activations in backward; saves HBM)")
    p.add_argument("--frozen-bn", action="store_true",
                   help="freeze BatchNorm statistics during training "
                        "(detection fine-tuning practice; each BN becomes "
                        "a fusable affine. Affine scale/bias stay "
                        "trainable, unlike torchvision's full freeze)")
    p.add_argument("--norm", default=None, choices=[None, "batch", "group"],
                   help="backbone normalization: 'batch' (reference "
                        "semantics) or 'group' (GroupNorm(32), BN-free — "
                        "no batch-stats reductions/fusion breaks; "
                        "torch-pretrained BN weights don't convert)")
    p.add_argument("--mu-dtype", default=None,
                   choices=[None, "float32", "bfloat16"],
                   help="dtype for Adam's first moment (bfloat16 halves "
                        "its HBM traffic in the update)")
    p.add_argument("--steps-per-dispatch", type=int, default=None,
                   help="fuse K train steps into one jitted dispatch "
                        "(lax.scan over K device-resident batches; "
                        "amortizes per-step Python dispatch, metrics "
                        "sync only at log boundaries)")
    p.add_argument("--grad-allreduce-dtype", default=None,
                   choices=[None, "float32", "bfloat16"],
                   help="dtype the gradient all-reduce rides in; "
                        "bfloat16 halves the psum bytes on the shard_map "
                        "backend and de-casts for fp32 optimizer math")
    p.add_argument("--nonfinite-policy", default=None,
                   choices=[None, "apply", "skip", "halt"],
                   help="what the jitted step does with a non-finite "
                        "gradient: skip (default) withholds the update "
                        "(params/opt state/BN stats unchanged, skipped=1 "
                        "in metrics), halt raises on the first skip, "
                        "apply is the unguarded update")
    p.add_argument("--max-consecutive-skips", type=int, default=None,
                   help="consecutive nonfinite-gradient skips before "
                        "training raises instead of free-running on a "
                        "divergent model (nonfinite-policy=skip)")
    p.add_argument("--loader-workers", type=int, default=None,
                   help="host input-pipeline worker count")
    p.add_argument("--loader-mode", default=None,
                   choices=[None, "thread", "process"],
                   help="input workers as GIL-releasing threads (native "
                        "decode) or forked processes (Python-bound work)")
    p.add_argument("--device-normalize", action="store_true",
                   help="ship uint8 images to the device and normalize "
                        "on-chip (4x less host->device transfer)")
    p.add_argument("--cache-ram", action="store_true",
                   help="cache decoded samples in host RAM (epoch 1 pays "
                        "the decode, later epochs are memcpy; bounded by "
                        "FRCNN_CACHE_MAX_BYTES, default 64 GiB)")
    p.add_argument("--cache-device", action="store_true",
                   help="device-resident dataset: upload all samples to "
                        "HBM once, ship only batch indices per step and "
                        "gather/augment inside the jitted step (pair with "
                        "--device-normalize; bounded by "
                        "FRCNN_DEVICE_CACHE_MAX_BYTES, default 8 GiB)")
    p.add_argument("--augment-hflip", action="store_true",
                   help="50%% horizontal-flip train augmentation "
                        "(deterministic per seed/epoch/index; the VOC "
                        "presets default it ON)")
    p.add_argument("--no-augment-hflip", action="store_true",
                   help="disable the flip (reproduces the reference's "
                        "no-augmentation training on VOC presets)")
    p.add_argument("--augment-scale", type=float, nargs=2, default=None,
                   metavar=("LO", "HI"),
                   help="random scale-jitter augmentation, e.g. 0.75 1.25 "
                        "(fixed canvas: zoom-out pads, zoom-in crops; "
                        "deterministic per seed/epoch/index)")
    p.add_argument("--augment-scale-device", action="store_true",
                   help="run the jitter's image resample on device (host "
                        "transforms boxes only; removes the per-sample "
                        "host resample cost from ingest)")
    p.add_argument("--augment-device", action="store_true",
                   help="run ALL enabled augmentations (flip/scale/"
                        "translate) as jitted batch ops inside the "
                        "compiled step; the host loader ships raw pixels "
                        "plus per-row (index, epoch) tags and never "
                        "touches image bytes (data.augment_device)")
    p.add_argument("--augment-translate", type=float, default=None,
                   metavar="FRAC",
                   help="random translation jitter up to FRAC of the "
                        "canvas per axis (device-mode only: requires "
                        "--augment-device; boxes shifted and clamped, "
                        "collapsed rows masked; data.augment_translate)")
    p.add_argument("--train-resolutions", default=None, metavar="HxW,HxW",
                   help="multi-scale bucketed training, e.g. "
                        "'300x300,600x600': each dispatch chunk is "
                        "deterministically hashed to one bucket and "
                        "trained through that bucket's own compiled "
                        "program (on-device resize + box rescale; "
                        "data.train_resolutions)")
    p.add_argument("--sampling-strategy", default=None,
                   choices=[None, "random", "topk_iou"],
                   help="second-stage ROI sampling "
                        "(train.sampling_strategy): 'random' draws the "
                        "pos/neg quotas uniformly (reference recipe); "
                        "'topk_iou' keeps the highest-IoU positives and "
                        "hardest negatives deterministically "
                        "(arXiv:1702.02138 biased sampling)")
    p.add_argument("--prefetch-device", type=int, default=None, metavar="N",
                   help="double-buffered DEVICE staging: a producer thread "
                        "collates and starts the next batch's host->device "
                        "transfer while the current dispatch runs (N = "
                        "buffer depth, 2 = classic double buffering, "
                        "0 = off). Chunk-aware under --steps-per-dispatch; "
                        "works with every feed incl. --cache-device")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="scheduled checkpoints snapshot to host and "
                        "serialize + CRC-manifest on a background writer "
                        "(training blocks only if the previous save is "
                        "still in flight); emergency/final/crash saves "
                        "stay synchronous. Multi-process runs keep the "
                        "snapshot on device and every rank's writer "
                        "thread joins the collective save")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compilation cache: compiled "
                        "programs are written here and restarts "
                        "deserialize instead of re-running XLA (pair with "
                        "the 'warmup' subcommand to prepopulate)")
    p.add_argument("--num-model", type=int, default=None,
                   help="size of the mesh's model axis")
    p.add_argument("--spatial", action="store_true",
                   help="shard image rows over the model axis (spatial "
                        "partitioning; GSPMD conv halo exchange)")
    p.add_argument("--mesh-shape", default=None, metavar="DP,MP",
                   help="2D device mesh as 'DP,MP' (e.g. 2,4): DP-way "
                        "data parallelism x MP-way model parallelism with "
                        "parameters sharded 1/MP over the model axis "
                        "(mesh.param_sharding; requires the jit "
                        "auto-partitioning backend)")


def _threadsan_session(enabled: bool):
    """Context manager installing the runtime lock sanitizer BEFORE the
    threaded subsystems are constructed (their instance locks/queues must
    be created under the patched factories), printing the report on exit."""
    import contextlib

    if not enabled:
        return contextlib.nullcontext(None)

    @contextlib.contextmanager
    def session():
        from replication_faster_rcnn_tpu.analysis.threadsan import (
            ThreadSanitizer,
        )

        san = ThreadSanitizer()
        with san:
            yield san
        rep = san.report()
        print(
            f"threadsan: {len(rep['inversions'])} lock-order inversion(s), "
            f"{rep['locks_tracked']} lock(s) and "
            f"{rep['queues_tracked']} queue(s) tracked",
            file=sys.stderr,
        )

    return session()


def cmd_train(args) -> int:
    if getattr(args, "elastic", False):
        # fleet supervisor mode: this process never touches jax — it
        # spawns the real training child per fleet generation and
        # re-forms the fleet when the child dies of a lost rank
        return _cmd_train_elastic(args)
    with _threadsan_session(getattr(args, "threadsan", False)) as san:
        return _cmd_train_impl(args, san)


def _cmd_train_elastic(args) -> int:
    """--elastic: per-host fleet supervisor (parallel/elastic.py).

    Spawns the training child (this same CLI minus --elastic, plus the
    generation's topology flags) and loops the re-formation protocol:
    a child that exits EXIT_FLEET_SHRINK — its elastic agent detected a
    peer's lease expiring — triggers claim/plan arbitration with the
    other surviving supervisors through the shared fleet dir, and the
    child respawns at the surviving world size with --resume, a bumped
    coordinator port and FRCNN_FLEET_GENERATION exported. Exit 0 and
    EXIT_PREEMPTED propagate; any other child exit means this host is
    the casualty and its supervisor leaves the fleet."""
    import os
    import subprocess

    from replication_faster_rcnn_tpu.config import get_config
    from replication_faster_rcnn_tpu.parallel import elastic

    world = args.num_processes or 1
    rank = args.process_id or 0
    coordinator = args.coordinator or "127.0.0.1:9911"
    host, _, port = coordinator.rpartition(":")
    fleet_dir = os.path.join(args.workdir, "fleet")
    el_cfg = get_config(args.config).elastic
    argv0 = list(getattr(args, "_argv", None) or sys.argv[1:])

    def spawn(generation, rank, world, coordinator):
        child = elastic.child_argv(
            argv0, generation=generation, rank=rank, world=world,
            coordinator=coordinator,
        )
        return subprocess.Popen(
            [sys.executable, "-m", "replication_faster_rcnn_tpu", *child],
            env=elastic.child_env(os.environ, fleet_dir, generation),
        )

    return elastic.run_supervisor(
        spawn,
        fleet_dir=fleet_dir,
        rank=rank,
        world=world,
        host=host or "127.0.0.1",
        base_port=int(port),
        settle_s=el_cfg.settle_s,
        max_generations=el_cfg.max_generations,
    )


def _cmd_train_impl(args, san=None) -> int:
    _apply_device(args.device)
    _apply_distributed(args)
    if args.debug_nans:
        from replication_faster_rcnn_tpu.utils.debug import enable_nan_checks

        enable_nan_checks()
    from replication_faster_rcnn_tpu.train import Trainer

    cfg = _build_config(args)
    if cfg.debug.chaos_spec:
        from replication_faster_rcnn_tpu.faultlib import failpoints

        failpoints.configure(cfg.debug.chaos_spec)
    trainer = Trainer(
        cfg,
        workdir=args.workdir,
        telemetry_dir=args.telemetry,
        stall_timeout_s=args.stall_timeout,
    )
    if san is not None and trainer.watchdog is not None:
        san.register_gauges(trainer.watchdog)
    if args.pretrained_backbone:
        trainer.load_pretrained_backbone(args.pretrained_backbone)
    from replication_faster_rcnn_tpu.utils.profiling import trace

    from replication_faster_rcnn_tpu.train.fault import (
        EXIT_FLEET_SHRINK,
        EXIT_PREEMPTED,
        FleetShrink,
        GracefulShutdown,
        Preempted,
        check_step_metrics,
    )

    if args.steps:
        # bounded-step mode (smoke/CI): iterate the feed cyclically
        # (the index sampler in --cache-device mode, the loader otherwise)
        import itertools

        feed = trainer.sampler if trainer.device_cache is not None else trainer.loader
        it = itertools.cycle(iter(feed))

        # honor --resume here too: the preemption message tells the user to
        # restart with it, and bounded-step runs are preemptible as well.
        # --steps N is a global-step target, so a resumed run does the rest.
        start = trainer.restore() if args.resume else 0
        if start:
            print(f"resumed from checkpoint at step {start}", file=sys.stderr)

        def _log(i, metrics, row=None):
            import jax

            with trainer.tracer.span("step/sync", cat="sync"):
                host_metrics = jax.device_get(metrics)
            if row is not None:
                host_metrics = {k: v[row] for k, v in host_metrics.items()}
            trainer.logger.log(i, check_step_metrics(host_metrics, i))
            trainer.skip_monitor.drain()

        k = trainer.steps_per_dispatch
        log_every = max(1, args.log_every)
        try:
            with trainer.telemetry_session(), trainer.strict_session(), \
                    GracefulShutdown() as shutdown:
                with trace(args.profile):
                    done = start
                    while done < args.steps:
                        # full chunks ride the fused dispatch; a remainder
                        # shorter than K falls back to the per-step path
                        fused = k > 1 and args.steps - done >= k
                        take = k if fused else 1
                        with trainer.tracer.span("data/fetch", cat="data"):
                            batches = [next(it) for _ in range(take)]
                        # multi-scale buckets: bounded-step runs have no
                        # epoch loop, so the bucket hash keys off the
                        # global step (deterministic across restarts)
                        bucket = (
                            feed.bucket_of(done)
                            if trainer.jitted_bucket_steps is not None
                            else None
                        )
                        if fused:
                            metrics = trainer.train_chunk(batches, bucket=bucket)
                        else:
                            metrics = trainer.train_one_batch(
                                batches[0], bucket=bucket
                            )
                        if trainer.watchdog is not None:
                            trainer.watchdog.beat(step=done + take, phase="train")
                        # same cadence as the per-step loop: log the first
                        # 0-indexed step i in this dispatch with i % log_every
                        # == 0 (chunk-aware: index into the stacked metrics)
                        for i in range(done, done + take):
                            if i % log_every == 0:
                                _log(i, metrics, row=(i - done) if fused else None)
                                break
                        done += take
                        if shutdown.requested:
                            # same dispatch-boundary semantics as the epoch
                            # loop: emergency checkpoint, then distinct code
                            trainer._fault_incident(
                                "preempted", step=done,
                                reason=shutdown.reason or "signal",
                            )
                            trainer.save(kind="emergency")
                            raise Preempted(done, shutdown.reason or "signal")
                    trainer.skip_monitor.drain()
        except Preempted as p:
            print(f"{p} (exit {EXIT_PREEMPTED})", file=sys.stderr)
            return EXIT_PREEMPTED
        return 0
    try:
        with trace(args.profile):
            trainer.train(resume=args.resume, log_every=args.log_every)
    except Preempted as p:
        print(f"{p} (exit {EXIT_PREEMPTED})", file=sys.stderr)
        return EXIT_PREEMPTED
    except FleetShrink as fs:
        # the elastic agent already wrote the durable shrink intent the
        # supervisor re-forms from, and deliberately saved nothing (a
        # checkpoint save is a cross-process collective — it would hang
        # on the dead peer). Hard-exit: a normal interpreter exit would
        # run jax.distributed's atexit shutdown, which can wedge on the
        # dead peer, and the coordination service SIGABRTs us at ~10s
        # regardless.
        import os

        print(f"{fs} (exit {EXIT_FLEET_SHRINK})", file=sys.stderr)
        sys.stderr.flush()
        sys.stdout.flush()
        os._exit(EXIT_FLEET_SHRINK)
    except BaseException as e:
        if args.on_crash_checkpoint:
            # best-effort: persist whatever state survived the crash; the
            # manifest tags it kind="crash" so restore tooling can tell
            print(
                f"crash ({type(e).__name__}); attempting --on-crash-checkpoint "
                "save",
                file=sys.stderr,
            )
            trainer.save(kind="crash", required=False)
        raise
    trainer.save(kind="final")
    return 0


def cmd_eval(args) -> int:
    _apply_device(args.device)
    from replication_faster_rcnn_tpu.data import make_dataset
    from replication_faster_rcnn_tpu.eval import Evaluator
    from replication_faster_rcnn_tpu.train.trainer import load_eval_variables

    cfg = _build_config(args)
    from replication_faster_rcnn_tpu.train.warmup import maybe_enable_compile_cache

    maybe_enable_compile_cache(cfg)
    model, variables = load_eval_variables(cfg, args.workdir, args.checkpoint_step)
    dataset = make_dataset(cfg.data, args.split)
    ev = Evaluator(cfg, model)
    if cfg.debug.strict:
        from replication_faster_rcnn_tpu.analysis.strict import StrictHarness

        ev.strict = StrictHarness(cfg.debug.strict_warmup)
        with ev.strict.session():
            result = ev.evaluate(
                variables, dataset, batch_size=cfg.train.batch_size,
                max_images=args.max_images,
            )
    else:
        result = ev.evaluate(
            variables, dataset, batch_size=cfg.train.batch_size,
            max_images=args.max_images,
        )
    if cfg.eval.metric == "coco":
        print(
            f"mAP@[.50:.95]: {result['mAP']:.4f} "
            f"(AP50 {result.get('AP50', float('nan')):.4f}, "
            f"AP75 {result.get('AP75', float('nan')):.4f})"
        )
        if "AP_small" in result:
            print(
                f"  area: small {result['AP_small']:.4f}  "
                f"medium {result['AP_medium']:.4f}  "
                f"large {result['AP_large']:.4f}  (-1 = no gt in range)"
            )
    else:
        print(f"mAP@{cfg.eval.iou_thresh}: {result['mAP']:.4f}")
    if args.per_class and "ap_per_class" in result:
        import numpy as np

        from replication_faster_rcnn_tpu.config import COCO_CLASSES, VOC_CLASSES

        names = {len(VOC_CLASSES): VOC_CLASSES, len(COCO_CLASSES): COCO_CLASSES}.get(
            cfg.model.num_classes,
            [str(i) for i in range(cfg.model.num_classes)],
        )
        aps = result["ap_per_class"]
        for c in range(1, cfg.model.num_classes):
            ap = aps[c]
            shown = "   n/a" if not np.isfinite(ap) else f"{ap:6.4f}"
            print(f"  {names[c]:>16s}  AP {shown}")
    return 0


def cmd_quantize(args) -> int:
    """PTQ calibration (+ optional sensitivity sweep) -> sidecar artifact.

    Calibrates per-channel int8 weight scales and activation ranges from
    a small sweep through the inference forward, optionally runs the
    per-layer-group sensitivity sweep (quantize one group at a time;
    groups whose response-reconstruction error or mAP drop crosses the
    `quant.*` budgets fall back to bf16), and writes the CRC-manifested
    sidecar `frcnn serve --params-dtype int8` loads.
    """
    import dataclasses as _dc
    import json

    _apply_device(args.device)
    from replication_faster_rcnn_tpu import quant
    from replication_faster_rcnn_tpu.train.fault import config_hash
    from replication_faster_rcnn_tpu.train.trainer import load_eval_variables

    cfg = _build_config(args)
    q = cfg.quant
    if args.calib_batches is not None:
        q = _dc.replace(q, calib_batches=args.calib_batches)
    if args.calib_batch_size is not None:
        q = _dc.replace(q, calib_batch_size=args.calib_batch_size)
    cfg = cfg.replace(quant=q)
    model, variables = load_eval_variables(cfg, args.workdir, args.checkpoint_step)

    if cfg.data.dataset == "synthetic" or args.synthetic_calib:
        batches = quant.synthetic_calibration_batches(
            cfg, cfg.quant.calib_batches, cfg.quant.calib_batch_size
        )
    else:
        from replication_faster_rcnn_tpu.data import make_dataset

        batches = quant.dataset_calibration_batches(
            make_dataset(cfg.data, args.split),
            cfg.quant.calib_batches,
            cfg.quant.calib_batch_size,
        )
    artifact = quant.calibrate(model, variables, batches, cfg)

    if args.sweep:
        from replication_faster_rcnn_tpu.quant.sensitivity import sweep

        eval_fn = None
        if args.sweep_map_images:
            from replication_faster_rcnn_tpu.data import make_dataset
            from replication_faster_rcnn_tpu.eval import Evaluator

            ev = Evaluator(cfg, model)
            eval_ds = make_dataset(cfg.data, args.eval_split)
            eval_fn = lambda v: ev.evaluate(  # noqa: E731
                v,
                eval_ds,
                batch_size=cfg.train.batch_size,
                max_images=args.sweep_map_images,
            )["mAP"]
        artifact = sweep(model, variables, artifact, batches, cfg, eval_fn)

    path = args.output or quant.default_artifact_path(cfg, args.workdir)
    quant.save_artifact(path, artifact, config_hash=config_hash(cfg))
    print(
        json.dumps(
            {
                "artifact": path,
                "groups": sorted(artifact["groups"]),
                "plan": artifact["plan"],
                "sensitivity": {
                    g: rec
                    for g, rec in artifact.get("sensitivity", {}).items()
                },
                "calib": artifact["calib"],
            },
            indent=2,
        )
    )
    return 0


def cmd_bench(args) -> int:
    _apply_device(args.device)
    from replication_faster_rcnn_tpu.benchmark import main as bench_main

    # pass flag overrides through; None keeps the flagship default setup
    flagged = any(
        v is not None
        for v in (
            args.dataset, args.data_root, args.image_size, args.backbone,
            args.roi_op, args.batch_size, args.lr, args.epochs, args.seed,
            args.num_model, args.mesh_shape, args.backend, args.mu_dtype,
            args.loader_workers,
            args.loader_mode, args.augment_scale, args.norm,
            args.steps_per_dispatch, args.grad_allreduce_dtype,
            args.nonfinite_policy, args.max_consecutive_skips,
            args.prefetch_device, args.compile_cache,
        )
    ) or (
        args.spatial or args.remat or args.shard_opt or args.augment_hflip
        or args.frozen_bn or args.augment_scale_device
        or getattr(args, "augment_device", False)
        or getattr(args, "augment_translate", None) is not None
        or args.no_augment_hflip or args.cache_ram or args.device_normalize
        or getattr(args, "cache_device", False)
        or args.async_checkpoint
        or args.config != "voc_resnet18"
    )
    if args.compile_cache:
        from replication_faster_rcnn_tpu.train.warmup import enable_compile_cache

        enable_compile_cache(args.compile_cache)
    bench_main(_build_config(args) if flagged else None, profile_dir=args.profile)
    return 0


def cmd_warmup(args) -> int:
    """AOT-compile the train (and optionally eval) programs for a config
    without touching data or parameters — typically with --compile-cache
    set, so a later real run (same config/mesh/jaxlib) starts with every
    program already compiled (train/warmup.py)."""
    _apply_device(args.device)
    import json

    from replication_faster_rcnn_tpu.telemetry import spans as tspans
    from replication_faster_rcnn_tpu.train.warmup import (
        maybe_enable_compile_cache,
        warmup_compile,
    )

    cfg = _build_config(args)
    cache_path = maybe_enable_compile_cache(cfg)
    tracer = None
    if args.telemetry:
        import os

        os.makedirs(args.telemetry, exist_ok=True)
        tracer = tspans.SpanTracer(
            os.path.join(args.telemetry, "trace.json"),
            max_events=cfg.telemetry.trace_max_events,
        )
        tspans.set_tracer(tracer)
    try:
        times = warmup_compile(
            cfg,
            include_eval=not args.train_only,
            include_serving=args.serving,
        )
    finally:
        if tracer is not None:
            tracer.flush()
    out = {"compile_seconds": times}
    if cache_path:
        out["compile_cache"] = cache_path
    print(json.dumps(out, indent=2))
    return 0


def cmd_predict(args) -> int:
    _apply_device(args.device)
    import json
    import os

    from replication_faster_rcnn_tpu.eval.predict import (
        draw_detections,
        predict_images,
    )
    from replication_faster_rcnn_tpu.train.trainer import load_eval_variables

    cfg = _build_config(args)
    model, variables = load_eval_variables(cfg, args.workdir, args.checkpoint_step)
    paths = list(args.image)
    # all paths go through the serving engine as one submission wave, so
    # same-bucket images share micro-batched dispatches
    dets = predict_images(cfg, model, variables, paths, args.score_thresh)
    if len(paths) == 1:
        print(json.dumps(dets[0], indent=2))
    else:
        print(json.dumps(dict(zip(paths, dets)), indent=2))
    if args.output:
        if len(paths) == 1:
            draw_detections(paths[0], dets[0], args.output)
            print(f"annotated image written to {args.output}")
        else:
            root, ext = os.path.splitext(args.output)
            for i, (path, d) in enumerate(zip(paths, dets)):
                out = f"{root}.{i}{ext or '.jpg'}"
                draw_detections(path, d, out)
                print(f"annotated image written to {out}")
    return 0


def cmd_serve(args) -> int:
    """Bucketed AOT serving (serving/): compile every (resolution x
    batch) bucket program at startup, hold the inference params resident
    on device, and serve HTTP requests through the continuous
    micro-batching engine."""
    with _threadsan_session(getattr(args, "threadsan", False)):
        return _cmd_serve_impl(args)


def _replica_trace_rank(replica_id: str) -> int:
    """Stable nonzero rank for a replica's trace file name. The
    telemetry report merges DIR/trace.json (the fleet front writes it —
    rank 0) with every DIR/trace.rankN.json sibling, so replicas
    sharing the front's DIR need a small stable N >= 1: the digits of
    the conventional r<K> ids shifted by one, else a crc of the id."""
    import re as _re
    import zlib

    m = _re.search(r"(\d+)$", replica_id)
    if m:
        return int(m.group(1)) + 1
    return zlib.crc32(replica_id.encode()) % 9000 + 1000


def _cmd_serve_impl(args) -> int:
    _apply_device(args.device)
    import contextlib
    import dataclasses as _dc
    import json

    from replication_faster_rcnn_tpu.serving.engine import InferenceEngine
    from replication_faster_rcnn_tpu.serving.server import make_server
    from replication_faster_rcnn_tpu.train.trainer import load_eval_variables
    from replication_faster_rcnn_tpu.train.warmup import (
        maybe_enable_compile_cache,
    )

    cfg = _build_config(args)
    serving = cfg.serving
    if args.max_delay_ms is not None:
        serving = _dc.replace(serving, max_delay_ms=args.max_delay_ms)
    if args.bucket_batch_sizes:
        serving = _dc.replace(
            serving,
            batch_sizes=tuple(
                int(b) for b in args.bucket_batch_sizes.split(",")
            ),
        )
    if args.resolutions:
        serving = _dc.replace(
            serving,
            resolutions=tuple(
                tuple(int(x) for x in r.split("x"))
                for r in args.resolutions.split(",")
            ),
        )
    if args.params_dtype:
        serving = _dc.replace(serving, params_dtype=args.params_dtype)
    if args.request_timeout_s is not None:
        serving = _dc.replace(serving, request_timeout_s=args.request_timeout_s)
    if args.adaptive_delay:
        serving = _dc.replace(serving, adaptive_delay=True)
    cfg = cfg.replace(serving=serving)
    if cfg.debug.chaos_spec:
        from replication_faster_rcnn_tpu.faultlib import failpoints

        failpoints.configure(cfg.debug.chaos_spec)
    maybe_enable_compile_cache(cfg)
    tracer = None
    if args.telemetry:
        import os

        from replication_faster_rcnn_tpu.telemetry import spans as tspans

        os.makedirs(args.telemetry, exist_ok=True)
        rank = (
            _replica_trace_rank(args.replica_id) if args.replica_id else None
        )
        name = f"trace.rank{rank}.json" if rank else "trace.json"
        tracer = tspans.SpanTracer(
            os.path.join(args.telemetry, name),
            rank=rank,
            max_events=cfg.telemetry.trace_max_events,
        )
        tspans.set_tracer(tracer)
    model, variables = load_eval_variables(cfg, args.workdir, args.checkpoint_step)
    artifact_path = None
    if cfg.serving.params_dtype == "int8":
        # resolve the sidecar next to the served checkpoint; the engine
        # raises QuantArtifactError (naming `frcnn quantize`) if missing
        from replication_faster_rcnn_tpu.quant import default_artifact_path

        artifact_path = default_artifact_path(cfg, args.workdir)
    engine = InferenceEngine(
        cfg,
        model,
        variables,
        warmup=True,
        artifact_path=artifact_path,
        model_version=(
            str(args.checkpoint_step)
            if args.checkpoint_step is not None
            else "0"
        ),
    )
    stack = contextlib.ExitStack()
    if args.strict or cfg.debug.strict:
        from replication_faster_rcnn_tpu.analysis.strict import StrictHarness

        engine.strict = StrictHarness(
            warmup_dispatches=cfg.debug.strict_warmup
        )
        stack.enter_context(engine.strict.session())
    print(
        json.dumps(
            {
                "buckets": [list(b) for b in engine.buckets],
                "batch_sizes": list(engine.batch_sizes),
                "max_delay_ms": cfg.serving.max_delay_ms,
                "params_dtype": cfg.serving.params_dtype,
                "params_bytes": engine.params_bytes,
                "compile_seconds": engine.compile_seconds,
                "model_version": engine.model_version,
                "strict": engine.strict is not None,
            },
            indent=2,
        )
    )
    def _swap_handler(version: str):
        # POST /swap: load the requested checkpoint step from this
        # replica's workdir and hot-swap the engine. The engine stages +
        # validates the new buffer before flipping, so a bad version
        # errors here and serving continues on the current one.
        prior = engine.model_version
        _, new_vars = load_eval_variables(cfg, args.workdir, int(version))
        engine.swap_params(new_vars, version)
        return prior

    server = make_server(
        engine,
        args.host,
        args.port,
        score_thresh=args.score_thresh,
        replica_id=args.replica_id,
        swap_handler=_swap_handler if args.workdir else None,
    )
    host, port = server.server_address[:2]
    print(
        f"serving on http://{host}:{port}/ "
        "(POST /predict {\"paths\": [...]}, GET /healthz, GET /stats)",
        flush=True,
    )
    # graceful drain on SIGTERM: advertise draining in /healthz first so
    # a fleet router's prober pulls this replica out of rotation, hold
    # the listener open for fleet.drain_grace_s (in-flight + newly routed
    # requests still complete), then stop ACCEPTING (server.shutdown must
    # run off the serve_forever thread or it deadlocks); the finally
    # block below closes the listener and drains the engine
    import signal
    import threading
    import time as _time

    grace_s = cfg.fleet.drain_grace_s if args.replica_id else 0.0

    def _drain(signum, frame):  # noqa: ARG001 - signal signature
        print(
            f"SIGTERM: draining (grace {grace_s}s, then stop accepting)...",
            file=sys.stderr,
        )
        server.draining = True

        def _stop() -> None:
            if grace_s > 0:
                _time.sleep(grace_s)
            server.shutdown()

        threading.Thread(target=_stop, daemon=True).start()

    prev_term = signal.signal(signal.SIGTERM, _drain)
    with stack:
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            signal.signal(signal.SIGTERM, prev_term)
            server.server_close()
            engine.close()
            if tracer is not None:
                tracer.flush()
    return 0


def cmd_fleet(args) -> int:
    """Self-healing multi-replica serving front (serving/fleet/): a
    health-checked registry probes every `frcnn serve` replica's
    /healthz on a lease, and the router consistent-hashes requests over
    the live rotation with per-replica circuit breakers, failover
    re-dispatch, p99-hedged retries, a content-hash result cache, and
    canary/shadow traffic splits. Pure host-side routing — no jax, no
    model; the replicas own the compute."""
    with _threadsan_session(getattr(args, "threadsan", False)):
        return _cmd_fleet_impl(args)


def _cmd_fleet_impl(args) -> int:
    import dataclasses as _dc
    import json
    import os

    from replication_faster_rcnn_tpu.config import FleetConfig
    from replication_faster_rcnn_tpu.serving import fleet as fleet_mod

    if not args.replica:
        print("fleet: need at least one --replica URL", file=sys.stderr)
        return 2
    overrides = {
        k: v
        for k, v in {
            "probe_interval_s": args.probe_interval_s,
            "lease_timeout_s": args.lease_timeout_s,
            "breaker_threshold": args.breaker_threshold,
            "max_attempts": args.max_attempts,
            "request_timeout_s": args.request_timeout_s,
            "cache_entries": args.cache_entries,
            "canary_fraction": args.canary_fraction,
        }.items()
        if v is not None
    }
    if args.no_hedge:
        overrides["hedge"] = False
    fleet_cfg = _dc.replace(FleetConfig(), **overrides)
    if args.chaos_spec:
        from replication_faster_rcnn_tpu.faultlib import failpoints

        failpoints.configure(args.chaos_spec)

    tracer = None
    if args.telemetry:
        from replication_faster_rcnn_tpu.config import TelemetryConfig
        from replication_faster_rcnn_tpu.telemetry import spans as tspans

        os.makedirs(args.telemetry, exist_ok=True)
        tracer = tspans.SpanTracer(
            os.path.join(args.telemetry, "trace.json"),
            max_events=TelemetryConfig().trace_max_events,
        )
        tspans.set_tracer(tracer)

    registry = fleet_mod.ReplicaRegistry(fleet_cfg)
    for url in args.replica:
        registry.add(url, fleet_mod.HTTPReplicaClient(url, url))
    for url in args.canary or []:
        registry.add(url, fleet_mod.HTTPReplicaClient(url, url), role="canary")
    for url in args.shadow or []:
        registry.add(url, fleet_mod.HTTPReplicaClient(url, url), role="shadow")
    router = fleet_mod.FleetRouter(registry, fleet_cfg)
    prober = fleet_mod.Prober(registry, fleet_cfg.probe_interval_s).start()
    server = fleet_mod.make_fleet_server(router, args.host, args.port)
    host, port = server.server_address[:2]
    print(
        json.dumps(
            {
                "replicas": list(args.replica),
                "canaries": list(args.canary or []),
                "shadows": list(args.shadow or []),
                "hedge": fleet_cfg.hedge,
                "probe_interval_s": fleet_cfg.probe_interval_s,
                "lease_timeout_s": fleet_cfg.lease_timeout_s,
            },
            indent=2,
        )
    )
    print(
        f"fleet router on http://{host}:{port}/ "
        "(POST /predict {\"paths\": [...]}, GET /healthz, GET /stats)",
        flush=True,
    )
    # same drain discipline as the replicas: /healthz says draining
    # first, the listener keeps answering for the grace window, then the
    # accept loop stops and the prober/hedge pool are joined
    import signal
    import threading
    import time as _time

    def _drain(signum, frame):  # noqa: ARG001 - signal signature
        print(
            f"SIGTERM: draining fleet front "
            f"(grace {fleet_cfg.drain_grace_s}s)...",
            file=sys.stderr,
        )
        server.draining = True

        def _stop() -> None:
            if fleet_cfg.drain_grace_s > 0:
                _time.sleep(fleet_cfg.drain_grace_s)
            server.shutdown()

        threading.Thread(target=_stop, daemon=True).start()

    prev_term = signal.signal(signal.SIGTERM, _drain)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        server.server_close()
        prober.stop()
        router.close()
        if args.telemetry:
            os.makedirs(args.telemetry, exist_ok=True)
            path = os.path.join(args.telemetry, "fleet.jsonl")
            with open(path, "a") as fh:
                fh.write(json.dumps(router.snapshot()) + "\n")
            print(f"fleet telemetry appended to {path}", file=sys.stderr)
            if tracer is not None:
                tracer.flush()
    return 0


def cmd_chaos(args) -> int:
    """Chaos acceptance harness (faultlib/chaos.py): a tiny seeded fault
    schedule exercised against the REAL loader / orbax checkpoint +
    manifest / micro-batcher machinery, asserting the recovery invariants
    (skip-and-substitute, verified-restore walk-back, worker survival)
    and that two runs under the same seed log the identical fault
    sequence. Exit 0 = all invariants held."""
    if not args.smoke:
        print("chaos: pass --smoke (the only implemented mode)", file=sys.stderr)
        return 2
    import json
    import shutil
    import tempfile

    from replication_faster_rcnn_tpu.faultlib import chaos

    workdir = args.workdir
    cleanup = workdir is None
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="frcnn-chaos-")
    try:
        result = chaos.run_smoke(workdir, seed=args.seed)
    except chaos.ChaosSmokeError as e:
        print(f"chaos smoke FAILED: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(
            f"chaos smoke ok: seed={result['seed']} "
            f"injected_events={result['injected_events']} "
            f"elapsed_s={result['elapsed_s']}"
        )
        for leg, detail in result["legs"].items():
            print(f"  {leg}: {detail}")
    if cleanup:
        shutil.rmtree(workdir, ignore_errors=True)
    return 0


def cmd_rollout(args) -> int:
    """Rolling weight rollout control plane (serving/rollout/): discover
    checkpoint versions the trainer published to WORKDIR/manifests/
    (feed.jsonl + manifest scan), validate eligibility BEFORE any
    replica drains (manifest CRC fields, topology, config hash, int8
    quant sidecar), then drive a rolling fleet upgrade over --replica
    URLs: hold/drain one replica, POST /swap, rejoin-gate at the new
    version, canary-gate the first swapped replica on burn-rate +
    shadow-diff windows, promote the wave or roll it back first-class."""
    import dataclasses as _dc
    import json
    import os
    import time

    from replication_faster_rcnn_tpu.config import get_config
    from replication_faster_rcnn_tpu.serving import fleet as fleet_mod
    from replication_faster_rcnn_tpu.serving.rollout import (
        RolloutController,
        RolloutWatcher,
        VersionFeed,
    )

    cfg = get_config(args.config)
    if args.probe_interval_s is not None:
        cfg = cfg.replace(
            fleet=_dc.replace(
                cfg.fleet, probe_interval_s=args.probe_interval_s
            )
        )
    if args.poll_interval_s is not None:
        cfg = cfg.replace(
            rollout=_dc.replace(
                cfg.rollout, poll_interval_s=args.poll_interval_s
            )
        )
    if args.chaos_spec:
        from replication_faster_rcnn_tpu.faultlib import failpoints

        failpoints.configure(args.chaos_spec)
    feed = VersionFeed(
        args.workdir, config=None if args.no_config_checks else cfg
    )

    if args.validate_only:
        verdicts = [feed.validate(step) for step in feed.poll()]
        print(
            json.dumps(
                {
                    "workdir": feed.workdir,
                    "versions": [
                        {
                            "step": v.step,
                            "eligible": v.eligible,
                            "reasons": v.reasons,
                        }
                        for v in verdicts
                    ],
                },
                indent=2,
            )
        )
        return 0

    if not args.replica:
        print("rollout: need at least one --replica URL", file=sys.stderr)
        return 2
    registry = fleet_mod.ReplicaRegistry(cfg.fleet)
    for url in args.replica:
        registry.add(url, fleet_mod.HTTPReplicaClient(url, url))
    router = fleet_mod.FleetRouter(registry, cfg.fleet)
    prober = fleet_mod.Prober(registry, cfg.fleet.probe_interval_s).start()
    controller = RolloutController(registry, router, cfg, feed=feed)
    try:
        if args.watch:
            log_path = os.path.join(feed.workdir, "rollout.jsonl")
            watcher = RolloutWatcher(feed, controller, log_path=log_path)
            watcher.start()
            print(
                f"watching {feed.workdir} every "
                f"{cfg.rollout.poll_interval_s}s for eligible versions "
                f"(wave log: {log_path}); ctrl-c to stop",
                flush=True,
            )
            try:
                while True:
                    time.sleep(60)
            except KeyboardInterrupt:
                pass
            finally:
                watcher.stop()
            return 0
        # one-shot wave (--once is the default mode)
        if args.step is not None:
            result = controller.rollout(str(args.step))
        else:
            verdict = feed.latest_eligible()
            if verdict is None:
                print(
                    "rollout: no eligible version published under "
                    f"{feed.workdir} (try --validate-only for reasons)",
                    file=sys.stderr,
                )
                return 1
            result = controller.rollout(verdict.version, verdict=verdict)
        print(json.dumps(result.to_dict(), indent=2))
        return 0 if result.outcome in ("promoted", "noop") else 1
    finally:
        prober.stop()
        router.close()


def cmd_viz(args) -> int:
    """Visual sanity artifacts (reference `utils/anchors.py:64-77` anchor
    plot and `utils/data_loader.py:119-134` gt overlay, as a real command)."""
    _apply_device(args.device)
    cfg = _build_config(args)
    from replication_faster_rcnn_tpu.utils import viz

    if args.what == "anchors":
        viz.draw_anchor_centers(cfg, args.output)
    else:  # sample
        from replication_faster_rcnn_tpu.data.loader import make_dataset

        ds = make_dataset(cfg.data, args.split)
        viz.draw_gt_overlay(ds[args.index], cfg, args.output)
    print(f"{args.what} visualization written to {args.output}")
    return 0


def cmd_trace_summary(args) -> int:
    """Op-level time table from a captured profiler trace (the dir passed
    to --profile). Pure host-side parsing — no jax import, safe with a
    dead TPU tunnel."""
    import json

    from replication_faster_rcnn_tpu.utils.xplane import (
        find_xplane_files,
        format_table,
        op_table,
    )

    if not find_xplane_files(args.trace_dir):
        print(f"no *.xplane.pb under {args.trace_dir}", file=sys.stderr)
        return 1
    rows = op_table(args.trace_dir, plane_filter=args.plane, top=args.top)
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"trace_dir": args.trace_dir, "ops": rows}, f, indent=2)
        print(f"op table written to {args.json}")
    print(format_table(rows))
    return 0


def cmd_check(args) -> int:
    """Static lint gate over the package (or explicit paths): jaxlint's
    jit-hygiene rules JX001-JX007, threadlint's host-concurrency rules
    TL001-TL006, obslint's unified-metrics contract OB001, and
    shardlint's sharding & collective-cost rules SL001-SL006 (over the
    committed fingerprint bank — pass bank JSON paths to lint one
    bank), resolved against the shared analysis/baseline.toml. No
    lowering or compilation anywhere — fast enough to gate every PR.
    Exits nonzero on any unsuppressed finding or stale waiver; --rules
    narrows to a comma-separated subset (an analyzer with no selected
    rule is skipped entirely)."""
    import json

    from replication_faster_rcnn_tpu.analysis import (
        jaxlint,
        obslint,
        shardlint,
        threadlint,
    )

    analyzers = [
        ("jaxlint", jaxlint),
        ("threadlint", threadlint),
        ("obslint", obslint),
        ("shardlint", shardlint),
    ]
    selected = None
    if getattr(args, "rules", None):
        selected = {r.strip().upper() for r in args.rules.split(",") if r.strip()}
        known = (
            set(jaxlint.RULES)
            | set(threadlint.RULES)
            | set(obslint.RULES)
            | set(shardlint.RULES)
        )
        unknown = selected - known
        if unknown:
            print(
                f"unknown rule(s): {', '.join(sorted(unknown))} "
                f"(known: {', '.join(sorted(known))})",
                file=sys.stderr,
            )
            return 2
        analyzers = [
            (name, mod) for name, mod in analyzers if selected & set(mod.RULES)
        ]

    def run(mod):
        if args.paths:
            return mod.lint_paths(args.paths, baseline=args.baseline)
        if args.baseline is not None:
            return mod.lint_package(baseline=args.baseline)
        return mod.lint_package()

    def keep(rule):
        return selected is None or rule in selected

    results = [(name, run(mod), mod.RULES) for name, mod in analyzers]
    findings = [
        f for _, r, _ in results for f in r.findings if keep(f.rule)
    ]
    stale = [
        w for _, r, _ in results for w in r.stale_waivers if keep(w.rule)
    ]
    suppressed = [
        (f, reason)
        for _, r, _ in results
        for f, reason in r.suppressed
        if keep(f.rule)
    ]
    excluded_count = sum(
        1 for _, r, _ in results for f in r.excluded if keep(f.rule)
    )
    rules = {
        rule: desc
        for _, _, mod_rules in results
        for rule, desc in mod_rules.items()
        if keep(rule)
    }
    if args.json:
        payload = {
            "rules": rules,
            "findings": [f.to_dict() for f in findings],
            "suppressed": [
                {**f.to_dict(), "reason": reason} for f, reason in suppressed
            ],
            "excluded_count": excluded_count,
            "stale_waivers": [
                dataclasses.asdict(w) for _, r, _ in results
                for w in r.stale_waivers if keep(w.rule)
            ],
            "ok": not findings and not stale,
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f)
        baseline_name = args.baseline or "analysis/baseline.toml"
        for w in stale:
            print(
                f"stale waiver ({baseline_name}:{w.line}): {w.rule} "
                f"{w.path} [{w.func}] matched nothing — the violation it "
                f"suppressed (reason: {w.reason!r}) is gone; delete the "
                f"[[waiver]] entry at line {w.line}"
            )
        if args.verbose:
            for f, reason in suppressed:
                print(f"waived: {f}\n    reason: {reason}")
        names = "+".join(name for name, _, _ in results) or "no analyzers"
        print(
            f"{names}: {len(findings)} finding(s), "
            f"{len(suppressed)} waived, "
            f"{excluded_count} excluded, "
            f"{len(stale)} stale waiver(s) "
            f"({len(rules)} rules)"
        )
    return 1 if (findings or stale) else 0


def cmd_audit(args) -> int:
    """HLO program auditor (analysis/hlolint.py): AOT-lower every
    registered (feed × K) train program + eval for the audited config,
    enforce the compiled-artifact contracts HX001-HX004 (donation
    aliasing, dtype, collectives, memory budget), the SL005 comm-byte
    budget (static wire-byte estimate vs analysis.comm_budget_bytes and
    the banked value), and compare against the committed fingerprint
    bank (HX005/HX006). The third static gate next to `frcnn check`
    (AST + bank) and --strict (runtime); exits nonzero on any contract
    violation or unexplained fingerprint drift."""
    import json
    import os

    # the audit's spmd programs need a multi-device mesh; on a CPU-only
    # host ask XLA for virtual devices BEFORE jax initializes (matches
    # the test tier's 8-device topology; no-op when jax is already up)
    if "jax" not in sys.modules and args.device in ("auto", "cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count=8".strip()
            )
    _apply_device(args.device)

    from replication_faster_rcnn_tpu.analysis import hlolint
    from replication_faster_rcnn_tpu.config import get_config

    cfg = hlolint.audit_config() if args.config == "ci" else get_config(args.config)
    programs = [p for p in args.programs.split(",") if p] if args.programs else None
    result = hlolint.run_audit(
        cfg,
        programs=programs,
        update=args.update,
        fingerprint_dir=args.fingerprint_dir,
        hbm_budget_bytes=args.hbm_budget,
    )
    if args.json:
        print(json.dumps(result.to_dict(), indent=2))
    else:
        for v in result.violations:
            print(v)
        verdict = (
            "re-banked" if result.updated and result.ok
            else ("ok" if result.ok else "FAILED")
        )
        print(
            f"audit: {len(result.programs)} program(s), "
            f"{len(result.violations)} violation(s) -> {verdict} "
            f"(bank: {result.bank_file})"
        )
    return 1 if result.violations else 0


def cmd_telemetry(args) -> int:
    """Phase-time + train-health report from a --telemetry run dir. Pure
    host-side parsing (telemetry/report.py) — no jax import, safe with a
    dead TPU tunnel, runnable on a laptop holding only the artifacts.
    --trace-id narrows to one request's cross-process hop timeline from
    the merged trace (router + replica spans under one trace id)."""
    import json

    from replication_faster_rcnn_tpu.telemetry.report import (
        TRACE_FILE,
        format_report,
        format_trace_timeline,
        load_trace_events,
        rank_variants,
        summarize_run,
        trace_timeline,
    )

    if getattr(args, "trace_id", None):
        events = []
        for _rank, path in rank_variants(args.run_dir, TRACE_FILE):
            events.extend(load_trace_events(path))
        timeline = trace_timeline(events, args.trace_id)
        if timeline is None:
            print(
                f"no spans for trace id {args.trace_id!r} under "
                f"{args.run_dir}",
                file=sys.stderr,
            )
            return 1
        if args.json:
            with open(args.json, "w") as f:
                json.dump(timeline, f, indent=2)
            print(f"timeline written to {args.json}")
        print(format_trace_timeline(timeline))
        return 0

    summary = summarize_run(args.run_dir)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
        print(f"summary written to {args.json}")
    print(format_report(summary))
    return 0 if summary["artifacts"] else 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(prog="replication_faster_rcnn_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p_train = sub.add_parser("train", help="train a detector")
    _add_common(p_train)
    p_train.add_argument("--workdir", default="checkpoints")
    p_train.add_argument("--steps", type=int, default=0,
                         help="run exactly N steps instead of the epoch loop")
    p_train.add_argument("--log-every", type=int, default=10)
    p_train.add_argument("--resume", action="store_true")
    p_train.add_argument("--pretrained-backbone", default=None,
                         help="torch resnet .pth to graft (reference readme.md:10-12)")
    p_train.add_argument("--eval-every", type=int, default=None,
                         help="run val mAP every N epochs (0 = never)")
    p_train.add_argument("--profile", default=None, metavar="DIR",
                         help="jax.profiler trace of the training loop")
    p_train.add_argument("--telemetry", default=None, metavar="DIR",
                         help="write run telemetry here: trace.json "
                              "(Chrome-trace spans), metrics.jsonl (step "
                              "metrics + train-health scalars), "
                              "watchdog.jsonl + progress.json (stall "
                              "watchdog); summarize with the 'telemetry' "
                              "subcommand")
    p_train.add_argument("--stall-timeout", type=float, default=300.0,
                         help="seconds without step progress before the "
                              "telemetry watchdog records a stall snapshot "
                              "(needs --telemetry)")
    p_train.add_argument("--on-crash-checkpoint", action="store_true",
                         help="on an unhandled training crash, best-effort "
                              "save a checkpoint (manifest kind 'crash') "
                              "before re-raising; SIGTERM/SIGINT preemption "
                              "always emergency-saves and exits 75")
    p_train.add_argument("--debug-nans", action="store_true",
                         help="enable jax_debug_nans (every jit output "
                              "checked; errors pinpoint the emitting op)")
    p_train.add_argument("--elastic", action="store_true",
                         help="elastic fleet mode: this process becomes a "
                              "per-host supervisor that spawns the real "
                              "training child and survives rank loss — a "
                              "lost rank's lease expiry re-forms the fleet "
                              "at the surviving world size, resuming from "
                              "the last verified checkpoint INSIDE the "
                              "same epoch (parallel/elastic.py; pair with "
                              "--checkpoint-every-steps to bound rollback)")
    p_train.set_defaults(fn=cmd_train)

    p_eval = sub.add_parser("eval", help="evaluate mAP")
    _add_common(p_eval)
    p_eval.add_argument("--workdir", default="checkpoints")
    p_eval.add_argument("--split", default="val")
    p_eval.add_argument("--checkpoint-step", type=int, default=None)
    p_eval.add_argument("--max-images", type=int, default=None)
    p_eval.add_argument("--per-class", action="store_true",
                        help="print the per-class AP table")
    p_eval.add_argument("--iou-thresh", type=float, default=None,
                        help="matching IoU for VOC mAP (default 0.5)")
    p_eval.add_argument("--use-07-metric", action="store_true",
                        help="VOC2007 11-point AP instead of area-under-PR")
    p_eval.add_argument("--metric", default=None, choices=[None, "voc", "coco"],
                        help="voc: mAP@iou-thresh; coco: mAP@[.50:.95]")
    p_eval.add_argument("--tta-hflip", action="store_true",
                        help="flip test-time augmentation: mirrored second "
                             "forward, candidates merged before NMS "
                             "(~2x eval compute for a small mAP gain)")
    p_eval.set_defaults(fn=cmd_eval)

    p_bench = sub.add_parser("bench", help="train-step throughput")
    _add_common(p_bench)
    p_bench.add_argument("--profile", default=None, metavar="DIR",
                         help="write a jax.profiler trace of the timed "
                              "loop (TensorBoard/Perfetto)")
    p_bench.set_defaults(fn=cmd_bench)

    p_warm = sub.add_parser(
        "warmup",
        help="AOT-compile the train/eval programs for a config (pair with "
             "--compile-cache to make later real-run startups compile-free)",
    )
    _add_common(p_warm)
    p_warm.add_argument("--train-only", action="store_true",
                        help="skip the eval inference program")
    p_warm.add_argument("--serving", action="store_true",
                        help="also AOT-compile the serving engine's bucket "
                             "matrix (serving.resolutions x batch_sizes), "
                             "so a later 'serve' start is compile-free "
                             "with --compile-cache")
    p_warm.add_argument("--telemetry", default=None, metavar="DIR",
                        help="write compile/* spans to DIR/trace.json")
    p_warm.set_defaults(fn=cmd_warmup)

    p_pred = sub.add_parser("predict", help="detect objects in images")
    _add_common(p_pred)
    p_pred.add_argument("--image", required=True, nargs="+", metavar="PATH",
                        help="image path(s); multiple paths route through "
                             "the serving engine as one micro-batched wave")
    p_pred.add_argument("--workdir", default="checkpoints")
    p_pred.add_argument("--checkpoint-step", type=int, default=None)
    p_pred.add_argument("--score-thresh", type=float, default=0.5)
    p_pred.add_argument("--output", default=None,
                        help="write the image with boxes drawn to this path "
                             "(with multiple inputs: PATH.0.ext, PATH.1.ext, "
                             "...)")
    p_pred.set_defaults(fn=cmd_predict)

    p_serve = sub.add_parser(
        "serve",
        help="bucketed AOT inference serving: pre-compile every "
             "(resolution x batch) bucket program, keep params resident "
             "on device, micro-batch concurrent HTTP requests "
             "(POST /predict)",
    )
    _add_common(p_serve)
    p_serve.add_argument("--workdir", default="checkpoints")
    p_serve.add_argument("--checkpoint-step", type=int, default=None)
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=8008,
                         help="TCP port (0 = pick a free one)")
    p_serve.add_argument("--score-thresh", type=float, default=0.5)
    p_serve.add_argument("--max-delay-ms", type=float, default=None,
                         help="micro-batch deadline: max ms a request "
                              "waits for batch-mates before a partial "
                              "flush (serving.max_delay_ms)")
    p_serve.add_argument("--bucket-batch-sizes", default=None, metavar="N,M",
                         help="compiled batch sizes per bucket, e.g. '1,8' "
                              "(serving.batch_sizes)")
    p_serve.add_argument("--resolutions", default=None, metavar="HxW,HxW",
                         help="bucket resolutions, e.g. '300x300,600x600' "
                              "(default: image_size and its half)")
    p_serve.add_argument("--params-dtype", default=None,
                         choices=[None, "float32", "bfloat16", "int8"],
                         help="resident inference param dtype "
                              "(serving.params_dtype). float32: the "
                              "checkpoint as-is; bfloat16: halves HBM "
                              "residency (flax casts to compute dtype "
                              "per-layer regardless); int8: ~4x smaller "
                              "residency — quantized weights + scales "
                              "stay device-resident and every bucket "
                              "dispatches its serve_*__int8 program. "
                              "int8 REQUIRES the calibration sidecar "
                              "written by `frcnn quantize` (per-channel "
                              "scales + per-layer int8/bf16 plan) next "
                              "to the checkpoint; startup fails with an "
                              "actionable error without it")
    p_serve.add_argument("--request-timeout-s", type=float, default=None,
                         help="per-request deadline "
                              "(serving.request_timeout_s): handler waits "
                              "time out to 504 and queued entries past "
                              "deadline are dropped at flush time, never "
                              "dispatched (0 = no deadline)")
    p_serve.add_argument("--adaptive-delay", action="store_true",
                         help="SLO-driven micro-batch deadlines "
                              "(serving.adaptive_delay): adapt per-bucket "
                              "max_delay_ms from observed queue-wait p99 "
                              "with bounded multiplicative steps inside "
                              "[delay_floor_ms, delay_ceiling_ms]")
    p_serve.add_argument("--replica-id", default=None, metavar="ID",
                         help="name this replica in /healthz for fleet "
                              "membership; also enables the SIGTERM "
                              "drain-grace window (fleet.drain_grace_s: "
                              "advertise draining, keep serving, then stop "
                              "accepting) so the fleet router rotates the "
                              "replica out without dropped traffic")
    p_serve.add_argument("--telemetry", default=None, metavar="DIR",
                         help="write request hop spans (serve/request, "
                              "serve/queue_wait, serve/dispatch) to a "
                              "Chrome-trace file in DIR: trace.json, or "
                              "trace.rankN.json when --replica-id is set "
                              "so replicas can share the fleet front's DIR "
                              "and `frcnn telemetry DIR --trace-id X` "
                              "merges them into one timeline")
    p_serve.set_defaults(fn=cmd_serve)

    p_quant = sub.add_parser(
        "quantize",
        help="PTQ calibration for int8 serving: per-channel weight "
             "scales + activation ranges from a small calibration "
             "sweep, optional per-layer sensitivity sweep (--sweep) "
             "emitting an int8-vs-bf16 plan, written as a CRC-checked "
             "sidecar artifact `frcnn serve --params-dtype int8` loads",
    )
    _add_common(p_quant)
    p_quant.add_argument("--workdir", default="checkpoints")
    p_quant.add_argument("--checkpoint-step", type=int, default=None)
    p_quant.add_argument("--output", default=None, metavar="PATH",
                         help="artifact path (default: quant.artifact if "
                              "set, else WORKDIR/quant_artifact.json)")
    p_quant.add_argument("--split", default="train",
                         help="dataset split calibration batches are "
                              "drawn from (index order, deterministic)")
    p_quant.add_argument("--eval-split", default="val",
                         help="split for the --sweep-map-images mini "
                              "eval")
    p_quant.add_argument("--calib-batches", type=int, default=None,
                         help="calibration batches (quant.calib_batches)")
    p_quant.add_argument("--calib-batch-size", type=int, default=None,
                         help="images per calibration batch "
                              "(quant.calib_batch_size)")
    p_quant.add_argument("--synthetic-calib", action="store_true",
                         help="force synthetic calibration images even "
                              "for a real dataset config")
    p_quant.add_argument("--sweep", action="store_true",
                         help="per-layer-group sensitivity sweep "
                              "(arXiv:1806.00370): quantize one group at "
                              "a time, measure response-reconstruction "
                              "error (and mAP drop with "
                              "--sweep-map-images); groups crossing the "
                              "quant.sensitivity_* budgets fall back to "
                              "bf16 in the plan")
    p_quant.add_argument("--sweep-map-images", type=int, default=None,
                         metavar="N",
                         help="with --sweep: also measure each group's "
                              "mAP delta on N eval images")
    p_quant.set_defaults(fn=cmd_quantize)

    p_fleet = sub.add_parser(
        "fleet",
        help="self-healing multi-replica serving front: health-checked "
             "replica registry (lease-staleness probes), consistent-hash "
             "routing with a content-hash result cache, per-replica "
             "circuit breakers, failover, p99-hedged retries, canary + "
             "shadow traffic (serving/fleet/)",
    )
    p_fleet.add_argument("--replica", action="append", metavar="URL",
                         help="serving replica base URL (repeatable), e.g. "
                              "http://127.0.0.1:8008 — start each with "
                              "`frcnn serve --replica-id ...`")
    p_fleet.add_argument("--canary", action="append", metavar="URL",
                         help="canary replica URL: a deterministic "
                              "fleet.canary_fraction slice of the "
                              "content-hash space tries it first")
    p_fleet.add_argument("--shadow", action="append", metavar="URL",
                         help="shadow replica URL: mirrored traffic, "
                              "responses diffed (never returned)")
    p_fleet.add_argument("--host", default="127.0.0.1")
    p_fleet.add_argument("--port", type=int, default=8010,
                         help="TCP port (0 = pick a free one)")
    p_fleet.add_argument("--probe-interval-s", type=float, default=None,
                         help="/healthz probe cadence per replica "
                              "(fleet.probe_interval_s)")
    p_fleet.add_argument("--lease-timeout-s", type=float, default=None,
                         help="probe-staleness horizon before a replica "
                              "is declared dead (fleet.lease_timeout_s)")
    p_fleet.add_argument("--breaker-threshold", type=int, default=None,
                         help="consecutive dispatch failures that open a "
                              "replica's circuit breaker "
                              "(fleet.breaker_threshold)")
    p_fleet.add_argument("--max-attempts", type=int, default=None,
                         help="primary + failover attempts per request "
                              "(fleet.max_attempts)")
    p_fleet.add_argument("--request-timeout-s", type=float, default=None,
                         help="per-attempt replica call deadline "
                              "(fleet.request_timeout_s)")
    p_fleet.add_argument("--cache-entries", type=int, default=None,
                         help="content-hash result cache size, 0 disables "
                              "(fleet.cache_entries)")
    p_fleet.add_argument("--canary-fraction", type=float, default=None,
                         help="fraction of the content-hash space routed "
                              "to the canary first (fleet.canary_fraction)")
    p_fleet.add_argument("--no-hedge", action="store_true",
                         help="disable hedged retries (fleet.hedge=False): "
                              "dispatch becomes strictly sequential "
                              "failover")
    p_fleet.add_argument("--chaos-spec", default=None, metavar="SPEC",
                         help="arm failpoints (site:kind:prob:seed[:arg]) "
                              "— the fleet sites are router.dispatch and "
                              "router.probe, plus http.handler on the "
                              "front itself")
    p_fleet.add_argument("--threadsan", action="store_true",
                         help="record runtime thread-interaction traces "
                              "for the router/prober threads "
                              "(analysis/threadsan.py)")
    p_fleet.add_argument("--telemetry", default=None, metavar="DIR",
                         help="append a final router/registry snapshot to "
                              "DIR/fleet.jsonl on shutdown (read by "
                              "`frcnn telemetry`) and write the router's "
                              "request/attempt spans to DIR/trace.json — "
                              "point replicas' `serve --telemetry` at the "
                              "same DIR for the merged cross-process "
                              "`--trace-id` timeline")
    p_fleet.set_defaults(fn=cmd_fleet)

    p_chaos = sub.add_parser(
        "chaos",
        help="deterministic fault-injection acceptance harness "
             "(faultlib): seeded failpoint schedule against the real "
             "loader/checkpoint/micro-batcher machinery; asserts the "
             "fault-tolerance invariants hold and that the same seed "
             "reproduces the identical fault sequence",
    )
    p_chaos.add_argument("--smoke", action="store_true",
                         help="tiny seeded schedule on synthetic data "
                              "(finishes in seconds); currently the only "
                              "mode, so required")
    p_chaos.add_argument("--seed", type=int, default=0,
                         help="schedule seed; the run is a pure function "
                              "of it")
    p_chaos.add_argument("--workdir", default=None, metavar="DIR",
                         help="scratch dir for checkpoint legs (default: "
                              "a fresh temp dir, removed on success)")
    p_chaos.add_argument("--json", action="store_true",
                         help="print the full result record as JSON")
    p_chaos.set_defaults(fn=cmd_chaos)

    p_roll = sub.add_parser(
        "rollout",
        help="rolling weight rollout: validate checkpoint versions "
             "published to WORKDIR/manifests/ (pre-drain eligibility "
             "gate), then drive a rolling fleet upgrade over --replica "
             "URLs — drain → hot-swap (POST /swap) → rejoin-gate → "
             "gated canary promote, with first-class rollback "
             "(serving/rollout/)",
    )
    p_roll.add_argument("--workdir", required=True, metavar="DIR",
                        help="trainer workdir whose manifests/ feed is "
                             "the version source (the replicas must "
                             "serve from the same workdir so POST /swap "
                             "can load the step)")
    p_roll.add_argument("--config", default="voc_resnet18",
                        help="preset the fleet serves (eligibility "
                             "checks the manifest config hash and, for "
                             "int8, the quant sidecar against it)")
    p_roll.add_argument("--no-config-checks", action="store_true",
                        help="skip the config-hash and int8-sidecar "
                             "eligibility checks (manifest integrity + "
                             "topology still judged)")
    p_roll.add_argument("--replica", action="append", metavar="URL",
                        help="serving replica base URL (repeatable); "
                             "each must run `frcnn serve --replica-id "
                             "... --workdir ...` so /swap is enabled")
    p_roll.add_argument("--validate-only", action="store_true",
                        help="print every published version's "
                             "eligibility verdict as JSON and exit — no "
                             "replica is touched")
    p_roll.add_argument("--once", action="store_true",
                        help="run exactly one rollout wave to the "
                             "newest eligible version (or --step) and "
                             "exit; this is the default mode")
    p_roll.add_argument("--step", type=int, default=None,
                        help="with --once: roll to this checkpoint step "
                             "instead of the newest eligible one (still "
                             "validated first)")
    p_roll.add_argument("--watch", action="store_true",
                        help="poll the manifest feed forever "
                             "(rollout.poll_interval_s) and run a wave "
                             "per newly eligible version; wave results "
                             "append to WORKDIR/rollout.jsonl")
    p_roll.add_argument("--probe-interval-s", type=float, default=None,
                        help="/healthz probe cadence "
                             "(fleet.probe_interval_s)")
    p_roll.add_argument("--poll-interval-s", type=float, default=None,
                        help="manifest feed poll cadence for --watch "
                             "(rollout.poll_interval_s)")
    p_roll.add_argument("--chaos-spec", default=None, metavar="SPEC",
                        help="arm failpoints (site:kind:prob:seed[:arg])"
                             " — the rollout sites are rollout.swap "
                             "(before each per-replica swap RPC) and "
                             "rollout.promote (at the promote decision)")
    p_roll.set_defaults(fn=cmd_rollout)

    p_viz = sub.add_parser("viz", help="visual sanity artifacts "
                                       "(anchor centers / gt overlay)")
    _add_common(p_viz)
    p_viz.add_argument("what", choices=["anchors", "sample"])
    p_viz.add_argument("--output", required=True)
    p_viz.add_argument("--split", default="train")
    p_viz.add_argument("--index", type=int, default=0,
                       help="dataset sample index (what=sample)")
    p_viz.set_defaults(fn=cmd_viz)

    p_trace = sub.add_parser(
        "trace-summary",
        help="per-op time table from a --profile trace dir (no TF needed)",
    )
    p_trace.add_argument("trace_dir")
    p_trace.add_argument("--top", type=int, default=25)
    p_trace.add_argument("--plane", default=None,
                         help="substring filter on the plane name "
                              "(default: device planes, else all)")
    p_trace.add_argument("--json", default=None, metavar="PATH",
                         help="also write the table as JSON")
    p_trace.set_defaults(fn=cmd_trace_summary)

    p_tel = sub.add_parser(
        "telemetry",
        help="phase-time + train-health report from a --telemetry run dir",
    )
    p_tel.add_argument("run_dir")
    p_tel.add_argument("--json", default=None, metavar="PATH",
                       help="also write the summary as JSON")
    p_tel.add_argument("--trace-id", default=None, metavar="HEX32",
                       help="print one request's hop timeline (queue-wait/"
                            "compute/network per hop) from the merged trace "
                            "instead of the full report")
    p_tel.set_defaults(fn=cmd_telemetry)

    p_check = sub.add_parser(
        "check",
        help="static lint gate: jit-hygiene (jaxlint JX001-JX007) + "
             "host-concurrency contracts (threadlint TL001-TL006) + "
             "unified-metrics contract (obslint OB001) + sharding/"
             "collective-cost contracts over the fingerprint bank "
             "(shardlint SL001-SL006) against the committed suppression "
             "baseline; exits nonzero on any unsuppressed finding",
    )
    p_check.add_argument("paths", nargs="*",
                         help="files to lint (default: the whole package)")
    p_check.add_argument("--rules", default=None, metavar="R1,R2,...",
                         help="run/report only these rules (e.g. "
                              "'TL001,SL005'; default: all JX + TL + OB "
                              "+ SL rules)")
    p_check.add_argument("--baseline", default=None, metavar="TOML",
                         help="suppression file (default: the committed "
                              "analysis/baseline.toml; pass /dev/null to "
                              "see raw findings)")
    p_check.add_argument("--json", action="store_true",
                         help="machine-readable findings on stdout")
    p_check.add_argument("-v", "--verbose", action="store_true",
                         help="also print waived findings with reasons")
    p_check.set_defaults(fn=cmd_check)

    p_audit = sub.add_parser(
        "audit",
        help="HLO program auditor (rules HX001-HX006 + SL005 comm-byte "
             "budget): donation/dtype/collective/memory contracts + "
             "fingerprint drift over the compiled (feed x K) programs; "
             "third gate next to 'check' and --strict",
    )
    p_audit.add_argument("--config", default="ci",
                         help="'ci' = the small audited-matrix config "
                              "(default; what the committed fingerprints "
                              "were banked with), or any preset name")
    p_audit.add_argument("--device", default="auto",
                         choices=["auto", "tpu", "cpu"],
                         help="JAX backend (cpu/auto gets 8 virtual "
                              "devices for the spmd programs)")
    p_audit.add_argument("--programs", default=None, metavar="A,B,...",
                         help="comma-separated subset of program names to "
                              "lower (default: the full feed x K matrix + "
                              "eval)")
    p_audit.add_argument("--update", action="store_true",
                         help="re-bank: write the collected fingerprints "
                              "to the bank instead of failing on drift")
    p_audit.add_argument("--fingerprint-dir", default=None, metavar="DIR",
                         help="override analysis.fingerprint_dir (default: "
                              "the committed analysis/fingerprints/)")
    p_audit.add_argument("--hbm-budget", type=int, default=None,
                         metavar="BYTES",
                         help="override analysis.hbm_budget_bytes for the "
                              "HX004 peak-memory gate")
    p_audit.add_argument("--json", action="store_true",
                         help="machine-readable result on stdout")
    p_audit.set_defaults(fn=cmd_audit)

    args = parser.parse_args(argv)
    # the elastic supervisor rewrites the EXACT argv this process was
    # invoked with into each generation's child argv
    args._argv = list(argv) if argv is not None else list(sys.argv[1:])
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
