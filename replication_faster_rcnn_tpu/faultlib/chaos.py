"""Seeded chaos smoke harness (`frcnn chaos --smoke`).

A fast, CI-tier acceptance run for the failpoint subsystem: arm a tiny
seeded schedule against REAL components — the loader's
retry-then-substitute path, the checkpoint+manifest+verified-restore
walk-back, the micro-batcher's per-flush error relay — and assert the
recovery invariants hold, twice, with identical injected-event logs
(the determinism pin). No jitted training and no model build, so the
whole thing runs in seconds on CPU; the full-training chaos leg lives
in the slow tier (tests/test_fault_train.py).

Legs:

1. **loader** — ``loader.fetch`` IOErrors at p=0.4: every fetch must
   still return a sample (retry or nearest-following substitution),
   skips stay within the recorded budget.
2. **checkpoint** — two verified saves, then a ``checkpoint.write``
   torn-write on the newest step: ``verified_restore`` must walk back
   to the older verifiable step and report the torn one discarded.
3. **batcher** — a guaranteed ``batcher.flush`` IOError on the first
   flush: exactly that flush's futures fail, the worker survives, and
   the next flush succeeds.
4. **fleet** — a simulated 2-rank elastic fleet driven single-threaded
   (fake clock, manual beats): a seeded ``heartbeat.beat`` drop kills
   rank 1 on exactly its 2nd lease renewal, the survivor's watchdog
   declares it lost once the lease ages out, writes the shrink intent,
   and the re-form protocol (claim → plan) lands on a 1-rank fleet;
   then a seeded ``collective.init`` drop replays the bring-up-time
   variant of the same loss.  Same machinery as the real
   ``frcnn train --elastic`` path (parallel/elastic.py), minus the
   process boundaries.
5. **fleet_router** — a 3-replica serving fleet driven single-threaded
   (fake clock, manual probes, hedging off): a seeded ``router.probe``
   IOError delays one replica's admission by exactly one probe round, a
   seeded ``router.dispatch`` drop kills the selected replica
   mid-request through the router's kill hook — failover must answer
   the request anyway — then the dead replica's lease ages out
   (DEAD, out of rotation), it revives, and rejoins after
   ``rejoin_probes`` clean probes.  Same machinery as the real
   ``frcnn fleet`` path (serving/fleet/), minus the processes.
6. **rollout** — a rolling weight rollout over a 3-replica fleet driven
   single-threaded (fake clock, injected sleep/probe): an unpublished
   version is rejected by the pre-drain eligibility gate without
   touching any replica, then a seeded ``rollout.swap`` drop kills the
   first wave mid-swap (after hold+drain, before the swap RPC) — the
   controller must abort the wave, reverse-roll the drained replica,
   and reconverge the fleet on the old version — and the retry wave
   (the drop is spent) must hold/drain/swap/rejoin every replica,
   canary-gate the first one, and land the whole fleet on the new
   version.  Same machinery as the real ``frcnn rollout`` path
   (serving/rollout/), minus the processes.
7. **determinism** — all legs run twice under the same seed; the two
   injected-event logs must match exactly.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List

import numpy as np

from replication_faster_rcnn_tpu.faultlib import failpoints

__all__ = ["ChaosSmokeError", "run_smoke", "smoke_rules"]


class ChaosSmokeError(AssertionError):
    """A recovery invariant did not hold under the injected schedule."""


def smoke_rules(seed: int) -> List[failpoints.Rule]:
    """The smoke schedule: loader IOErrors, one torn checkpoint write,
    one flush IOError — all decided by ``seed``."""
    return [
        failpoints.Rule("loader.fetch", "ioerror", 0.4, seed),
        # the FIRST save lands clean so the walk-back has somewhere to go;
        # the second is torn mid-write (after=1, max_fires=1 → exactly hit 1)
        failpoints.Rule(
            "checkpoint.write", "torn_write", 1.0, seed + 1,
            arg=4, max_fires=1, after=1,
        ),
        failpoints.Rule(
            "batcher.flush", "ioerror", 1.0, seed + 2, max_fires=1
        ),
        # the fleet leg beats ranks 0,1 strictly alternating through ONE
        # registry, so per-site hit indices map onto ranks: after=3 lands
        # the drop on hit 3 = rank 1's 2nd renewal (arg names the victim)
        failpoints.Rule(
            "heartbeat.beat", "drop", 1.0, seed + 3,
            arg=1, max_fires=1, after=3,
        ),
        # bring-up variant: inits fire rank 0 then rank 1, after=1 lands
        # the drop on rank 1's init
        failpoints.Rule(
            "collective.init", "drop", 1.0, seed + 4,
            arg=1, max_fires=1, after=1,
        ),
        # fleet_router leg: dispatch attempts hit in request order —
        # requests a, b pass (hits 0, 1), the drop lands on request c's
        # first attempt (hit 2) and the router's kill hook makes the
        # selected replica actually die; the failover attempt is hit 3
        failpoints.Rule(
            "router.dispatch", "drop", 1.0, seed + 5, max_fires=1, after=2
        ),
        # probes hit per replica in registration order (r0, r1, r2 per
        # round): after=4 fails exactly r1's probe in round 2, delaying
        # its admission to rotation by one round — transient, max_fires=1
        failpoints.Rule(
            "router.probe", "ioerror", 1.0, seed + 6, max_fires=1, after=4
        ),
        # rollout leg: the first rollout.swap hit is wave 1's first
        # replica (post-drain, pre-RPC) — the mid-swap kill. max_fires=1
        # spends the rule, so the retry wave's three hits pass clean
        failpoints.Rule(
            "rollout.swap", "drop", 1.0, seed + 7, max_fires=1
        ),
    ]


def _check(ok: bool, msg: str) -> None:
    if not ok:
        raise ChaosSmokeError(msg)


def _loader_leg(seed: int) -> Dict[str, Any]:
    from replication_faster_rcnn_tpu.config import DataConfig
    from replication_faster_rcnn_tpu.data import SyntheticDataset
    from replication_faster_rcnn_tpu.data.loader import fetch_sample

    cfg = DataConfig(dataset="synthetic", image_size=(16, 16), max_boxes=4)
    ds = SyntheticDataset(cfg, length=8)
    skips: List[int] = []
    for i in range(len(ds)):
        sample = fetch_sample(ds, i, on_skip=lambda idx, exc: skips.append(idx))
        _check(
            isinstance(sample, dict) and "image" in sample,
            f"loader leg: fetch_sample({i}) returned no sample under faults",
        )
        _check(
            np.isfinite(np.asarray(sample["image"])).all(),
            f"loader leg: substituted sample {i} is not finite",
        )
    return {"fetches": len(ds), "skipped": len(skips)}


def _checkpointed_save(mgr, workdir: str, step: int, state) -> None:
    """One save through the same failpoint wiring the trainer uses:
    consult ``checkpoint.write`` first (ioerror raises before any bytes
    land), save + manifest, then apply a returned torn-write/CRC fault
    to the step directory so restore-time verification must catch it."""
    import orbax.checkpoint as ocp

    from replication_faster_rcnn_tpu.train import fault

    inj = failpoints.fire("checkpoint.write", step=int(step), writer="smoke")
    mgr.save(step, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    fault.write_manifest(workdir, step, state, None, kind="scheduled")
    if inj is not None and inj.kind in ("torn_write", "crc_corrupt"):
        step_dir = failpoints.find_step_dir(
            workdir, step, exclude=(fault.MANIFEST_DIRNAME,)
        )
        _check(step_dir is not None, f"checkpoint leg: no step dir for {step}")
        touched = failpoints.apply_file_fault(inj, step_dir)
        _check(bool(touched), f"checkpoint leg: fault touched no files at {step}")


def _checkpoint_leg(workdir: str, seed: int) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    from replication_faster_rcnn_tpu.train import fault

    rng = np.random.RandomState(seed)
    state = {
        "params": {"w": rng.rand(8, 8).astype(np.float32)},
        "step": np.zeros((), np.int64),
    }
    mgr = ocp.CheckpointManager(
        workdir, options=ocp.CheckpointManagerOptions(max_to_keep=4, create=True)
    )
    try:
        # step 1 saves clean (the torn-write rule is max_fires=1 but its
        # decision stream may pass early hits); keep saving until the
        # single torn write lands, then verify the walk-back
        torn_step = None
        for step in (1, 2, 3):
            state = dict(state, step=np.full((), step, np.int64))
            before = len(failpoints.event_log())
            _checkpointed_save(mgr, workdir, step, state)
            fired = [
                e
                for e in failpoints.event_log()[before:]
                if e["site"] == "checkpoint.write"
            ]
            if fired:
                torn_step = step
                break
        _check(
            torn_step is not None,
            "checkpoint leg: torn-write rule (prob=1.0) never fired",
        )
        template = {
            "params": {"w": np.zeros((8, 8), np.float32)},
            "step": np.zeros((), np.int64),
        }
        logs: List[str] = []
        result = fault.verified_restore(
            mgr, template, workdir, log=logs.append
        )
        _check(
            result.step < torn_step,
            f"checkpoint leg: restored step {result.step} is not older than "
            f"the torn step {torn_step}",
        )
        _check(
            any(s == torn_step for s, _ in result.discarded),
            f"checkpoint leg: torn step {torn_step} was not discarded "
            f"(discarded={result.discarded})",
        )
        _check(
            fault.verify_state(result.manifest, result.state) == [],
            "checkpoint leg: fallback state failed manifest verification",
        )
        return {"torn_step": torn_step, "restored_step": result.step}
    finally:
        mgr.close()


def _batcher_leg() -> Dict[str, Any]:
    from replication_faster_rcnn_tpu.serving.batcher import MicroBatcher

    # threadless mode (start=False + explicit _service_once): grouping is
    # deterministic — both submits land in ONE flush of 2, so exactly one
    # batcher.flush hit is consulted per pair regardless of scheduling
    with MicroBatcher(
        lambda key, items: [x * 2 for x in items],
        max_batch=2,
        max_delay_s=60.0,
        depth=8,
        name="chaos-smoke-batcher",
        start=False,
    ) as mb:
        first = [mb.submit("k", i) for i in range(2)]
        mb._service_once(block=False)  # queues entry 0 (group of 1)
        mb._service_once(block=False)  # entry 1 completes the group: flush
        errs = []
        for f in first:
            try:
                f.result(timeout=0)
            except failpoints.ChaosError as e:
                errs.append(e)
        _check(
            len(errs) == 2,
            f"batcher leg: injected flush IOError hit {len(errs)}/2 futures",
        )
        # the batcher must survive the failed flush (max_fires=1 spent)
        second = [mb.submit("k", i) for i in range(2)]
        mb._service_once(block=False)
        mb._service_once(block=False)
        got = [f.result(timeout=0) for f in second]
        _check(got == [0, 2], f"batcher leg: post-fault flush returned {got}")
    return {"failed_futures": len(errs), "recovered": True}


def _fleet_leg(workdir: str, seed: int) -> Dict[str, Any]:
    import os

    from replication_faster_rcnn_tpu.parallel import elastic

    fleet_dir = os.path.join(workdir, "fleet")
    now = [0.0]
    dead: List[int] = []
    incidents: List[Dict[str, Any]] = []

    def _agent(rank: int) -> elastic.ElasticAgent:
        return elastic.ElasticAgent(
            fleet_dir,
            generation=0,
            rank=rank,
            world=2,
            heartbeat_interval_s=0.5,
            lease_timeout_s=1.0,
            clock=lambda: now[0],
            # sudden death, minus the os._exit: the rank just stops beating
            on_drop=lambda r=rank: dead.append(r),
            on_lost=lambda lost, survivors: incidents.append(
                {"event": "fleet_rank_lost", "lost": lost, "survivors": survivors}
            ),
            exit_on_shrink=False,
        )

    agents = [_agent(0), _agent(1)]
    # strict r0,r1 beat alternation through the shared registry — the
    # smoke rule's after=3 deterministically lands the drop on rank 1's
    # 2nd renewal (hit 3); a dead rank never beats again
    for _ in range(2):
        for a in agents:
            if a.rank not in dead:
                a.beat()
        now[0] += 0.5
    _check(dead == [1], f"fleet leg: seeded drop killed ranks {dead}, not [1]")

    # the survivor keeps renewing; rank 1's lease (last written at t=0.0)
    # ages past the 1.0s timeout while rank 0's stays fresh
    lost: List[int] = []
    for _ in range(3):
        agents[0].beat()
        lost = agents[0].lost_ranks()
        if lost:
            break
        now[0] += 0.5
    _check(lost == [1], f"fleet leg: watchdog saw lost={lost}, want [1]")
    # the watchdog's loss path: observer -> durable intent -> check()
    # (exit_on_shrink=False stands in for the os._exit(76) hand-off)
    agents[0]._on_peer_lost(lost)
    _check(
        agents[0].check() == [1],
        f"fleet leg: main-thread check() saw {agents[0].check()}, want [1]",
    )
    intent = elastic.read_intent(fleet_dir, 0)
    _check(
        intent is not None
        and intent["lost"] == [1]
        and intent["survivors"] == [0],
        f"fleet leg: durable shrink intent is wrong: {intent}",
    )
    _check(
        incidents == [{"event": "fleet_rank_lost", "lost": [1], "survivors": [0]}],
        f"fleet leg: on_lost observer saw {incidents}",
    )

    # re-form: the survivor claims generation 1; as lowest claimant it
    # arbitrates the plan — a 1-rank fleet
    elastic.write_claim(fleet_dir, 1, 0)
    claims = elastic.read_claims(fleet_dir, 1, 2)
    _check(claims == [0], f"fleet leg: gen-1 claims {claims}, want [0]")
    elastic.write_plan(fleet_dir, 1, claims)
    plan = elastic.read_plan(fleet_dir, 1)
    _check(
        plan == {"generation": 1, "survivors": [0], "world": 1},
        f"fleet leg: gen-1 plan is wrong: {plan}",
    )

    # bring-up variant: replay the same loss at collective-init time —
    # rank 0 inits first, the seeded drop (after=1) names rank 1
    init_deaths: List[int] = []
    for r in (0, 1):
        inj = failpoints.fire("collective.init", num_processes=2, process_id=r)
        if inj is not None and inj.kind == "drop" and int(inj.arg) == r:
            init_deaths.append(r)
    _check(
        init_deaths == [1],
        f"fleet leg: init-time drop killed ranks {init_deaths}, not [1]",
    )
    return {
        "dropped_rank": dead[0],
        "reformed_world": plan["world"],
        "init_dropped_rank": init_deaths[0],
    }


def _fleet_router_leg(seed: int) -> Dict[str, Any]:
    from replication_faster_rcnn_tpu.config import FleetConfig
    from replication_faster_rcnn_tpu.serving import fleet as fleet_mod

    # hedging off + fake clock + manual probes: every failpoint hit index
    # is a pure function of this leg's call sequence, so the seeded
    # schedule replays identically (the determinism pin)
    cfg = FleetConfig(
        hedge=False,
        probe_interval_s=0.5,
        lease_timeout_s=1.2,
        rejoin_probes=2,
        breaker_threshold=2,
        breaker_cooldown_s=1.0,
        cache_entries=8,
        canary_fraction=0.0,
    )
    now = [0.0]
    clients = {
        rid: fleet_mod.LocalReplicaClient(rid, lambda p: p * 2)
        for rid in ("r0", "r1", "r2")
    }
    registry = fleet_mod.ReplicaRegistry(cfg, clock=lambda: now[0])
    for rid, client in clients.items():
        registry.add(rid, client)

    def _probe_round() -> None:
        registry.probe_once()
        now[0] += 0.5

    # round 1: everyone's 1st ok probe; round 2: the seeded router.probe
    # IOError (after=4) fails exactly r1's probe, so r0/r2 reach the
    # rejoin_probes=2 gate and r1 is held back one round
    _probe_round()
    _probe_round()
    _check(
        registry.in_rotation() == ["r0", "r2"],
        f"fleet_router leg: rotation after the faulted probe round is "
        f"{registry.in_rotation()}, want ['r0', 'r2']",
    )
    _probe_round()
    _probe_round()
    _check(
        registry.in_rotation() == ["r0", "r1", "r2"],
        f"fleet_router leg: r1 did not rejoin after the transient probe "
        f"fault: {registry.in_rotation()}",
    )

    router = fleet_mod.FleetRouter(
        registry,
        cfg,
        clock=lambda: now[0],
        kill_hook=lambda rid: clients[rid].kill(),
    )
    # requests a, b dispatch clean (router.dispatch hits 0, 1)
    _check(
        router.dispatch(3, content_hash="img-a") == 6,
        "fleet_router leg: request a returned the wrong result",
    )
    _check(
        router.dispatch(4, content_hash="img-b") == 8,
        "fleet_router leg: request b returned the wrong result",
    )
    # request c: the seeded drop (hit 2) kills its selected replica
    # mid-request; failover (hit 3) must answer anyway
    victim = router.candidates("img-c")[0]
    _check(
        router.dispatch(5, content_hash="img-c") == 10,
        "fleet_router leg: failover did not absorb the replica kill",
    )
    _check(
        clients[victim].killed,
        f"fleet_router leg: kill hook did not kill {victim!r}",
    )
    _check(
        router.stats["failovers"] == 1,
        f"fleet_router leg: failovers={router.stats['failovers']}, want 1",
    )
    # the dead replica stops answering probes; its lease (1.2s) ages out
    # within three 0.5s rounds and the registry declares it DEAD
    for _ in range(3):
        _probe_round()
    _check(
        registry.state_of(victim) == "dead",
        f"fleet_router leg: victim state is {registry.state_of(victim)!r}, "
        "want 'dead' after lease timeout",
    )
    _check(
        victim not in registry.in_rotation(),
        "fleet_router leg: dead replica still in rotation",
    )
    # drain/rejoin: the replica restarts and re-enters rotation after
    # rejoin_probes clean probes — no operator action
    clients[victim].revive()
    _probe_round()
    _probe_round()
    _check(
        registry.state_of(victim) == "healthy",
        f"fleet_router leg: revived replica is "
        f"{registry.state_of(victim)!r}, want 'healthy'",
    )
    # duplicate image: answered from the content-hash cache, no dispatch
    _check(
        router.dispatch(3, content_hash="img-a") == 6
        and router.stats["cache_hits"] == 1,
        "fleet_router leg: duplicate content was not served from cache",
    )
    return {
        "victim": victim,
        "failovers": router.stats["failovers"],
        "cache_hits": router.stats["cache_hits"],
        "rejoined": True,
    }


def _rollout_leg(workdir: str, seed: int) -> Dict[str, Any]:
    import os

    from replication_faster_rcnn_tpu.config import (
        FasterRCNNConfig,
        FleetConfig,
        RolloutConfig,
    )
    from replication_faster_rcnn_tpu.serving import fleet as fleet_mod
    from replication_faster_rcnn_tpu.serving.rollout import (
        RolloutController,
        VersionFeed,
    )
    from replication_faster_rcnn_tpu.train import fault

    # publish two real versions: manifest + feed line + a step dir, so
    # the pre-drain eligibility gate judges the same artifacts the
    # trainer writes (config=None on the feed skips the hash check —
    # there is no training config in this leg)
    wd = os.path.join(workdir, "rollout")
    rng = np.random.RandomState(seed)
    for step in (1, 2):
        state = {"params": {"w": rng.rand(4, 4).astype(np.float32)}}
        os.makedirs(os.path.join(wd, str(step)), exist_ok=True)
        fault.write_manifest(wd, step, state, None, kind="scheduled")
        fault.publish_manifest_event(wd, step)
    feed = VersionFeed(wd, config=None)

    cfg = FasterRCNNConfig().replace(
        fleet=FleetConfig(
            hedge=False,
            probe_interval_s=0.5,
            lease_timeout_s=2.0,
            rejoin_probes=2,
            canary_fraction=0.25,
            cache_entries=0,
        ),
        rollout=RolloutConfig(
            drain_timeout_s=2.0,
            swap_timeout_s=5.0,
            rejoin_timeout_s=10.0,
            canary_hold_s=1.0,
            canary_min_requests=0,
        ),
    )
    # fake replicas: a mutable version map + swap/health callables —
    # LocalReplicaClient's swap() is the same surface the HTTP transport
    # gives the controller against real `frcnn serve` replicas
    now = [0.0]
    versions = {"r0": "1", "r1": "1", "r2": "1"}
    clients = {
        rid: fleet_mod.LocalReplicaClient(
            rid,
            lambda p: p * 2,
            health_fn=lambda rid=rid: {
                "ok": True,
                "model_version": versions[rid],
                "bucket_queue_depths": {},
            },
            swap_fn=lambda v, rid=rid: versions.__setitem__(rid, v),
        )
        for rid in ("r0", "r1", "r2")
    }
    registry = fleet_mod.ReplicaRegistry(cfg.fleet, clock=lambda: now[0])
    for rid, client in clients.items():
        registry.add(rid, client)
    for _ in range(cfg.fleet.rejoin_probes):
        registry.probe_once()
        now[0] += 0.5
    _check(
        registry.in_rotation() == ["r0", "r1", "r2"],
        f"rollout leg: fleet never admitted: {registry.in_rotation()}",
    )
    router = fleet_mod.FleetRouter(registry, cfg.fleet, clock=lambda: now[0])
    controller = RolloutController(
        registry,
        router,
        cfg,
        feed=feed,
        clock=lambda: now[0],
        sleep=lambda s: now.__setitem__(0, now[0] + s),
    )

    def _names(result) -> List[str]:
        return [e["event"] for e in result.events]

    # an unpublished version must be rejected before any replica drains
    gate = controller.rollout("9")
    _check(
        gate.outcome == "ineligible"
        and _names(gate) == ["wave_ineligible", "wave_done"],
        f"rollout leg: unpublished version verdict was {gate.outcome!r} "
        f"with events {_names(gate)}",
    )

    # wave 1: the seeded rollout.swap drop is the mid-swap kill on the
    # first (already held + drained) replica — abort, reverse-roll it,
    # reconverge the fleet on the old version
    wave1 = controller.rollout("2")
    _check(
        wave1.outcome == "aborted"
        and "injected mid-swap kill" in (wave1.reason or ""),
        f"rollout leg: wave 1 was {wave1.outcome!r} ({wave1.reason!r}), "
        "want the injected abort",
    )
    _check(
        _names(wave1)
        == [
            "wave_started",
            "replica_hold",
            "wave_aborted",
            "replica_rolled_back",
            "wave_done",
        ],
        f"rollout leg: wave 1 events were {_names(wave1)}",
    )
    _check(
        registry.in_rotation() == ["r0", "r1", "r2"]
        and set(versions.values()) == {"1"}
        and set(registry.model_versions().values()) == {"1"},
        "rollout leg: fleet did not reconverge on the old version after "
        f"the aborted wave (versions={versions}, "
        f"rotation={registry.in_rotation()})",
    )

    # wave 2: the drop is spent — hold/drain/swap/rejoin each replica,
    # canary-gate the first, promote, finish the wave
    wave2 = controller.rollout("2")
    _check(
        wave2.outcome == "promoted"
        and wave2.swapped == ["r0", "r1", "r2"],
        f"rollout leg: retry wave was {wave2.outcome!r} "
        f"(swapped={wave2.swapped}), want a full promotion",
    )
    _check(
        "canary_promoted" in _names(wave2),
        f"rollout leg: retry wave skipped the canary gate: {_names(wave2)}",
    )
    _check(
        registry.in_rotation() == ["r0", "r1", "r2"]
        and set(versions.values()) == {"2"}
        and set(registry.model_versions().values()) == {"2"},
        "rollout leg: fleet did not land on the new version "
        f"(versions={versions}, registry={registry.model_versions()})",
    )
    _check(
        all(registry.role_of(rid) == "serving" for rid in clients),
        f"rollout leg: a canary role leaked past promotion: "
        f"{[registry.role_of(rid) for rid in clients]}",
    )
    return {
        "gate": gate.outcome,
        "wave1": wave1.outcome,
        "wave1_rolled_back": wave1.rolled_back,
        "wave2": wave2.outcome,
        "final_versions": dict(versions),
    }


def _one_pass(workdir: str, seed: int) -> Dict[str, Any]:
    failpoints.configure(smoke_rules(seed))
    try:
        legs = {
            "loader": _loader_leg(seed),
            "checkpoint": _checkpoint_leg(workdir, seed),
            "batcher": _batcher_leg(),
            "fleet": _fleet_leg(workdir, seed),
            "fleet_router": _fleet_router_leg(seed),
            "rollout": _rollout_leg(workdir, seed),
        }
        events = failpoints.event_log()
    finally:
        failpoints.disarm()
    return {"legs": legs, "events": events}


def run_smoke(workdir: str, seed: int = 0) -> Dict[str, Any]:
    """Run the smoke schedule twice under ``seed`` and assert every
    recovery invariant plus run-to-run event-log identity. Raises
    :class:`ChaosSmokeError` on any violation; returns a summary."""
    import os

    t0 = time.monotonic()
    first = _one_pass(os.path.join(workdir, "pass1"), seed)
    second = _one_pass(os.path.join(workdir, "pass2"), seed)
    _check(
        first["events"] == second["events"],
        "determinism leg: the same seed produced different injected-event "
        f"logs\nfirst:  {json.dumps(first['events'])}\n"
        f"second: {json.dumps(second['events'])}",
    )
    _check(bool(first["events"]), "determinism leg: schedule injected nothing")
    return {
        "ok": True,
        "seed": seed,
        "legs": first["legs"],
        "injected_events": len(first["events"]),
        "events": first["events"],
        "elapsed_s": round(time.monotonic() - t0, 3),
    }
