"""Deterministic fault injection (failpoints) + chaos harnesses.

`failpoints` is the seeded registry of named injection sites threaded
through the data loader, checkpoint writers, device prefetcher,
micro-batcher, HTTP handler and collective init; `chaos` is the seeded
smoke harness behind `frcnn chaos --smoke`.
"""

from replication_faster_rcnn_tpu.faultlib import failpoints

# `chaos` is imported lazily by its users (it pulls in data/checkpoint
# machinery, which itself consults `failpoints` — an eager import here
# would be circular).
__all__ = ["failpoints"]
